"""Meta/tagging framework (reference: RapidsMeta.scala:74,547,927).

Wraps a CPU physical plan into a parallel meta-tree; ``tag_for_tpu`` marks
each node and expression convertible-or-not with recorded reasons;
``convert_if_needed`` then builds the device plan for convertible subtrees.
Per-op enable flags are auto-derived from rule names
(``spark.rapids.sql.exec.<Name>`` / ``spark.rapids.sql.expression.<Name>``)
exactly like ExecRule/ExprRule.confKey in GpuOverrides.scala:211-303.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Type

from ..columnar import dtypes as dt
from ..columnar.dtypes import TypeSig
from ..conf import RapidsConf
from ..expr.base import Expression
from .physical import PhysicalPlan

__all__ = ["ExprMeta", "ExecMeta", "ExprRule", "ExecRule",
           "EXPR_RULES", "EXEC_RULES", "register_expr_rule",
           "register_exec_rule", "wrap_plan", "render_analyzed_plan"]


class BaseMeta:
    def __init__(self):
        self.reasons: List[str] = []

    def cannot_run(self, reason: str):
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_run(self) -> bool:
        return not self.reasons


class ExprMeta(BaseMeta):
    def __init__(self, expr: Expression, rule: "Optional[ExprRule]"):
        super().__init__()
        self.expr = expr
        self.rule = rule
        self.children = [wrap_expr(c) for c in expr.children]

    def tag(self, conf: RapidsConf):
        for c in self.children:
            c.tag(conf)
        name = type(self.expr).__name__
        if self.rule is None:
            self.cannot_run(f"expression {name} has no device implementation")
            return
        if not conf.is_op_enabled(self.rule.conf_key):
            self.cannot_run(f"expression {name} disabled by {self.rule.conf_key}")
            return
        self.rule.tag(self, conf)
        for c in self.children:
            if not c.can_run:
                self.cannot_run(
                    f"child expression {type(c.expr).__name__} cannot run: "
                    + "; ".join(c.reasons))

    def all_reasons(self) -> List[str]:
        return self.reasons


class ExecMeta(BaseMeta):
    def __init__(self, plan: PhysicalPlan, rule: "Optional[ExecRule]"):
        super().__init__()
        self.plan = plan
        self.rule = rule
        self.children = [wrap_plan_node(c) for c in plan.children]
        self.expr_metas: List[ExprMeta] = [
            wrap_expr(e) for e in (rule.exprs_of(plan) if rule else [])]

    def tag(self, conf: RapidsConf):
        for c in self.children:
            c.tag(conf)
        name = type(self.plan).__name__
        if self.rule is None:
            self.cannot_run(f"{name} has no device implementation")
            return
        if not conf.is_op_enabled(self.rule.conf_key):
            self.cannot_run(f"{name} disabled by {self.rule.conf_key}")
            return
        # output schema type check
        for f in self.plan.schema:
            for r in self.rule.output_sig.reasons_not_supported(f.dtype):
                self.cannot_run(f"output column {f.name}: {r}")
        # input schema type check (reference: ExecChecks input sigs,
        # TypeChecks.scala:702) — a host->device transition uploads the whole
        # child batch, so unsupported child columns block device lowering
        for child_plan in self.plan.children:
            for f in child_plan.schema:
                for r in self.rule.output_sig.reasons_not_supported(f.dtype):
                    self.cannot_run(f"input column {f.name}: {r}")
        for em in self.expr_metas:
            em.tag(conf)
            if not em.can_run:
                self.cannot_run(
                    f"expression {em.expr!r} cannot run: " + "; ".join(em.reasons))
        self.rule.tag(self, conf)

    def convert_if_needed(self, conf: RapidsConf) -> PhysicalPlan:
        new_children = [c.convert_if_needed(conf) for c in self.children]
        if self.can_run and self.rule is not None:
            return self.rule.convert(self.plan, new_children, conf)
        return _replace_children(self.plan, new_children)

    # -- explain -------------------------------------------------------------
    def explain(self, indent: int = 0, not_on_device_only: bool = False) -> str:
        pad = "  " * indent
        name = type(self.plan).__name__
        lines = []
        if self.can_run:
            if not not_on_device_only:
                lines.append(f"{pad}* {name} will run on TPU")
        else:
            lines.append(f"{pad}! {name} cannot run on TPU because "
                         + "; ".join(self.reasons))
        for c in self.children:
            sub = c.explain(indent + 1, not_on_device_only)
            if sub:
                lines.append(sub)
        return "\n".join(l for l in lines if l)

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


class ExprRule:
    def __init__(self, cls: Type[Expression], sig: TypeSig,
                 tag_fn: Optional[Callable[[ExprMeta, RapidsConf], None]] = None,
                 note: str = ""):
        self.cls = cls
        self.sig = sig
        self.tag_fn = tag_fn
        self.note = note
        self.conf_key = f"spark.rapids.sql.expression.{cls.__name__}"

    def tag(self, meta: ExprMeta, conf: RapidsConf):
        e = meta.expr
        try:
            out_t = e.data_type
        except Exception as ex:  # unresolved
            meta.cannot_run(f"cannot determine type: {ex}")
            return
        for r in self.sig.reasons_not_supported(out_t):
            meta.cannot_run(f"output: {r}")
        for c in e.children:
            try:
                ct = c.data_type
            except Exception:
                continue
            for r in self.sig.reasons_not_supported(ct):
                meta.cannot_run(f"input {type(c).__name__}: {r}")
        if self.tag_fn is not None:
            self.tag_fn(meta, conf)


class ExecRule:
    def __init__(self, cls: Type[PhysicalPlan], output_sig: TypeSig,
                 convert_fn: Callable[[PhysicalPlan, List[PhysicalPlan], RapidsConf],
                                      PhysicalPlan],
                 exprs_fn: Optional[Callable[[PhysicalPlan], Sequence[Expression]]] = None,
                 tag_fn: Optional[Callable[[ExecMeta, RapidsConf], None]] = None,
                 note: str = ""):
        self.cls = cls
        self.output_sig = output_sig
        self.convert_fn = convert_fn
        self.exprs_fn = exprs_fn
        self.tag_fn = tag_fn
        self.note = note
        name = cls.__name__.replace("Cpu", "")
        self.conf_key = f"spark.rapids.sql.exec.{name}"

    def exprs_of(self, plan: PhysicalPlan) -> Sequence[Expression]:
        return self.exprs_fn(plan) if self.exprs_fn else []

    def tag(self, meta: ExecMeta, conf: RapidsConf):
        if self.tag_fn is not None:
            self.tag_fn(meta, conf)

    def convert(self, plan: PhysicalPlan, children: List[PhysicalPlan],
                conf: RapidsConf) -> PhysicalPlan:
        return self.convert_fn(plan, children, conf)


EXPR_RULES: Dict[type, ExprRule] = {}
EXEC_RULES: Dict[type, ExecRule] = {}


def register_expr_rule(cls, sig: TypeSig, tag_fn=None, note: str = "") -> ExprRule:
    rule = ExprRule(cls, sig, tag_fn, note)
    EXPR_RULES[cls] = rule
    return rule


def register_exec_rule(cls, output_sig: TypeSig, convert_fn, exprs_fn=None,
                       tag_fn=None, note: str = "") -> ExecRule:
    rule = ExecRule(cls, output_sig, convert_fn, exprs_fn, tag_fn, note)
    EXEC_RULES[cls] = rule
    return rule


def wrap_expr(e: Expression) -> ExprMeta:
    rule = None
    for cls in type(e).__mro__:  # rules may be registered on base classes
        if cls in EXPR_RULES:
            rule = EXPR_RULES[cls]
            break
    return ExprMeta(e, rule)


def wrap_plan_node(p: PhysicalPlan) -> ExecMeta:
    rule = None
    for cls in type(p).__mro__:
        if cls in EXEC_RULES:
            rule = EXEC_RULES[cls]
            break
    return ExecMeta(p, rule)


def wrap_plan(p: PhysicalPlan) -> ExecMeta:
    return wrap_plan_node(p)


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE rendering: the POST-OVERRIDE plan tree (what actually
# executed — device execs, transitions, whole-stage fusions) annotated with
# each node's runtime stats and % of query wall. The reference only tags
# plans pre-execution (ExplainPlan); pairing the tree with measured
# NodeStats is what makes a 0.5x-geomean regression attributable.
# ---------------------------------------------------------------------------
def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _fmt_metric(name: str, v) -> Optional[str]:
    from ..utils import metrics as M
    if isinstance(v, dict):  # histogram summary: show the median only
        p50 = v.get("p50")
        return f"{name}.p50={p50:.0f}" if p50 is not None else None
    if name in M.TIME_METRICS:
        return f"{name}={v:.4f}s"
    if name in M.BYTE_METRICS:
        return f"{name}={_fmt_bytes(v)}"
    return f"{name}={v}"


def render_analyzed_plan(nodes, total_s: float, kernels=None) -> str:
    """Annotate an executed plan tree with runtime metrics.

    ``nodes`` are profiler NodeStats (or event-log node dicts with the same
    keys): name/desc/depth/node_id/parent_id/wall_s/rows/batches/metrics.
    Percentages use SELF time (wall minus direct children), so they sum to
    at most 100% across the tree."""
    from ..tools.profiler import compute_self_times
    rows = [_as_node_dict(n) for n in nodes]
    self_s = compute_self_times(rows)
    covered = 0.0
    for n in rows:
        n["self_s"] = self_s[n["node_id"]]
        covered += n["self_s"]
    pct_cov = 100.0 * covered / total_s if total_s > 0 else 0.0
    lines = ["== Physical Plan (EXPLAIN ANALYZE) ==",
             f"query wall {total_s:.4f}s; {len(rows)} operators, "
             f"self times cover {pct_cov:.0f}% of wall", ""]
    from ..utils import metrics as M
    for n in rows:
        pct = 100.0 * n["self_s"] / total_s if total_s > 0 else 0.0
        pad = "  " * n["depth"]
        desc = f" [{n['desc'][:48]}]" if n.get("desc") else ""
        lines.append(f"{pad}{n['name']}{desc}")
        detail = (f"wall {n['wall_s']:.4f}s  self {n['self_s']:.4f}s "
                  f"({pct:.1f}%)  rows {n['rows']}  batches {n['batches']}")
        extras = []
        metrics = n.get("metrics") or {}
        order = [M.OP_TIME, M.SORT_TIME, M.AGG_TIME, M.JOIN_TIME,
                 M.UPLOAD_TIME, M.UPLOAD_BYTES, M.DOWNLOAD_TIME,
                 M.DOWNLOAD_BYTES, M.SHUFFLE_BYTES,
                 M.SHUFFLE_PARTITION_TIME, M.COMPILE_TIME,
                 M.COMPILE_CACHE_HITS, M.COMPILE_CACHE_MISSES,
                 M.SPILL_BYTES, M.PEAK_DEVICE_MEMORY]
        seen = set()
        for key in order:
            if key in metrics:
                seen.add(key)
                s = _fmt_metric(key, metrics[key])
                if s:
                    extras.append(s)
        for key in sorted(metrics):
            if key not in seen and key not in (M.NUM_OUTPUT_ROWS,
                                               M.NUM_OUTPUT_BATCHES,
                                               M.BATCH_ROWS_HISTOGRAM):
                s = _fmt_metric(key, metrics[key])
                if s:
                    extras.append(s)
        lines.append(f"{pad}    {detail}")
        if extras:
            lines.append(f"{pad}    " + "  ".join(extras))
    if kernels:
        lines.append("")
        lines.append("== XLA kernels (this query) ==")
        for k in sorted(kernels, key=lambda e: -e.get("compile_s", 0.0))[:8]:
            cost = k.get("cost") or {}
            bits = [f"compile {k.get('compile_s', 0.0):.3f}s",
                    f"hits {k.get('hits', 0)}"]
            if k.get("node_name"):
                bits.append(f"node {k['node_name']}")
            if "flops" in cost:
                bits.append(f"flops {cost['flops']:.3g}")
            if "bytes accessed" in cost:
                bits.append(f"bytes {_fmt_bytes(cost['bytes accessed'])}")
            mem = k.get("memory") or {}
            if "temp_bytes" in mem:
                bits.append(f"temp {_fmt_bytes(mem['temp_bytes'])}")
            lines.append(f"  {k['signature'][:72]:<74}" + "  ".join(bits))
    return "\n".join(lines)


def _as_node_dict(n) -> dict:
    if isinstance(n, dict):
        return dict(n)
    return {"name": n.name, "desc": n.desc, "depth": n.depth,
            "node_id": n.node_id, "parent_id": n.parent_id,
            "wall_s": n.wall_s, "rows": n.rows, "batches": n.batches,
            "metrics": getattr(n, "metrics", {}) or {}}


def _replace_children(plan: PhysicalPlan, children: List[PhysicalPlan]) -> PhysicalPlan:
    if list(plan.children) == children:
        return plan
    plan.children = tuple(children)
    if hasattr(plan, "child") and len(children) == 1:
        plan.child = children[0]
    if hasattr(plan, "left") and len(children) == 2:
        plan.left, plan.right = children
    return plan
