"""Device parquet scan operator.

Reference: GpuFileSourceScanExec + GpuParquetScanBase — the scan itself is a
device operator whose output is already columnar device memory. Here each
row group decodes through io/parquet_device.py (host byte plumbing + device
run-expansion/dictionary-gather kernels); columns outside the device subset
ride along via per-column host decode + upload, so the scan's output is one
DeviceTable per row group either way.
"""
from __future__ import annotations

import io as _io
from typing import Iterator, List, Optional

from ..columnar.device import DeviceTable
from ..plan.physical import PhysicalPlan
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuParquetScanExec"]


class TpuParquetScanExec(TpuExec):
    def __init__(self, source, columns: Optional[List[str]],
                 schema, min_bucket: int):
        super().__init__()
        self.source = source
        self.columns = list(columns) if columns else None
        self.children = ()
        self.schema = schema
        self.min_bucket = min_bucket

    @property
    def num_partitions(self) -> int:
        return self.source.partitions()

    def node_desc(self) -> str:
        return (f"{self.source.name()} device-decode "
                f"cols={self.columns or '*'}")

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..conf import MULTITHREAD_READ_NUM_THREADS
        from ..io.prefetch import prefetched
        cols = self.columns or self.schema.names
        files = self.source._file_parts[pidx]
        nthreads = self.source.conf.get(MULTITHREAD_READ_NUM_THREADS)

        def read_bytes(p):
            with open(p, "rb") as f:
                return f.read()

        # bounded file read-ahead overlapping IO with device decode
        # (reference: MultiFileCloudParquetPartitionReader's read pool)
        for path, raw in prefetched(files, read_bytes, max(2, nthreads)):
            yield from self._decode_file(path, raw, cols)

    def _decode_file(self, path: str, raw: bytes,
                     cols) -> Iterator[DeviceTable]:
        import pyarrow.parquet as pq

        from ..io.file_block import set_input_file
        from ..io.parquet_device import decode_row_group
        set_input_file(path, 0, len(raw))
        pf = pq.ParquetFile(_io.BytesIO(raw))
        for rg in range(pf.metadata.num_row_groups):
            with self.metrics.timed(M.OP_TIME):
                table, n_dev = decode_row_group(
                    raw, pf.metadata, rg, pf.schema_arrow, cols,
                    self.min_bucket, conf=self.source.conf)
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            self.metrics.add(M.NUM_OUTPUT_ROWS, int(table.num_rows))
            self.metrics.add("deviceDecodedColumns", n_dev)
            yield table
