"""Device parquet scan operator.

Reference: GpuFileSourceScanExec + GpuParquetScanBase — the scan itself is a
device operator whose output is already columnar device memory. Here each
row group decodes through io/parquet_device.py (host byte plumbing + device
run-expansion/dictionary-gather kernels); columns outside the device subset
ride along via per-column host decode + upload, so the scan's output is one
DeviceTable per row group either way.
"""
from __future__ import annotations

import io as _io
from typing import Iterator, List, Optional

from ..columnar.device import DeviceTable, resolve_min_bucket
from ..plan.physical import PhysicalPlan
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuParquetScanExec", "TpuCsvScanExec", "TpuJsonScanExec"]


class TpuParquetScanExec(TpuExec):
    def __init__(self, source, columns: Optional[List[str]],
                 schema, min_bucket: Optional[int] = None):
        super().__init__()
        self.source = source
        self.columns = list(columns) if columns else None
        self.children = ()
        self.schema = schema
        self.min_bucket = resolve_min_bucket(min_bucket)

    @property
    def num_partitions(self) -> int:
        return self.source.partitions()

    def node_desc(self) -> str:
        return (f"{self.source.name()} device-decode "
                f"cols={self.columns or '*'}")

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..conf import MULTITHREAD_READ_NUM_THREADS
        from ..io.prefetch import prefetched
        cols = self.columns or self.schema.names
        files = self.source._file_parts[pidx]
        nthreads = self.source.conf.get(MULTITHREAD_READ_NUM_THREADS)

        def read_bytes(p):
            with open(p, "rb") as f:
                return f.read()

        # bounded file read-ahead overlapping IO with device decode
        # (reference: MultiFileCloudParquetPartitionReader's read pool)
        for path, raw in prefetched(files, read_bytes, max(2, nthreads)):
            yield from self._decode_file(path, raw, cols)

    def _decode_file(self, path: str, raw: bytes,
                     cols) -> Iterator[DeviceTable]:
        import pyarrow.parquet as pq

        from ..io.file_block import set_input_file
        from ..io.parquet_device import decode_row_group
        set_input_file(path, 0, len(raw))
        pf = pq.ParquetFile(_io.BytesIO(raw))
        for rg in range(pf.metadata.num_row_groups):
            with self.metrics.timed(M.OP_TIME):
                table, n_dev = decode_row_group(
                    raw, pf.metadata, rg, pf.schema_arrow, cols,
                    self.min_bucket, conf=self.source.conf)
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            # row count from parquet metadata, not the device batch: the
            # scan metric must not block on the decode's async dispatch
            self.metrics.add(M.NUM_OUTPUT_ROWS,
                             pf.metadata.row_group(rg).num_rows)
            self.metrics.add("deviceDecodedColumns", n_dev)
            yield table


class TpuCsvScanExec(TpuExec):
    """CSV scan with device field-split + typed parse (round-4 VERDICT
    item 4; reference: GpuTextBasedPartitionReader.scala:44). The host
    only frames lines (one vectorized newline scan); separator splitting
    and numeric/date parsing run as one jitted byte-matrix program."""

    def __init__(self, source, columns: Optional[List[str]],
                 schema, min_bucket: Optional[int] = None):
        super().__init__()
        self.source = source
        self.columns = list(columns) if columns else None
        self.children = ()
        self.schema = schema        # already column-pruned by the planner
        self.min_bucket = resolve_min_bucket(min_bucket)

    @property
    def num_partitions(self) -> int:
        return self.source.partitions()

    def node_desc(self) -> str:
        return (f"{self.source.name()} device-decode "
                f"cols={self.columns or '*'}")

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..conf import MULTITHREAD_READ_NUM_THREADS
        from ..io.prefetch import prefetched

        files = self.source._file_parts[pidx]
        nthreads = self.source.conf.get(MULTITHREAD_READ_NUM_THREADS)

        def read_bytes(p):
            with open(p, "rb") as f:
                return f.read()

        for path, raw in prefetched(files, read_bytes, max(2, nthreads)):
            yield from self._decode_file(path, raw)

    def _decode_file(self, path: str, raw: bytes) -> Iterator[DeviceTable]:
        import numpy as _np

        from ..io.csv_device import decode_lines, split_lines
        from ..io.file_block import set_input_file

        set_input_file(path, 0, len(raw))
        if b'"' in raw:
            # the tag-time gate only sniffs the first file's head; a quoted
            # field ANYWHERE disqualifies the device field-splitter for
            # this file — parse it host-side and upload (correctness over
            # placement, like the reference's per-file fallbacks)
            yield from self._host_fallback_file(path)
            return
        full_schema = self.source.schema()
        fields = [(f.name, f.dtype) for f in full_schema]
        col_indices = [full_schema.names.index(n)
                       for n in self.schema.names]
        sep = ord(self.source.sep)

        starts, lengths = split_lines(raw, skip_header=self.source.header)
        # ragged-row gate: the host reader RAISES on inconsistent field
        # counts (pyarrow "Expected N columns"); the device splitter would
        # silently null/ignore — route such files to the host parser so
        # both placements fail identically
        buf = _np.frombuffer(raw, dtype=_np.uint8)
        sep_pos = _np.flatnonzero(buf == _np.uint8(sep))
        nseps = (_np.searchsorted(sep_pos, starts + lengths)
                 - _np.searchsorted(sep_pos, starts))
        if len(starts) and not (nseps == len(fields) - 1).all():
            yield from self._host_fallback_file(path)
            return
        key_prefix = (f"csv|{sep}|"
                      + ",".join(f"{i}:{fields[i][1]!r}"
                                 for i in col_indices))
        yield from self._decode_line_batches(
            raw, starts, lengths, fields, col_indices, key_prefix,
            lambda: (lambda m, ln: decode_lines(m, ln, fields, sep,
                                                col_indices)))

    def _decode_line_batches(self, raw, starts, lengths, fields,
                             col_indices, key_prefix, builder
                             ) -> Iterator[DeviceTable]:
        """Shared line-batch loop for the text decoders: bucket lines into
        a byte matrix, run the cached jitted decoder, assemble the
        DeviceTable (zero-row edge cases live here, once)."""
        import jax.numpy as jnp
        import numpy as _np

        from ..columnar import dtypes as dt
        from ..columnar.device import (DeviceColumn, DeviceTable,
                                       bucket_rows, bucket_width)
        from ..io.csv_device import lines_to_matrix
        from ..utils.compile_cache import cached_jit

        names = self.schema.names
        batch_rows = self.source.batch_rows
        total = len(starts)
        pos = 0
        while pos < total or (pos == 0 and total == 0):
            s = starts[pos:pos + batch_rows]
            l = lengths[pos:pos + batch_rows]
            n = len(s)
            cap = bucket_rows(max(n, 1), self.min_bucket)
            width = bucket_width(max(int(l.max()) if n else 0, 1))
            with self.metrics.timed(M.OP_TIME):
                mat = lines_to_matrix(raw, s, l, cap, width)
                lens = _np.zeros(cap, dtype=_np.int32)
                lens[:n] = l
                fn = cached_jit(f"{key_prefix}|{cap}x{width}", builder)
                decoded = fn(jnp.asarray(mat), jnp.asarray(lens))
                iota = _np.arange(cap, dtype=_np.int32)
                row_mask = jnp.asarray(iota < n)
                cols = []
                for entry, idx in zip(decoded, col_indices):
                    d = fields[idx][1]
                    if isinstance(d, dt.StringType):
                        data, valid, flen = entry
                        valid = jnp.logical_and(valid, row_mask)
                        cols.append(DeviceColumn(data, valid, d, flen))
                    else:
                        data, valid = entry
                        valid = jnp.logical_and(valid, row_mask)
                        cols.append(DeviceColumn(data, valid, d, None))
                table = DeviceTable(tuple(cols), row_mask,
                                    jnp.asarray(n, jnp.int32), tuple(names))
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            self.metrics.add(M.NUM_OUTPUT_ROWS, n)
            yield table
            pos += batch_rows
            if total == 0:
                break

    def _host_fallback_file(self, path: str) -> Iterator[DeviceTable]:
        """Host pyarrow parse + upload for files the device splitter cannot
        handle (quotes / ragged rows discovered after the tag-time
        sample). Reuses the source's batching so the zero-row edge cases
        live in one place."""
        from ..columnar.device import DeviceTable as _DT
        t = self.source._read_file(path)
        for ht in self.source._slice_out(t, self.columns or None):
            yield _DT.from_host(ht, self.min_bucket)
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            self.metrics.add(M.NUM_OUTPUT_ROWS, ht.num_rows)


class TpuJsonScanExec(TpuCsvScanExec):
    """JSON-lines scan with device span-extraction + typed parse
    (reference: GpuJsonScan.scala). Shares the line-framing/batching
    machinery with the CSV scan; only the per-batch decode differs."""

    def _decode_file(self, path: str, raw: bytes) -> Iterator[DeviceTable]:
        from ..io.csv_device import split_lines
        from ..io.json_device import decode_json_lines
        from ..io.file_block import set_input_file

        set_input_file(path, 0, len(raw))
        if b"\\" in raw:
            # escapes discovered past the tag-time sample: host parse
            yield from self._host_fallback_file(path)
            return
        full_schema = self.source.schema()
        fields = [(f.name, f.dtype) for f in full_schema]
        col_indices = [full_schema.names.index(n)
                       for n in self.schema.names]
        starts, lengths = split_lines(raw, skip_header=False)
        # JSON kernels bake field NAMES into the traced program (token
        # matching), so the cache key must carry them — two sources with
        # same-position dtypes but different keys may NOT share a program
        key_prefix = ("json|"
                      + ",".join(f"{fields[i][0]}:{fields[i][1]!r}"
                                 for i in col_indices))
        yield from self._decode_line_batches(
            raw, starts, lengths, fields, col_indices, key_prefix,
            lambda: (lambda m, ln: decode_json_lines(m, ln, fields,
                                                     col_indices)))
