"""Mesh-parallel stage execution: post-exchange operators as ONE shard_map.

The ICI exchange (shuffle/ici.py) re-homes rows with a single
``jax.lax.all_to_all``, but the per-partition consumer contract then
breaks its output into n per-device partitions that downstream operators
drain as n SEQUENTIAL single-device programs — on an 8-device mesh
~80-90% of MULTICHIP wall is serialized compute, not shuffle
(MULTICHIP_r06.json: shuffle_wall_frac 0.11-0.21). This module closes
that gap, the TPU analogue of the reference's "partitioned operators run
on all executors at once" property (SURVEY §2.7, the point of the UCX
tier): ``TpuMeshStageExec`` takes the exchange's output STILL sharded
(keep-sharded mode, exec/exchange.py) and runs the downstream stage —
the same project/filter/partial-aggregate set the whole-stage pass fuses
— as one ``shard_map`` XLA program over the ``dp`` axis, so all n
partitions compute simultaneously on n devices.

Chain membership goes one step beyond the fusible set: a FINAL-mode hash
aggregate (merge of partial states) is mesh-capable too, because after
the exchange each shard holds its entire hash partition — applying the
merge kernel once per shard IS the complete final aggregate, provided
the exchange streamed exactly ONE chunk. That single-chunk precondition
is the **unshard boundary rule**, and the exchange enforces it at the
source: kept chunks are not spill-registered, so on a SECOND streamed
chunk the exchange reverts to split mode mid-stream (registering the
kept chunk) to preserve its out-of-core contract, and every mesh
consumer sees ``sharded_chunks() == None``. On that, or when the mesh
program terminally fails (classified XLA error — a miscompile, an OOM
past the ladder), the stage falls back to the
existing per-partition path: the exchange late-splits its kept-sharded
chunks (``_ensure_split``) and the ORIGINAL operator topology — child
links intact underneath this node — executes with its own
``with_host_fallback`` boundaries, while the failure feeds the
quarantine store (exec/fallback.py) so the next session plans around it.

Telemetry: the mesh dispatch notes a ``mesh_stage`` phase and the
one-time XLA build a ``compile`` phase on the ici tier, so
shuffle_summary's tier breakdown reconciles post-exchange compute that
rides the collective program cache.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P

from ..columnar.device import DeviceTable, resolve_scalars, shrink_to_fit
from ..conf import register_conf
from ..parallel.shard_compat import shard_map
from ..shuffle import telemetry as shuffle_telemetry
from ..utils import faults
from ..utils import metrics as M
from .base import TpuExec
from .exchange import TpuShuffleExchangeExec, _split_sharded
from .wholestage import TpuWholeStageExec, _fusible, _with_children

__all__ = ["TpuMeshStageExec", "plan_mesh_stages", "MESH_STAGE_ENABLED",
           "clear_mesh_programs"]

MESH_STAGE_ENABLED = register_conf(
    "spark.rapids.tpu.mesh.stageExecution.enabled",
    "Run post-exchange fusible stages (project/filter/partial-aggregate "
    "chains, plus the final-mode aggregate merge) as ONE shard_map XLA "
    "program over the device mesh, consuming the ICI exchange's output "
    "still sharded — all n partitions compute simultaneously instead of "
    "one sequential dispatch per partition. Only affects plans whose "
    "exchange runs on the ICI tier (session has a mesh); non-mesh plans "
    "and non-fusible consumers keep the per-partition path.", True)

# Mesh-stage programs are AOT-compiled (lower + compile) and cached by
# semantic key — same design as the exchange program cache
# (shuffle/ici.py): repeated same-shape stages reuse the executable, and
# the one-time XLA compile is timed as its own observatory phase.
_PROGRAMS: "OrderedDict[tuple, object]" = OrderedDict()
_PROGRAMS_MAX = 64


def clear_mesh_programs() -> None:
    """Drop cached mesh-stage executables (test hygiene: compiled-program
    caches accumulate per shape family, tests/conftest.py)."""
    _PROGRAMS.clear()


def _is_final_agg(node) -> bool:
    from .aggregate import TpuHashAggregateExec
    return isinstance(node, TpuHashAggregateExec) and node.mode == "final"


def _mesh_capable(node) -> bool:
    """Whether ``node`` can join a mesh-stage chain: the whole-stage
    fusible set, an already-fused whole stage, or a final-mode hash
    aggregate without collect ops (collects need a per-batch host-synced
    width pass, exec/aggregate.py)."""
    if not isinstance(node, TpuExec) or len(node.children) != 1:
        return False
    if isinstance(node, TpuWholeStageExec):
        return True
    if _is_final_agg(node):
        return not node._has_collect()
    return _fusible(node)


class TpuMeshStageExec(TpuExec):
    """Runs a chain of post-exchange operators SPMD across the mesh.

    ``chain`` is [bottom, ..., top] exactly as in TpuWholeStageExec; the
    bottom's child is the keep-sharded ICI exchange. The original
    per-partition topology stays linked underneath (chain[0] -> exchange,
    chain[i] -> chain[i-1]) so the fallback path can execute it
    unchanged."""

    EXTRA_METRICS = (M.PIPELINE_WAIT,)

    def __init__(self, exchange: TpuShuffleExchangeExec,
                 chain: List[TpuExec]):
        super().__init__()
        assert chain, "empty mesh-stage chain"
        self.exchange = exchange
        self.chain = list(chain)
        self.child = exchange
        self.children = (exchange,)
        self.schema = self.chain[-1].schema
        self.mesh = exchange.mesh
        self.axis = exchange.axis
        # per-partition output batches once materialized; None after a
        # fallback (the original topology serves execute_columnar then)
        self._results: Optional[List[List[DeviceTable]]] = None
        self._fell_back = False
        self._mat_lock = threading.Lock()
        exchange.request_keep_sharded()

    def absorb(self, node: TpuExec) -> "TpuMeshStageExec":
        """Grow the chain upward during the planner rewrite. The node's
        child link is pointed back at the current chain top (the rewrite
        had re-parented it onto this exec) so the fallback topology stays
        the original per-partition plan."""
        _with_children(node, [self.chain[-1]])
        self.chain.append(node)
        self.schema = node.schema
        return self

    @property
    def num_partitions(self) -> int:
        return self.exchange.num_partitions

    def node_name(self):
        inner = "+".join(type(n).__name__.replace("Tpu", "")
                         .replace("Exec", "") for n in self.chain)
        return f"TpuMeshStage[{inner}]"

    def node_desc(self) -> str:
        return f"mesh n={self.num_partitions} axis={self.axis}"

    def plan_signature(self) -> str:
        return "MESH|" + "||".join(n.plan_signature() for n in self.chain)

    def _has_final_agg(self) -> bool:
        return any(_is_final_agg(n) for n in self.chain)

    # -- execution ------------------------------------------------------------
    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        self._materialize()
        if self._results is None:
            # unshard boundary / terminal-failure fallback: the original
            # per-partition topology (still linked under this node, with
            # its own retry + host-fallback boundaries) serves the drain
            yield from self.chain[-1].execute_columnar(pidx)
            return
        from ..io.file_block import clear_input_file
        clear_input_file()  # post-shuffle rows have no single source file
        for t in self._results[pidx]:
            yield t

    def _materialize(self) -> None:
        with self._mat_lock:
            if self._results is not None or self._fell_back:
                return
            from ..parallel.pipeline import exempt_admission
            with exempt_admission():
                self._materialize_locked()

    def _materialize_locked(self) -> None:
        from .fallback import classify_failure, quarantine_on_failure
        n = self.num_partitions
        chunks = self.exchange.sharded_chunks()
        if chunks is None:
            # a per-partition consumer split the output first (plan reuse)
            self._fell_back = True
            return
        if not chunks:
            self._results = [[] for _ in range(n)]
            return
        if self._has_final_agg() and len(chunks) > 1:
            # unshard boundary rule: the final-merge-per-shard shortcut is
            # only complete when each shard holds its ENTIRE partition —
            # true iff the exchange streamed exactly one chunk
            self._fell_back = True
            return
        try:
            with quarantine_on_failure(self):
                outs = [self._dispatch_chunk(c) for c, _ in chunks]
        except Exception as e:
            # classified terminal failures (miscompile, OOM past the
            # ladder) degrade to the per-partition path — quarantine was
            # already noted above; anything unclassified is a real bug
            # and propagates
            if classify_failure(e) is None:
                raise
            self._fell_back = True
            return
        per_part: List[List[DeviceTable]] = [[] for _ in range(n)]
        if self._has_final_agg():
            # final-aggregate contract parity (exec/aggregate.py): one
            # compacted batch per partition; counts resolve in ONE funnel
            # transfer, then feed the compaction so it never re-syncs. A
            # shard with NO input and NO output rows yields nothing — the
            # per-partition path's keyed aggregate skips input-less
            # partitions entirely (an ungrouped aggregate still emits its
            # one state row and is kept by the rows check)
            parts = outs[0]
            (_, in_rows) = chunks[0]
            counts = resolve_scalars(*[t.num_rows for t in parts])
            for i, (t, cnt) in enumerate(zip(parts, counts)):
                rows = int(cnt)
                if rows == 0 and in_rows[i] == 0:
                    continue
                out = shrink_to_fit(t, num_rows=rows)
                per_part[i].append(out)
                self.account_batch(rows)
        else:
            # the split path spill-registers only NON-EMPTY shards
            # (exchange._register_split), so a shard the exchange sent no
            # rows yields no batch downstream — mirror that; a 0-row
            # result a filter produced from a non-empty shard still
            # yields, exactly as per-partition execution would
            for parts, (_, in_rows) in zip(outs, chunks):
                for i, t in enumerate(parts):
                    if in_rows[i] == 0:
                        continue
                    per_part[i].append(t)
                    self.account_batch()
        self._results = per_part

    def _dispatch_chunk(self, chunk: DeviceTable) -> List[DeviceTable]:
        """Run the composed chain over one kept-sharded exchanged chunk as
        a single SPMD program; split the (still sharded) result into
        per-device partition views."""
        n = self.num_partitions
        action = faults.fire("mesh.dispatch")
        if action is not None and action != "delay":
            if action == "oom":
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: injected device OOM at "
                    "mesh.dispatch (faults action=oom)")
            # the INTERNAL status string a miscompiled mesh program
            # produces, so classify_failure routes it down the same
            # degrade-to-per-partition path a real miscompile would take
            raise RuntimeError(
                "INTERNAL: injected mesh-stage dispatch failure "
                "(mesh.dispatch)")
        prog = self._program(chunk)
        with self.metrics.timed(M.OP_TIME):
            t0 = shuffle_telemetry.clock()
            out_cols, out_mask = prog(chunk.columns, chunk.row_mask)
            shuffle_telemetry.note_transfer(
                "ici", "mesh_stage", shuffle_id=self.exchange.telemetry_sid,
                t0=t0, queue_depth=n, wire_bytes=lambda: chunk.nbytes())
        out = DeviceTable(tuple(out_cols), out_mask,
                          jnp.sum(out_mask, dtype=jnp.int32),
                          tuple(self.schema.names))
        return _split_sharded(out, n)

    def _program(self, chunk: DeviceTable):
        """AOT-build (or fetch) the shard_map executable for this chain at
        this chunk's shapes; the XLA build is timed as the observatory's
        ``compile`` phase (never as stage wall)."""
        leaves, treedef = jax.tree_util.tree_flatten(chunk.columns)
        key = (self.plan_signature(), self.axis,
               tuple(str(d) for d in self.mesh.devices.flat),
               str(treedef),
               tuple((l.shape, str(l.dtype)) for l in leaves),
               (chunk.row_mask.shape, str(chunk.row_mask.dtype)))
        prog = _PROGRAMS.get(key)
        if prog is not None:
            _PROGRAMS.move_to_end(key)
            return prog
        names = chunk.names
        axis = self.axis
        fns = [node.batch_fn() for node in self.chain]

        def local(columns, mask):
            table = DeviceTable(columns, mask,
                                jnp.sum(mask, dtype=jnp.int32), names)
            for f in fns:
                table = f(table)
            return table.columns, table.row_mask

        col_specs = jax.tree_util.tree_map(lambda _: P(axis), chunk.columns)
        fn = jax.jit(shard_map(local, mesh=self.mesh,
                               in_specs=(col_specs, P(axis)),
                               out_specs=(P(axis), P(axis)), check=False))
        t0 = shuffle_telemetry.clock()
        prog = fn.lower(chunk.columns, chunk.row_mask).compile()
        shuffle_telemetry.note_transfer(
            "ici", "compile", shuffle_id=self.exchange.telemetry_sid,
            t0=t0, queue_depth=self.num_partitions)
        _PROGRAMS[key] = prog
        while len(_PROGRAMS) > _PROGRAMS_MAX:
            _PROGRAMS.popitem(last=False)
        return prog


def plan_mesh_stages(plan, conf=None):
    """Bottom-up pass rewriting ``exchange -> mesh-capable chain`` into
    TpuMeshStageExec. Runs AFTER whole-stage fusion (plan/overrides.py),
    so a fused TpuWholeStageExec sitting directly on an ICI exchange is
    absorbed whole; consecutive mesh-capable unary parents (e.g. a final
    aggregate, then the projection above it) keep extending the chain.
    Non-fusible consumers (sorts, joins, collect aggregates) stop the
    chain — that node consumes per-partition output at the unshard
    boundary exactly as before."""
    from ..plan.physical import PhysicalPlan

    if conf is not None and not conf.get(MESH_STAGE_ENABLED):
        return plan

    def rebuild(node: PhysicalPlan) -> PhysicalPlan:
        node = _with_children(node, [rebuild(c) for c in node.children])
        if _mesh_capable(node):
            ch = node.children[0]
            if isinstance(ch, TpuMeshStageExec):
                # at most one final aggregate per chain (a second one
                # would need a re-exchange between them anyway)
                if not (_is_final_agg(node) and ch._has_final_agg()):
                    return ch.absorb(node)
            elif isinstance(ch, TpuShuffleExchangeExec) \
                    and ch.num_partitions > 1:
                return TpuMeshStageExec(ch, [node])
        return node

    return rebuild(plan)
