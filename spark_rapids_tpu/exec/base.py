"""TpuExec — base class for device operators (reference: GpuExec.scala:208).

A TpuExec produces ``DeviceTable`` batches via ``execute_columnar``; the
row-oriented ``execute`` inherited from PhysicalPlan is implemented once here
as download (matching GpuColumnarToRowExec being the only row bridge).

Fusibility: operators whose per-batch work is a pure function
``DeviceTable -> DeviceTable`` return it from ``batch_fn()``; the planner's
whole-stage pass (exec/wholestage.py) composes adjacent fusible operators into
a single jitted XLA computation — the TPU analogue of Spark's whole-stage
codegen, and the replacement for cuDF's kernel-per-call execution.
"""
from __future__ import annotations

from typing import Callable, Iterator, Optional, Tuple

from ..columnar.device import DeviceTable
from ..columnar.host import HostTable
from ..parallel.pipeline import note_progress
from ..plan.physical import PhysicalPlan
from ..utils import metrics as M
from ..utils.metrics import CORE_NODE_METRICS, MetricRegistry

__all__ = ["TpuExec"]


class TpuExec(PhysicalPlan):
    """Columnar-only device operator.

    Every instance carries a ``MetricRegistry`` with the core metric set
    (rows / batches / opTime — reference: the ESSENTIAL GpuMetric tier,
    GpuExec.scala:44-60) pre-registered; subclasses declare additional
    always-present metrics via ``EXTRA_METRICS``. The event-log writer and
    the profiler snapshot this registry per (query, node) — the tier-1
    metric-lint test enforces that concrete operators actually update it.
    """

    #: extra metric names a subclass guarantees to register (e.g. sortTime)
    EXTRA_METRICS: tuple = ()

    def __init__(self):
        self.metrics = MetricRegistry()
        for name in CORE_NODE_METRICS + tuple(type(self).EXTRA_METRICS):
            self.metrics.metric(name)

    def account_batch(self, rows=None) -> None:
        """Fold one produced batch into the core metrics. ``rows`` must be a
        HOST int when provided — passing a device scalar would force a sync
        on the hot path, so operators only report rows where the count is
        already host-resident (the profiler counts exact rows externally).

        Also bumps the engine-wide progress marker the health watchdog
        compares across ticks (parallel/pipeline.py): without this,
        sequential execution (pipeline.enabled=false) never touches a
        prefetch queue or a pooled task and a long healthy drain would
        read as a stall."""
        self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
        if rows is not None:
            self.metrics.add(M.NUM_OUTPUT_ROWS, int(rows))
        note_progress()

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions if self.children else 1

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        raise NotImplementedError(type(self).__name__)

    def batch_fn(self) -> Optional[Callable[[DeviceTable], DeviceTable]]:
        """Pure per-batch device function, or None if not fusible."""
        return None

    def host_batch_fn(self) -> Optional[Callable[[HostTable], HostTable]]:
        """Host-engine equivalent of ``batch_fn`` (``HostTable ->
        HostTable``), or None when the operator has no batch-local host
        path. Non-None makes this operator recoverable at RUN time: the
        fallback boundary (exec/fallback.py with_host_fallback) re-runs
        a terminally-failed batch through it instead of failing the
        query. Operators whose semantics span batches (final aggregates,
        sorts, joins) return None — they quarantine on terminal failure
        but cannot fall back mid-stream."""
        return None

    @property
    def fusible(self) -> bool:
        """Whether per-batch application preserves semantics (operators that
        must see all batches — final aggregates, sorts — override to False)."""
        return self.batch_fn() is not None

    def plan_signature(self) -> str:
        """Canonical signature of this node's traced computation, used to key
        the global XLA compile cache (utils/compile_cache.py)."""
        child_schema = repr(self.children[0].schema) \
            if self.children and hasattr(self.children[0], "schema") else ""
        return f"{type(self).__name__}|{self.node_desc()}|{child_schema}"

    def execute(self, pidx: int) -> Iterator[HostTable]:
        for batch in self.execute_columnar(pidx):
            yield batch.to_host()

    def child_device_batches(self, pidx: int) -> Iterator[DeviceTable]:
        child = self.children[0]
        assert isinstance(child, TpuExec) or hasattr(child, "execute_columnar"), \
            f"device exec {type(self).__name__} over non-columnar child " \
            f"{type(child).__name__} (missing transition)"
        return child.execute_columnar(pidx)
