"""Runtime degradation layer: host fallback + operator quarantine.

The reference plugin's defining robustness property is that a query
never dies because the accelerated path couldn't run it — unsupported
operators fall back to CPU at plan time (plan/overrides.py). This
module extends that property to RUN time: when a device operator's
dispatch fails terminally — the OOM escalation ladder exhausted
(:class:`~..memory.retry.DeviceOomError`) or XLA raised a classified
non-retryable error (compile failure, ``INVALID_ARGUMENT``,
``INTERNAL``) — the batch is downloaded, re-executed through the host
engine's implementation of the same operator, and re-uploaded, so the
query degrades per-operator instead of failing per-query.

**Fallback boundary.** :func:`with_host_fallback(node, device_fn,
host_fn)` wraps an operator's per-batch dispatch. ``device_fn`` is the
full ladder-protected device path; ``host_fn`` is the operator's
host-engine batch function (``HostTable -> HostTable``; None for
operators with no batch-local host equivalent — those still quarantine
on terminal failure, they just re-raise). Every completed fallback
leaves a schema-v10 ``fallback`` event-log record (operator, reason,
bytes moved, wall) and bumps the recovery ledger's ``host_fallbacks``
key.

**Quarantine.** Repeated runtime fallbacks mean repeated pay-the-
failure-then-recover tax. The process-wide quarantine store — keyed by
(operator class, plan-signature hash, failure class), persisted as
``quarantine.json`` next to the compile-cache manifest — counts
fallbacks per key; once a key crosses
``spark.rapids.tpu.fallback.quarantine.threshold`` the planner's
quarantine pass (plan/overrides.py) trial-converts each candidate node
at tag time and routes matching operators to host AT PLAN TIME, with
``df.explain()`` showing the quarantine reason. Entries expire after
``quarantine.ttlSeconds`` and the store is bounded by
``quarantine.maxEntries`` (oldest evicted first).
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..conf import register_conf

__all__ = [
    "with_host_fallback",
    "quarantine_on_failure",
    "classify_failure",
    "configure_fallback",
    "persist_quarantine",
    "quarantine_entries",
    "quarantine_reason",
    "note_quarantine",
    "plan_quarantine_pass",
    "fallback_stats",
    "drain_fallback_records",
    "reset_fallback_state",
]

FALLBACK_ENABLED = register_conf(
    "spark.rapids.tpu.fallback.enabled",
    "Runtime host fallback: when a device operator's dispatch fails "
    "terminally (OOM ladder exhausted, or a non-retryable XLA error), "
    "download the batch, re-execute it through the host engine's "
    "implementation and re-upload — the query degrades per-operator "
    "instead of failing per-query. Each fallback writes a schema-v10 "
    "fallback event-log record.",
    True)

QUARANTINE_ENABLED = register_conf(
    "spark.rapids.tpu.fallback.quarantine.enabled",
    "Operator quarantine: count runtime fallbacks per (operator class, "
    "plan signature, failure class); past quarantine.threshold the "
    "planner routes that operator to host at PLAN time (explain shows "
    "the reason), so repeated traffic stops paying the "
    "fail-then-fallback tax. Persisted as quarantine.json next to the "
    "compile-cache manifest when the persistent cache is enabled.",
    True)

QUARANTINE_THRESHOLD = register_conf(
    "spark.rapids.tpu.fallback.quarantine.threshold",
    "Runtime fallbacks a (operator, plan-signature, failure-class) key "
    "must accumulate before the planner quarantines it to host.",
    3, checker=lambda v: None if v >= 1 else f"threshold must be >= 1, got {v}")

QUARANTINE_TTL = register_conf(
    "spark.rapids.tpu.fallback.quarantine.ttlSeconds",
    "Quarantine entry lifetime in seconds; expired entries are pruned "
    "on load and lookup, so a quarantined operator gets retried on the "
    "device after the TTL (the failure may have been environmental).",
    86400.0, conf_type=float,
    checker=lambda v: None if v > 0 else f"ttlSeconds must be > 0, got {v}")

QUARANTINE_MAX_ENTRIES = register_conf(
    "spark.rapids.tpu.fallback.quarantine.maxEntries",
    "Upper bound on quarantine-store entries; the oldest entries are "
    "evicted first (a runaway failure storm must not grow the store "
    "without bound).",
    256, checker=lambda v: None if v >= 1 else f"maxEntries must be >= 1, got {v}")

# sticky module config (configure_fallback; defaults match the conf
# registrations so bare unit tests get the production behavior)
_ENABLED = True
_Q_ENABLED = True
_Q_THRESHOLD = 3
_Q_TTL_S = 86400.0
_Q_MAX = 256


# ---------------------------------------------------------------------------
# failure classification: which terminal errors are fallback-eligible
# ---------------------------------------------------------------------------
#: (marker substring, failure class) — first match wins. INVALID_ARGUMENT
#: before INTERNAL: XLA nests both in compile diagnostics.
_XLA_CLASSES: Tuple[Tuple[str, str], ...] = (
    ("INVALID_ARGUMENT", "xla_invalid_argument"),
    ("UNIMPLEMENTED", "xla_unimplemented"),
    ("Compilation failure", "xla_compile"),
    ("compilation failure", "xla_compile"),
    ("INTERNAL", "xla_internal"),
)


def classify_failure(e: BaseException) -> Optional[str]:
    """The failure class when ``e`` is a terminal device failure the
    host-fallback boundary may recover from, else None (re-raise).

    A :class:`QueryTimeoutError` is never fallback-eligible — the query
    is being cancelled, not rescued. A retryable OOM normally never
    reaches the boundary raw (the ladder inside ``device_fn`` consumes
    it and terminates in DeviceOomError); if one does escape, it is
    still a recoverable device failure and classifies as ``oom``."""
    from ..utils.deadline import QueryTimeoutError
    if isinstance(e, QueryTimeoutError):
        return None
    from ..memory.retry import DeviceOomError, is_retryable_oom
    if isinstance(e, DeviceOomError):
        return "oom_exhausted"
    if not isinstance(e, RuntimeError):  # XlaRuntimeError subclasses this
        return None
    msg = str(e)
    for marker, cls in _XLA_CLASSES:
        if marker in msg:
            return cls
    if is_retryable_oom(e):
        return "oom"
    return None


# ---------------------------------------------------------------------------
# telemetry: counters (stats registry), drainable records (event log v10)
# ---------------------------------------------------------------------------
_STATS_LOCK = threading.Lock()
_COUNTS: Dict[str, Any] = {
    "host_fallbacks": 0,        # batches re-executed through the host engine
    "fallback_bytes_down": 0,   # D2H bytes moved for fallback inputs
    "fallback_bytes_up": 0,     # H2D bytes re-uploaded after host execution
    "fallback_failures": 0,     # terminal failures with no host path (re-raised)
    "quarantine_notes": 0,      # fallback events folded into the store
    "quarantine_plan_routes": 0,  # nodes the planner routed to host
}
_RECORDS: List[Dict[str, Any]] = []


def _bump(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _COUNTS[key] = _COUNTS.get(key, 0) + n


def fallback_stats() -> Dict[str, Any]:
    """Stats-registry source (/metrics gauges under the fallback_ prefix)."""
    with _STATS_LOCK:
        out: Dict[str, Any] = dict(_COUNTS)
    out["quarantine_entries"] = _QUARANTINE.size()
    return out


def drain_fallback_records() -> List[Dict[str, Any]]:
    """Pop completed-fallback records (the event-log writer turns each
    into one schema-v10 ``fallback`` record on the owning query)."""
    global _RECORDS
    with _STATS_LOCK:
        out, _RECORDS = _RECORDS, []
    return out


def reset_fallback_state() -> None:
    """Test hook: zero counters, drop pending records, clear the
    in-memory quarantine store (the on-disk store is untouched)."""
    global _RECORDS
    with _STATS_LOCK:
        for k in list(_COUNTS):
            _COUNTS[k] = 0
        _RECORDS = []
    _QUARANTINE.clear()


# ---------------------------------------------------------------------------
# quarantine store
# ---------------------------------------------------------------------------
def _sig_hash(plan_signature: str) -> str:
    return hashlib.sha256(plan_signature.encode("utf-8")).hexdigest()[:16]


class _QuarantineStore:
    """(operator class, plan-signature hash, failure class) -> fallback
    count + last-seen + reason. TTL-pruned on load and lookup, bounded
    by maxEntries (oldest last_ts evicted first)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[str, Any]] = {}

    @staticmethod
    def key(operator: str, sig_hash: str, failure_class: str) -> str:
        return f"{operator}|{sig_hash}|{failure_class}"

    def note(self, operator: str, sig_hash: str, failure_class: str,
             reason: str) -> int:
        """Fold one terminal device failure in; returns the new count."""
        now = time.time()
        k = self.key(operator, sig_hash, failure_class)
        with self._lock:
            ent = self._entries.get(k)
            if ent is None:
                ent = {"operator": operator, "sig_hash": sig_hash,
                       "failure_class": failure_class, "count": 0,
                       "first_ts": now, "last_ts": now, "reason": ""}
                self._entries[k] = ent
            ent["count"] += 1
            ent["last_ts"] = now
            ent["reason"] = reason[:200]
            self._evict_locked(now)
            return ent["count"]

    def check(self, operator: str, sig_hash: str) -> Optional[str]:
        """The quarantine reason when ANY failure class for (operator,
        sig) crossed the threshold and is not expired, else None."""
        now = time.time()
        with self._lock:
            self._prune_locked(now)
            for ent in self._entries.values():
                if (ent["operator"] == operator
                        and ent["sig_hash"] == sig_hash
                        and ent["count"] >= _Q_THRESHOLD):
                    return (f"{ent['count']} runtime "
                            f"{ent['failure_class']} failure(s), last: "
                            f"{ent['reason']}")
        return None

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._entries.values()]

    def size(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def _prune_locked(self, now: float) -> None:
        dead = [k for k, e in self._entries.items()
                if now - e["last_ts"] > _Q_TTL_S]
        for k in dead:
            del self._entries[k]

    def _evict_locked(self, now: float) -> None:
        self._prune_locked(now)
        while len(self._entries) > _Q_MAX:
            oldest = min(self._entries, key=lambda k: self._entries[k]["last_ts"])
            del self._entries[oldest]

    # -- persistence (the compile-cache manifest idiom: atomic replace on
    # write, corruption-tolerant on read) ------------------------------------
    def load(self, path: str) -> None:
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            entries = doc.get("entries", {})
            if not isinstance(entries, dict):
                return
        except (OSError, json.JSONDecodeError, AttributeError):
            return  # missing/corrupt store: start empty, never fail startup
        now = time.time()
        with self._lock:
            for k, e in entries.items():
                if not isinstance(e, dict) or "count" not in e:
                    continue
                self._entries[k] = e
            self._prune_locked(now)
            self._evict_locked(now)

    def persist(self, path: str) -> None:
        with self._lock:
            self._prune_locked(time.time())
            doc = {"version": 1, "entries": dict(self._entries)}
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass  # srtpu: net-ok(quarantine persistence is best-effort; a read-only cache dir must not fail session close)


_QUARANTINE = _QuarantineStore()


def _quarantine_path() -> Optional[str]:
    """quarantine.json beside the compile-cache manifest, or None when
    the persistent cache tier is disabled (store stays session-only)."""
    from ..utils.compile_cache import persistent_cache_dir
    tier = persistent_cache_dir()
    if not tier:
        return None
    return os.path.join(tier, "quarantine.json")


def configure_fallback(conf) -> None:
    """Apply spark.rapids.tpu.fallback.* (TpuSession chokepoint; sticky)
    and load the persisted quarantine store when quarantine is on."""
    global _ENABLED, _Q_ENABLED, _Q_THRESHOLD, _Q_TTL_S, _Q_MAX
    _ENABLED = bool(conf.get(FALLBACK_ENABLED))
    _Q_ENABLED = bool(conf.get(QUARANTINE_ENABLED))
    _Q_THRESHOLD = int(conf.get(QUARANTINE_THRESHOLD))
    _Q_TTL_S = float(conf.get(QUARANTINE_TTL))
    _Q_MAX = int(conf.get(QUARANTINE_MAX_ENTRIES))
    if _ENABLED and _Q_ENABLED:
        path = _quarantine_path()
        if path:
            _QUARANTINE.load(path)


def persist_quarantine() -> None:
    """Flush the quarantine store next to the compile-cache manifest
    (TpuSession.close); no-op when quarantine is off, empty, or the
    persistent cache tier is disabled."""
    if not (_ENABLED and _Q_ENABLED) or _QUARANTINE.size() == 0:
        return
    path = _quarantine_path()
    if path:
        _QUARANTINE.persist(path)


def quarantine_entries() -> List[Dict[str, Any]]:
    return _QUARANTINE.entries()


def quarantine_reason(operator: str, plan_signature: str) -> Optional[str]:
    """The quarantine reason for a (device operator class, plan
    signature), or None. Zero store lookups when quarantine is idle."""
    if not (_ENABLED and _Q_ENABLED) or _QUARANTINE.size() == 0:
        return None
    return _QUARANTINE.check(operator, _sig_hash(plan_signature))


def note_quarantine(operator: str, plan_signature: str, failure_class: str,
                    reason: str) -> None:
    if not (_ENABLED and _Q_ENABLED):
        return
    _QUARANTINE.note(operator, _sig_hash(plan_signature), failure_class,
                     reason)
    _bump("quarantine_notes")


# ---------------------------------------------------------------------------
# the fallback boundary
# ---------------------------------------------------------------------------
def _quarantine_targets(node) -> List[Tuple[str, str]]:
    """(operator class, plan signature) keys a terminal failure at
    ``node`` charges. A fused whole-stage charges every chain MEMBER:
    the planner's quarantine pass trial-converts individual operators
    (fusion happens after conversion), so member-level keys are what it
    can match — and XLA fuses the chain into one program, so any member
    may be the culprit."""
    chain = getattr(node, "chain", None)
    nodes = list(chain) if chain else [node]
    out = []
    for n in nodes:
        try:
            out.append((type(n).__name__, n.plan_signature()))
        except Exception:  # srtpu: degrade-ok(best-effort signature walk while HANDLING a device failure — the member just goes un-quarantined)
            continue
    return out


def with_host_fallback(node, device_fn: Callable[[Any], Any],
                       host_fn: Optional[Callable[[Any], Any]]):
    """Wrap one device operator's per-batch dispatch in the runtime
    degradation boundary.

    ``device_fn(batch)`` is the ladder-protected device path (typically
    a ``with_retry_split`` closure). ``host_fn(host_table)`` is the
    operator's host-engine equivalent, or None for operators without a
    batch-local host path — a terminal failure then still notes the
    quarantine store (so the NEXT session plans the operator on host)
    before re-raising. Returns ``device_fn`` unchanged when fallback is
    disabled (zero overhead on the hot path)."""
    if not _ENABLED:
        return device_fn

    def run(batch):
        try:
            return device_fn(batch)
        except Exception as e:
            cls = classify_failure(e)
            if cls is None:
                raise
            reason = f"{type(e).__name__}: {str(e)[:160]}"
            for op_name, sig in _quarantine_targets(node):
                note_quarantine(op_name, sig, cls, reason)
            if host_fn is None:
                _bump("fallback_failures")
                raise
            return _host_fallback(node, batch, host_fn, e, cls, reason)
    return run


class quarantine_on_failure:
    """Note-only degradation boundary for operators whose semantics span
    batches (final aggregates, sorts, joins): a terminal device failure
    inside the block cannot be recovered mid-stream, but it still feeds
    the quarantine store so the NEXT session plans the operator on host.
    The exception always propagates."""

    def __init__(self, node):
        self._node = node

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is None or not _ENABLED:
            return False
        cls = classify_failure(exc)
        if cls is not None:
            reason = f"{type(exc).__name__}: {str(exc)[:160]}"
            for op_name, sig in _quarantine_targets(self._node):
                note_quarantine(op_name, sig, cls, reason)
            _bump("fallback_failures")
        return False


def _host_fallback(node, batch, host_fn, exc, failure_class: str,
                   reason: str):
    """Download -> host execute -> re-upload, with the v10 record."""
    from ..columnar.device import DeviceTable
    t0 = time.perf_counter()
    try:
        ht = batch.to_host()
    except Exception:
        # a donated batch's buffers may be dead after the failed
        # dispatch; the ladder hands the resurrection hook back on its
        # structured error (memory/retry.py rematerialize)
        remat = getattr(exc, "rematerialize", None)
        if remat is None:
            raise exc
        ht = remat().to_host()
    bytes_down = int(ht.nbytes())
    out_host = host_fn(ht)
    out = DeviceTable.from_host(out_host)
    bytes_up = int(out.nbytes())
    wall = time.perf_counter() - t0
    op_name = type(node).__name__
    print(f"# device failure in {op_name} ({failure_class}): batch "
          f"re-executed on the host engine ({bytes_down} bytes down, "
          f"{bytes_up} bytes up)", file=sys.stderr)
    _bump("host_fallbacks")
    _bump("fallback_bytes_down", bytes_down)
    _bump("fallback_bytes_up", bytes_up)
    from ..utils import faults
    faults.note_recovery("host_fallbacks")
    rec = {"ts": time.time(), "operator": op_name,
           "context": str(getattr(node, "node_desc", lambda: "")())[:200],
           "failure_class": failure_class, "reason": reason,
           "rows": int(out_host.num_rows), "bytes_down": bytes_down,  # srtpu: sync-ok(out_host is a HostTable — num_rows is a host int, no device sync)
           "bytes_up": bytes_up, "wall_s": wall}
    with _STATS_LOCK:
        _RECORDS.append(rec)
    return out


# ---------------------------------------------------------------------------
# plan-time quarantine pass (called from plan/overrides.py after tag)
# ---------------------------------------------------------------------------
def plan_quarantine_pass(meta, conf) -> None:
    """Route quarantined operators to host at PLAN time. For every
    still-convertible node, trial-convert it (with its UNCONVERTED CPU
    children — conversion preserves child schemas, which is all the
    device plan_signature reads) to learn the device class + signature
    it WOULD run as, and mark it cannot_run when the store says that key
    has crossed the threshold. Zero work when the store is empty."""
    if not (_ENABLED and _Q_ENABLED) or _QUARANTINE.size() == 0:
        return
    for m in meta.walk():
        if not m.can_run or m.rule is None:
            continue
        try:
            dev = m.rule.convert(m.plan, list(m.plan.children), conf)
            sig = dev.plan_signature()
            op_name = type(dev).__name__
        except Exception:  # srtpu: degrade-ok(plan-time trial conversion — un-trial-convertible nodes simply are not quarantined)
            continue
        reason = quarantine_reason(op_name, sig)
        if reason:
            _bump("quarantine_plan_routes")
            m.cannot_run(f"quarantined: {reason}")
