"""Device hash aggregate (reference: aggregate.scala — GpuHashAggregateIterator
at :181, partial/final projections at :193-208, GpuHashAggregateExec at :1319).

TPU-first re-design: cuDF's hash-based groupby assumes dynamic output sizes;
XLA wants static shapes. We use a **sort-based groupby** entirely inside one
jitted computation:

    lexsort rows by (active, key nulls, key values)   -> equal keys adjacent
    boundary flags -> segment ids (cumsum)            -> static capacity
    jax.ops.segment_{sum,min,max} reductions          -> per-group states
    representative-row gather                         -> group key columns

Output capacity == input capacity (groups <= rows), so the whole kernel is one
static-shape XLA program that fuses with upstream project/filter. Grouped
float keys are normalized (-0.0 -> +0.0, NaNs equal) matching Spark's
NormalizeFloatingNumbers pass.

Per-batch partial aggregation emits one aggregated batch per input batch; the
exchange + final merge reduce across batches/partitions exactly like the
reference's merge passes.
"""
from __future__ import annotations

from typing import Callable, Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.device import DeviceColumn, DeviceTable
from ..conf import register_conf
from ..plan.physical import AggSpec, PhysicalPlan
from ..plan.schema import Field, Schema
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuHashAggregateExec"]

_BIG = np.int64(2**62)


def _minmax_identity(xp_dtype, for_min: bool):
    if xp_dtype == jnp.bool_:
        return True if for_min else False
    info = jnp.finfo(xp_dtype) if jnp.issubdtype(xp_dtype, jnp.floating) \
        else jnp.iinfo(xp_dtype)
    return info.max if for_min else info.min


def _normalize_float_key(v: jax.Array) -> jax.Array:
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = jnp.where(v == 0, jnp.zeros_like(v), v)
    return v


def _key_code_words(kc) -> "Tuple[List[jax.Array], Optional[jax.Array]]":
    """Column -> (1-D surrogate sort/equality words most-significant first,
    optional NaN flag).

    Strings/binary pack 8 bytes per uint64 word big-endian, plus the length
    as the final tiebreak word — zero padding would otherwise conflate
    "ab" with "ab\\x00". Word-wise unsigned order == lexicographic byte
    order, so device groupby/sort accept string keys of ANY width without a
    dictionary pass (the reference relies on cudf's native string keys;
    SURVEY §7 hard part (b))."""
    from ..columnar.device import pack_string_key_words
    if isinstance(kc.dtype, (dt.StringType, dt.BinaryType)):
        return pack_string_key_words(kc.data, kc.lengths), None
    if isinstance(kc.dtype, dt.StructType):
        # struct keys: concatenate each field's surrogate words, folding
        # the per-field null and NaN flags in as words of their own —
        # equality over the flattened word list == struct equality
        # (reference: struct group-by keys, TypeChecks.scala:166 nesting)
        words: "List[jax.Array]" = []
        for child in kc.children:
            words.append(jnp.logical_not(child.validity))
            cw, nan = _key_code_words(child)
            # zero the value words of null fields so all null-field rows
            # group together regardless of the plane's stale contents
            words.extend(jnp.where(child.validity, w,
                                   jnp.zeros_like(w)) for w in cw)
            if nan is not None:
                words.append(jnp.logical_and(nan, child.validity))
        return words, None
    if dt.is_d128(kc.dtype):
        from ..expr.decimal128 import d128_key_words
        return d128_key_words(kc.data), None
    v = _normalize_float_key(kc.data)
    if jnp.issubdtype(v.dtype, jnp.floating):
        nan = jnp.isnan(v)
        return [jnp.where(nan, jnp.full_like(v, jnp.inf), v)], nan
    return [v], None


def _key_small_fields(kc):
    """Column -> (value words, [(small_field, nbits), ...]) where the small
    fields (string lengths, null/NaN flags) are equality-relevant but only
    need a few bits each — the caller bit-packs them into shared meta
    words so the lexsort runs over FAR fewer operands (sort cost scales
    with operand count; Q1's 2 string keys drop from 7 operands to 3).
    Value words are zeroed on null rows so null-key groups can't split on
    stale plane contents."""
    from ..columnar.device import pack_string_key_words
    valid = kc.validity
    smalls = [(jnp.logical_not(valid).astype(jnp.uint64), 1)]

    def z(w):
        return jnp.where(valid, w, jnp.zeros_like(w))

    if isinstance(kc.dtype, (dt.StringType, dt.BinaryType)):
        w = kc.data.shape[1]
        words = [z(x) for x in
                 pack_string_key_words(kc.data, kc.lengths)[:-1]]
        lbits = max(int(w).bit_length(), 1)
        smalls.append((z(kc.lengths.astype(jnp.uint64)), lbits))
        return words, smalls
    words, nan = _key_code_words(kc)
    words = [z(x) for x in words]
    if nan is not None:
        smalls.append((jnp.logical_and(nan, valid).astype(jnp.uint64), 1))
    return words, smalls


def _pack_meta_words(bit_fields) -> "List[jax.Array]":
    """[(u64 field, nbits), ...] -> u64 words, most-significant field
    first; a new word starts when 64 bits fill up. Equality over the words
    == equality over the fields, and the FIRST field occupies the top bits
    of word 0 (so making it the not-active flag keeps active rows sorted
    first)."""
    words: "List[jax.Array]" = []
    acc = None
    used = 0
    for field, nbits in bit_fields:
        if acc is None or used + nbits > 64:
            if acc is not None:
                words.append(acc << jnp.uint64(64 - used))
            acc = field
            used = nbits
        else:
            acc = (acc << jnp.uint64(nbits)) | field
            used += nbits
    if acc is not None:
        words.append(acc << jnp.uint64(64 - used))
    return words


def _keys_equal_prev(sv: jax.Array) -> jax.Array:
    """eq[i] = sv[i] == sv[i-1] (with NaN==NaN); eq[0] = False."""
    prev = jnp.roll(sv, 1, axis=0)
    eq = sv == prev
    if jnp.issubdtype(sv.dtype, jnp.floating):
        eq = jnp.logical_or(eq, jnp.logical_and(jnp.isnan(sv), jnp.isnan(prev)))
    return eq.at[0].set(False) if eq.ndim == 1 else eq


def _seg_sum(x, gid, cap):
    """segment_sum that lowers to a plain reduce when there is one segment
    (a scatter-add over a single bucket is a serial loop on XLA:CPU and
    wasted scatter traffic everywhere; the ungrouped aggregate hits this
    on every batch)."""
    if cap == 1:
        return jnp.sum(x, axis=0, keepdims=True)
    return jax.ops.segment_sum(x, gid, num_segments=cap)


def _seg_min(x, gid, cap):
    if cap == 1:
        return jnp.min(x, axis=0, keepdims=True)
    return jax.ops.segment_min(x, gid, num_segments=cap)


def _seg_max(x, gid, cap):
    if cap == 1:
        return jnp.max(x, axis=0, keepdims=True)
    return jax.ops.segment_max(x, gid, num_segments=cap)


def _reduce_segment(op: str, vals: jax.Array, contrib: jax.Array,
                    gid: jax.Array, cap: int, pos: jax.Array,
                    out_dt: dt.DataType) -> Tuple[jax.Array, jax.Array]:
    """Per-group reduction -> (values[cap], validity[cap])."""
    out_dtype = jnp.dtype(np.bool_ if isinstance(out_dt, dt.BooleanType)
                          else out_dt.np_dtype())
    counts = _seg_sum(contrib.astype(jnp.int64), gid, cap)
    has = counts > 0
    if op == "count":
        return counts.astype(out_dtype), jnp.ones(cap, dtype=bool)
    if dt.is_d128(out_dt):
        from ..expr.decimal128 import d128_from_i64, d128_segment_sum
        if op == "sum":
            limbs = vals if vals.ndim == 2 else d128_from_i64(vals)
            out, over = d128_segment_sum(limbs, contrib, gid, cap,
                                         out_dt.precision)
            return out, jnp.logical_and(has, jnp.logical_not(over))
        if op in ("first", "last"):
            p = jnp.where(contrib, -pos if op == "last" else pos,
                          jnp.full_like(pos, _BIG))
            best = _seg_min(p, gid, cap)
            idx = -best if op == "last" else best
            idx = jnp.clip(idx, 0, vals.shape[0] - 1).astype(jnp.int32)
            return jnp.take(vals, idx, axis=0), has
        raise TypeError(f"decimal128 aggregate op {op!r} is host-only")
    if op in ("sum", "sumsq"):
        x = vals.astype(out_dtype)
        if op == "sumsq":
            x = x * x
        x = jnp.where(contrib, x, jnp.zeros_like(x))
        return _seg_sum(x, gid, cap), has
    if op == "min" or op == "max":
        ident = _minmax_identity(vals.dtype, op == "min")
        x = vals
        isfloat = jnp.issubdtype(vals.dtype, jnp.floating)
        if isfloat:
            # Spark total order: NaN is the largest double
            nan = jnp.isnan(vals)
            x = jnp.where(nan, jnp.full_like(vals, jnp.inf if op == "min"
                                             else -jnp.inf), vals)
        x = jnp.where(contrib, x, jnp.full_like(x, ident))
        red = _seg_min if op == "min" else _seg_max
        out = red(x, gid, cap)
        if isfloat:
            nan_contrib = jnp.logical_and(contrib, nan)
            nan_counts = _seg_sum(nan_contrib.astype(jnp.int32), gid, cap)
            if op == "min":
                nonnan = _seg_sum(
                    jnp.logical_and(contrib, jnp.logical_not(nan)).astype(jnp.int32),
                    gid, cap)
                out = jnp.where(jnp.logical_and(has, nonnan == 0),
                                jnp.full_like(out, jnp.nan), out)
            else:
                out = jnp.where(nan_counts > 0, jnp.full_like(out, jnp.nan), out)
        return out.astype(out_dtype), has
    if op in ("first", "last"):
        p = jnp.where(contrib, -pos if op == "last" else pos,
                      jnp.full_like(pos, _BIG))
        best = _seg_min(p, gid, cap)
        idx = -best if op == "last" else best
        idx = jnp.clip(idx, 0, vals.shape[0] - 1).astype(jnp.int32)
        return jnp.take(vals, idx, axis=0).astype(out_dtype), has
    if op == "any":
        x = jnp.where(contrib, vals, jnp.zeros_like(vals))
        return _seg_max(x.astype(jnp.int32), gid, cap).astype(bool), has
    if op == "all":
        x = jnp.where(contrib, vals, jnp.ones_like(vals))
        return _seg_min(x.astype(jnp.int32), gid, cap).astype(bool), has
    raise ValueError(op)


_COLLECT_OPS = frozenset(
    {"collect_list", "collect_set", "merge_lists", "merge_sets"})
_BIG32 = np.int32(2**31 - 1)


def _word_bits_u32(w: jax.Array) -> jax.Array:
    """Equality word -> u32 hash contribution (bit-exact per value)."""
    if jnp.issubdtype(w.dtype, jnp.floating):
        if w.dtype == jnp.float32:
            u = jax.lax.bitcast_convert_type(w, jnp.uint32)
            return u
        u = jax.lax.bitcast_convert_type(w.astype(jnp.float64), jnp.uint64)
    elif w.dtype == jnp.bool_:
        return w.astype(jnp.uint32)
    else:
        u = w.astype(jnp.uint64)
    return (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32) \
        ^ (u >> jnp.uint64(32)).astype(jnp.uint32)


def _hash_group_ids(table: "DeviceTable", key_names: List[str]):
    """SORT-FREE exact grouping: hash keys into row-count buckets, resolve
    each bucket's minimum-index candidate's whole key-class per round, and
    rehash unresolved rows until none remain (a lax.while_loop — compile
    cost is one body regardless of rounds; expected 2-4 rounds).

    Returns the same contract as _sorted_group_ids but with the IDENTITY
    order: every consumer (segment reductions, representative gather,
    collect ranks) is order-agnostic, so the GROUPING contributes no
    lax.sort to the program (collect_set/merge_sets dedup still sorts
    elements) — the escape hatch for toolchains where sort compilation is
    pathological (see spark.rapids.tpu.groupby.strategy), and the closest
    analogue of the reference's cuDF HASH groupby."""
    from ..shuffle.manager import _fmix_device
    cap = table.capacity
    active = table.row_mask
    key_cols = [table.column(k) for k in key_names]
    bit_fields = []
    value_words: List[jax.Array] = []
    for kc in key_cols:
        words, smalls = _key_small_fields(kc)
        value_words.extend(words)
        bit_fields.extend(smalls)
    words = value_words + _pack_meta_words(bit_fields)

    h = jnp.zeros(cap, dtype=jnp.uint32)
    for i, w in enumerate(words):
        h = h ^ _fmix_device(_word_bits_u32(w) ^ jnp.uint32(i + 1))
        h = h * jnp.uint32(5) + jnp.uint32(0xE6546B64)

    iota = jnp.arange(cap, dtype=jnp.int32)

    def cond(state):
        r, winner, unresolved = state
        return jnp.logical_and(jnp.any(unresolved), r < cap)

    def body(state):
        r, winner, unresolved = state
        hr = _fmix_device(h ^ (r.astype(jnp.uint32)
                               * jnp.uint32(2654435761)))
        bucket = (hr % jnp.uint32(cap)).astype(jnp.int32)
        cand_src = jnp.where(unresolved, iota, cap)
        cand = jax.ops.segment_min(cand_src, bucket, num_segments=cap)
        w = jnp.take(cand, bucket)
        w_safe = jnp.clip(w, 0, cap - 1)
        eq = jnp.logical_and(unresolved, w < cap)
        for word in words:
            eq = jnp.logical_and(
                eq, word == jnp.take(word, w_safe, axis=0))
        winner = jnp.where(eq, w_safe, winner)
        unresolved = jnp.logical_and(unresolved, jnp.logical_not(eq))
        return r + 1, winner, unresolved

    _, winner, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), iota, active))
    is_rep = jnp.logical_and(active, winner == iota)
    rep_rank = jnp.cumsum(is_rep.astype(jnp.int32)) - 1
    gid = jnp.clip(jnp.take(rep_rank, winner), 0, cap - 1)
    num_groups = jnp.sum(is_rep.astype(jnp.int32))
    boundary = is_rep
    return iota, active, gid, boundary, num_groups


GROUPBY_STRATEGY = register_conf(
    "spark.rapids.tpu.groupby.strategy",
    "Device group-by algorithm: 'sort' (lexsort + boundaries — the "
    "static-shape default on CPU), 'hash' (bucket-resolve rounds; no "
    "lax.sort in the GROUPING — collect_set dedup still sorts), or "
    "'auto' (= hash: faster on every measured backend, and immune to "
    "the pathologically slow sort compilation seen on some TPU "
    "toolchains; reference analogue: cuDF hash groupby vs sort "
    "groupby).", "auto",
    checker=lambda v: None if str(v).lower() in ("auto", "sort", "hash")
    else "must be auto|sort|hash")


def _resolve_groupby_strategy() -> str:
    """sort|hash from the active session conf; AUTO = hash (measured
    faster than the lexsort path on CPU — TPC-H Q1 2.55x vs 0.82x — and
    sort compilation is the pathological op for some TPU toolchains)."""
    from ..session import TpuSession
    sess = TpuSession._active
    v = "auto"
    if sess is not None and GROUPBY_STRATEGY is not None:
        v = str(sess.conf.get(GROUPBY_STRATEGY)).lower()
    return "hash" if v == "auto" else v


def _sorted_group_ids(table: "DeviceTable", key_names: List[str]):
    """Lexsort rows so equal keys are adjacent (active first) and label
    groups. -> (order, active_s, gid, boundary, num_groups).

    The per-key null/NaN/length flags bit-pack into shared "meta" uint64
    words (the not-active flag in the top bits of meta word 0, so active
    rows sort first) — only group EQUALITY must survive the packing, not
    any particular inter-group order, so the lexsort runs over the value
    words + one or two meta words instead of ~3 operands per key."""
    cap = table.capacity
    active = table.row_mask
    key_cols = [table.column(k) for k in key_names]
    bit_fields = [(jnp.logical_not(active).astype(jnp.uint64), 1)]
    value_words: List[jax.Array] = []
    for kc in key_cols:
        words, smalls = _key_small_fields(kc)
        value_words.extend(words)
        bit_fields.extend(smalls)
    meta = _pack_meta_words(bit_fields)
    # lexsort: LAST entry is most significant -> meta[0] (active bit) is
    # primary, remaining meta words next, value words after
    sort_keys = list(reversed(value_words)) + list(reversed(meta))
    order = jnp.lexsort(tuple(sort_keys))
    active_s = jnp.take(active, order)
    same = jnp.ones(cap, dtype=bool)
    for wd in value_words + meta:
        same = jnp.logical_and(same,
                               _keys_equal_prev(jnp.take(wd, order)))
    boundary = jnp.logical_and(jnp.logical_not(same), active_s)
    boundary = boundary.at[0].set(active_s[0])
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    gid = jnp.clip(gid, 0, cap - 1)
    num_groups = jnp.sum(boundary.astype(jnp.int32))
    return order, active_s, gid, boundary, num_groups


def _first_occurrence_in_group(sv: jax.Array, gid: jax.Array,
                               contrib: jax.Array) -> jax.Array:
    """True for the first contributing row of each (group, value) pair —
    collect_set dedup that preserves first-insertion row order."""
    v = sv
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.int32)
    if jnp.issubdtype(v.dtype, jnp.floating):
        # total order for grouping equal values adjacently
        v = _normalize_float_key(v)
    order2 = jnp.lexsort((v, gid, jnp.logical_not(contrib)))
    v2 = jnp.take(v, order2)
    g2 = jnp.take(gid, order2)
    c2 = jnp.take(contrib, order2)
    dup = jnp.logical_and(v2 == jnp.roll(v2, 1), g2 == jnp.roll(g2, 1))
    dup = jnp.logical_and(dup, jnp.logical_and(c2, jnp.roll(c2, 1)))
    dup = dup.at[0].set(False)
    first2 = jnp.logical_and(c2, jnp.logical_not(dup))
    return jnp.zeros_like(contrib).at[order2].set(first2)


def _row_dedup_sorted(mat: jax.Array, lens: jax.Array):
    """Per-row: sort elements, drop adjacent duplicates, compact left
    (merge_sets — partial states may repeat values across map sides).

    Sorting happens on an integer surrogate key (floats via the monotone
    bit trick, NaN greatest) with an int64-max pad sentinel, and the
    ORIGINAL values are gathered by that order — so NaN dedups against
    NaN, no pad value can leak into the data, and bool/float dtypes come
    back unchanged."""
    W = mat.shape[1]
    j = jnp.arange(W, dtype=jnp.int32)
    in_len = j[None, :] < lens[:, None]
    is_float = jnp.issubdtype(mat.dtype, jnp.floating)
    if is_float:
        # monotone bit surrogate (IEEE trick): order-preserving injection
        # into uint64, with -0.0 normalized so it dedups against +0.0
        v = jnp.where(mat == 0, jnp.zeros_like(mat), mat)
        if mat.dtype == jnp.float32:
            u = jax.lax.bitcast_convert_type(v, jnp.uint32)
            top = jnp.uint32(1) << jnp.uint32(31)
        else:
            u = jax.lax.bitcast_convert_type(v, jnp.uint64)
            top = jnp.uint64(1) << jnp.uint64(63)
        key = jnp.where((u & top) != 0, ~u, u | top).astype(jnp.uint64)
    elif mat.dtype == jnp.bool_:
        key = mat.astype(jnp.int64)
    else:
        key = mat.astype(jnp.int64)
    # exact pads-last ordering: stable sort by key, then stable sort by
    # the pad flag — composition = lexsort((key, is_pad)) per row, with
    # no sentinel that could collide with a real extreme value
    pad_flag = jnp.logical_not(in_len)
    order1 = jnp.argsort(key, axis=1, stable=True)
    p1 = jnp.take_along_axis(pad_flag, order1, axis=1)
    order2 = jnp.argsort(p1, axis=1, stable=True)
    order = jnp.take_along_axis(order1, order2, axis=1)
    sk = jnp.take_along_axis(key, order, axis=1)
    spad = jnp.take_along_axis(pad_flag, order, axis=1)
    sv = jnp.take_along_axis(mat, order, axis=1)
    dup = jnp.logical_and(sk == jnp.roll(sk, 1, axis=1),
                          jnp.logical_not(
                              jnp.logical_or(spad,
                                             jnp.roll(spad, 1, axis=1))))
    if is_float:
        # `==` dedup semantics (the host engine's): NaN never equals NaN,
        # so same-bit NaNs must NOT merge at the merge pass either
        nan_s = jnp.isnan(sv)
        dup = jnp.logical_and(dup, jnp.logical_not(
            jnp.logical_or(nan_s, jnp.roll(nan_s, 1, axis=1))))
    dup = dup.at[:, 0].set(False)
    # pads sort strictly last, so the first ``lens`` slots are the reals
    keep = jnp.logical_and(j[None, :] < lens[:, None],
                           jnp.logical_not(dup))
    order2 = jnp.argsort(jnp.logical_not(keep), axis=1, stable=True)
    out = jnp.take_along_axis(sv, order2, axis=1)
    newlens = keep.sum(axis=1).astype(jnp.int32)
    out = jnp.where(j[None, :] < newlens[:, None], out,
                    jnp.zeros((), out.dtype))
    return out, newlens


def _collect_segment(op: str, sv: jax.Array, slen, contrib: jax.Array,
                     gid: jax.Array, cap: int, width: int):
    """Per-group collect into a (cap, width) list matrix + lengths.

    Update ops scatter scalar rows by within-group rank; merge ops scatter
    whole element runs by within-group element offset. Callers size
    ``width`` from a host-synced size pass (the dynamic-width escape
    hatch; reference: cuDF list columns size their child dynamically)."""
    if op == "collect_set":
        contrib = jnp.logical_and(
            contrib, _first_occurrence_in_group(sv, gid, contrib))
        op = "collect_list"
    if op == "collect_list":
        c32 = contrib.astype(jnp.int32)
        prefix = jnp.cumsum(c32) - c32      # contributing rows before this
        base = jax.ops.segment_min(
            jnp.where(contrib, prefix, _BIG32), gid, num_segments=cap)
        within = jnp.where(contrib, prefix - base[gid], 0)
        r_idx = jnp.where(contrib, gid, cap)        # trash row for skips
        c_idx = jnp.where(contrib, jnp.clip(within, 0, width), width)
        out = jnp.zeros((cap + 1, width + 1), sv.dtype)
        out = out.at[r_idx, c_idx].set(sv)
        lens = jax.ops.segment_sum(c32, gid, num_segments=cap) \
            .astype(jnp.int32)
        return out[:cap, :width], jnp.minimum(lens, width)
    # merge_lists / merge_sets: sv is (n, Win) + per-row lengths
    lens_eff = jnp.where(contrib, slen.astype(jnp.int32), 0)
    prefix = jnp.cumsum(lens_eff) - lens_eff
    base = jax.ops.segment_min(
        jnp.where(contrib, prefix, _BIG32), gid, num_segments=cap)
    elem_base = prefix - base[gid]
    win = sv.shape[1]
    j = jnp.arange(win, dtype=jnp.int32)[None, :]
    valid_e = j < lens_eff[:, None]
    r_idx = jnp.where(valid_e, gid[:, None], cap)
    c_idx = jnp.where(valid_e,
                      jnp.clip(elem_base[:, None] + j, 0, width), width)
    out = jnp.zeros((cap + 1, width + 1), sv.dtype)
    out = out.at[r_idx, c_idx].set(sv)
    lens = jnp.minimum(
        jax.ops.segment_sum(lens_eff, gid, num_segments=cap), width) \
        .astype(jnp.int32)
    out = out[:cap, :width]
    if op == "merge_sets":
        return _row_dedup_sorted(out, lens)
    return out, lens


class TpuHashAggregateExec(TpuExec):
    """Same pre-projected input contract as CpuHashAggregateExec."""

    EXTRA_METRICS = (M.AGG_TIME,)

    def __init__(self, child: PhysicalPlan, key_names: List[str],
                 specs: List[AggSpec], mode: str):
        super().__init__()
        assert mode in ("partial", "final")
        self.child = child
        self.children = (child,)
        self.key_names = list(key_names)
        self.specs = specs
        self.mode = mode
        key_fields = [child.schema.field(k) for k in key_names]
        state_fields = [Field(n, d, nb) for s in specs
                        for (n, d, nb) in s.state_fields]
        self.schema = Schema(key_fields + state_fields)

    @property
    def fusible(self) -> bool:
        # partial mode may emit one state-batch per input batch (downstream
        # merge reduces them); final mode must merge across batches itself.
        # collect_* needs a per-batch host-synced width pass, so it cannot
        # join a whole-stage program
        return self.mode == "partial" and not self._has_collect()

    def _columns_ops(self) -> List[Tuple[str, str, str, dt.DataType]]:
        out = []
        for s in self.specs:
            ops = s.update_ops if self.mode == "partial" else s.merge_ops
            in_cols = s.input_cols if self.mode == "partial" \
                else [n for (n, _, _) in s.state_fields]
            for (in_col, op, (out_col, out_dt, _)) in zip(in_cols, ops, s.state_fields):
                out.append((in_col, op, out_col, out_dt))
        return out

    def _has_collect(self) -> bool:
        return any(op in _COLLECT_OPS
                   for (_, op, _, _) in self._columns_ops())

    def host_batch_fn(self):
        # host-engine partial aggregation over one downloaded batch — the
        # per-table body of CpuHashAggregateExec.execute. Only the
        # fusible (partial, no collect_*) form gets a fallback path: its
        # per-batch state outputs merge downstream exactly like the
        # device partial's would
        if not self.fusible:
            return None
        key_names = list(self.key_names)
        cols_ops = self._columns_ops()
        out_names = list(self.schema.names)
        schema = self.schema
        child_schema = self.child.schema

        def fn(table):
            import numpy as np
            from ..columnar.host import HostColumn, HostTable
            from ..plan.host_groupby import group_codes, host_group_reduce
            from ..plan.physical import _empty_values
            if table.num_rows == 0:
                if key_names:
                    return HostTable(
                        out_names,
                        [HostColumn(f.dtype, _empty_values(f.dtype))
                         for f in schema])
                # grand aggregate over an empty batch: one null/zero row
                table = HostTable(
                    [c for c, _, _, _ in cols_ops],
                    [HostColumn(child_schema.field(c).dtype,
                                _empty_values(child_schema.field(c).dtype))
                     for c, _, _, _ in cols_ops])
            gid, ngroups, rep = group_codes(table, key_names)
            out_cols = []
            for k in key_names:
                out_cols.append(table.column(k).take(rep))
            for in_col, op, out_col, out_dt in cols_ops:
                vals, validity = host_group_reduce(
                    op, table.column(in_col), gid, ngroups, out_dt)
                if not isinstance(out_dt, (dt.StringType, dt.BinaryType,
                                           dt.ArrayType, dt.StructType,
                                           dt.MapType)) \
                        and not dt.is_d128(out_dt) \
                        and vals.dtype != out_dt.np_dtype():
                    with np.errstate(invalid="ignore"):
                        vals = vals.astype(out_dt.np_dtype())
                if validity is not None and validity.all():
                    validity = None
                out_cols.append(HostColumn(out_dt, vals, validity))
            return HostTable(out_names, out_cols)
        return fn

    # -- kernels -------------------------------------------------------------
    def batch_fn(self, list_width: int = 0
                 ) -> Callable[[DeviceTable], DeviceTable]:
        cols_ops = self._columns_ops()
        key_names = self.key_names
        out_names = tuple(self.schema.names)

        def ungrouped(table: DeviceTable) -> DeviceTable:
            cap_out = 8  # tiny fixed capacity for the single state row
            out_cols = []
            pos = jnp.arange(table.capacity, dtype=jnp.int64)
            for in_col, op, out_col, out_dt in cols_ops:
                col = table.column(in_col)
                contrib = table.row_mask if col.all_valid \
                    else jnp.logical_and(col.validity, table.row_mask)
                gid = jnp.zeros(table.capacity, dtype=jnp.int32)
                if op in _COLLECT_OPS:
                    data1, lens1 = _collect_segment(
                        op, col.data, col.lengths, contrib, gid, 1,
                        list_width)
                    data = jnp.zeros((cap_out, list_width), data1.dtype) \
                        .at[0].set(data1[0])
                    lens = jnp.zeros(cap_out, jnp.int32).at[0].set(lens1[0])
                    validity = jnp.zeros(cap_out, bool).at[0].set(True)
                    out_cols.append(
                        DeviceColumn(data, validity, out_dt, lens))
                    continue
                vals1, has1 = _reduce_segment(
                    op, col.data, contrib, gid, 1, pos, out_dt)
                vals = jnp.zeros((cap_out,) + vals1.shape[1:],
                                 dtype=vals1.dtype).at[0].set(vals1[0])
                validity = jnp.zeros(cap_out, dtype=bool).at[0].set(has1[0])
                out_cols.append(DeviceColumn(vals, validity, out_dt, None))
            iota = jnp.arange(cap_out, dtype=jnp.int32)
            return DeviceTable(tuple(out_cols), iota < 1,
                               jnp.asarray(1, jnp.int32), out_names)

        # collect ops need CONTIGUOUS groups: their within-group ranks
        # come from global prefix sums, which only equal within-group
        # ranks when equal keys are adjacent — so collects force the
        # sorted grouping regardless of strategy
        has_collect = any(op in _COLLECT_OPS for (_, op, _, _) in cols_ops)
        group_ids = _hash_group_ids \
            if (_resolve_groupby_strategy() == "hash" and not has_collect) \
            else _sorted_group_ids

        def grouped(table: DeviceTable) -> DeviceTable:
            cap = table.capacity
            order, active_s, gid, boundary, num_groups = \
                group_ids(table, key_names)
            key_cols = [table.column(k) for k in key_names]
            pos = jnp.arange(cap, dtype=jnp.int64)
            # ---- representative sorted-row per group for key output
            rep_src = jnp.where(active_s, pos, jnp.full_like(pos, _BIG))
            rep = jnp.clip(jax.ops.segment_min(rep_src, gid, num_segments=cap),
                           0, cap - 1).astype(jnp.int32)
            out_cols: List[DeviceColumn] = []
            iota = jnp.arange(cap, dtype=jnp.int32)
            group_mask = iota < num_groups
            for kc in key_cols:
                # representative-row gather; DeviceColumn.gather recurses
                # into struct children and the element-validity plane
                g = kc.gather(order, keep_all_valid=True) \
                    .gather(rep, keep_all_valid=True)
                out_cols.append(g.with_validity(
                    jnp.logical_and(g.validity, group_mask)))
            # ---- state reductions
            for in_col, op, out_col, out_dt in cols_ops:
                col = table.column(in_col)
                sv = jnp.take(col.data, order, axis=0)
                contrib = active_s if col.all_valid else jnp.logical_and(
                    jnp.take(col.validity, order), active_s)
                if op in _COLLECT_OPS:
                    slen = None if col.lengths is None \
                        else jnp.take(col.lengths, order)
                    data, lens = _collect_segment(
                        op, sv, slen, contrib, gid, cap, list_width)
                    lens = jnp.where(group_mask, lens, 0)
                    out_cols.append(
                        DeviceColumn(data, group_mask, out_dt, lens))
                    continue
                vals, has = _reduce_segment(op, sv, contrib, gid, cap, pos,
                                            out_dt)
                validity = jnp.logical_and(has, group_mask) if op != "count" \
                    else group_mask
                out_cols.append(DeviceColumn(vals, validity, out_dt, None))
            return DeviceTable(tuple(out_cols), group_mask,
                               num_groups.astype(jnp.int32), out_names)

        return ungrouped if not key_names else grouped

    def plan_signature(self) -> str:
        child_schema = repr(self.children[0].schema) \
            if hasattr(self.children[0], "schema") else ""
        return (f"HashAgg|{self.mode}|{self.key_names}|"
                f"{self._columns_ops()!r}|{child_schema}")

    def _canon_exec(self) -> Tuple["TpuHashAggregateExec", str]:
        """Schema-erased clone + cache key: column names become positional
        (c0..cN in, o0..oM out) so structurally identical aggregations in
        DIFFERENT queries share one compiled program. Shapes/dtypes that
        remain distinct retrace inside the shared jax.jit wrapper — the key
        only needs what the *builder closure* captures (mode, positions,
        ops, output dtypes)."""
        child_fields = list(self.child.schema.fields)
        pos = {f.name: i for i, f in enumerate(child_fields)}
        ops = self._columns_ops()
        nk = len(self.key_names)
        canon_ops = [(f"c{pos[in_col]}", op, f"o{nk + j}", out_dt)
                     for j, (in_col, op, _, out_dt) in enumerate(ops)]
        clone = TpuHashAggregateExec.__new__(TpuHashAggregateExec)
        TpuExec.__init__(clone)
        clone.mode = self.mode
        clone.key_names = [f"c{pos[k]}" for k in self.key_names]
        clone.specs = []
        clone._columns_ops = lambda: canon_ops      # instance-level override
        clone.schema = Schema([Field(f"o{j}", f.dtype, f.nullable)
                               for j, f in enumerate(self.schema.fields)])
        clone.child = _SchemaOnly(Schema(
            [Field(f"c{i}", f.dtype, f.nullable)
             for i, f in enumerate(child_fields)]))
        clone.children = (clone.child,)
        has_collect = any(op in _COLLECT_OPS for (_, op, _, _) in ops)
        eff_strategy = "sort" if has_collect \
            else _resolve_groupby_strategy()
        key = (f"HashAggC|{self.mode}|k{[pos[k] for k in self.key_names]}|"
               f"{[(pos[i], op, repr(odt)) for (i, op, _, odt) in ops]}|"
               f"g={eff_strategy}")
        return clone, key

    def _sizes_fn(self) -> Callable[[DeviceTable], jax.Array]:
        """Max list width any collect op needs for one batch (the host
        syncs this one int to pick a bucketed static width)."""
        cols_ops = [co for co in self._columns_ops() if co[1] in _COLLECT_OPS]
        key_names = self.key_names

        # sizes exist only for collect ops, which force sorted grouping
        group_ids = _sorted_group_ids

        def sizes(table: DeviceTable) -> jax.Array:
            cap = table.capacity
            if key_names:
                order, active_s, gid, _, _ = group_ids(
                    table, key_names)
            else:
                order = jnp.arange(cap, dtype=jnp.int32)
                active_s = table.row_mask
                gid = jnp.zeros(cap, dtype=jnp.int32)
            w = jnp.asarray(1, jnp.int32)
            for in_col, op, _, _ in cols_ops:
                col = table.column(in_col)
                contrib = active_s if col.all_valid else jnp.logical_and(
                    jnp.take(col.validity, order), active_s)
                if op in ("collect_list", "collect_set"):
                    per = jax.ops.segment_sum(
                        contrib.astype(jnp.int32), gid, num_segments=cap)
                else:
                    lens = jnp.take(col.lengths, order).astype(jnp.int32)
                    per = jax.ops.segment_sum(
                        jnp.where(contrib, lens, 0), gid, num_segments=cap)
                w = jnp.maximum(w, per.max())
            return w
        return sizes

    def _collect_width(self, table: DeviceTable, key: str) -> int:
        from ..columnar.device import bucket_width
        from ..utils.compile_cache import cached_jit
        sizes = cached_jit(key + "|sizes", self._sizes_fn)
        return bucket_width(max(int(sizes(table)), 1), min_width=4)

    def _canon_fn(self) -> Callable[[DeviceTable], DeviceTable]:
        """Schema-erased cached aggregate callable: canonical-rename in,
        run the shared program, rename out."""
        from ..utils.compile_cache import cached_jit
        canon, ckey = self._canon_exec()
        out_names = tuple(self.schema.names)
        if not self._has_collect():
            base = cached_jit(ckey, canon.batch_fn)

            def fn(batch: DeviceTable) -> DeviceTable:
                return base(batch.canonical()).with_names(out_names)
            return fn

        def fn(batch: DeviceTable) -> DeviceTable:
            bc = batch.canonical()  # per-batch static width, cached per bucket
            w = canon._collect_width(bc, ckey)
            out = cached_jit(ckey + f"|W{w}",
                             lambda: canon.batch_fn(list_width=w))(bc)
            return out.with_names(out_names)
        return fn

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..columnar.device import concat_device_tables, shrink_to_fit
        from ..memory.catalog import SpillPriorities, get_catalog
        from ..memory.retry import (split_device_rows, with_retry,
                                    with_retry_split)
        fn = self._canon_fn()
        merge_fn = None  # built lazily, loop-invariant
        catalog = get_catalog()
        pending = None  # SpillableDeviceTable holding the running merge state

        def agg_combine(outs):
            """Split-and-retry combiner: half-outputs are PARTIAL states
            with overlapping keys, so plain row-concat would double-count
            groups — re-aggregate the concat through the merge exec."""
            nonlocal merge_fn
            both = concat_device_tables(outs)
            if merge_fn is None:
                merge_fn = self._merged_exec()._canon_fn()
            return merge_fn(both)

        # only the partial pass is splittable: its half-outputs are
        # mergeable states. A final-mode aggregate emits finished values
        # (e.g. avg = sum/count), which no merge pass can recombine.
        splitter = split_device_rows if self.mode == "partial" else None

        def chunked_inputs():
            """Stage child batches and aggregate one CONCAT per ~1M-row
            chunk: one sort-based groupby over the chunk replaces a
            per-batch aggregate + pairwise merge cascade (4 batches would
            otherwise cost 7 lexsorts; chunking costs 1). The chunk bound
            keeps the concat out-of-core-safe; anything beyond one chunk
            still reduces through the pairwise merge below."""
            staged: List[DeviceTable] = []
            cap = 0
            for b in self.child_device_batches(pidx):
                staged.append(b)
                cap += b.capacity
                if cap >= (1 << 20):
                    yield staged[0] if len(staged) == 1 \
                        else concat_device_tables(staged)
                    staged, cap = [], 0
            if staged:
                yield staged[0] if len(staged) == 1 \
                    else concat_device_tables(staged)

        from .fallback import quarantine_on_failure
        try:
            for batch in chunked_inputs():
                # note-only boundary: aggregate state spans batches, so a
                # terminal failure can't fall back mid-stream — but it
                # feeds the quarantine store for plan-time routing
                with quarantine_on_failure(self), \
                        self.metrics.timed(M.AGG_TIME):
                    # shrink to the group bucket: the running state must
                    # not scale with input capacity (out-of-core bound)
                    out = shrink_to_fit(with_retry_split(
                        fn, batch, splitter=splitter, combiner=agg_combine,
                        scope="partial-agg", context=self.node_desc()))
                if pending is None:
                    pending = catalog.register(
                        out, SpillPriorities.ACTIVE_ON_DECK)
                else:
                    # merge-as-you-go keeps one running aggregated batch;
                    # shrink-to-groups stops its capacity growing with the
                    # batch count, and the catalog registration lets memory
                    # pressure spill it between input batches (reference:
                    # aggregate.scala merge passes under targetSize).
                    # concat pads to a pow2 bucket, so the merge program
                    # compiles for one or two capacities, not per sum.
                    with pending as prev:
                        both = concat_device_tables([prev, out])
                    if merge_fn is None:
                        merge_fn = self._merged_exec()._canon_fn()
                    # spill-only retry: the concat'd pair is already at
                    # the group bucket — there is nothing useful to halve
                    merged = shrink_to_fit(with_retry(
                        merge_fn, both, scope="agg-merge",
                        context=self.node_desc()))
                    pending.close()
                    pending = catalog.register(
                        merged, SpillPriorities.ACTIVE_ON_DECK)
            if pending is None:
                if not self.key_names:
                    empty = _empty_device_table(self.child.schema, 8)
                    self.account_batch()
                    yield fn(empty)
                return
            self.account_batch()
            yield pending.get()
        finally:
            if pending is not None:
                pending.close()

    def _merged_exec(self) -> "TpuHashAggregateExec":
        """Exec that re-aggregates concatenated partial outputs."""
        merged = TpuHashAggregateExec.__new__(TpuHashAggregateExec)
        TpuExec.__init__(merged)
        merged.key_names = self.key_names
        merged.mode = "final"
        # after the partial pass the state columns are inputs to merge ops
        specs = []
        for s in self.specs:
            ms = AggSpec(s.prefix, s.fn)
            specs.append(ms)
        merged.specs = specs
        merged.child = _SchemaOnly(self.schema)
        merged.children = (merged.child,)
        merged.schema = self.schema
        return merged

    def _merge_batch_fn(self):
        """Re-aggregate concatenated partial outputs (merge semantics)."""
        return self._merged_exec().batch_fn()

    def node_desc(self):
        return f"mode={self.mode} keys={self.key_names}"


class _SchemaOnly:
    def __init__(self, schema: Schema):
        self.schema = schema


def _empty_device_table(schema: Schema, cap: int) -> DeviceTable:
    def empty_col(d: dt.DataType) -> DeviceColumn:
        kids = None
        if isinstance(d, (dt.StringType, dt.BinaryType)):
            data = jnp.zeros((cap, 8), dtype=jnp.uint8)
            lengths = jnp.zeros(cap, dtype=jnp.int32)
        elif dt.is_d128(d):
            data = jnp.zeros((cap, 2), dtype=jnp.int64)
            lengths = None
        elif isinstance(d, dt.ArrayType):
            np_dt = jnp.bool_ if isinstance(d.element_type, dt.BooleanType) \
                else d.element_type.np_dtype()
            data = jnp.zeros((cap, 4), dtype=np_dt)
            lengths = jnp.zeros(cap, dtype=jnp.int32)
        elif isinstance(d, dt.StructType):
            data = jnp.zeros(cap, dtype=jnp.uint8)
            lengths = None
            kids = tuple(empty_col(f.data_type) for f in d.fields)
        elif isinstance(d, dt.MapType):
            data = jnp.zeros(cap, dtype=jnp.uint8)
            lengths = None
            kids = (empty_col(dt.ArrayType(d.key_type, False)),
                    empty_col(dt.ArrayType(d.value_type, True)))
        else:
            data = jnp.zeros(cap, dtype=d.np_dtype())
            lengths = None
        return DeviceColumn(data, jnp.zeros(cap, dtype=bool), d, lengths,
                            None, kids)

    cols = [empty_col(f.dtype) for f in schema]
    return DeviceTable(tuple(cols), jnp.zeros(cap, dtype=bool),
                       jnp.asarray(0, jnp.int32), tuple(schema.names))
