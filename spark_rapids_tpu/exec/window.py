"""Device window exec (reference: GpuWindowExec.scala — running-window
optimization at :161,1346; frame -> rolling/scan mapping in
GpuWindowExpression.scala).

TPU-first: one lexsort puts rows in (partition, order) layout; every window
function is then a data-parallel kernel over that layout inside a single jit:

- segment flags + ``lax.associative_scan`` give segmented cumulative ops
  (the running-window scan path)
- entire-partition aggregates are segment reductions gathered back per row
- bounded ROWS frames use clamped prefix-sum differences (sum/count/avg)
- ranking functions are index arithmetic over segment starts / peer flags

All static shapes; no per-partition loops.
"""
from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.device import DeviceColumn, DeviceTable, concat_device_tables
from ..expr.aggregates import (AggregateFunction, Average, Count, CountStar,
                               Max, Min, Sum)
from ..expr.base import EvalContext
from ..expr.functions import SortOrder
from ..expr.window import (DenseRank, Lag, Lead, NTile, Rank, RowNumber,
                           WindowExpression)
from ..plan.physical import PhysicalPlan
from ..plan.schema import Field, Schema
from ..utils import metrics as M
from ..utils.compile_cache import cached_jit
from .base import TpuExec
from .sort import _order_keys

__all__ = ["TpuWindowExec"]


def _segmented_scan(vals: jax.Array, new_seg: jax.Array, op) -> jax.Array:
    """Inclusive segmented scan: resets at rows where new_seg is True."""
    def combine(a, b):
        fa, va = a
        fb, vb = b
        return jnp.logical_or(fa, fb), jnp.where(fb, vb, op(va, vb))
    _, out = jax.lax.associative_scan(combine, (new_seg, vals))
    return out


def _eq_prev_values(values, lengths=None) -> jax.Array:
    """Per-row equality with the previous row (Spark grouping semantics:
    NaN == NaN, -0.0 == 0.0); string columns compare the full byte row +
    length so zero padding can't conflate "ab" with "ab\x00"."""
    v = values
    if v.ndim == 2:  # string/binary byte matrix
        eq = jnp.all(v == jnp.roll(v, 1, axis=0), axis=1)
        if lengths is not None:
            eq = jnp.logical_and(eq, lengths == jnp.roll(lengths, 1))
        return eq
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = jnp.where(v == 0, jnp.zeros_like(v), v)
        return (v == jnp.roll(v, 1)) \
            | (jnp.isnan(v) & jnp.isnan(jnp.roll(v, 1)))
    return v == jnp.roll(v, 1)


def _seg_info(table: DeviceTable, part_names: List[str]):
    """Assumes rows already sorted by partition keys: returns
    (new_seg flags, seg_start index per row, pos, pos_in_seg)."""
    cap = table.capacity
    pos = jnp.arange(cap, dtype=jnp.int64)
    active = table.row_mask
    new_seg = jnp.zeros(cap, dtype=bool).at[0].set(True)
    for k in part_names:
        c = table.column(k)
        eq = _eq_prev_values(c.data, c.lengths)
        null = jnp.logical_not(c.validity)
        eq = jnp.where(null | jnp.roll(null, 1), null & jnp.roll(null, 1), eq)
        new_seg = jnp.logical_or(new_seg, jnp.logical_not(eq).at[0].set(True))
    # inactive rows are at the end after compact-sort; give them their own seg
    new_seg = jnp.logical_or(new_seg, jnp.logical_not(active)
                             != jnp.logical_not(jnp.roll(active, 1)))
    new_seg = new_seg.at[0].set(True)
    seg_start = _segmented_scan(jnp.where(new_seg, pos, 0), new_seg,
                                lambda a, b: jnp.maximum(a, b))
    # simpler: seg_start via scan of "carry start"
    seg_start = _segmented_scan(pos * new_seg, new_seg, jnp.maximum)
    return new_seg, seg_start, pos, pos - seg_start


def _peer_flags(table: DeviceTable, orders: Sequence[SortOrder],
                new_seg: jax.Array) -> jax.Array:
    """True where a new peer group (distinct order keys) starts."""
    if not orders:
        return new_seg
    ctx = EvalContext.for_device(table)
    neq = jnp.zeros(table.capacity, dtype=bool)
    for o in orders:
        c = o.expr.eval(ctx)
        eq = _eq_prev_values(c.values, getattr(c, "lengths", None))
        valid = c.validity if c.validity is not None \
            else jnp.ones(table.capacity, dtype=bool)
        null = jnp.logical_not(valid)
        eq = jnp.where(null | jnp.roll(null, 1), null & jnp.roll(null, 1), eq)
        neq = jnp.logical_or(neq, jnp.logical_not(eq))
    return jnp.logical_or(new_seg, neq).at[0].set(True)


class TpuWindowExec(TpuExec):
    def __init__(self, child: PhysicalPlan,
                 window_cols: Sequence[Tuple[str, WindowExpression]],
                 child_names: Sequence[str]):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.window_cols = list(window_cols)
        self.child_names = list(child_names)
        fields = list(child.schema.fields)
        for name, w in self.window_cols:
            fields.append(Field(name, w.data_type, w.nullable))
        self.schema = Schema(fields)

    def node_desc(self):
        return ", ".join(n for n, _ in self.window_cols)

    def plan_signature(self) -> str:
        descs = [f"{n}={w!r}" for n, w in self.window_cols]
        return f"Window|{descs}|{self.child.schema!r}"

    @property
    def fusible(self) -> bool:
        return False  # needs whole-partition batches

    def _kernel(self):
        window_cols = self.window_cols
        spec0 = window_cols[0][1].spec
        out_names = tuple(self.schema.names)

        def fn(table: DeviceTable) -> DeviceTable:
            # sort by (partition keys, order keys); actives first
            part_orders = [SortOrder(e, True) for e in spec0.partition_exprs]
            orders = part_orders + list(spec0.orders)
            keys = _order_keys(table, orders) if orders else \
                [jnp.logical_not(table.row_mask)]
            order = jnp.lexsort(tuple(keys))
            cols = tuple(c.gather(order, keep_all_valid=True)
                         for c in table.columns)
            iota = jnp.arange(table.capacity, dtype=jnp.int32)
            mask = iota < table.num_rows
            sorted_t = DeviceTable(cols, mask, table.num_rows, table.names)
            # partition segments: evaluate partition exprs on sorted table
            ctx = EvalContext.for_device(sorted_t)
            part_cols = []
            part_names = []
            scratch = sorted_t
            for i, e in enumerate(spec0.partition_exprs):
                c = e.eval(ctx)
                validity = c.validity if c.validity is not None \
                    else jnp.ones(sorted_t.capacity, dtype=bool)
                part_cols.append(DeviceColumn(c.values, validity, c.dtype,
                                              c.lengths))
                part_names.append(f"_wp{i}")
            scratch = DeviceTable(tuple(sorted_t.columns) + tuple(part_cols),
                                  mask, sorted_t.num_rows,
                                  tuple(sorted_t.names) + tuple(part_names))
            new_seg, seg_start, pos, pos_in_seg = _seg_info(scratch, part_names)
            out_cols = list(sorted_t.columns)
            for name, w in window_cols:
                out_cols.append(_window_column(scratch, w, new_seg, seg_start,
                                               pos, pos_in_seg, mask))
            return DeviceTable(tuple(out_cols), mask, sorted_t.num_rows,
                               out_names)
        return fn

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        batches = list(self.child_device_batches(pidx))
        if not batches:
            return
        table = concat_device_tables(batches) if len(batches) > 1 else batches[0]
        fn = cached_jit(self.plan_signature(), self._kernel)
        with self.metrics.timed(M.OP_TIME):
            out = fn(table)
        self.account_batch()
        yield out


def _window_column(scratch: DeviceTable, w: WindowExpression,
                   new_seg, seg_start, pos, pos_in_seg, mask) -> DeviceColumn:
    cap = scratch.capacity
    fn = w.fn
    all_valid = jnp.ones(cap, dtype=bool)
    if isinstance(fn, RowNumber):
        return DeviceColumn((pos_in_seg + 1).astype(jnp.int32), all_valid,
                            dt.INT, None)
    if isinstance(fn, NTile):
        seg_len = _seg_len(new_seg, seg_start, pos, cap)
        k = fn.n
        base = seg_len // k
        rem = seg_len % k
        cut = rem * (base + 1)
        tile = jnp.where(pos_in_seg < cut,
                         pos_in_seg // jnp.maximum(base + 1, 1),
                         rem + (pos_in_seg - cut) // jnp.maximum(base, 1))
        return DeviceColumn((tile + 1).astype(jnp.int32), all_valid, dt.INT,
                            None)
    if isinstance(fn, (Rank, DenseRank)):
        peers = _peer_flags(scratch, w.spec.orders, new_seg)
        if isinstance(fn, DenseRank):
            dr = _segmented_scan(peers.astype(jnp.int64), new_seg,
                                 lambda a, b: a + b)
            return DeviceColumn(dr.astype(jnp.int32), all_valid, dt.INT, None)
        first_of_peer = _segmented_scan(jnp.where(peers, pos, 0), new_seg,
                                        jnp.maximum)
        return DeviceColumn((first_of_peer - seg_start + 1).astype(jnp.int32),
                            all_valid, dt.INT, None)
    if isinstance(fn, (Lag, Lead)):
        off = fn.offset if isinstance(fn, Lead) else -fn.offset
        ctx = EvalContext.for_device(scratch)
        c = fn.child.eval(ctx)
        src = jnp.clip(pos + off, 0, cap - 1).astype(jnp.int32)
        seg_len = _seg_len(new_seg, seg_start, pos, cap)
        in_seg = jnp.logical_and(pos_in_seg + off >= 0,
                                 pos_in_seg + off < seg_len)
        vals = jnp.take(c.values, src, axis=0)
        valid = jnp.take(c.valid_mask(ctx), src) & in_seg
        if fn.default is not None:
            vals = jnp.where(in_seg, vals,
                             jnp.full_like(vals, fn.default))
            valid = jnp.logical_or(valid, jnp.logical_not(in_seg))
        lengths = None if c.lengths is None else jnp.take(c.lengths, src)
        return DeviceColumn(vals, valid & mask, c.dtype, lengths)
    if isinstance(fn, AggregateFunction):
        return _agg_window_device(scratch, w, new_seg, seg_start, pos,
                                  pos_in_seg, mask)
    raise NotImplementedError(type(fn).__name__)


def _seg_len(new_seg, seg_start, pos, cap):
    # segment end: next segment's start (propagated backwards)
    rev_new = jnp.flip(new_seg)
    rev_pos = jnp.flip(pos)
    # for each row (reversed), the minimum pos of the NEXT segment start at or
    # after it == first new_seg position after current row + 1 ... compute via
    # reverse segmented scan of "start of my segment" on flipped array:
    # flipped segments are delimited one off; easier: seg_end = seg_start of
    # next seg. seg_end[i] = min over j>i of (pos[j] where new_seg[j]) else cap
    nxt = jnp.where(new_seg, pos, cap)
    rev_min = jnp.flip(jax.lax.associative_scan(jnp.minimum, jnp.flip(nxt)))
    # rev_min[i] = min(nxt[i:]) -> next boundary at or after i; but boundary at
    # own segment start should not count: use strictly-after by shifting
    after = jnp.concatenate([rev_min[1:], jnp.asarray([cap], rev_min.dtype)])
    seg_end = after
    return seg_end - seg_start


def _agg_window_device(scratch, w, new_seg, seg_start, pos, pos_in_seg, mask
                       ) -> DeviceColumn:
    fn = w.fn
    frame = w.spec.frame
    cap = scratch.capacity
    ctx = EvalContext.for_device(scratch)
    if isinstance(fn, CountStar):
        vals = jnp.ones(cap, dtype=jnp.int64)
        valid = mask
        in_dt = dt.LONG
    else:
        c = fn.children[0].eval(ctx)
        vals = c.values
        valid = (c.validity if c.validity is not None
                 else jnp.ones(cap, dtype=bool)) & mask
        in_dt = c.dtype
    out_dt = fn.data_type
    np_out = jnp.dtype(out_dt.np_dtype())

    _is_float = jnp.issubdtype(vals.dtype, jnp.floating)

    def prefix_pair():
        x = jnp.where(valid, vals, jnp.zeros_like(vals)).astype(
            jnp.float64 if _is_float else jnp.int64)
        # non-finite-aware prefix sums: a NaN/±inf in the running sum would
        # poison every LATER frame (csum[hi]-csum[lo] = nan-nan or inf-inf)
        # even when the frame excludes that row; sum zeros instead and
        # re-derive the float-sum result per frame from non-finite counts
        if _is_float:
            def ccount(m):
                return jnp.concatenate([jnp.zeros(1, jnp.int64),
                                        jnp.cumsum(m.astype(jnp.int64))])
            nanm = valid & jnp.isnan(vals)
            posm = valid & (vals == jnp.inf)
            negm = valid & (vals == -jnp.inf)
            x = jnp.where(nanm | posm | negm, jnp.float64(0), x)
            specials = (ccount(nanm), ccount(posm), ccount(negm))
        else:
            specials = None
        csum = jnp.concatenate([jnp.zeros(1, x.dtype), jnp.cumsum(x)])
        ccnt = jnp.concatenate([jnp.zeros(1, jnp.int64),
                                jnp.cumsum(valid.astype(jnp.int64))])
        return csum, ccnt, specials

    def reduce_frame(lo, hi):
        csum, ccnt, specials = prefix_pair()
        s = csum[hi] - csum[lo]
        if specials is not None:
            cnan, cpos, cneg = specials
            nn = cnan[hi] - cnan[lo]
            pp = cpos[hi] - cpos[lo]
            gg = cneg[hi] - cneg[lo]
            s = jnp.where((nn > 0) | ((pp > 0) & (gg > 0)),
                          jnp.float64(jnp.nan),
                          jnp.where(pp > 0, jnp.float64(jnp.inf),
                                    jnp.where(gg > 0, jnp.float64(-jnp.inf),
                                              s)))
        return finish(s, ccnt[hi] - ccnt[lo])

    def finish(s, cnt):
        if isinstance(fn, (Count, CountStar)):
            return DeviceColumn(cnt.astype(jnp.int64),
                                jnp.ones(cap, dtype=bool), dt.LONG, None)
        if isinstance(fn, Sum):
            return DeviceColumn(s.astype(np_out), cnt > 0, out_dt, None)
        avg = s.astype(jnp.float64) / jnp.maximum(cnt, 1)
        return DeviceColumn(avg, cnt > 0, dt.DOUBLE, None)

    seg_len = _seg_len(new_seg, seg_start, pos, cap)
    if frame.is_unbounded_entire or (not w.spec.orders and frame.is_running):
        if isinstance(fn, (Sum, Count, CountStar, Average)):
            return reduce_frame(seg_start, seg_start + seg_len)
        # min/max entire partition: forward + effectively segment reduce;
        # do running scan then take value at segment end
        col = _running_minmax(fn, vals, valid, new_seg)
        end_idx = jnp.clip(seg_start + seg_len - 1, 0, cap - 1).astype(jnp.int32)
        v = jnp.take(col[0], end_idx)
        has = jnp.take(col[1], end_idx)
        return DeviceColumn(v.astype(np_out), has & mask, out_dt, None)
    if frame.is_running:
        if frame.kind == "range" and w.spec.orders:
            peers = _peer_flags(scratch, w.spec.orders, new_seg)
            # hi = end of my peer group: next peer boundary after me
            nxt = jnp.where(peers, pos, cap)
            rev_min = jnp.flip(jax.lax.associative_scan(
                jnp.minimum, jnp.flip(nxt)))
            after = jnp.concatenate([rev_min[1:],
                                     jnp.asarray([cap], rev_min.dtype)])
            hi = jnp.minimum(after, seg_start + seg_len)
        else:
            hi = pos + 1
        if isinstance(fn, (Sum, Count, CountStar, Average)):
            return reduce_frame(seg_start, hi)
        run_v, run_has = _running_minmax(fn, vals, valid, new_seg)
        idx = jnp.clip(hi - 1, 0, cap - 1).astype(jnp.int32)
        return DeviceColumn(jnp.take(run_v, idx).astype(np_out),
                            jnp.take(run_has, idx) & mask, out_dt, None)
    seg_end = seg_start + seg_len
    if frame.kind == "rows":
        s = seg_start if frame.start is None else jnp.maximum(
            pos + frame.start, seg_start)
        e = seg_end if frame.end is None else jnp.minimum(
            pos + frame.end + 1, seg_end)
    elif frame.kind == "range" and len(w.spec.orders) == 1:
        sk, null_mask, scale = _device_range_sort_key(scratch,
                                                      w.spec.orders[0])

        def tgt(offset):
            t = sk + offset
            return t if null_mask is None else jnp.where(null_mask, sk, t)

        s = seg_start if frame.start is None else _device_bsearch(
            sk, tgt(frame.start * scale), seg_start, seg_end, strict=False)
        e = seg_end if frame.end is None else _device_bsearch(
            sk, tgt(frame.end * scale), seg_start, seg_end, strict=True)
    else:
        raise NotImplementedError(
            f"{type(fn).__name__} over {frame.describe()} on device")
    e = jnp.maximum(e, s)
    if isinstance(fn, (Sum, Count, CountStar, Average)):
        return reduce_frame(s, e)
    if isinstance(fn, (Min, Max)):
        return _device_range_minmax(isinstance(fn, Min), vals, valid,
                                    s, e, out_dt, cap)
    raise NotImplementedError(
        f"{type(fn).__name__} over {frame.describe()} on device")


def _device_range_sort_key(scratch: DeviceTable, order: SortOrder):
    """Sort-axis key for bounded RANGE frames -> (sk, null_mask, scale);
    identical rules to the host engine's _range_sort_key: integral/date/
    decimal keys stay int64 (decimal offsets scale to value units), float
    keys use float64 with NaN at the top; DESC negates; null keys collapse
    to a +-extreme sentinel peer window."""
    ctx = EvalContext.for_device(scratch)
    c = order.expr.eval(ctx)
    scale = 1
    if isinstance(c.dtype, dt.DecimalType):
        scale = 10 ** c.dtype.scale
    if jnp.issubdtype(c.values.dtype, jnp.floating):
        sk = c.values.astype(jnp.float64)
        sk = jnp.where(jnp.isnan(sk), jnp.inf, sk)
        lo_sent, hi_sent = -jnp.inf, jnp.inf
    else:
        sk = c.values.astype(jnp.int64)
        lo_sent = jnp.iinfo(jnp.int64).min
        hi_sent = jnp.iinfo(jnp.int64).max
    if not order.ascending:
        sk = -sk
    null_mask = None
    if c.validity is not None:
        null_mask = jnp.logical_not(c.validity)
        sent = lo_sent if order.nulls_first else hi_sent
        sk = jnp.where(null_mask, jnp.asarray(sent, sk.dtype), sk)
    return sk, null_mask, scale


def _device_bsearch(sk, target, lo0, hi0, strict: bool):
    """First pos in [lo0, hi0) with sk[pos] >= target (> when strict);
    fixed-depth vectorized binary search (static log2(cap) iterations)."""
    cap = sk.shape[0]
    lo = lo0.astype(jnp.int64)
    hi = hi0.astype(jnp.int64)
    for _ in range(max(1, cap.bit_length())):
        active = lo < hi
        mid = (lo + hi) // 2
        mv = jnp.take(sk, jnp.clip(mid, 0, cap - 1))
        go_right = (mv <= target) if strict else (mv < target)
        lo = jnp.where(jnp.logical_and(active, go_right), mid + 1, lo)
        hi = jnp.where(jnp.logical_and(active,
                                       jnp.logical_not(go_right)), mid, hi)
    return lo


def _device_range_minmax(is_min: bool, vals, valid, lo, hi, out_dt, cap
                         ) -> DeviceColumn:
    """Per-row [lo, hi) min/max via a power-of-two sparse table (the device
    mirror of the host engine's _range_minmax), Spark NaN total order."""
    np_out = jnp.dtype(out_dt.np_dtype())
    isfloat = jnp.issubdtype(vals.dtype, jnp.floating)
    if isfloat:
        nan_mask = jnp.isnan(vals)
        work = jnp.where(nan_mask, jnp.inf if is_min else -jnp.inf, vals)
        ident = jnp.asarray(jnp.inf if is_min else -jnp.inf, work.dtype)
    else:
        nan_mask = jnp.zeros(cap, dtype=bool)
        work = vals.astype(jnp.int64)
        ident = jnp.asarray(jnp.iinfo(jnp.int64).max if is_min
                            else jnp.iinfo(jnp.int64).min, jnp.int64)
    work = jnp.where(valid, work, ident)
    op = jnp.minimum if is_min else jnp.maximum
    tables = [work]
    k = 1
    while (1 << k) <= cap:
        prev = tables[-1]
        half = 1 << (k - 1)
        shifted = jnp.concatenate(
            [prev[half:], jnp.full(half, ident, prev.dtype)])
        tables.append(op(prev, shifted))
        k += 1
    T = jnp.stack(tables)                                # (levels, cap)
    wlen = jnp.maximum(hi - lo, 0)
    kk = jnp.where(wlen > 0,
                   jnp.floor(jnp.log2(jnp.maximum(wlen, 1))), 0
                   ).astype(jnp.int32)
    a = T[kk, jnp.clip(lo, 0, cap - 1).astype(jnp.int32)]
    b_idx = hi - jnp.left_shift(jnp.int64(1), kk.astype(jnp.int64))
    b = T[kk, jnp.clip(b_idx, 0, cap - 1).astype(jnp.int32)]
    out = op(a, b)
    ccnt = jnp.concatenate([jnp.zeros(1, jnp.int64),
                            jnp.cumsum(valid.astype(jnp.int64))])
    cnt = ccnt[jnp.clip(hi, 0, cap)] - ccnt[jnp.clip(lo, 0, cap)]
    has = cnt > 0
    if isfloat:
        cnan = jnp.concatenate([
            jnp.zeros(1, jnp.int64),
            jnp.cumsum(jnp.logical_and(valid, nan_mask).astype(jnp.int64))])
        nnan = cnan[jnp.clip(hi, 0, cap)] - cnan[jnp.clip(lo, 0, cap)]
        if is_min:
            out = jnp.where(jnp.logical_and(has, cnt == nnan), jnp.nan, out)
        else:
            out = jnp.where(nnan > 0, jnp.nan, out)
    return DeviceColumn(out.astype(np_out), has, out_dt, None)


def _running_minmax(fn, vals, valid, new_seg):
    """Segmented running min/max with Spark NaN ordering; returns (vals, has)."""
    is_min = isinstance(fn, Min)
    isfloat = jnp.issubdtype(vals.dtype, jnp.floating)
    x = vals
    if isfloat:
        nan = jnp.isnan(vals)
        x = jnp.where(nan, jnp.full_like(vals, jnp.inf if is_min else -jnp.inf),
                      vals)
        # NaN counts tracked separately for Spark total order
    ident = (jnp.finfo(x.dtype).max if jnp.issubdtype(x.dtype, jnp.floating)
             else jnp.iinfo(x.dtype).max) if is_min else \
        (jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating)
         else jnp.iinfo(x.dtype).min)
    x = jnp.where(valid, x, jnp.full_like(x, ident))
    op = jnp.minimum if is_min else jnp.maximum
    run = _segmented_scan(x, new_seg, op)
    has = _segmented_scan(valid.astype(jnp.int64), new_seg,
                          lambda a, b: a + b) > 0
    if isfloat:
        nan_run = _segmented_scan((valid & jnp.isnan(vals)).astype(jnp.int64),
                                  new_seg, lambda a, b: a + b)
        nonnan_run = _segmented_scan(
            (valid & jnp.logical_not(jnp.isnan(vals))).astype(jnp.int64),
            new_seg, lambda a, b: a + b)
        if is_min:
            run = jnp.where(has & (nonnan_run == 0),
                            jnp.full_like(run, jnp.nan), run)
        else:
            run = jnp.where(nan_run > 0, jnp.full_like(run, jnp.nan), run)
    return run, has
