"""Basic device operators: Project / Filter / Range / Union / Limit
(reference: basicPhysicalOperators.scala:115,313,540 and limit.scala).

Project and Filter are pure per-batch functions — Filter only ANDs the
selection mask (no gather!), so a filter+project chain fuses into one XLA
computation with zero intermediate materialization.
"""
from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.device import (DeviceColumn, DeviceTable,
                               resolve_min_bucket)
from ..expr.base import EvalContext, Expression
from ..plan.physical import PhysicalPlan
from ..plan.schema import Field, Schema
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuProjectExec", "TpuFilterExec", "TpuRangeExec", "TpuUnionExec",
           "TpuLocalLimitExec", "TpuExpandExec", "TpuSampleExec",
           "eval_exprs_device"]


def eval_exprs_device(table: DeviceTable, exprs: Sequence[Expression],
                      names: Sequence[str], partition_id: int = 0,
                      batch_row_offset: int = 0) -> DeviceTable:
    ctx = EvalContext.for_device(table, partition_id=partition_id,
                                 batch_row_offset=batch_row_offset)
    cols: List[DeviceColumn] = []
    for e in exprs:
        c = e.eval(ctx)
        validity = c.validity
        if validity is None:
            validity = jnp.ones(table.capacity, dtype=bool)
        values = c.values
        if not isinstance(c.dtype, (dt.StringType, dt.BinaryType,
                                    dt.ArrayType, dt.StructType,
                                    dt.MapType)):
            want = c.dtype.np_dtype()
            if values.dtype != want:
                values = values.astype(want)
        kids = None if c.children is None \
            else tuple(ctx.to_device_column(k) for k in c.children)
        cols.append(DeviceColumn(values, validity, c.dtype, c.lengths,
                                 c.elem_validity, kids))
    return DeviceTable(tuple(cols), table.row_mask, table.num_rows, tuple(names))


class TpuProjectExec(TpuExec):
    def __init__(self, child: PhysicalPlan, exprs: Sequence[Expression],
                 names: Sequence[str]):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.exprs = list(exprs)
        self.names = list(names)
        self.schema = Schema([Field(n, e.data_type, e.nullable)
                              for n, e in zip(names, exprs)])

    def batch_fn(self) -> Callable[[DeviceTable], DeviceTable]:
        exprs, names = self.exprs, self.names

        def fn(table: DeviceTable) -> DeviceTable:
            return eval_exprs_device(table, exprs, names)
        return fn

    def host_batch_fn(self):
        # the host-engine projection over one downloaded batch
        # (plan/physical.py CpuProjectExec's per-batch body); context-
        # dependent exprs need the real task context and cannot fall back
        if any(e.tree_context_dependent() for e in self.exprs):
            return None
        exprs, names = self.exprs, self.names

        def fn(table):
            from ..plan.physical import host_eval_exprs
            return host_eval_exprs(table, exprs, names)
        return fn

    def plan_signature(self) -> str:
        child_schema = repr(self.children[0].schema) if self.children else ""
        return f"Project|{[repr(e) for e in self.exprs]}|{self.names}|{child_schema}"

    @property
    def fusible(self) -> bool:
        # context-dependent exprs (partition id / monotonic id / rand) need a
        # per-partition context, so they stay out of whole-stage fusion
        return not any(e.tree_context_dependent() for e in self.exprs)

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..utils.compile_cache import cached_jit
        if not self.fusible:
            # eager device evaluation with an explicit task context
            offset = 0
            for batch in self.child_device_batches(pidx):
                with self.metrics.timed(M.OP_TIME):
                    out = eval_exprs_device(batch, self.exprs, self.names,
                                            partition_id=pidx,
                                            batch_row_offset=offset)
                offset += batch.capacity
                self.account_batch()
                yield out
            return
        from ..memory.retry import split_device_rows, with_retry_split
        from .fallback import with_host_fallback
        fn = cached_jit(self.plan_signature(), self.batch_fn)
        # degradation boundary: ladder inside (spill → retry → split),
        # host fallback outside — a terminal device failure re-runs the
        # batch through the host projection instead of failing the query
        run = with_host_fallback(
            self,
            lambda b: with_retry_split(fn, b, splitter=split_device_rows,
                                       scope="project",
                                       context=self.node_desc()),
            self.host_batch_fn())
        for batch in self.child_device_batches(pidx):
            with self.metrics.timed(M.OP_TIME):
                # row-wise: halves concat back into the same projection
                out = run(batch)
            self.account_batch()
            yield out

    def node_desc(self):
        return ", ".join(self.names)


class TpuFilterExec(TpuExec):
    def __init__(self, child: PhysicalPlan, condition: Expression):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.condition = condition
        self.schema = child.schema

    def batch_fn(self) -> Callable[[DeviceTable], DeviceTable]:
        cond = self.condition

        def fn(table: DeviceTable) -> DeviceTable:
            ctx = EvalContext.for_device(table)
            c = cond.eval(ctx)
            keep = c.values
            if c.validity is not None:
                keep = jnp.logical_and(keep, c.validity)
            return table.filter_mask(keep)
        return fn

    def host_batch_fn(self):
        # the host-engine filter over one downloaded batch
        # (plan/physical.py CpuFilterExec's per-batch body)
        if self.condition.tree_context_dependent():
            return None
        cond = self.condition

        def fn(table):
            import numpy as np
            from ..expr.base import EvalContext as _Ctx
            ctx = _Ctx.for_host(table)
            c = cond.eval(ctx)
            keep = np.asarray(c.values, dtype=np.bool_)  # srtpu: sync-ok(host fallback path over a downloaded host table)
            if c.validity is not None:
                keep = keep & c.validity
            return table.take(np.nonzero(keep)[0])
        return fn

    def plan_signature(self) -> str:
        child_schema = repr(self.children[0].schema) if self.children else ""
        return f"Filter|{self.condition!r}|{child_schema}"

    @property
    def fusible(self) -> bool:
        return not self.condition.tree_context_dependent()

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..utils.compile_cache import cached_jit
        if not self.fusible:
            cond = self.condition
            offset = 0
            for batch in self.child_device_batches(pidx):
                with self.metrics.timed(M.OP_TIME):
                    ctx = EvalContext.for_device(batch, partition_id=pidx,
                                                 batch_row_offset=offset)
                    c = cond.eval(ctx)
                    keep = c.values
                    if c.validity is not None:
                        keep = jnp.logical_and(keep, c.validity)
                    out = batch.filter_mask(keep)
                offset += batch.capacity
                self.account_batch()
                yield out
            return
        from ..memory.retry import split_device_rows, with_retry_split
        from .fallback import with_host_fallback
        fn = cached_jit(self.plan_signature(), self.batch_fn)
        # degradation boundary (see TpuProjectExec): ladder inside,
        # host fallback outside
        run = with_host_fallback(
            self,
            lambda b: with_retry_split(fn, b, splitter=split_device_rows,
                                       scope="filter",
                                       context=self.node_desc()),
            self.host_batch_fn())
        for batch in self.child_device_batches(pidx):
            with self.metrics.timed(M.OP_TIME):
                # row-wise: filtering halves and concatenating preserves
                # the partition's surviving rows and their order
                out = run(batch)
            self.account_batch()
            yield out

    def node_desc(self):
        return repr(self.condition)


class TpuSampleExec(TpuExec):
    """Device Bernoulli sample (reference: GpuPartitionwiseSampledRDD /
    GpuPoissonSampler). Batches are compacted and the running row offset is
    tracked by TRUE row count so the position-hash decisions match the host
    engine row-for-row."""

    def __init__(self, child: PhysicalPlan, fraction: float, seed: int):
        super().__init__()
        from ..expr.hashing import SampleMask
        self.child = child
        self.children = (child,)
        self.fraction = fraction
        self.seed = seed
        self.mask_expr = SampleMask(fraction, seed)
        self.schema = child.schema

    def plan_signature(self) -> str:
        return f"Sample|{self.fraction}|{self.seed}|{self.schema!r}"

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..utils.compile_cache import cached_jit
        mask_expr = self.mask_expr

        def make():
            def fn(table: DeviceTable, offset) -> DeviceTable:
                ctx = EvalContext.for_device(table, partition_id=pidx,
                                             batch_row_offset=offset)
                c = mask_expr.eval(ctx)
                return table.filter_mask(c.values)
            return fn
        from ..memory.retry import with_retry
        fn = cached_jit(self.plan_signature() + f"|p{pidx}", make)
        # device-resident row offset: the accumulation rides async
        # dispatch, so sampling never blocks the host between batches
        offset = jnp.zeros((), dtype=jnp.int64)
        for batch in self.child_device_batches(pidx):
            with self.metrics.timed(M.OP_TIME):
                batch = batch.compact()
                # spill-only retry: the sample mask hashes ABSOLUTE row
                # positions, so row-axis halves (which renumber rows from
                # 0) would sample different rows — unsplittable
                out = with_retry(fn, batch, offset,
                                 scope="sample", context=self.node_desc())
            offset = offset + batch.num_rows.astype(jnp.int64)
            self.account_batch()
            yield out

    def node_desc(self):
        return f"fraction={self.fraction} seed={self.seed}"


class TpuExpandExec(TpuExec):
    """Device Expand: the P projections evaluate in ONE traced kernel and
    stack into a (P * capacity)-row batch — fully static shapes (reference:
    GpuExpandExec.scala emits per-projection batches; stacking suits XLA
    better than P small launches)."""

    def __init__(self, child: PhysicalPlan, projections, names, schema):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.projections = projections
        self.names = list(names)
        self.schema = schema

    def batch_fn(self) -> Callable[[DeviceTable], DeviceTable]:
        projections, names = self.projections, self.names

        def fn(table: DeviceTable) -> DeviceTable:
            from ..columnar.device import concat_device_tables
            parts = [eval_exprs_device(table, proj, names)
                     for proj in projections]
            if len(parts) == 1:
                return parts[0]
            return concat_device_tables(parts)
        return fn

    def plan_signature(self) -> str:
        child_schema = repr(self.children[0].schema) if self.children else ""
        return ("Expand|"
                f"{[[repr(e) for e in p] for p in self.projections]}|"
                f"{self.names}|{child_schema}")

    @property
    def fusible(self) -> bool:
        return not any(e.tree_context_dependent()
                       for p in self.projections for e in p)

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..columnar.device import concat_device_tables
        from ..utils.compile_cache import cached_jit
        if not self.fusible:
            # context-dependent projections need the real task context
            offset = 0
            for batch in self.child_device_batches(pidx):
                with self.metrics.timed(M.OP_TIME):
                    parts = [eval_exprs_device(batch, proj, self.names,
                                               partition_id=pidx,
                                               batch_row_offset=offset)
                             for proj in self.projections]
                    out = parts[0] if len(parts) == 1 \
                        else concat_device_tables(parts)
                offset += batch.capacity
                self.account_batch()
                yield out
            return
        from ..memory.retry import with_retry
        fn = cached_jit(self.plan_signature(), self.batch_fn)
        for batch in self.child_device_batches(pidx):
            with self.metrics.timed(M.OP_TIME):
                # spill-only retry: expand interleaves P projections per
                # batch, so half-outputs would reorder rows across the
                # projection boundary — unsplittable
                out = with_retry(fn, batch, scope="expand",
                                 context=self.node_desc())
            self.account_batch()
            yield out

    def node_desc(self):
        return f"{len(self.projections)} projections"


class TpuRangeExec(TpuExec):
    def __init__(self, start: int, end: int, step: int, num_partitions: int = 1,
                 min_bucket: Optional[int] = None, max_batch_rows: int = 1 << 22):
        super().__init__()
        import math
        self.start, self.end, self.step = start, end, step
        self._parts = num_partitions
        self.min_bucket = resolve_min_bucket(min_bucket)
        self.max_batch_rows = max_batch_rows
        self.children = ()
        self.schema = Schema([Field("id", dt.LONG, False)])
        self._total = max(0, math.ceil((end - start) / step))

    @property
    def num_partitions(self) -> int:
        return self._parts

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        import math
        per = math.ceil(self._total / self._parts) if self._total else 0
        lo = min(self._total, pidx * per)
        hi = min(self._total, (pidx + 1) * per)
        pos = lo
        while pos < hi:
            n = min(self.max_batch_rows, hi - pos)
            from ..columnar.device import bucket_rows
            with self.metrics.timed(M.OP_TIME):
                cap = bucket_rows(max(n, 1), self.min_bucket)
                iota = jnp.arange(cap, dtype=jnp.int64)
                values = jnp.asarray(self.start, jnp.int64) \
                    + jnp.asarray(self.step, jnp.int64) * (iota + pos)
                mask = iota < n
                col = DeviceColumn(values, mask, dt.LONG, None)
            self.account_batch(rows=n)
            yield DeviceTable((col,), mask, jnp.asarray(n, jnp.int32), ("id",))
            pos += n


class TpuUnionExec(TpuExec):
    def __init__(self, children: Sequence[PhysicalPlan]):
        super().__init__()
        self.children = tuple(children)
        self.schema = children[0].schema

    @property
    def num_partitions(self) -> int:
        return sum(c.num_partitions for c in self.children)

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        for c in self.children:
            if pidx < c.num_partitions:
                for b in c.execute_columnar(pidx):
                    self.account_batch()
                    yield DeviceTable(b.columns, b.row_mask, b.num_rows,
                                      tuple(self.schema.names))
                return
            pidx -= c.num_partitions
        raise IndexError(pidx)


class TpuLocalLimitExec(TpuExec):
    """Per-partition limit: compacts then masks the first n rows."""

    def __init__(self, child: PhysicalPlan, n: int):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.n = n
        self.schema = child.schema

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        remaining = self.n

        @jax.jit
        def take(table: DeviceTable, k) -> DeviceTable:
            t = table.compact()
            iota = jnp.arange(t.capacity, dtype=jnp.int32)
            nr = jnp.minimum(t.num_rows, k).astype(jnp.int32)
            mask = iota < nr
            return DeviceTable(t.columns, mask, nr, t.names)

        from ..columnar.device import resolve_scalars
        for batch in self.child_device_batches(pidx):
            if remaining <= 0:
                return
            with self.metrics.timed(M.OP_TIME):
                out = take(batch, jnp.asarray(remaining, jnp.int32))
            # early-exit decision: one batched-funnel transfer per batch
            (emitted,) = resolve_scalars(out.num_rows)
            emitted = int(emitted)
            remaining -= emitted
            self.account_batch(rows=emitted)
            yield out
