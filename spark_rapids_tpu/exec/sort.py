"""Device sort (reference: GpuSortExec.scala — FullSortSingleBatch /
OutOfCoreSort / SortEachBatch modes at :39-41,69).

TPU shape: one lexsort over transformed key arrays inside one jitted program
(FullSortSingleBatch). When the input exceeds the batch-size budget, the
OutOfCoreSort path sorts each batch into a spillable run (registered with the
BufferCatalog so memory pressure migrates runs to host/disk), then merges
runs with a sentinel-sort: each round pulls a fixed-size chunk per run plus
each run's next unconsumed row flagged as a sentinel, sorts the union, and
emits exactly the prefix before the first sentinel — rows provably <= every
unseen row. All comparisons happen on device; only the emitted-count scalar
syncs to host.

Spark ordering semantics: nulls first/last per order, NaN greater than all
numbers, -0.0 == 0.0.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.device import (DeviceColumn, DeviceTable, append_column,
                               resolve_min_bucket, resolve_scalars,
                               bucket_rows, concat_device_tables, drop_column,
                               shrink_to_fit, slice_rows)
from ..expr.base import EvalContext
from ..expr.functions import SortOrder
from ..plan.physical import PhysicalPlan
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuSortExec", "device_sort_table"]

_SENT = "__ooc_sentinel"


def _order_keys(table: DeviceTable, orders: Sequence[SortOrder]) -> List[jax.Array]:
    """lexsort key list (minor..major) implementing Spark ordering."""
    ctx = EvalContext.for_device(table)
    keys: List[jax.Array] = []
    for o in reversed(list(orders)):
        c = o.expr.eval(ctx)
        v = c.values
        if jnp.issubdtype(v.dtype, jnp.floating):
            nan = jnp.isnan(v)
            v = jnp.where(v == 0, jnp.zeros_like(v), v)       # -0.0 -> 0.0
            v = jnp.where(nan, jnp.full_like(v, jnp.inf), v)  # NaN sorts high
            nan_key = nan  # among +inf ties, NaN after true inf
            if not o.ascending:
                v = -v
                nan_key = jnp.logical_not(nan)
            keys.append(nan_key)
            keys.append(v)
        elif dt.is_d128(c.dtype):  # two-limb decimal: biased uint64 words
            from ..expr.decimal128 import d128_key_words
            words = d128_key_words(v)
            if not o.ascending:  # bit inversion reverses unsigned order
                words = [~w for w in words]
            for wd in reversed(words):
                keys.append(wd)
        elif v.ndim == 2:  # string/binary: packed uint64 surrogate words
            from ..columnar.device import pack_string_key_words
            words = pack_string_key_words(v, c.lengths)
            if not o.ascending:  # bit inversion reverses unsigned order
                words = [~w for w in words]
            for wd in reversed(words):  # append LSW first; MSW nearest null key
                keys.append(wd)
        elif v.dtype == jnp.bool_:
            keys.append(v != o.ascending)
        else:
            keys.append(v if o.ascending else -v)
        valid = c.validity
        if valid is None:
            valid = jnp.ones(table.capacity, dtype=bool)
        null = jnp.logical_not(valid)
        # nulls_first: null sorts as 0 (before valid=1); else after
        null_key = jnp.logical_not(null) if o.nulls_first else null
        keys.append(null_key)
    # primary: active rows first
    keys.append(jnp.logical_not(table.row_mask))
    return keys


def device_sort_table(table: DeviceTable, orders: Sequence[SortOrder]) -> DeviceTable:
    keys = _order_keys(table, orders)
    order = jnp.lexsort(tuple(keys))
    # sort permutation parks masked-off rows past num_rows; the dense
    # prefix mask below exposes only real rows (all_valid survives)
    cols = tuple(c.gather(order, keep_all_valid=True)
                 for c in table.columns)
    iota = jnp.arange(table.capacity, dtype=jnp.int32)
    mask = iota < table.num_rows
    return DeviceTable(cols, mask, table.num_rows, table.names)


class TpuTakeOrderedExec(TpuExec):
    """Device top-n (reference: GpuTakeOrderedAndProjectExec, limit.scala).

    Folds batches through a running top-n: sort batch, truncate to n,
    concat with state, sort, truncate — state stays at a bucketed n-row
    capacity so the kernel shapes are stable across batches."""

    EXTRA_METRICS = (M.SORT_TIME,)

    def __init__(self, child, orders: Sequence[SortOrder], n: int,
                 min_bucket: Optional[int] = None):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.orders = list(orders)
        self.n = n
        self.schema = child.schema
        self.min_bucket = resolve_min_bucket(min_bucket)

    def plan_signature(self) -> str:
        return (f"TakeOrdered|{self.n}|"
                f"{[(repr(o.expr), o.ascending, o.nulls_first) for o in self.orders]}|"
                f"{self.schema!r}")

    def _topn_fn(self, cap_key: str):
        from ..utils.compile_cache import cached_jit
        orders, n = self.orders, self.n
        cap = bucket_rows(max(n, 1), self.min_bucket)

        def make():
            def fn(table: DeviceTable) -> DeviceTable:
                s = device_sort_table(table, orders)
                iota = jnp.arange(s.capacity, dtype=jnp.int32)
                keep = jnp.minimum(s.num_rows, jnp.int32(n))
                mask = iota < keep
                cols = tuple(
                    DeviceColumn(c.data[:cap], jnp.logical_and(
                        c.validity[:cap], mask[:cap]), c.dtype,
                        None if c.lengths is None else c.lengths[:cap])
                    for c in s.columns) if s.capacity > cap else tuple(
                    DeviceColumn(c.data, jnp.logical_and(c.validity, mask),
                                 c.dtype, c.lengths) for c in s.columns)
                out_mask = mask[:cap] if s.capacity > cap else mask
                return DeviceTable(cols, out_mask, keep, s.names)
            return fn
        return cached_jit(self.plan_signature() + cap_key, make)

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..memory.retry import (split_device_rows, with_retry,
                                    with_retry_split)

        def topn_combine(outs):
            """Half top-n's are each sorted-and-truncated; re-running
            top-n over their concat restores the global order + bound."""
            merged = concat_device_tables(outs)
            return self._topn_fn(f"|cap{merged.capacity}")(merged)

        from .fallback import quarantine_on_failure
        state = None
        for batch in self.child_device_batches(pidx):
            # note-only boundary: top-n state spans batches, so a terminal
            # failure can't fall back mid-stream — but it quarantines
            with quarantine_on_failure(self), \
                    self.metrics.timed(M.SORT_TIME):
                top = with_retry_split(
                    lambda b: self._topn_fn(f"|cap{b.capacity}")(b), batch,
                    splitter=split_device_rows, combiner=topn_combine,
                    scope="topn", context=self.node_desc())
                if state is None:
                    state = top
                else:
                    merged = concat_device_tables([state, top])
                    # spill-only: the running state is already bounded at
                    # the bucketed n-row capacity
                    state = with_retry(
                        self._topn_fn(f"|cap{merged.capacity}"), merged,
                        scope="topn-merge", context=self.node_desc())
        if state is not None:
            self.account_batch()
            yield state

    def node_desc(self):
        return f"n={self.n}"


class TpuSortExec(TpuExec):
    EXTRA_METRICS = (M.SORT_TIME,)

    def __init__(self, child: PhysicalPlan, orders: Sequence[SortOrder],
                 min_bucket: Optional[int] = None,
                 batch_bytes: int = 512 * 1024 * 1024):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.orders = list(orders)
        self.schema = child.schema
        self.min_bucket = resolve_min_bucket(min_bucket)
        self.batch_bytes = batch_bytes

    def _sort_fn(self, cap_key: str):
        from ..utils.compile_cache import cached_jit
        orders = self.orders
        return cached_jit(self.plan_signature() + cap_key,
                          lambda: (lambda t: device_sort_table(t, orders)))

    def _sort_combine(self, outs):
        """Split-and-retry combiner: half-sorts are only locally ordered,
        so re-sort their concat — by combine time the ladder has spilled
        everything else, leaving the merged sort the whole HBM."""
        merged = concat_device_tables(outs)
        return self._sort_fn(f"|cap{merged.capacity}")(merged)

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..memory.retry import split_device_rows, with_retry_split
        batches = list(self.child_device_batches(pidx))
        if not batches:
            return
        total_bytes = sum(b.nbytes() for b in batches)
        if len(batches) == 1 or total_bytes <= self.batch_bytes:
            # FullSortSingleBatch mode
            from .fallback import quarantine_on_failure
            table = concat_device_tables(batches) if len(batches) > 1 \
                else batches[0]
            with quarantine_on_failure(self), \
                    self.metrics.timed(M.SORT_TIME):
                out = with_retry_split(
                    lambda t: self._sort_fn(f"|cap{t.capacity}")(t), table,
                    splitter=split_device_rows, combiner=self._sort_combine,
                    scope="sort", context=self.node_desc())
            self.account_batch()
            yield out
            return
        yield from self._out_of_core(batches)

    # -- OutOfCoreSort mode ---------------------------------------------------
    def _out_of_core(self, batches: List[DeviceTable]
                     ) -> Iterator[DeviceTable]:
        from ..memory.catalog import SpillPriorities, get_catalog
        from ..memory.retry import split_device_rows, with_retry_split
        from .fallback import quarantine_on_failure
        catalog = get_catalog()
        runs = []  # (SpillableDeviceTable, active_rows)
        try:
            with quarantine_on_failure(self), \
                    self.metrics.timed(M.SORT_TIME):
                sorted_bs = [with_retry_split(
                    lambda t: self._sort_fn(f"|cap{t.capacity}")(t), b,
                    splitter=split_device_rows,
                    combiner=self._sort_combine,
                    scope="sort", context=self.node_desc())
                    for b in batches]
                # every run's sort dispatches before the host blocks:
                # one batched-funnel transfer resolves all run counts
                counts = resolve_scalars(
                    *[b.num_rows for b in sorted_bs])
                for sorted_b, n in zip(sorted_bs, counts):
                    n = int(n)
                    if n:
                        runs.append((catalog.register(
                            sorted_b, SpillPriorities.INPUT), n))
            yield from self._merge_runs(runs)
        finally:
            for run, _ in runs:
                run.close()

    def _merge_runs(self, runs) -> Iterator[DeviceTable]:
        if not runs:
            return
        k = len(runs)
        target_rows = max(r for _, r in runs)
        chunk = bucket_rows(max(self.min_bucket, target_rows // k),
                            self.min_bucket)
        cursors = [0] * k
        carry: Optional[DeviceTable] = None
        while carry is not None or any(c < n for c, (_, n) in
                                       zip(cursors, runs)):
            inputs: List[DeviceTable] = []
            flags: List[bool] = []
            if carry is not None:
                inputs.append(carry)
                flags.append(False)
            for i, (run, nrows) in enumerate(runs):
                if cursors[i] >= nrows:
                    continue
                with run as t:
                    inputs.append(slice_rows(t, cursors[i], chunk))
                    flags.append(False)
                    cursors[i] = min(cursors[i] + chunk, nrows)
                    if cursors[i] < nrows:  # next unseen row = sentinel
                        inputs.append(slice_rows(t, cursors[i], 1))
                        flags.append(True)
            tagged = [append_column(
                t, _SENT, DeviceColumn(
                    jnp.full(t.capacity, f, dtype=bool),
                    jnp.ones(t.capacity, dtype=bool), dt.BOOLEAN, None))
                for t, f in zip(inputs, flags)]
            merged = concat_device_tables(tagged, self.min_bucket)
            with self.metrics.timed(M.SORT_TIME):
                # spill-only: merge inputs are fixed-size chunks already
                # bounded by the out-of-core chunking policy
                from ..memory.retry import with_retry
                sorted_m = with_retry(
                    self._sort_fn(f"|merge{merged.capacity}"), merged,
                    scope="sort-merge", context=self.node_desc())
            sent = jnp.logical_and(sorted_m.column(_SENT).data,
                                   sorted_m.row_mask)
            # the emitted-count decision stays on device; ONE batched
            # transfer then resolves both loop controls (emit count and
            # carry count) instead of three scalar syncs per round
            emit_dev = jnp.where(jnp.any(sent),
                                 jnp.argmax(sent).astype(jnp.int32),
                                 sorted_m.num_rows)
            iota = jnp.arange(sorted_m.capacity, dtype=jnp.int32)
            rest_mask = jnp.logical_and(
                iota >= emit_dev,
                jnp.logical_not(sorted_m.column(_SENT).data))
            rest = drop_column(sorted_m.filter_mask(rest_mask), _SENT)
            emit_n, rest_n = resolve_scalars(emit_dev, rest.num_rows)
            emit_n, rest_n = int(emit_n), int(rest_n)
            if emit_n > 0:
                out = drop_column(
                    sorted_m.filter_mask(iota < emit_n), _SENT)
                self.account_batch(rows=emit_n)
                yield shrink_to_fit(out, self.min_bucket, num_rows=emit_n)
            carry = shrink_to_fit(rest, self.min_bucket, num_rows=rest_n) \
                if rest_n else None

    def node_desc(self):
        return ", ".join(f"{o.expr!r} {'ASC' if o.ascending else 'DESC'}"
                         for o in self.orders)
