"""Device sort (reference: GpuSortExec.scala — FullSortSingleBatch /
OutOfCoreSort / SortEachBatch modes; this implements the single-batch mode,
out-of-core splitting arrives with the spill framework).

TPU shape: one lexsort over transformed key arrays inside one jitted program.
Spark ordering semantics: nulls first/last per order, NaN greater than all
numbers, -0.0 == 0.0.
"""
from __future__ import annotations

from typing import Iterator, List, Sequence

import jax
import jax.numpy as jnp

from ..columnar.device import DeviceTable, concat_device_tables
from ..expr.base import EvalContext
from ..expr.functions import SortOrder
from ..plan.physical import PhysicalPlan
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuSortExec", "device_sort_table"]


def _order_keys(table: DeviceTable, orders: Sequence[SortOrder]) -> List[jax.Array]:
    """lexsort key list (minor..major) implementing Spark ordering."""
    ctx = EvalContext.for_device(table)
    keys: List[jax.Array] = []
    for o in reversed(list(orders)):
        c = o.expr.eval(ctx)
        v = c.values
        if jnp.issubdtype(v.dtype, jnp.floating):
            nan = jnp.isnan(v)
            v = jnp.where(v == 0, jnp.zeros_like(v), v)       # -0.0 -> 0.0
            v = jnp.where(nan, jnp.full_like(v, jnp.inf), v)  # NaN sorts high
            nan_key = nan  # among +inf ties, NaN after true inf
            if not o.ascending:
                v = -v
                nan_key = jnp.logical_not(nan)
            keys.append(nan_key)
            keys.append(v)
        elif v.ndim == 2:  # string/binary: packed uint64 surrogate words
            from ..columnar.device import pack_string_key_words
            words = pack_string_key_words(v, c.lengths)
            if not o.ascending:  # bit inversion reverses unsigned order
                words = [~w for w in words]
            for wd in reversed(words):  # append LSW first; MSW nearest null key
                keys.append(wd)
        elif v.dtype == jnp.bool_:
            keys.append(v != o.ascending)
        else:
            keys.append(v if o.ascending else -v)
        valid = c.validity
        if valid is None:
            valid = jnp.ones(table.capacity, dtype=bool)
        null = jnp.logical_not(valid)
        # nulls_first: null sorts as 0 (before valid=1); else after
        null_key = jnp.logical_not(null) if o.nulls_first else null
        keys.append(null_key)
    # primary: active rows first
    keys.append(jnp.logical_not(table.row_mask))
    return keys


def device_sort_table(table: DeviceTable, orders: Sequence[SortOrder]) -> DeviceTable:
    keys = _order_keys(table, orders)
    order = jnp.lexsort(tuple(keys))
    cols = tuple(c.gather(order) for c in table.columns)
    iota = jnp.arange(table.capacity, dtype=jnp.int32)
    mask = iota < table.num_rows
    return DeviceTable(cols, mask, table.num_rows, table.names)


class TpuSortExec(TpuExec):
    def __init__(self, child: PhysicalPlan, orders: Sequence[SortOrder]):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.orders = list(orders)
        self.schema = child.schema

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        batches = list(self.child_device_batches(pidx))
        if not batches:
            return
        table = concat_device_tables(batches) if len(batches) > 1 else batches[0]
        from ..utils.compile_cache import cached_jit
        orders = self.orders
        fn = cached_jit(self.plan_signature(),
                        lambda: (lambda t: device_sort_table(t, orders)))
        with self.metrics.timed(M.SORT_TIME):
            yield fn(table)

    def node_desc(self):
        return ", ".join(f"{o.expr!r} {'ASC' if o.ascending else 'DESC'}"
                         for o in self.orders)
