"""TpuShuffleExchangeExec — the planner-reachable device (ICI) exchange tier.

Reference mapping: GpuShuffleExchangeExecBase.scala:146 (device exchange
exec) + GpuPartitioning.sliceInternalOnGpu (GpuPartitioning.scala:49,130).
The TPU-native design replaces per-partition slicing + transport with ONE
``jax.lax.all_to_all`` over the mesh's ``dp`` axis (shuffle/ici.py): rows are
re-homed across ICI links inside a single XLA program, no host staging.

Right-sized quotas: a cheap count pass (download of the int32 partition-id
vector only) sizes the per-(source, destination) slot quota before the
exchange compiles, killing the n_devices× intermediate blowup of the naive
static shape. Quotas are bucketed so repeated exchanges reuse the cached XLA
program. The count pass runs on the coordinating process — the analogue of
the reference's driver-side sampling for range bounds (GpuRangePartitioner).

The host-staged ``ShuffleExchangeExec`` (plan/physical.py) remains the
always-available tier, exactly like the reference's default-Spark-shuffle
mode vs the RapidsShuffleManager (SURVEY §2.7).
"""
from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.device import (DeviceColumn, DeviceTable, bucket_rows,
                               resolve_min_bucket, shard_row_counts,
                               concat_device_tables)
from ..conf import register_conf
from ..plan.physical import HashPartitioning, PhysicalPlan
from ..shuffle import telemetry as shuffle_telemetry
from ..utils import metrics as M
from ..utils import movement
from .base import TpuExec

__all__ = ["TpuShuffleExchangeExec", "TpuLocalExchangeExec", "SHUFFLE_MODE",
           "pad_table_capacity"]

SHUFFLE_MODE = register_conf(
    "spark.rapids.tpu.shuffle.mode",
    "Shuffle exchange tier: 'auto' uses the on-device ICI all-to-all when "
    "the session has a device mesh attached and the device-local coalesce "
    "when it does not (single chip); 'ici' builds a mesh over all "
    "addressable devices; 'local' forces the single-device coalesce tier; "
    "'host' forces the host-staged tier (reference: rapids shuffle manager "
    "vs default Spark shuffle, SURVEY §2.7).", "auto",
    checker=lambda v: None if v in ("auto", "host", "ici", "local")
    else f"must be one of auto/host/ici/local, got {v!r}")

# movement-observatory site identities (utils/movement.py SITES)
_MOVE_CHUNK = ("spark_rapids_tpu/exec/exchange.py"
               "::TpuShuffleExchangeExec._exchange_chunk")

# shuffle-observatory identities for planner exchanges: a process-wide
# counter (manager shuffle ids are per-manager and the planner tiers
# never allocate one)
_EXCHANGE_IDS = __import__("itertools").count()
EXCHANGE_CHUNK_ROWS = register_conf(
    "spark.rapids.tpu.shuffle.exchangeChunkRows",
    "Max staged row capacity per device-exchange chunk. Child batches "
    "stream through the ICI all-to-all in bounded chunks instead of one "
    "concat of the entire input, so the exchange stays out-of-core: only "
    "one chunk is staged on devices at a time and finished output shards "
    "can spill (reference: the streaming per-batch exchange, "
    "GpuShuffleExchangeExecBase.scala:146).", 1 << 19,
    checker=lambda v: None if int(v) > 0 else "must be positive")


def pad_table_capacity(table: DeviceTable, capacity: int) -> DeviceTable:
    """Grow a table's padded capacity (new slots masked off)."""
    if capacity <= table.capacity:
        return table
    extra = capacity - table.capacity

    def pad_col(c: DeviceColumn) -> DeviceColumn:
        pad_width = ((0, extra),) + ((0, 0),) * (c.data.ndim - 1)
        return DeviceColumn(
            jnp.pad(c.data, pad_width),
            jnp.pad(c.validity, (0, extra)), c.dtype,
            None if c.lengths is None else jnp.pad(c.lengths, (0, extra)),
            None if c.elem_validity is None
            else jnp.pad(c.elem_validity, ((0, extra), (0, 0))),
            None if c.children is None
            else tuple(pad_col(k) for k in c.children))

    return DeviceTable(tuple(pad_col(c) for c in table.columns),
                       jnp.pad(table.row_mask, (0, extra)),
                       table.num_rows, table.names)


class TpuShuffleExchangeExec(TpuExec):
    """Hash exchange as a mesh collective; output partition = mesh shard."""

    EXTRA_METRICS = (M.SHUFFLE_BYTES, M.PIPELINE_WAIT)

    def __init__(self, child: PhysicalPlan, partitioning: HashPartitioning,
                 mesh, min_bucket: Optional[int] = None, axis: str = "dp",
                 chunk_rows: int = 1 << 19):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.partitioning = partitioning
        self.mesh = mesh
        self.axis = axis
        self.min_bucket = resolve_min_bucket(min_bucket)
        self.chunk_rows = max(int(chunk_rows), 1)
        self.schema = child.schema
        self.telemetry_sid = next(_EXCHANGE_IDS)
        # spill handles per partition, one per exchanged chunk
        self._shards: Optional[List[List]] = None
        # keep-sharded mode (exec/mesh.py): a mesh-capable consumer takes
        # the exchanged output STILL row-sharded over the mesh — no
        # _split_sharded, no per-shard spill registration; the chunk
        # tables live here until the mesh stage dispatches over them (or
        # a per-partition consumer forces a late split, _ensure_split)
        self._keep_sharded = False
        self._sharded_chunks: Optional[List[DeviceTable]] = None
        # per-chunk, per-shard input row counts (host ints — the batched
        # count sync pays for them anyway): the mesh stage uses them to
        # mirror the split path's non-empty-shard-only drain contract
        self._sharded_chunk_rows: Optional[List[List[int]]] = None
        # v7 skew telemetry: per-output-partition rows (free — the bulk
        # shard_rows sync) and byte estimates accumulated across chunks;
        # the event log turns this into a shuffle_skew record
        self._skew_rows: Optional[List[int]] = None
        self._skew_bytes: Optional[List[int]] = None
        # pipelined partition drains race to materialize; exactly one wins
        # (parallel/pipeline.py pipelined_collect contract)
        self._mat_lock = __import__("threading").Lock()

    @property
    def num_partitions(self) -> int:
        return int(self.mesh.shape[self.axis])

    def node_desc(self) -> str:
        return (f"ici keys={self.partitioning.key_names} "
                f"n={self.num_partitions}")

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        self._materialize()
        self._ensure_split()
        from ..io.file_block import clear_input_file
        clear_input_file()  # post-shuffle rows have no single source file
        for handle in self._shards[pidx]:
            yield handle.get()

    # -- keep-sharded consumer API (exec/mesh.py) -----------------------------
    def request_keep_sharded(self) -> None:
        """Planner hook: the consumer is mesh-capable, so materialization
        should keep exchanged chunks row-sharded over the mesh instead of
        splitting them into per-device spill-registered partitions. Must
        be called before the exchange materializes (plan rewrite time)."""
        self._keep_sharded = True

    def sharded_chunks(self) -> Optional[List[tuple]]:
        """Materialize and return ``(chunk, shard_rows)`` pairs — each
        exchanged chunk table still row-sharded over the mesh ``dp``
        axis (one entry per streamed chunk) with its per-shard input row
        counts (host ints, from the chunk's batched count sync). Returns
        None when the output already split per-partition (keep-sharded
        was never requested, or a per-partition consumer forced the
        split first) — the caller must use the per-partition
        ``execute_columnar`` path instead."""
        self._materialize()
        with self._mat_lock:
            if self._shards is not None:
                return None
            return list(zip(self._sharded_chunks or [],
                            self._sharded_chunk_rows or []))

    def _ensure_split(self) -> None:
        """Late per-partition conversion of keep-sharded output: a
        non-mesh consumer (the mesh stage's fallback path, or a plan that
        reused the exchange) needs spill-registered per-device shards
        after all."""
        if self._shards is not None:
            return
        with self._mat_lock:
            if self._shards is not None:
                return
            # registration's budget check can spill; never block on the
            # semaphore while holding this shared lock (PR-3 class)
            from ..parallel.pipeline import exempt_admission
            with exempt_admission():
                chunks, self._sharded_chunks = self._sharded_chunks, None
                self._sharded_chunk_rows = None
                n = self.num_partitions
                shards: List[List] = [[] for _ in range(n)]
                for t in chunks or []:
                    self._register_split(t, shards)
                self._shards = shards

    # -- the exchange ---------------------------------------------------------
    def _materialize(self) -> None:
        """Stream child batches through the all-to-all in bounded chunks.

        Only one chunk's input is staged on devices at a time (the in-
        flight chunk is catalog-registered at ACTIVE priority so earlier
        output shards spill first when the budget tightens), keeping the
        exchange out-of-core — the operator that sees the most data must
        not require the whole input resident (reference: per-batch
        streaming in GpuShuffleExchangeExecBase.scala:146)."""
        with self._mat_lock:
            if self._shards is not None or self._sharded_chunks is not None:
                return
            # never block on the semaphore while holding this shared lock
            # (parallel/pipeline.py exempt_admission invariant)
            from ..parallel.pipeline import exempt_admission
            with exempt_admission():
                self._materialize_locked()

    def _materialize_locked(self) -> None:
        from ..parallel.pipeline import maybe_prefetched
        n = self.num_partitions
        shards: List[List] = [[] for _ in range(n)]
        if self._keep_sharded:
            self._sharded_chunks = []
            self._sharded_chunk_rows = []
        self._skew_rows = [0] * n
        self._skew_bytes = [0] * n
        total_rows = 0
        # NOTE: child batch consumption stays OUTSIDE the op timer — the
        # upstream pipeline accounts its own opTime; only the exchange
        # work (concat/count/all-to-all, inside _exchange_chunk) is ours
        pending: List[DeviceTable] = []
        staged = 0

        def all_child_batches():
            """Map-side production across every input partition; the ICI
            collective itself must stay on one thread, so the overlap is a
            bounded prefetch of child batches under it."""
            for p in range(self.child.num_partitions):  # srtpu: mesh-ok(map-side INPUT production: upstream partitions stream into the collective, the ICI all-to-all itself runs mesh-wide)
                yield from self.child_device_batches(p)

        batches = maybe_prefetched(all_child_batches, stage="shuffle_map",
                                   registry=self.metrics)
        for b in batches:
            # no per-batch row-count sync here: int(b.num_rows) would
            # block the map loop on every upstream batch (ROADMAP item
            # 1). All-masked batches flow through — the count pass parks
            # their rows and the quota ignores them.
            if not b.capacity:
                continue
            pending.append(b)
            staged += b.capacity
            if staged >= self.chunk_rows:
                total_rows += self._exchange_chunk(pending, shards)
                pending, staged = [], 0
        if pending:
            total_rows += self._exchange_chunk(pending, shards)
        if self._keep_sharded:
            # output stays one sharded table per chunk (the mesh stage
            # dispatches over all shards at once); _shards stays None
            # until a per-partition consumer forces _ensure_split
            self.metrics.add(M.NUM_OUTPUT_BATCHES,
                             len(self._sharded_chunks))
        else:
            self._shards = shards
            self.metrics.add(M.NUM_OUTPUT_BATCHES,
                             sum(len(s) for s in shards))
        self.metrics.add(M.NUM_OUTPUT_ROWS, total_rows)

    def _exchange_chunk(self, batches: List[DeviceTable],
                        shards: List[List]) -> int:
        """All-to-all one bounded chunk; append per-partition spill handles.

        Only this method sits inside the op timer — child batch
        production accounts its own opTime upstream."""
        from ..memory.catalog import SpillPriorities, get_catalog
        from ..shuffle.ici import ici_all_to_all_exchange, shard_table
        from ..shuffle.manager import device_partition_ids

        n = self.num_partitions
        catalog = get_catalog()
        with self.metrics.timed(M.OP_TIME):
            table = concat_device_tables(batches, self.min_bucket)
            chunk_nbytes = table.nbytes()
            self.metrics.add(M.SHUFFLE_BYTES, chunk_nbytes)
            # observatory enqueue note mirrors the shuffleBytes metric
            # exactly (pre-padding logical bytes), so the shuffle_summary
            # tier breakdown reconciles with the operator metric
            shuffle_telemetry.note_transfer(
                "ici", "enqueue", shuffle_id=self.telemetry_sid,
                logical_bytes=chunk_nbytes)
            per_shard = bucket_rows(
                max(1, -(-table.capacity // n)), self.min_bucket)
            table = pad_table_capacity(table, per_shard * n)
            # account the in-flight chunk: registration's budget check
            # spills already-finished output shards down-tier to make room
            inflight = catalog.register(table,
                                        SpillPriorities.ACTIVE_ON_DECK)
            try:
                # count pass: partition ids only (4 bytes/row) -> quota
                keys = self.partitioning.key_names
                pid = jax.jit(lambda t: jnp.where(
                    t.row_mask, device_partition_ids(t, keys, n), n))(table)
                t0 = movement.clock()
                pid_host = np.asarray(jax.device_get(pid))  # srtpu: sync-ok(the deliberate partition-count funnel: one transfer sizes every shard buffer for the chunk)
                movement.note_d2h(_MOVE_CHUNK, pid_host.nbytes, t0)
                src = np.arange(table.capacity) // per_shard
                active = pid_host < n
                counts = np.zeros((n, n), dtype=np.int64)
                np.add.at(counts, (src[active], pid_host[active]), 1)
                max_cnt = int(counts.max()) if active.any() else 1
                quota = min(per_shard, bucket_rows(max_cnt, self.min_bucket))

                sharded = shard_table(table, self.mesh, self.axis)
                del table, batches
                exchanged = ici_all_to_all_exchange(
                    sharded, keys, self.mesh, self.axis, quota=quota,
                    telemetry_sid=self.telemetry_sid)
                if self._keep_sharded and self._sharded_chunks:
                    # a SECOND chunk is streaming: kept-sharded chunks
                    # are not spill-registered, so accumulating them
                    # would break the exchange's out-of-core contract
                    # (only one chunk's worth resident, earlier output
                    # spillable). The contract wins — revert to split
                    # mode, registering the kept chunk; the mesh stage
                    # sees sharded_chunks() == None and falls back to
                    # the per-partition path (exec/mesh.py)
                    self._keep_sharded = False
                    kept, self._sharded_chunks = self._sharded_chunks, None
                    self._sharded_chunk_rows = None
                    for t in kept:
                        self._register_split(t, shards)
                if self._keep_sharded:
                    # mesh-capable consumer: the chunk stays ONE sharded
                    # table (no split, no per-shard spill registration —
                    # the mesh stage dispatches over it next); only the
                    # per-destination row counts sync, for skew + quota
                    # telemetry parity with the split path
                    t0 = movement.clock()
                    shard_rows = jax.device_get(  # srtpu: sync-ok(batched count sync, 4B per shard once per chunk)
                        shard_row_counts(exchanged, n))
                    movement.note_d2h(_MOVE_CHUNK, 4 * len(shard_rows), t0)
                    self._sharded_chunks.append(exchanged)
                    self._sharded_chunk_rows.append(
                        [int(c) for c in shard_rows])
                else:
                    shard_rows = self._register_split(exchanged, shards)
                # v7 skew: per-destination rows come free with the bulk
                # count sync; bytes are estimated as rows × the chunk's
                # mean row width (per-shard padded nbytes would read
                # uniform regardless of the actual distribution)
                chunk_total = int(sum(int(c) for c in shard_rows))
                bpr = chunk_nbytes / max(1, chunk_total)
                for i, cnt in enumerate(shard_rows):
                    self._skew_rows[i] += int(cnt)
                    self._skew_bytes[i] += int(round(int(cnt) * bpr))
                return chunk_total
            finally:
                inflight.close()

    def _register_split(self, exchanged: DeviceTable,
                        shards: List[List]) -> List[int]:
        """Split one exchanged chunk into per-device partition views and
        spill-register each non-empty shard so the catalog accounts for
        them and can spill them until downstream consumption; the entries
        release at query end (release_spill_handles), with a GC finalizer
        fallback. Returns the per-shard row counts."""
        from ..memory.catalog import SpillPriorities, get_catalog
        catalog = get_catalog()
        n = self.num_partitions
        parts = _split_sharded(exchanged, n)
        # ONE bulk D2H of n 4-byte scalars replaces a blocking round
        # trip per shard plus one more for the row total
        t0 = movement.clock()
        shard_rows = jax.device_get(  # srtpu: sync-ok(batched count sync, 4B per shard once per chunk)
            [t.num_rows for t in parts])
        movement.note_d2h(_MOVE_CHUNK, 4 * len(shard_rows), t0)
        for i, (t, cnt) in enumerate(zip(parts, shard_rows)):
            if not int(cnt):
                continue
            h = catalog.register(t, SpillPriorities.OUTPUT_FOR_SHUFFLE)
            self._own_spill_handle(h)
            shards[i].append(h)
        return [int(c) for c in shard_rows]

    def shuffle_skew(self) -> Optional[dict]:
        """v7 event-log payload: the per-output-partition row/byte
        distribution accumulated across exchanged chunks. None until the
        exchange materialized (skew records only describe work done)."""
        if self._skew_rows is None:
            return None
        from ..utils.metrics import build_skew_record
        return build_skew_record(self._skew_rows, self._skew_bytes)


class TpuLocalExchangeExec(TpuExec):
    """Single-chip device-resident exchange: the whole input coalesces into
    ONE spill-registered output partition, never leaving the device.

    With one addressable chip there is no locality to exploit and no
    transport to ride: hash, range and single partitioning contracts are
    all trivially satisfied by a single output partition (all rows of any
    key land together; global order is whatever the downstream sort makes
    of its one partition). The host-staged tier's download-partition-upload
    round trip — the single largest overhead of single-chip plans — is
    gone; out-of-core pressure is handled downstream (grace join, OOC
    sort/agg) and by the catalog spill handles held here.

    The local analogue of Spark AQE's local shuffle reader; tier selection
    mirrors the reference's RapidsShuffleManager vs default-Spark-shuffle
    split (SURVEY §2.7; GpuShuffleExchangeExecBase.scala:146)."""

    EXTRA_METRICS = (M.SHUFFLE_BYTES,)

    def __init__(self, child: PhysicalPlan, partitioning,
                 min_bucket: Optional[int] = None):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.partitioning = partitioning
        self.min_bucket = resolve_min_bucket(min_bucket)
        self.schema = child.schema
        self.telemetry_sid = next(_EXCHANGE_IDS)
        self._handles: Optional[List] = None
        # v7 skew telemetry: one output partition, so the distribution is
        # trivially balanced — recorded anyway for a uniform record set
        self._skew: Optional[tuple] = None
        self._mat_lock = __import__("threading").Lock()

    @property
    def num_partitions(self) -> int:
        return 1

    def node_desc(self) -> str:
        return "local n=1"

    def _materialize(self) -> None:
        with self._mat_lock:
            if self._handles is not None:
                return
            from ..parallel.pipeline import exempt_admission
            with exempt_admission():
                self._materialize_locked()

    def _materialize_locked(self) -> None:
        from ..memory.catalog import SpillPriorities, get_catalog
        from ..parallel.pipeline import parallel_map
        catalog = get_catalog()
        from ..columnar.device import resolve_scalars, shrink_to_fit
        # node context is thread-local; drain() runs on pool workers, so
        # capture the query identity here (the materializing thread holds
        # the instrumented node scope) and attribute notes explicitly
        from ..utils import node_context
        _ctx = node_context.current()
        _qid = _ctx.query_id if _ctx is not None else None

        def drain(p: int):
            """One map-side partition: drain, compact, spill-register.
            Runs per-partition on the bounded task pool (parallel map-side
            writes) — the catalog and metric registries are thread-safe."""
            out = []
            batches = list(self.child_device_batches(p))
            if not batches:
                return out
            # ONE batched-funnel transfer resolves every map batch's row
            # count for the partition (was one 4B sync per batch); every
            # batch's compute has dispatched before the host blocks
            ns = resolve_scalars(*[b.num_rows for b in batches])
            for b, n in zip(batches, ns):
                n = int(n)
                if not n:
                    continue
                with self.metrics.timed(M.OP_TIME):
                    # the exchange is a compaction point (design rule 2 in
                    # columnar/device.py): post-filter / fused-partial-agg
                    # batches can be mostly masked slack — forwarding full
                    # capacity would inflate every downstream kernel
                    shrunk = shrink_to_fit(b, self.min_bucket, num_rows=n)
                    nbytes = shrunk.nbytes()
                    self.metrics.add(M.SHUFFLE_BYTES, nbytes)
                    # mirrors the shuffleBytes metric add exactly so the
                    # shuffle_summary tier bytes reconcile with it
                    shuffle_telemetry.note_transfer(
                        "local", "enqueue",
                        shuffle_id=self.telemetry_sid, partition=p,
                        logical_bytes=nbytes, query_id=_qid)
                    h = catalog.register(
                        shrunk, SpillPriorities.OUTPUT_FOR_SHUFFLE)
                self._own_spill_handle(h)
                out.append((h, n, nbytes))
            return out

        per_part = parallel_map(drain, range(self.child.num_partitions),
                                stage="local_exchange_map")
        handles: List = [h for part in per_part for h, _n, _b in part]
        rows = sum(n for part in per_part for _h, n, _b in part)
        nbytes = sum(b for part in per_part for _h, _n, b in part)
        self._handles = handles
        self._skew = ([rows], [nbytes])
        self.metrics.add(M.NUM_OUTPUT_BATCHES, len(handles))
        self.metrics.add(M.NUM_OUTPUT_ROWS, rows)

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        self._materialize()
        from ..io.file_block import clear_input_file
        clear_input_file()  # post-shuffle rows have no single source file
        for handle in self._handles:
            yield handle.get()

    def shuffle_skew(self) -> Optional[dict]:
        """v7 event-log payload (single-partition tier: imbalance 1.0)."""
        if self._skew is None:
            return None
        from ..utils.metrics import build_skew_record
        return build_skew_record(*self._skew)


def _split_sharded(table: DeviceTable, n: int) -> List[Optional[DeviceTable]]:
    """Per-shard views of a row-sharded table (zero-copy: each output batch
    is the addressable shard living on its own device)."""

    def parts(arr: jax.Array) -> List[jax.Array]:
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        assert len(shards) == n, f"{len(shards)} shards, expected {n}"
        return [s.data for s in shards]

    mask_parts = parts(table.row_mask)

    def split_col(c: DeviceColumn) -> List[DeviceColumn]:
        d = parts(c.data)
        v = parts(c.validity)
        l = None if c.lengths is None else parts(c.lengths)
        e = None if c.elem_validity is None else parts(c.elem_validity)
        kids = None if c.children is None \
            else [split_col(k) for k in c.children]
        return [DeviceColumn(d[i], v[i], c.dtype,
                             None if l is None else l[i],
                             None if e is None else e[i],
                             None if kids is None
                             else tuple(ks[i] for ks in kids))
                for i in range(n)]

    col_parts = [split_col(c) for c in table.columns]
    out: List[Optional[DeviceTable]] = []
    for i in range(n):
        cols = tuple(cp[i] for cp in col_parts)
        mask = mask_parts[i]
        out.append(DeviceTable(cols, mask, jnp.sum(mask, dtype=jnp.int32),
                               table.names))
    return out
