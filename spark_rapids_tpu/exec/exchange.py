"""TpuShuffleExchangeExec — the planner-reachable device (ICI) exchange tier.

Reference mapping: GpuShuffleExchangeExecBase.scala:146 (device exchange
exec) + GpuPartitioning.sliceInternalOnGpu (GpuPartitioning.scala:49,130).
The TPU-native design replaces per-partition slicing + transport with ONE
``jax.lax.all_to_all`` over the mesh's ``dp`` axis (shuffle/ici.py): rows are
re-homed across ICI links inside a single XLA program, no host staging.

Right-sized quotas: a cheap count pass (download of the int32 partition-id
vector only) sizes the per-(source, destination) slot quota before the
exchange compiles, killing the n_devices× intermediate blowup of the naive
static shape. Quotas are bucketed so repeated exchanges reuse the cached XLA
program. The count pass runs on the coordinating process — the analogue of
the reference's driver-side sampling for range bounds (GpuRangePartitioner).

The host-staged ``ShuffleExchangeExec`` (plan/physical.py) remains the
always-available tier, exactly like the reference's default-Spark-shuffle
mode vs the RapidsShuffleManager (SURVEY §2.7).
"""
from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.device import (DeviceColumn, DeviceTable, bucket_rows,
                               concat_device_tables)
from ..conf import register_conf
from ..plan.physical import HashPartitioning, PhysicalPlan
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuShuffleExchangeExec", "SHUFFLE_MODE", "pad_table_capacity"]

SHUFFLE_MODE = register_conf(
    "spark.rapids.tpu.shuffle.mode",
    "Shuffle exchange tier: 'auto' uses the on-device ICI all-to-all when "
    "the session has a device mesh attached, else the host-staged exchange; "
    "'ici' builds a mesh over all addressable devices; 'host' forces the "
    "host-staged tier (reference: rapids shuffle manager vs default Spark "
    "shuffle, SURVEY §2.7).", "auto",
    checker=lambda v: None if v in ("auto", "host", "ici")
    else f"must be one of auto/host/ici, got {v!r}")


def pad_table_capacity(table: DeviceTable, capacity: int) -> DeviceTable:
    """Grow a table's padded capacity (new slots masked off)."""
    if capacity <= table.capacity:
        return table
    extra = capacity - table.capacity

    def pad_col(c: DeviceColumn) -> DeviceColumn:
        pad_width = ((0, extra),) + ((0, 0),) * (c.data.ndim - 1)
        return DeviceColumn(
            jnp.pad(c.data, pad_width),
            jnp.pad(c.validity, (0, extra)), c.dtype,
            None if c.lengths is None else jnp.pad(c.lengths, (0, extra)))

    return DeviceTable(tuple(pad_col(c) for c in table.columns),
                       jnp.pad(table.row_mask, (0, extra)),
                       table.num_rows, table.names)


class TpuShuffleExchangeExec(TpuExec):
    """Hash exchange as a mesh collective; output partition = mesh shard."""

    def __init__(self, child: PhysicalPlan, partitioning: HashPartitioning,
                 mesh, min_bucket: int = 1024, axis: str = "dp"):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.partitioning = partitioning
        self.mesh = mesh
        self.axis = axis
        self.min_bucket = min_bucket
        self.schema = child.schema
        self._shards: Optional[List] = None  # spill handles per partition

    @property
    def num_partitions(self) -> int:
        return int(self.mesh.shape[self.axis])

    def node_desc(self) -> str:
        return (f"ici keys={self.partitioning.key_names} "
                f"n={self.num_partitions}")

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        self._materialize()
        from ..io.file_block import clear_input_file
        clear_input_file()  # post-shuffle rows have no single source file
        handle = self._shards[pidx]
        if handle is not None:
            yield handle.get()

    # -- the exchange ---------------------------------------------------------
    def _materialize(self) -> None:
        if self._shards is not None:
            return
        from ..shuffle.ici import ici_all_to_all_exchange, shard_table

        n = self.num_partitions
        batches: List[DeviceTable] = []
        for p in range(self.child.num_partitions):
            batches.extend(self.child_device_batches(p))
        if not batches:
            self._shards = [None] * n
            return
        with self.metrics.timed(M.OP_TIME):
            table = concat_device_tables(batches, self.min_bucket)
            per_shard = bucket_rows(
                max(1, -(-table.capacity // n)), self.min_bucket)
            table = pad_table_capacity(table, per_shard * n)

            # count pass: partition ids only (4 bytes/row) -> quota
            from ..shuffle.manager import device_partition_ids
            keys = self.partitioning.key_names
            pid = jax.jit(lambda t: jnp.where(
                t.row_mask, device_partition_ids(t, keys, n), n))(table)
            pid_host = np.asarray(jax.device_get(pid))
            src = np.arange(table.capacity) // per_shard
            active = pid_host < n
            counts = np.zeros((n, n), dtype=np.int64)
            np.add.at(counts, (src[active], pid_host[active]), 1)
            max_cnt = int(counts.max()) if active.any() else 1
            quota = min(per_shard, bucket_rows(max_cnt, self.min_bucket))

            sharded = shard_table(table, self.mesh, self.axis)
            del table, batches
            exchanged = ici_all_to_all_exchange(
                sharded, keys, self.mesh, self.axis, quota=quota)
            # register output shards so the catalog accounts for them and can
            # spill them after downstream consumption; finalizer releases the
            # entries when the plan is garbage-collected
            import weakref
            from ..memory.catalog import SpillPriorities, get_catalog
            catalog = get_catalog()
            shards = []
            for t in _split_sharded(exchanged, n):
                h = catalog.register(t, SpillPriorities.OUTPUT_FOR_SHUFFLE)
                weakref.finalize(self, _close_quietly, h)
                shards.append(h)
            self._shards = shards
        self.metrics.add(M.NUM_OUTPUT_BATCHES, n)
        self.metrics.add(M.NUM_OUTPUT_ROWS, int(jnp.sum(exchanged.row_mask)))


def _close_quietly(handle):
    try:
        handle.close()
    except Exception:
        pass


def _split_sharded(table: DeviceTable, n: int) -> List[Optional[DeviceTable]]:
    """Per-shard views of a row-sharded table (zero-copy: each output batch
    is the addressable shard living on its own device)."""

    def parts(arr: jax.Array) -> List[jax.Array]:
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        assert len(shards) == n, f"{len(shards)} shards, expected {n}"
        return [s.data for s in shards]

    mask_parts = parts(table.row_mask)
    col_parts = []
    for c in table.columns:
        col_parts.append((parts(c.data), parts(c.validity),
                          None if c.lengths is None else parts(c.lengths)))
    out: List[Optional[DeviceTable]] = []
    for i in range(n):
        cols = tuple(
            DeviceColumn(d[i], v[i], c.dtype, None if l is None else l[i])
            for (d, v, l), c in zip(col_parts, table.columns))
        mask = mask_parts[i]
        out.append(DeviceTable(cols, mask, jnp.sum(mask, dtype=jnp.int32),
                               table.names))
    return out
