"""Cached (materialized) scans — device-resident df.cache().

Reference analogue: ParquetCachedBatchSerializer (SURVEY §2.8) lets
``df.cache()`` keep columnar batches on device. Here the cache stores
DeviceTables keyed by partition in a storage object owned by the *logical*
plan node, so repeated executions of the same DataFrame skip upload and
upstream compute entirely. The CPU engine caches HostTables symmetrically.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional

from ..columnar.device import DeviceTable
from ..columnar.host import HostTable
from ..plan.physical import PhysicalPlan
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["CacheStorage", "CpuCacheExec", "TpuCacheExec"]


class CacheStorage:
    def __init__(self):
        self.host: Dict[int, List[HostTable]] = {}
        # device entries are SpillableDeviceTable handles (memory/catalog.py)
        self.device: Dict[int, list] = {}

    def clear(self):
        self.host.clear()
        for handles in self.device.values():
            for h in handles:
                h.close()
        self.device.clear()


class CpuCacheExec(PhysicalPlan):
    def __init__(self, child: PhysicalPlan, storage: CacheStorage):
        self.child = child
        self.children = (child,)
        self.storage = storage
        self.schema = child.schema

    def execute(self, pidx: int) -> Iterator[HostTable]:
        cached = self.storage.host.get(pidx)
        if cached is not None:
            yield from cached
            return
        acc: List[HostTable] = []
        for b in self.child.execute(pidx):
            acc.append(b)
            yield b
        self.storage.host[pidx] = acc


class TpuCacheExec(TpuExec):
    """Cached batches are registered with the buffer catalog as spillable
    (priority BROADCAST-level), so cached data yields HBM under pressure and
    transparently restores from host/disk tiers on re-access."""

    def __init__(self, child: PhysicalPlan, storage: CacheStorage):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.storage = storage
        self.schema = child.schema

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        cached = self.storage.device.get(pidx)
        if cached is not None:
            self.metrics.add("cacheHits", 1)
            for handle in cached:
                yield handle.get()
            return
        from ..memory import SpillPriorities, get_catalog
        acc: List[DeviceTable] = []
        for b in self.child_device_batches(pidx):
            acc.append(b)
            yield b
        # register only after a full drain; an abandoned generator (e.g.
        # under a limit) must not leak catalog entries
        catalog = get_catalog()
        self.storage.device[pidx] = [
            catalog.register(b, SpillPriorities.BROADCAST) for b in acc]
