"""Cached (materialized) scans — device-resident df.cache().

Reference analogue: ParquetCachedBatchSerializer (SURVEY §2.8) lets
``df.cache()`` keep columnar batches on device. Here the cache stores
DeviceTables keyed by partition in a storage object owned by the *logical*
plan node, so repeated executions of the same DataFrame skip upload and
upstream compute entirely. The CPU engine caches HostTables symmetrically.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional

from ..columnar.device import DeviceTable
from ..columnar.host import HostTable
from ..conf import register_conf
from ..plan.physical import PhysicalPlan
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["CacheStorage", "CpuCacheExec", "TpuCacheExec",
           "CACHE_COMPRESS_CODEC"]

CACHE_COMPRESS_CODEC = register_conf(
    "spark.rapids.tpu.cache.compressionCodec",
    "Codec for the HOST-side df.cache() storage: 'none' keeps live tables, "
    "'zlib'/'lz4' store compressed serialized frames (reference: "
    "ParquetCachedBatchSerializer's compressed columnar cache format). The "
    "device cache is spillable either way.", "none",
    checker=lambda v: None if v in ("none", "zlib", "lz4")
    else f"must be one of none/zlib/lz4, got {v!r}")


class CacheStorage:
    def __init__(self):
        self.host: Dict[int, List[HostTable]] = {}
        # compressed host cache: serialized frames (ParquetCachedBatch
        # analogue — a compact wire format instead of live objects)
        self.host_blobs: Dict[int, List[bytes]] = {}
        # device entries are SpillableDeviceTable handles (memory/catalog.py)
        self.device: Dict[int, list] = {}

    def clear(self):
        self.host.clear()
        self.host_blobs.clear()
        for handles in self.device.values():
            for h in handles:
                h.close()
        self.device.clear()


class CpuCacheExec(PhysicalPlan):
    """``codec`` != 'none' stores the host cache as compressed serialized
    frames instead of live tables (reference: ParquetCachedBatchSerializer
    keeps df.cache() in a compressed columnar format, SURVEY §2.8)."""

    def __init__(self, child: PhysicalPlan, storage: CacheStorage,
                 codec: str = "none"):
        self.child = child
        self.children = (child,)
        self.storage = storage
        self.codec = codec
        self.schema = child.schema

    def execute(self, pidx: int) -> Iterator[HostTable]:
        from ..shuffle.serializer import deserialize_table, serialize_table
        blobs = self.storage.host_blobs.get(pidx)
        if blobs is not None:
            for blob in blobs:
                yield deserialize_table(blob)
            return
        cached = self.storage.host.get(pidx)
        if cached is not None:
            yield from cached
            return
        acc: List[HostTable] = []
        for b in self.child.execute(pidx):
            acc.append(b)
            yield b
        if self.codec != "none":
            try:
                self.storage.host_blobs[pidx] = [
                    serialize_table(b, self.codec) for b in acc]
                return
            except Exception:
                # unserializable column type (NullType object buffers etc.):
                # caching live tables is always a safe fallback
                self.storage.host_blobs.pop(pidx, None)
        self.storage.host[pidx] = acc


class TpuCacheExec(TpuExec):
    """Cached batches are registered with the buffer catalog as spillable
    (priority BROADCAST-level), so cached data yields HBM under pressure and
    transparently restores from host/disk tiers on re-access."""

    def __init__(self, child: PhysicalPlan, storage: CacheStorage):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.storage = storage
        self.schema = child.schema

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        cached = self.storage.device.get(pidx)
        if cached is not None:
            self.metrics.add("cacheHits", 1)
            for handle in cached:
                self.account_batch()
                yield handle.get()
            return
        from ..memory import SpillPriorities, get_catalog
        from .transitions import take_exclusive
        acc: List[DeviceTable] = []
        for b in self.child_device_batches(pidx):
            # this node RETAINS the batch for re-execution: consume any
            # exclusive-ownership mark BEFORE the consumer sees it, or a
            # donating fused stage downstream would free buffers the cache
            # re-serves on the next collect (exec/transitions.py contract)
            take_exclusive(b)
            acc.append(b)
            self.account_batch()
            yield b
        # register only after a full drain; an abandoned generator (e.g.
        # under a limit) must not leak catalog entries
        catalog = get_catalog()
        self.storage.device[pidx] = [
            catalog.register(b, SpillPriorities.BROADCAST) for b in acc]
