"""Host<->device transitions and batch coalescing.

Reference equivalents:
- ``HostToDeviceExec``   ~ GpuRowToColumnarExec / HostColumnarToGpu
- ``DeviceToHostExec``   ~ GpuColumnarToRowExec / GpuBringBackToHost
- ``TpuCoalesceBatchesExec`` ~ GpuCoalesceBatches (GpuCoalesceBatches.scala:528)

The transition inserter (plan/transitions.py) places these where device
sections start/end, exactly like GpuTransitionOverrides.scala:37.
"""
from __future__ import annotations

import threading
import weakref
from typing import Iterator, List, Optional

from ..columnar.device import (DeviceTable, bucket_rows,
                               concat_device_tables, resolve_min_bucket)
from ..columnar.host import HostTable
from ..conf import register_conf
from ..plan.physical import PhysicalPlan
from ..utils import faults
from ..utils import metrics as M
from ..utils import movement
from ..utils.tracing import get_tracer
from .base import TpuExec

__all__ = ["HostToDeviceExec", "DeviceToHostExec", "TpuCoalesceBatchesExec",
           "clear_upload_cache", "upload_cache_stats", "mark_exclusive",
           "take_exclusive"]

SCAN_DEVICE_CACHE = register_conf(
    "spark.rapids.tpu.scan.deviceCache.enabled",
    "Keep scanned batches device-resident across executions. Sources that "
    "re-yield identical host batches (in-memory tables, cached scans) skip "
    "the host->device re-upload entirely; entries die with their source "
    "batch and a device OOM drops the whole cache. (reference: "
    "ParquetCachedBatchSerializer keeps Spark-cached data as device "
    "batches, com/nvidia/spark/rapids/shims/ParquetCachedBatchSerializer)",
    True)

SCAN_DEVICE_CACHE_MAX_BYTES = register_conf(
    "spark.rapids.tpu.scan.deviceCache.maxBytes",
    "Device-byte budget for the scan upload cache; uploads past the budget "
    "are not cached (data still flows, uncached). 0 disables caching.",
    2 << 30)

COALESCE_AFTER_UPLOAD = register_conf(
    "spark.rapids.tpu.coalesce.afterUpload.enabled",
    "Insert a TpuCoalesceBatchesExec above every host->device upload so "
    "many small scanned batches stitch into full-size device batches "
    "before compute (reference: GpuCoalesceBatches above GpuRowToColumnar "
    "via childrenCoalesceGoal).", False)

COALESCE_TARGET_BYTES = register_conf(
    "spark.rapids.tpu.coalesce.targetBytes",
    "Byte-based flush target for TpuCoalesceBatchesExec, alongside the "
    "row goal: a pending set flushes once its device bytes reach this "
    "bound even when the row target is far away, so wide schemas cannot "
    "accumulate an OOM-sized concat (reference: the TargetSize coalesce "
    "goal is byte-denominated, GpuCoalesceBatches.scala:93-200). "
    "0 disables the byte bound.", 512 * 1024 * 1024,
    checker=lambda v: None if int(v) >= 0 else "must be >= 0")


# ---------------------------------------------------------------------------
# donation-safe hand-off: an uploaded batch that is NOT retained by the
# upload cache is exclusively owned by its consumer, so a fused stage may
# donate its buffers to XLA (exec/wholestage.py donate_argnums) — cutting
# peak HBM per batch. Cached uploads are shared across executions and must
# never be donated. The mark rides the DeviceTable instance (plain
# dataclass) and is consumed exactly once.
# ---------------------------------------------------------------------------
def mark_exclusive(table: DeviceTable, origin: Optional[HostTable] = None,
                   min_bucket: Optional[int] = None) -> DeviceTable:
    table._tpu_exclusive = True
    if origin is not None:
        # donated-input OOM recovery (memory/retry.py wrap_jit_donating):
        # a failed donating dispatch may have consumed the buffers, so the
        # ladder re-materializes from the retained host-side origin — the
        # host batch is alive for the duration of the consumer's dispatch
        # anyway, so this pins no extra memory
        table._tpu_remat = lambda: DeviceTable.from_host(origin, min_bucket)  # srtpu: retry-ok(this lambda IS the ladder's recovery hook — wrap_jit_donating invokes it from inside the retry scope after spilling) srtpu: memtrack-ok(the fresh table replaces a donated batch inside the consuming dispatch and dies with it — never long-lived HBM)
    return table


def take_exclusive(table: DeviceTable) -> bool:
    """True once per exclusively-owned batch (clears the mark: after the
    consumer donates — or declines to — the buffers are no longer safely
    donatable by anyone else)."""
    if getattr(table, "_tpu_exclusive", False):
        table._tpu_exclusive = False
        return True
    return False

# Upload memoization keyed by host-batch IDENTITY (HostTable is mutable-ish
# and unhashable; identity is the right equivalence anyway — sources that
# cache decoded batches re-yield the same objects). A weakref death-callback
# removes the entry the moment its source batch is collected, so a recycled
# id() can never alias a stale upload.
#
# All cache state is guarded by _UPLOAD_LOCK. It must be an RLock: the
# weakref death-callback can fire from a GC pass triggered at any
# allocation, including while this thread already holds the lock. Lock
# order is catalog lock -> _UPLOAD_LOCK (the catalog reads cached bytes
# under its own lock); nothing here calls into the catalog while holding
# _UPLOAD_LOCK.
_UPLOAD_LOCK = threading.RLock()
_UPLOAD_CACHE: dict = {}   # id(batch) -> (weakref, {min_bucket: DeviceTable})
_CACHED_BYTES = 0          # running device-byte total of cached uploads
_CACHE_HITS = 0
_CACHE_INSERTS = 0
_CACHE_EVICTIONS = 0
_OOM_HOOKED = False


def _cached_bytes() -> int:
    with _UPLOAD_LOCK:
        return _CACHED_BYTES


def _drop_entry(key: int) -> None:
    """Weakref death-callback: remove a dead batch's uploads and keep the
    running byte counter consistent."""
    global _CACHED_BYTES, _CACHE_EVICTIONS
    with _UPLOAD_LOCK:
        entry = _UPLOAD_CACHE.pop(key, None)
        if entry is not None:
            _CACHED_BYTES -= sum(dt.nbytes() for dt in entry[1].values())
            _CACHE_EVICTIONS += 1


def clear_upload_cache() -> int:
    """Drop all device-resident scan uploads; returns bytes released."""
    global _CACHED_BYTES
    with _UPLOAD_LOCK:
        freed = _CACHED_BYTES
        _UPLOAD_CACHE.clear()
        _CACHED_BYTES = 0
    return freed


def upload_cache_stats() -> dict:
    """Process-wide upload-cache counters (feeds utils.metrics.StatsRegistry
    and per-query event-log deltas)."""
    with _UPLOAD_LOCK:
        return {"entries": len(_UPLOAD_CACHE), "bytes": _CACHED_BYTES,
                "hits": _CACHE_HITS, "inserts": _CACHE_INSERTS,
                "evictions": _CACHE_EVICTIONS}


def _hook_oom() -> None:
    """Register the cache with the buffer catalog: droppable on device OOM,
    and its device bytes visible to the catalog's peak/OOM accounting."""
    global _OOM_HOOKED
    if _OOM_HOOKED:
        return
    from ..memory.catalog import get_catalog
    cat = get_catalog()
    cat.register_oom_callback(clear_upload_cache)
    cat.register_external_bytes("upload_cache", _cached_bytes)
    _OOM_HOOKED = True


# movement-observatory site identity (utils/movement.py SITES)
_MOVE_UPLOAD = ("spark_rapids_tpu/exec/transitions.py"
                "::HostToDeviceExec._upload_retryable")


class HostToDeviceExec(TpuExec):
    EXTRA_METRICS = (M.UPLOAD_TIME, M.UPLOAD_BYTES, M.UPLOAD_CACHE_HITS,
                     M.PIPELINE_WAIT)

    def __init__(self, child: PhysicalPlan, min_bucket: Optional[int] = None,
                 cache_max_bytes: int = 0):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.schema = child.schema
        self.min_bucket = resolve_min_bucket(min_bucket)
        self.cache_max_bytes = cache_max_bytes

    def _upload_retryable(self, batch: HostTable) -> DeviceTable:
        """One H2D upload under the full OOM ladder (memory/retry.py):
        spill → retry → split the HOST batch and upload the halves (each
        half needs half the device allocation) → structured failure."""
        from ..memory.retry import split_host_rows, with_retry_split
        min_bucket = self.min_bucket

        def upload(hb: HostTable) -> DeviceTable:
            action = faults.fire("h2d.upload")
            if action is not None and action != "delay":
                raise faults.FaultInjectedError("h2d.upload", action)
            return DeviceTable.from_host(hb, min_bucket)  # srtpu: memtrack-ok(upload-cache bytes are accounted via register_external_bytes + clear_upload_cache OOM hook; uncached uploads are consumed/donated by the fused chain)

        def combine(outs):
            return concat_device_tables(outs, min_bucket)

        t0 = movement.clock()
        with get_tracer().span("h2d_upload", "upload",
                               rows=int(batch.num_rows)):  # srtpu: sync-ok(HostTable.num_rows is a host int on the upload side)
            dtb = with_retry_split(upload, batch, splitter=split_host_rows,
                                   combiner=combine, scope="h2d-upload",
                                   context=f"rows={int(batch.num_rows)}",  # srtpu: sync-ok(HostTable.num_rows is a host int on the upload side)
                                   fault_point="alloc.upload")
        movement.note_h2d(_MOVE_UPLOAD, dtb.nbytes, t0, origin=batch)
        return dtb

    def _upload(self, batch: HostTable) -> DeviceTable:
        global _CACHED_BYTES, _CACHE_HITS, _CACHE_INSERTS
        if not self.cache_max_bytes:
            dtb = self._upload_retryable(batch)
            self.metrics.add(M.UPLOAD_BYTES, dtb.nbytes())
            return mark_exclusive(dtb, origin=batch,
                                  min_bucket=self.min_bucket)
        key = id(batch)
        with _UPLOAD_LOCK:
            entry = _UPLOAD_CACHE.get(key)
            hit = None
            if entry is not None and entry[0]() is batch:
                hit = entry[1].get(self.min_bucket)
                if hit is not None:
                    _CACHE_HITS += 1
        if hit is not None:
            self.metrics.add(M.UPLOAD_CACHE_HITS, 1)
            return hit
        dtb = self._upload_retryable(batch)
        nbytes = dtb.nbytes()
        self.metrics.add(M.UPLOAD_BYTES, nbytes)
        cached = False
        with _UPLOAD_LOCK:
            if _CACHED_BYTES + nbytes <= self.cache_max_bytes:
                entry = _UPLOAD_CACHE.get(key)
                try:
                    if entry is None or entry[0]() is not batch:
                        if entry is not None:  # stale id-aliased entry
                            _CACHED_BYTES -= sum(
                                dt.nbytes() for dt in entry[1].values())
                        ref = weakref.ref(
                            batch, lambda _r, k=key: _drop_entry(k))
                        entry = _UPLOAD_CACHE[key] = (ref, {})
                    if self.min_bucket not in entry[1]:
                        entry[1][self.min_bucket] = dtb
                        _CACHED_BYTES += nbytes
                        _CACHE_INSERTS += 1
                        cached = True
                except TypeError:
                    pass  # un-weakref-able batch type: serve uncached
        if cached:
            # outside _UPLOAD_LOCK: these take the catalog lock (lock order
            # is catalog -> upload, never the reverse)
            _hook_oom()
            from ..memory.catalog import peek_catalog
            cat = peek_catalog()
            if cat is not None:
                cat.note_external_change()
        else:
            # not retained by the cache: the consumer owns the only
            # reference, so fused stages may donate it (wholestage.py)
            mark_exclusive(dtb, origin=batch, min_bucket=self.min_bucket)
        return dtb

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        # stage boundary: host decode/IO runs on a prefetch worker so the
        # NEXT batch decodes while THIS one uploads (double-buffered via
        # the bounded queue; parallel/pipeline.py)
        from ..parallel.pipeline import maybe_prefetched, stage_name
        child = maybe_prefetched(
            lambda: self.child.execute(pidx),
            stage=f"decode:{stage_name(self.child)}", registry=self.metrics)
        for batch in child:
            with self.metrics.timed(M.UPLOAD_TIME):
                dtb = self._upload(batch)
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            self.metrics.add(M.NUM_OUTPUT_ROWS, batch.num_rows)
            # batchRows histograms are observed by instrument_plan (once per
            # node) — observing here too would double-count under profiling
            yield dtb


class DeviceToHostExec(PhysicalPlan):
    def __init__(self, child: TpuExec):
        self.child = child
        self.children = (child,)
        self.schema = child.schema
        self.metrics = M.MetricRegistry()

    @property
    def num_partitions(self) -> int:
        return self.child.num_partitions

    def device_batches(self, pidx: int) -> List[DeviceTable]:
        """Drain the child's device batches WITHOUT materializing — the
        accumulate half of the deferred-D2H contract. Dispatch of later
        batches overlaps device execution of earlier ones (JAX async
        dispatch); nothing here blocks on device state."""
        # stage boundary: jitted compute (async dispatch) keeps running on
        # the prefetch worker while this thread accumulates/downloads
        from ..parallel.pipeline import maybe_prefetched, stage_name
        child = maybe_prefetched(
            lambda: self.child.execute_columnar(pidx),
            stage=f"compute:{stage_name(self.child)}", registry=self.metrics)
        return list(child)

    def download(self, batches: List[DeviceTable]) -> List[HostTable]:
        """Materialize accumulated device batches in ONE bulk device_get
        (columnar/device.py to_host_batched) — the other half of the
        deferred-D2H contract; pipelined_collect calls this once per
        output drain across every partition's batches."""
        from ..columnar.device import to_host_batched
        if not batches:
            return []
        with self.metrics.timed(M.DOWNLOAD_TIME), \
                get_tracer().span("d2h_download", "download",
                                  batches=len(batches)):
            hts = to_host_batched(batches)
        for batch, ht in zip(batches, hts):
            self.metrics.add(M.DOWNLOAD_BYTES, batch.nbytes())
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            self.metrics.add(M.NUM_OUTPUT_ROWS, ht.num_rows)
        return hts

    def execute(self, pidx: int) -> Iterator[HostTable]:
        from ..columnar.device import async_enabled
        if async_enabled():
            # deferred D2H: accumulate the partition's device batches,
            # then one bulk transfer for the whole drain
            yield from self.download(self.device_batches(pidx))
            return
        # sync-forcing debug mode (spark.rapids.tpu.async.enabled=false):
        # one blocking to_host per batch, so each download blocks at its
        # own site in the ledger/trace
        from ..parallel.pipeline import maybe_prefetched, stage_name
        child = maybe_prefetched(
            lambda: self.child.execute_columnar(pidx),
            stage=f"compute:{stage_name(self.child)}", registry=self.metrics)
        for batch in child:
            with self.metrics.timed(M.DOWNLOAD_TIME), \
                    get_tracer().span("d2h_download", "download",
                                      rows=int(batch.num_rows)):  # srtpu: sync-ok(sync-forcing debug mode: trace-span rows at the per-batch download boundary)
                ht = batch.to_host()
            self.metrics.add(M.DOWNLOAD_BYTES, batch.nbytes())
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            self.metrics.add(M.NUM_OUTPUT_ROWS, ht.num_rows)
            yield ht


class TpuCoalesceBatchesExec(TpuExec):
    """Concatenate small device batches up to a target row and/or byte goal.

    The reference distinguishes TargetSize vs RequireSingleBatch goals
    (CoalesceGoal lattice, GpuCoalesceBatches.scala:93-200); here the goal
    is expressed in rows (``target_rows``), bytes (``target_bytes`` — the
    TargetSize analogue, so wide schemas cannot accumulate an OOM-sized
    flush long before the row goal fills), or single-batch
    (``require_single``).
    """

    EXTRA_METRICS = (M.COALESCED_BYTES,)

    def __init__(self, child: PhysicalPlan, target_rows: int = 1 << 20,
                 require_single: bool = False, min_bucket: Optional[int] = None,
                 target_bytes: int = 0):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.schema = child.schema
        self.target_rows = target_rows
        self.target_bytes = int(target_bytes)
        self.require_single = require_single
        self.min_bucket = resolve_min_bucket(min_bucket)

    def node_desc(self) -> str:
        if self.require_single:
            return "goal=single"
        goal = f"rows={self.target_rows}"
        if self.target_bytes:
            goal += f" bytes={self.target_bytes}"
        return goal

    def _over_bytes(self, pending_bytes: int, extra: int = 0) -> bool:
        return bool(self.target_bytes) \
            and pending_bytes + extra > self.target_bytes

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        pending: List[DeviceTable] = []
        pending_rows = 0
        pending_bytes = 0
        for batch in self.child_device_batches(pidx):
            # capacity, not num_rows: the goal accounting stays sync-free
            # (capacity >= num_rows, so the row/byte goals flush
            # conservatively — never an over-sized concat)
            n = batch.capacity
            nb = batch.nbytes()
            if self.require_single:
                pending.append(batch)
                continue
            if pending and (pending_rows + n > self.target_rows
                            or self._over_bytes(pending_bytes, nb)):
                yield self._flush(pending)
                pending, pending_rows, pending_bytes = [], 0, 0
            pending.append(batch)
            pending_rows += n
            pending_bytes += nb
            if pending_rows >= self.target_rows \
                    or self._over_bytes(pending_bytes):
                yield self._flush(pending)
                pending, pending_rows, pending_bytes = [], 0, 0
        if pending:
            yield self._flush(pending)

    def _flush(self, pending: List[DeviceTable]) -> DeviceTable:
        from ..memory.retry import with_retry
        with self.metrics.timed(M.OP_TIME):
            # spill-only retry: a half-concat is not the requested
            # coalesce (and under require_single would be wrong) — the
            # byte-goal bound already caps the flush size
            out = with_retry(concat_device_tables, pending, self.min_bucket,
                             scope="coalesce", context=self.node_desc())
        self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
        self.metrics.add(M.COALESCED_BYTES, out.nbytes())
        return out
