"""Host<->device transitions and batch coalescing.

Reference equivalents:
- ``HostToDeviceExec``   ~ GpuRowToColumnarExec / HostColumnarToGpu
- ``DeviceToHostExec``   ~ GpuColumnarToRowExec / GpuBringBackToHost
- ``TpuCoalesceBatchesExec`` ~ GpuCoalesceBatches (GpuCoalesceBatches.scala:528)

The transition inserter (plan/transitions.py) places these where device
sections start/end, exactly like GpuTransitionOverrides.scala:37.
"""
from __future__ import annotations

from typing import Iterator, List

from ..columnar.device import DeviceTable, bucket_rows, concat_device_tables
from ..columnar.host import HostTable
from ..plan.physical import PhysicalPlan
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["HostToDeviceExec", "DeviceToHostExec", "TpuCoalesceBatchesExec"]


class HostToDeviceExec(TpuExec):
    def __init__(self, child: PhysicalPlan, min_bucket: int = 1024):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.schema = child.schema
        self.min_bucket = min_bucket

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        for batch in self.child.execute(pidx):
            with self.metrics.timed(M.UPLOAD_TIME):
                dtb = DeviceTable.from_host(batch, self.min_bucket)
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            self.metrics.add(M.NUM_OUTPUT_ROWS, batch.num_rows)
            yield dtb


class DeviceToHostExec(PhysicalPlan):
    def __init__(self, child: TpuExec):
        self.child = child
        self.children = (child,)
        self.schema = child.schema
        self.metrics = M.MetricRegistry()

    @property
    def num_partitions(self) -> int:
        return self.child.num_partitions

    def execute(self, pidx: int) -> Iterator[HostTable]:
        for batch in self.child.execute_columnar(pidx):
            with self.metrics.timed(M.DOWNLOAD_TIME):
                ht = batch.to_host()
            yield ht


class TpuCoalesceBatchesExec(TpuExec):
    """Concatenate small device batches up to a target row goal.

    The reference distinguishes TargetSize vs RequireSingleBatch goals
    (CoalesceGoal lattice, GpuCoalesceBatches.scala:93-200); here the goal is
    expressed in rows (``target_rows``) or single-batch (``require_single``).
    """

    def __init__(self, child: PhysicalPlan, target_rows: int = 1 << 20,
                 require_single: bool = False, min_bucket: int = 1024):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.schema = child.schema
        self.target_rows = target_rows
        self.require_single = require_single
        self.min_bucket = min_bucket

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        pending: List[DeviceTable] = []
        pending_rows = 0
        for batch in self.child_device_batches(pidx):
            n = int(batch.num_rows)
            if self.require_single or pending_rows + n <= self.target_rows \
                    or not pending:
                pending.append(batch)
                pending_rows += n
                if not self.require_single and pending_rows >= self.target_rows:
                    yield self._flush(pending)
                    pending, pending_rows = [], 0
            else:
                yield self._flush(pending)
                pending, pending_rows = [batch], n
        if pending:
            yield self._flush(pending)

    def _flush(self, pending: List[DeviceTable]) -> DeviceTable:
        with self.metrics.timed(M.OP_TIME):
            out = concat_device_tables(pending, self.min_bucket)
        self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
        return out
