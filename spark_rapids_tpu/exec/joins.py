"""Device equi-joins (reference: GpuHashJoin.scala:507 + JoinGatherer.scala +
AbstractGpuJoinIterator.scala out-of-core gather sub-partitioning;
GpuShuffledHashJoinExec / GpuBroadcastHashJoinExec wrappers).

TPU-first re-design — cuDF's hash join produces dynamically-sized gather maps;
XLA needs static shapes. Three-kernel pipeline per probe batch:

1. **Join codes** (exact, no hash collisions): concatenate build+probe key
   columns, one lexsort over (null flags, normalized values), boundary flags →
   dense group ids. Equal key tuples on either side get equal codes; null keys
   get per-row sentinel codes so they never match (Spark semantics); NaN keys
   match NaN; -0.0 == 0.0.
2. **Count kernel**: sort build codes once; per probe row,
   ``searchsorted(left/right)`` gives match count + start. One scalar
   (total pairs) syncs to host.
3. **Expand kernel**: compiled per *bucketed* output capacity chosen from the
   true total — the static-shape answer to cuDF's dynamic gather map.

Out-of-core (reference: AbstractGpuJoinIterator + the big-join
sub-partitioning): the build side registers with the BufferCatalog as a
spillable; a build side over the batch budget triggers a grace-style hash
sub-partition of BOTH sides (same key hash, independent seed) into spillable
buckets joined pairwise; an oversized gather output is produced in probe row
windows so no expand exceeds the budget.
"""
from __future__ import annotations

import math
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.device import (DeviceColumn, DeviceTable, bucket_rows,
                               concat_device_tables, shrink_to_fit,
                               slice_rows)
from ..expr.base import EvalContext, Expression
from ..plan.logical import _join_schema
from ..plan.physical import PhysicalPlan
from ..plan.schema import Schema
from ..utils import metrics as M
from ..utils.compile_cache import cached_jit
from .base import TpuExec

# grace sub-partitioning uses its own hash seed: the upstream exchange
# already partitioned rows by these keys with the default seed, so reusing
# it would send every row of one shard to a single grace bucket
_GRACE_SEED = 9001

__all__ = ["TpuShuffledHashJoinExec", "TpuBroadcastHashJoinExec"]


def _sort_key_arrays(cols: List[DeviceColumn], active: jax.Array):
    """lexsort keys (minor..major) + per-row null flag for a key column set."""
    keys = []
    anynull = jnp.zeros(active.shape[0], dtype=bool)
    for kc in reversed(cols):
        v = kc.data
        if jnp.issubdtype(v.dtype, jnp.floating):
            nan = jnp.isnan(v)
            v = jnp.where(v == 0, jnp.zeros_like(v), v)
            v = jnp.where(nan, jnp.full_like(v, jnp.inf), v)
            keys.append(v)
            keys.append(nan)
        else:
            keys.append(v)
    for kc in cols:
        anynull = jnp.logical_or(anynull, jnp.logical_not(kc.validity))
    return keys, anynull


def _join_codes(bcols: List[DeviceColumn], bactive: jax.Array,
                pcols: List[DeviceColumn], pactive: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
    """Dense int64 codes: equal key tuples <-> equal codes across both sides.

    Inactive/null-key rows get unique negative sentinels (never match).
    """
    nb = bactive.shape[0]
    npr = pactive.shape[0]
    cat_cols = []
    for bc, pc in zip(bcols, pcols):
        data = jnp.concatenate([bc.data, pc.data])
        validity = jnp.concatenate([bc.validity, pc.validity])
        cat_cols.append(DeviceColumn(data, validity, bc.dtype, None))
    active = jnp.concatenate([bactive, pactive])
    keys, anynull = _sort_key_arrays(cat_cols, active)
    usable = jnp.logical_and(active, jnp.logical_not(anynull))
    keys.append(jnp.logical_not(usable))  # primary: usable rows first
    order = jnp.lexsort(tuple(keys))
    usable_s = jnp.take(usable, order)
    # boundary among sorted usable rows (same logic as aggregate kernel)
    same = jnp.ones(nb + npr, dtype=bool)
    for kc in cat_cols:
        v = kc.data
        if jnp.issubdtype(v.dtype, jnp.floating):
            v = jnp.where(v == 0, jnp.zeros_like(v), v)
        sv = jnp.take(v, order)
        eq = sv == jnp.roll(sv, 1)
        if jnp.issubdtype(sv.dtype, jnp.floating):
            eq = jnp.logical_or(eq, jnp.logical_and(
                jnp.isnan(sv), jnp.isnan(jnp.roll(sv, 1))))
        eq = eq.at[0].set(False)
        same = jnp.logical_and(same, eq)
    boundary = jnp.logical_and(jnp.logical_not(same), usable_s)
    boundary = boundary.at[0].set(usable_s[0])
    gid_sorted = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    # scatter back to original positions
    gid = jnp.zeros(nb + npr, dtype=jnp.int64).at[order].set(gid_sorted)
    iota = jnp.arange(nb + npr, dtype=jnp.int64)
    gid = jnp.where(usable, gid, -(iota + 2))  # unique non-matching sentinels
    return gid[:nb], gid[nb:]


def _count_matches(bgid: jax.Array, pgid: jax.Array):
    """-> (b_order, b_sorted, starts, counts) for probe rows."""
    b_order = jnp.argsort(bgid)
    b_sorted = jnp.take(bgid, b_order)
    # sentinels are negative and unique so they contribute zero matches;
    # clamp probe sentinels to a value absent from build (-1)
    p = jnp.where(pgid < 0, jnp.full_like(pgid, -1), pgid)
    starts = jnp.searchsorted(b_sorted, p, side="left")
    ends = jnp.searchsorted(b_sorted, p, side="right")
    # build sentinels: strip them from matches (they sit < 0 in sorted order)
    counts = jnp.where(pgid < 0, 0, ends - starts)
    return b_order, starts.astype(jnp.int64), counts.astype(jnp.int64)


def _gather_columns(table: DeviceTable, idx: jax.Array, matched: jax.Array
                    ) -> List[DeviceColumn]:
    cols = []
    for c in table.columns:
        g = c.gather(idx)
        cols.append(g.with_validity(jnp.logical_and(g.validity, matched)))
    return cols


class _JoinKernels:
    """Builds the jitted count + expand kernels for a (schema, how) combo."""

    def __init__(self, exec_node: "TpuShuffledHashJoinExec"):
        self.node = exec_node

    def counts_fn(self):
        lkeys = self.node.left_keys
        rkeys = self.node.right_keys

        def fn(build: DeviceTable, probe: DeviceTable):
            bcols = [build.column(k) for k in rkeys]
            pcols = [probe.column(k) for k in lkeys]
            bgid, pgid = _join_codes(bcols, build.row_mask, pcols,
                                     probe.row_mask)
            b_order, starts, counts = _count_matches(bgid, pgid)
            return b_order, starts, counts
        return fn

    def expand_fn(self, out_cap: int, how: str):
        node = self.node

        def fn(build: DeviceTable, probe: DeviceTable, b_order, starts,
               counts):
            outer = how in ("left", "full")
            slot_counts = jnp.maximum(counts, 1) if outer else counts
            slot_counts = jnp.where(probe.row_mask, slot_counts, 0)
            cum = jnp.cumsum(slot_counts)
            total = cum[-1]
            offsets = cum - slot_counts
            j = jnp.arange(out_cap, dtype=jnp.int64)
            # probe row for each output slot
            pi = jnp.searchsorted(cum, j, side="right")
            pi = jnp.clip(pi, 0, probe.capacity - 1)
            k = j - jnp.take(offsets, pi)
            has_match = jnp.take(counts, pi) > 0
            b_sorted_pos = jnp.take(starts, pi) + k
            b_sorted_pos = jnp.clip(b_sorted_pos, 0, build.capacity - 1)
            bi = jnp.take(b_order, b_sorted_pos)
            valid_slot = j < total
            build_matched = jnp.logical_and(valid_slot, has_match)
            pcols = _gather_columns(probe, pi.astype(jnp.int32), valid_slot)
            bcols = _gather_columns(build, bi.astype(jnp.int32), build_matched)
            out_cols, names = node.assemble(pcols, bcols, build_matched)
            return DeviceTable(tuple(out_cols), valid_slot,
                               total.astype(jnp.int32), tuple(names))
        return fn

    def semi_mask_fn(self, anti: bool):
        def fn(probe: DeviceTable, counts):
            keep = counts == 0 if anti else counts > 0
            return probe.filter_mask(keep)
        return fn


class TpuShuffledHashJoinExec(TpuExec):
    """Equi-join: build side = right child, probe side = left child."""

    SUPPORTED = ("inner", "left", "left_semi", "left_anti")

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 how: str, condition: Optional[Expression], merge_keys: bool,
                 min_bucket: int = 1024,
                 batch_bytes: int = 512 * 1024 * 1024):
        super().__init__()
        assert how in self.SUPPORTED, how
        self.left, self.right = left, right
        self.children = (left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.how = how
        self.condition = condition
        self.merge_keys = merge_keys
        self.min_bucket = min_bucket
        self.batch_bytes = batch_bytes
        on = self.left_keys if merge_keys else None
        self.schema = _join_schema(left.schema, right.schema, on, how)
        self._kernels = _JoinKernels(self)

    @property
    def num_partitions(self) -> int:
        return self.left.num_partitions

    def node_desc(self):
        return f"{self.how} lkeys={self.left_keys} rkeys={self.right_keys}"

    def plan_signature(self) -> str:
        return (f"Join|{self.how}|{self.left_keys}|{self.right_keys}|"
                f"{self.merge_keys}|{self.condition!r}|"
                f"{self.left.schema!r}|{self.right.schema!r}")

    # -- column assembly (traced inside expand kernel) ------------------------
    def assemble(self, pcols: List[DeviceColumn], bcols: List[DeviceColumn],
                 build_matched: jax.Array):
        lnames = list(self.left.schema.names)
        rnames = list(self.right.schema.names)
        names: List[str] = []
        cols: List[DeviceColumn] = []
        if self.merge_keys:
            for k in self.left_keys:
                cols.append(pcols[lnames.index(k)])
                names.append(k)
            skip_l = set(self.left_keys)
            skip_r = set(self.right_keys)
        else:
            skip_l = set()
            skip_r = set()
        for n, c in zip(lnames, pcols):
            if n not in skip_l:
                names.append(n)
                cols.append(c)
        for n, c in zip(rnames, bcols):
            if n not in skip_r:
                names.append(n)
                cols.append(c)
        return cols, names

    # -- execution ------------------------------------------------------------
    def _build_table(self, pidx: int) -> DeviceTable:
        batches = list(_device_batches(self.right, pidx))
        if not batches:
            from .aggregate import _empty_device_table
            return _empty_device_table(self.right.schema, self.min_bucket)
        table = concat_device_tables(batches) if len(batches) > 1 else batches[0]
        return table

    def _max_out_rows(self) -> int:
        """Gather-output row budget derived from the byte budget."""
        row_bytes = 0
        for f in self.schema:
            if isinstance(f.dtype, (dt.StringType, dt.BinaryType)):
                row_bytes += 32  # width varies; assume a modest string
            else:
                row_bytes += f.dtype.np_dtype().itemsize
            row_bytes += 1  # validity
        return max(self.min_bucket, self.batch_bytes // max(row_bytes, 1))

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        build = self._build_table(pidx)
        if build.nbytes() > self.batch_bytes:
            yield from self._grace_join(build, pidx)
            return
        handle, own = self._register_build(build)
        del build  # the catalog handle is the owner from here on
        try:
            yield from self._probe_join(
                handle, _device_batches(self.left, pidx))
        finally:
            if own:
                handle.close()

    def _register_build(self, build: DeviceTable):
        """-> (SpillableDeviceTable, close_when_done)."""
        from ..memory.catalog import SpillPriorities, get_catalog
        return (get_catalog().register(build, SpillPriorities.ACTIVE_ON_DECK),
                True)

    def _probe_join(self, build_handle, probe_batches
                    ) -> Iterator[DeviceTable]:
        """Join probe batches against one spillable build table."""
        counts_fn = cached_jit(self.plan_signature() + "|counts",
                               self._kernels.counts_fn)
        for probe in probe_batches:
            with self.metrics.timed(M.JOIN_TIME), build_handle as build:
                b_order, starts, counts = counts_fn(build, probe)
                if self.how in ("left_semi", "left_anti"):
                    fn = cached_jit(
                        self.plan_signature() + "|semi",
                        lambda: self._kernels.semi_mask_fn(
                            self.how == "left_anti"))
                    yield fn(probe, counts)
                    continue
                outer = self.how in ("left", "full")
                slot_counts = np.asarray(
                    jnp.sum(jnp.where(
                        probe.row_mask,
                        jnp.maximum(counts, 1) if outer else counts, 0)))
                total = int(slot_counts)
                max_out = self._max_out_rows()
                if total > max_out:
                    # oversized gather: emit in probe row windows (reference:
                    # AbstractGpuJoinIterator sub-partitions the gather)
                    yield from self._windowed_expand(build, probe, total,
                                                     max_out, counts_fn)
                    continue
                out_cap = bucket_rows(max(total, 1), self.min_bucket)
                expand = cached_jit(
                    self.plan_signature() + f"|expand{out_cap}",
                    lambda: self._kernels.expand_fn(out_cap, self.how))
                out = expand(build, probe, b_order, starts, counts)
                yield self._apply_condition(out)

    def _apply_condition(self, out: DeviceTable) -> DeviceTable:
        if self.condition is None:
            return out
        cond_fn = cached_jit(self.plan_signature() + "|cond",
                             lambda: _condition_filter_fn(self.condition))
        return cond_fn(out)

    def _windowed_expand(self, build: DeviceTable, probe: DeviceTable,
                         total: int, max_out: int, counts_fn
                         ) -> Iterator[DeviceTable]:
        probe = probe.compact()
        nrows = max(1, int(probe.num_rows))
        # size windows by average multiplicity; skewed windows re-split below
        avg_mult = max(1.0, total / nrows)
        wsize = bucket_rows(max(self.min_bucket, int(max_out / avg_mult)),
                            self.min_bucket)
        outer = self.how in ("left", "full")
        start = 0
        while start < nrows:
            window = slice_rows(probe, start, wsize)
            start += wsize
            b_order, starts, counts = counts_fn(build, window)
            wtotal = int(np.asarray(jnp.sum(jnp.where(
                window.row_mask,
                jnp.maximum(counts, 1) if outer else counts, 0))))
            if wtotal == 0 and not outer:
                continue
            if wtotal > 2 * max_out and wsize > self.min_bucket:
                # skewed window: recurse with smaller windows
                yield from self._windowed_expand(build, window, wtotal,
                                                 max_out, counts_fn)
                continue
            out_cap = bucket_rows(max(wtotal, 1), self.min_bucket)
            expand = cached_jit(
                self.plan_signature() + f"|expand{out_cap}",
                lambda: self._kernels.expand_fn(out_cap, self.how))
            yield self._apply_condition(
                expand(build, window, b_order, starts, counts))

    # -- grace-style sub-partitioned join (build side over budget) -----------
    def _grace_split(self, table: DeviceTable, keys: List[str], n_sub: int
                     ) -> List[DeviceTable]:
        from ..shuffle.manager import device_partition_ids
        pid = device_partition_ids(table, keys, n_sub, seed=_GRACE_SEED)
        return [shrink_to_fit(table.filter_mask(pid == s), self.min_bucket)
                for s in range(n_sub)]

    def _grace_build_parts(self, build: DeviceTable, n_sub: int):
        """-> (list of build-part spill handles, close_when_done)."""
        from ..memory.catalog import SpillPriorities, get_catalog
        catalog = get_catalog()
        return [catalog.register(t, SpillPriorities.INPUT)
                for t in self._grace_split(build, self.right_keys, n_sub)], \
            True

    def _grace_join(self, build: DeviceTable, pidx: int
                    ) -> Iterator[DeviceTable]:
        from ..memory.catalog import SpillPriorities, get_catalog
        catalog = get_catalog()
        n_sub = min(64, max(2, math.ceil(build.nbytes() / self.batch_bytes)))
        build_parts, own_build = self._grace_build_parts(build, n_sub)
        del build
        probe_parts: List[List] = [[] for _ in range(n_sub)]
        try:
            for probe in _device_batches(self.left, pidx):
                for s, t in enumerate(self._grace_split(
                        probe, self.left_keys, n_sub)):
                    if int(t.num_rows):
                        probe_parts[s].append(
                            catalog.register(t, SpillPriorities.INPUT))
            for s in range(n_sub):
                def sub_batches():
                    for h in probe_parts[s]:
                        with h as t:
                            yield t
                if probe_parts[s]:
                    yield from self._probe_join(build_parts[s],
                                                sub_batches())
        finally:
            if own_build:
                for h in build_parts:
                    h.close()
            for hs in probe_parts:
                for h in hs:
                    h.close()


class TpuBroadcastHashJoinExec(TpuShuffledHashJoinExec):
    """Build side materialized once across partitions (reference:
    GpuBroadcastHashJoinExec + SerializeConcatHostBuffersDeserializeBatch)."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._bc_handle = None
        self._bc_grace_parts = None

    def _broadcast_handle(self):
        """Broadcast batch registered once with the BufferCatalog at
        BROADCAST priority — accounted and spillable rather than pinned to
        the exec node for the plan's lifetime. A finalizer releases the
        catalog entry when the plan is garbage-collected."""
        if self._bc_handle is None:
            import weakref
            from ..memory.catalog import SpillPriorities, get_catalog
            batches = []
            for p in range(self.right.num_partitions):
                batches.extend(_device_batches(self.right, p))
            if not batches:
                from .aggregate import _empty_device_table
                table = _empty_device_table(self.right.schema,
                                            self.min_bucket)
            else:
                table = concat_device_tables(batches) \
                    if len(batches) > 1 else batches[0]
            self._bc_handle = get_catalog().register(
                table, SpillPriorities.BROADCAST)
            weakref.finalize(self, _close_quietly, self._bc_handle)
        return self._bc_handle

    def _build_table(self, pidx: int) -> DeviceTable:
        return self._broadcast_handle().get()

    def _register_build(self, build: DeviceTable):
        return self._broadcast_handle(), False

    def _grace_build_parts(self, build: DeviceTable, n_sub: int):
        """Split the broadcast once; reuse the parts for every partition."""
        if self._bc_grace_parts is None:
            import weakref
            parts, _ = super()._grace_build_parts(build, n_sub)
            self._bc_grace_parts = parts
            for h in parts:
                weakref.finalize(self, _close_quietly, h)
        return self._bc_grace_parts, False


def _close_quietly(handle):
    try:
        handle.close()
    except Exception:
        pass


def _condition_filter_fn(condition: Expression):
    def fn(table: DeviceTable) -> DeviceTable:
        ctx = EvalContext.for_device(table)
        c = condition.eval(ctx)
        keep = c.values
        if c.validity is not None:
            keep = jnp.logical_and(keep, c.validity)
        return table.filter_mask(keep)
    return fn


def _device_batches(child: PhysicalPlan, pidx: int) -> Iterator[DeviceTable]:
    assert hasattr(child, "execute_columnar"), \
        f"join child {type(child).__name__} is not columnar (missing transition)"
    return child.execute_columnar(pidx)
