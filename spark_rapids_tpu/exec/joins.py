"""Device equi-joins (reference: GpuHashJoin.scala:507 + JoinGatherer.scala +
AbstractGpuJoinIterator.scala out-of-core gather sub-partitioning;
GpuShuffledHashJoinExec / GpuBroadcastHashJoinExec wrappers).

TPU-first re-design — cuDF's hash join produces dynamically-sized gather maps;
XLA needs static shapes. Three-kernel pipeline per probe batch:

1. **Join codes** (exact, no hash collisions): concatenate build+probe key
   columns, one lexsort over (null flags, normalized values), boundary flags →
   dense group ids. Equal key tuples on either side get equal codes; null keys
   get per-row sentinel codes so they never match (Spark semantics); NaN keys
   match NaN; -0.0 == 0.0.
2. **Count kernel**: sort build codes once; per probe row,
   ``searchsorted(left/right)`` gives match count + start. One scalar
   (total pairs) syncs to host.
3. **Expand kernel**: compiled per *bucketed* output capacity chosen from the
   true total — the static-shape answer to cuDF's dynamic gather map.

Out-of-core (reference: AbstractGpuJoinIterator + the big-join
sub-partitioning): the build side registers with the BufferCatalog as a
spillable; a build side over the batch budget triggers a grace-style hash
sub-partition of BOTH sides (same key hash, independent seed) into spillable
buckets joined pairwise; an oversized gather output is produced in probe row
windows so no expand exceeds the budget.
"""
from __future__ import annotations

import math
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import dtypes as dt
from ..columnar.device import (DeviceColumn, DeviceTable, bucket_rows,
                               resolve_min_bucket, resolve_scalars,
                               concat_device_tables, shrink_to_fit,
                               slice_rows)
from ..expr.base import EvalContext, Expression
from ..plan.logical import _join_schema
from ..plan.physical import PhysicalPlan
from ..plan.schema import Field, Schema
from ..utils import metrics as M
from ..utils.compile_cache import cached_jit
from .base import TpuExec

# grace sub-partitioning uses its own hash seed: the upstream exchange
# already partitioned rows by these keys with the default seed, so reusing
# it would send every row of one shard to a single grace bucket
_GRACE_SEED = 9001

__all__ = ["TpuShuffledHashJoinExec", "TpuBroadcastHashJoinExec",
           "TpuBroadcastNestedLoopJoinExec"]


def _concat_key_col(bc: DeviceColumn, pc: DeviceColumn) -> DeviceColumn:
    """Concatenate a build/probe key column pair (strings pad to a common
    width so the byte matrices stack)."""
    bdat, pdat = bc.data, pc.data
    lengths = None
    if bc.is_string_like:
        w = max(bdat.shape[1], pdat.shape[1])
        if bdat.shape[1] < w:
            bdat = jnp.pad(bdat, ((0, 0), (0, w - bdat.shape[1])))
        if pdat.shape[1] < w:
            pdat = jnp.pad(pdat, ((0, 0), (0, w - pdat.shape[1])))
        lengths = jnp.concatenate([bc.lengths, pc.lengths])
    data = jnp.concatenate([bdat, pdat])
    validity = jnp.concatenate([bc.validity, pc.validity])
    return DeviceColumn(data, validity, bc.dtype, lengths)


def _column_code_arrays(col: DeviceColumn) -> List[jax.Array]:
    """1-D arrays whose tuple-equality equals Spark key-equality for this
    column (NaN == NaN, -0.0 == 0.0, strings by bytes+length); lexsorting by
    them (minor..major over the returned order) groups equal keys."""
    from ..columnar.device import pack_string_key_words
    v = col.data
    if col.is_string_like:
        return pack_string_key_words(v, col.lengths)
    if dt.is_d128(col.dtype):
        from ..expr.decimal128 import d128_key_words
        return d128_key_words(v)
    if jnp.issubdtype(v.dtype, jnp.floating):
        nan = jnp.isnan(v)
        v = jnp.where(v == 0, jnp.zeros_like(v), v)
        # NaN -> +inf for a total order; the nan flag keeps real +inf distinct
        v = jnp.where(nan, jnp.full_like(v, jnp.inf), v)
        return [v, nan]
    return [v]


def _join_codes(bcols: List[DeviceColumn], bactive: jax.Array,
                pcols: List[DeviceColumn], pactive: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
    """Dense int64 codes: equal key tuples <-> equal codes across both sides.

    Inactive/null-key rows get unique negative sentinels (never match).
    """
    nb = bactive.shape[0]
    npr = pactive.shape[0]
    code_arrays: List[jax.Array] = []   # major..minor
    anynull = jnp.zeros(nb + npr, dtype=bool)
    for bc, pc in zip(bcols, pcols):
        cat = _concat_key_col(bc, pc)
        code_arrays.extend(_column_code_arrays(cat))
        anynull = jnp.logical_or(anynull, jnp.logical_not(cat.validity))
    active = jnp.concatenate([bactive, pactive])
    usable = jnp.logical_and(active, jnp.logical_not(anynull))
    # lexsort takes minor..major; prepend reversed codes, usable-first primary
    keys = list(reversed(code_arrays))
    keys.append(jnp.logical_not(usable))
    order = jnp.lexsort(tuple(keys))
    usable_s = jnp.take(usable, order)
    # boundary among sorted usable rows (same logic as aggregate kernel)
    same = jnp.ones(nb + npr, dtype=bool)
    for arr in code_arrays:
        sv = jnp.take(arr, order)
        eq = sv == jnp.roll(sv, 1)
        eq = eq.at[0].set(False)
        same = jnp.logical_and(same, eq)
    boundary = jnp.logical_and(jnp.logical_not(same), usable_s)
    boundary = boundary.at[0].set(usable_s[0])
    gid_sorted = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    # scatter back to original positions
    gid = jnp.zeros(nb + npr, dtype=jnp.int64).at[order].set(gid_sorted)
    iota = jnp.arange(nb + npr, dtype=jnp.int64)
    gid = jnp.where(usable, gid, -(iota + 2))  # unique non-matching sentinels
    return gid[:nb], gid[nb:]


def _co_locate(table: DeviceTable, ref: DeviceTable) -> DeviceTable:
    """Move ``table`` to ``ref``'s device when they differ (probe shards of
    an ICI exchange live one-per-chip; a jit cannot mix devices)."""
    try:
        td = next(iter(table.row_mask.devices()))
        rd = next(iter(ref.row_mask.devices()))
    except (AttributeError, TypeError):
        return table
    if td == rd:
        return table
    return jax.device_put(table, rd)


def _count_matches(bgid: jax.Array, pgid: jax.Array):
    """-> (b_order, b_sorted, starts, counts) for probe rows."""
    b_order = jnp.argsort(bgid)
    b_sorted = jnp.take(bgid, b_order)
    # sentinels are negative and unique so they contribute zero matches;
    # clamp probe sentinels to a value absent from build (-1)
    p = jnp.where(pgid < 0, jnp.full_like(pgid, -1), pgid)
    starts = jnp.searchsorted(b_sorted, p, side="left")
    ends = jnp.searchsorted(b_sorted, p, side="right")
    # build sentinels: strip them from matches (they sit < 0 in sorted order)
    counts = jnp.where(pgid < 0, 0, ends - starts)
    return b_order, starts.astype(jnp.int64), counts.astype(jnp.int64)


def _build_matched(bgid: jax.Array, pgid: jax.Array) -> jax.Array:
    """Per-build-row: does any probe row share its key? (right/full outer)."""
    p_sorted = jnp.sort(jnp.where(pgid < 0, jnp.full_like(pgid, -1), pgid))
    b = jnp.where(bgid < 0, jnp.full_like(bgid, -2), bgid)
    lo = jnp.searchsorted(p_sorted, b, side="left")
    hi = jnp.searchsorted(p_sorted, b, side="right")
    return jnp.logical_and(hi > lo, bgid >= 0)


def _gather_columns(table: DeviceTable, idx: jax.Array, matched: jax.Array
                    ) -> List[DeviceColumn]:
    cols = []
    for c in table.columns:
        g = c.gather(idx, keep_all_valid=True)
        cols.append(g.with_validity(jnp.logical_and(g.validity, matched)))
    return cols


def _null_device_column(dtype: dt.DataType, capacity: int) -> DeviceColumn:
    """All-null column of ``dtype`` (outer-join padding)."""
    from ..columnar.device import bucket_width
    if isinstance(dtype, (dt.StringType, dt.BinaryType)):
        return DeviceColumn(
            jnp.zeros((capacity, bucket_width(1)), dtype=jnp.uint8),
            jnp.zeros(capacity, dtype=bool), dtype,
            jnp.zeros(capacity, dtype=jnp.int32))
    if dt.is_d128(dtype):
        return DeviceColumn(jnp.zeros((capacity, 2), dtype=jnp.int64),
                            jnp.zeros(capacity, dtype=bool), dtype, None)
    np_dt = dtype.np_dtype()
    return DeviceColumn(jnp.zeros(capacity, dtype=np_dt),
                        jnp.zeros(capacity, dtype=bool), dtype, None)


_I64_MAX = np.int64(2**63 - 1)

from ..conf import register_conf  # noqa: E402  (grouped with sibling confs)

JOIN_STRATEGY = register_conf(
    "spark.rapids.tpu.join.strategy",
    "Unique-build-key (FK->PK) join algorithm: 'sort' (sorted build keys "
    "+ searchsorted), 'hash' (open-addressing slot table; no lax.sort in "
    "build prep or probe), or 'auto' (hash off-CPU, where sort "
    "compilation can be pathologically slow). Multi-key and non-unique "
    "builds always use the sorted count path; 'auto' = hash (measured "
    "faster on CPU and sort-compile-free for TPU; reference analogue: "
    "cuDF hash join vs sort-merge).", "auto",
    checker=lambda v: None if str(v).lower() in ("auto", "sort", "hash")
    else "must be auto|sort|hash")


def _resolve_join_strategy() -> str:
    from ..session import TpuSession
    sess = TpuSession._active
    v = str(sess.conf.get(JOIN_STRATEGY)).lower() if sess is not None \
        else "auto"
    return "hash" if v == "auto" else v


def _monotone_i64(v: jax.Array) -> jax.Array:
    """Order- and equality-preserving map of a key column into int64
    (Spark key semantics: NaN == NaN, -0.0 == 0.0). Integers/bool/date/
    timestamp widen; floats use the IEEE monotone bit trick after
    canonicalizing -0.0 and NaN."""
    if v.dtype == jnp.bool_ or jnp.issubdtype(v.dtype, jnp.integer):
        return v.astype(jnp.int64)
    if v.dtype == jnp.float32:
        v = v.astype(jnp.float64)  # lossless widen
    v = jnp.where(v == 0, jnp.zeros_like(v), v)          # -0.0 -> +0.0
    v = jnp.where(jnp.isnan(v), jnp.full_like(v, jnp.nan), v)  # one NaN
    u = jax.lax.bitcast_convert_type(v, jnp.uint64)
    top = jnp.uint64(1) << jnp.uint64(63)
    mono = jnp.where((u & top) != 0, ~u, u | top)        # monotone uint64
    return jax.lax.bitcast_convert_type(mono ^ top, jnp.int64)


def _key_view(table: DeviceTable, keys: Sequence[str]) -> DeviceTable:
    """Table of only the join-key columns under canonical names — the
    schema-erased input of the shared count kernel."""
    from ..columnar.device import canonical_names
    cols = tuple(table.column(k) for k in keys)
    return DeviceTable(cols, table.row_mask, table.num_rows,
                       canonical_names(len(cols)))


class _JoinSchemaOnly:
    def __init__(self, schema: Schema):
        self.schema = schema


def _condition_mask(condition: Expression, table: DeviceTable) -> jax.Array:
    """Residual-condition boolean mask over an assembled pair table."""
    ctx = EvalContext.for_device(table)
    c = condition.eval(ctx)
    keep = c.values
    if c.validity is not None:
        keep = jnp.logical_and(keep, c.validity)
    return jnp.logical_and(keep, table.row_mask)


class _JoinKernels:
    """Builds the jitted count + expand kernels for a (schema, how) combo."""

    def __init__(self, exec_node: "TpuShuffledHashJoinExec"):
        self.node = exec_node

    def counts_fn(self):
        """Key-view based: takes tables holding ONLY the join-key columns
        (canonical names), so one compiled count program serves every join
        with the same key layout, regardless of payload schema."""
        def fn(build_keys: DeviceTable, probe_keys: DeviceTable):
            bgid, pgid = _join_codes(
                list(build_keys.columns), build_keys.row_mask,
                list(probe_keys.columns), probe_keys.row_mask)
            b_order, starts, counts = _count_matches(bgid, pgid)
            return b_order, starts, counts, bgid, pgid
        return fn

    def matched_fn(self):
        """No-condition right/full general path: this probe batch's
        per-build-row key-match mask (ORed into the running seen mask by
        the caller)."""
        def fn(bgid, pgid):
            return _build_matched(bgid, pgid)
        return fn

    def build_prep_fn(self):
        """Direct single-key fast path, build half: map keys into the
        monotone int64 domain and sort ONCE per build table
        (invalid/masked rows pushed to a +max tail). Probe batches then
        only pay searchsorted — no build+probe concat, no per-batch
        build re-sort, exact (no hash)."""
        def fn(build_keys: DeviceTable):
            bc = build_keys.columns[0]
            bmask = jnp.logical_and(bc.validity, build_keys.row_mask)
            bv = _monotone_i64(bc.data)
            inv_b = jnp.logical_not(bmask)
            b_order = jnp.lexsort((bv, inv_b))
            sv = jnp.where(jnp.take(inv_b, b_order), _I64_MAX,
                           jnp.take(bv, b_order))
            nvalid = jnp.sum(bmask.astype(jnp.int64))
            # PK detection: no adjacent duplicates among the valid prefix
            # -> every probe row matches at most one build row, unlocking
            # the sync-free fixed-capacity join path (pk_join_fn)
            iota = jnp.arange(sv.shape[0], dtype=jnp.int64)
            dup = jnp.logical_and(sv[1:] == sv[:-1], (iota[1:] < nvalid))
            unique = jnp.logical_not(jnp.any(dup))
            return b_order, sv, nvalid, unique
        return fn

    def build_prep_hash_fn(self):
        """SORT-FREE build prep: vectorized open-addressing insertion into
        a 2x-capacity slot table (double hashing; each while_loop round
        claims empty slots by minimum row index). Duplicate keys are
        detected during insertion — the PK fast path only engages when the
        build side is unique, same as the sorted prep. No lax.sort
        anywhere (spark.rapids.tpu.join.strategy; reference analogue:
        cuDF's hash join build)."""
        def fn(build_keys: DeviceTable):
            bc = build_keys.columns[0]
            bmask = jnp.logical_and(bc.validity, build_keys.row_mask)
            bv = _monotone_i64(bc.data)
            cap = bv.shape[0]
            T = 2 * cap                       # pow2 (capacity is pow2)
            mask = jnp.uint32(T - 1)
            u = jax.lax.bitcast_convert_type(bv, jnp.uint64)
            lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
            hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
            from ..shuffle.manager import _fmix_device
            h1 = _fmix_device(lo ^ _fmix_device(hi))
            step = (_fmix_device(h1 ^ jnp.uint32(0x9E3779B9))
                    | jnp.uint32(1))          # odd: full cycle over pow2 T
            iota = jnp.arange(cap, dtype=jnp.int32)
            big = jnp.int32(cap)

            def cond(state):
                r, slot_row, placed, dup = state
                return jnp.logical_and(jnp.logical_not(jnp.all(placed)),
                                       r < T)

            def body(state):
                r, slot_row, placed, dup = state
                bucket = ((h1 + r.astype(jnp.uint32) * step) & mask) \
                    .astype(jnp.int32)
                occ = jnp.take(slot_row, bucket)
                occ_safe = jnp.clip(occ, 0, cap - 1)
                same = jnp.logical_and(occ >= 0,
                                       jnp.take(bv, occ_safe) == bv)
                dup = jnp.logical_or(
                    dup, jnp.logical_and(jnp.logical_not(placed), same))
                want = jnp.logical_and(jnp.logical_not(placed), occ < 0)
                cand = jnp.where(want, iota, big)
                claim = jax.ops.segment_min(cand, bucket, num_segments=T)
                won = jnp.logical_and(want,
                                      jnp.take(claim, bucket) == iota)
                slot_row = jnp.where(
                    jnp.logical_and(slot_row < 0, claim < big),
                    claim, slot_row)
                placed = jnp.logical_or(placed, won)
                return r + 1, slot_row, placed, dup

            init = (jnp.int32(0), jnp.full(T, -1, jnp.int32),
                    jnp.logical_not(bmask), jnp.zeros(cap, dtype=bool))
            _, slot_row, _, _ = jax.lax.while_loop(cond, body, init)

            # uniqueness via SELF-PROBE: walk each build key's chain; with
            # duplicates the later row's walk hits the earlier row first,
            # so found_row != self. (In-round dup insertion evades the
            # insertion-time check: two equal keys claiming different
            # slots in the same sweep never see each other.)
            def pcond(state):
                r, resolved, found_row = state
                return jnp.logical_and(jnp.logical_not(jnp.all(resolved)),
                                       r < T)

            def pbody(state):
                r, resolved, found_row = state
                bucket = ((h1 + r.astype(jnp.uint32) * step) & mask) \
                    .astype(jnp.int32)
                row = jnp.take(slot_row, bucket)
                empty = row < 0
                row_safe = jnp.clip(row, 0, cap - 1)
                eq = jnp.logical_and(jnp.logical_not(empty),
                                     jnp.take(bv, row_safe) == bv)
                hit = jnp.logical_and(jnp.logical_not(resolved), eq)
                found_row = jnp.where(hit, row_safe, found_row)
                resolved = jnp.logical_or(resolved,
                                          jnp.logical_or(empty, eq))
                return r + 1, resolved, found_row

            pinit = (jnp.int32(0), jnp.logical_not(bmask),
                     jnp.full(cap, -1, jnp.int32))
            _, _, found_row = jax.lax.while_loop(pcond, pbody, pinit)
            unique = jnp.all(jnp.logical_or(jnp.logical_not(bmask),
                                            found_row == iota))
            return slot_row, bv, unique
        return fn

    def pk_hash_join_fn(self, how: str):
        """Unique-build-key join via the hash slot table: each probe row
        walks its double-hash chain (one while_loop) until an empty slot
        (absent) or a key match. Counts are 0/1; output capacity == probe
        capacity; NO lax.sort in the program."""
        node = self.node

        def fn(build: DeviceTable, probe: DeviceTable,
               probe_keys: DeviceTable, slot_row, bv):
            pc = probe_keys.columns[0]
            pmask = jnp.logical_and(pc.validity, probe.row_mask)
            pv = _monotone_i64(pc.data)
            cap_b = bv.shape[0]
            T = slot_row.shape[0]
            mask = jnp.uint32(T - 1)
            u = jax.lax.bitcast_convert_type(pv, jnp.uint64)
            lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
            hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
            from ..shuffle.manager import _fmix_device
            h1 = _fmix_device(lo ^ _fmix_device(hi))
            step = (_fmix_device(h1 ^ jnp.uint32(0x9E3779B9))
                    | jnp.uint32(1))

            def cond(state):
                r, resolved, found, bi = state
                return jnp.logical_and(jnp.logical_not(jnp.all(resolved)),
                                       r < T)

            def body(state):
                r, resolved, found, bi = state
                bucket = ((h1 + r.astype(jnp.uint32) * step) & mask) \
                    .astype(jnp.int32)
                row = jnp.take(slot_row, bucket)
                empty = row < 0
                row_safe = jnp.clip(row, 0, cap_b - 1)
                eq = jnp.logical_and(jnp.logical_not(empty),
                                     jnp.take(bv, row_safe) == pv)
                hit = jnp.logical_and(jnp.logical_not(resolved), eq)
                found = jnp.logical_or(found, hit)
                bi = jnp.where(hit, row_safe, bi)
                resolved = jnp.logical_or(resolved,
                                          jnp.logical_or(empty, eq))
                return r + 1, resolved, found, bi

            n = pv.shape[0]
            init = (jnp.int32(0), jnp.logical_not(pmask),
                    jnp.zeros(n, dtype=bool), jnp.zeros(n, jnp.int32))
            _, _, found, bi = jax.lax.while_loop(cond, body, init)
            if how == "left_semi":
                return probe.filter_mask(found)
            if how == "left_anti":
                return probe.filter_mask(jnp.logical_not(found))
            keep = found if how == "inner" else probe.row_mask
            pcols = [c.with_validity(jnp.logical_and(c.validity, keep))
                     for c in probe.columns]
            bcols = _gather_columns(build, bi, found)
            out_cols, names = node.assemble(pcols, bcols, found)
            out_mask = jnp.logical_and(keep, probe.row_mask)
            return DeviceTable(tuple(out_cols), out_mask,
                               jnp.sum(out_mask, dtype=jnp.int32),
                               tuple(names))
        return fn

    def pk_join_fn(self, how: str):
        """Unique-build-key (FK->PK) join in ONE program: searchsorted
        lookup + gather, output capacity == probe capacity (counts are 0/1
        so no count sync, no windowing, no per-size expand recompiles —
        the hot TPC-H join shape; reference: GpuHashJoin's single-match
        gather specialization)."""
        node = self.node

        def fn(build: DeviceTable, probe: DeviceTable,
               probe_keys: DeviceTable, b_order, sv, nvalid):
            pc = probe_keys.columns[0]
            pmask = jnp.logical_and(pc.validity, probe.row_mask)
            pv = _monotone_i64(pc.data)
            pos = jnp.searchsorted(sv, pv, side="left")
            safe = jnp.clip(pos, 0, sv.shape[0] - 1)
            found = jnp.logical_and(
                jnp.logical_and(pos < nvalid,
                                jnp.take(sv, safe) == pv), pmask)
            if how == "left_semi":
                return probe.filter_mask(found)
            if how == "left_anti":
                return probe.filter_mask(jnp.logical_not(found))
            bi = jnp.take(b_order, safe).astype(jnp.int32)
            keep = found if how == "inner" else probe.row_mask
            pcols = [c.with_validity(jnp.logical_and(c.validity, keep))
                     for c in probe.columns]
            bcols = _gather_columns(build, bi, found)
            out_cols, names = node.assemble(pcols, bcols, found)
            mask = jnp.logical_and(keep, probe.row_mask)
            return DeviceTable(tuple(out_cols), mask,
                               jnp.sum(mask, dtype=jnp.int32), tuple(names))
        return fn

    def probe_count_fn(self, track: bool):
        """Direct path, probe half: two searchsorted passes clamped to the
        valid build prefix. Clamping makes sentinel collisions exact: for
        a probe key equal to the +max sentinel, the count still equals the
        number of VALID build rows holding that key (the tie region's
        valid entries all sit below ``nvalid``). ``track`` adds the
        per-build-row matched mask (right/full) from a probe-side sort."""
        def fn(b_order, sv, nvalid, probe_keys: DeviceTable):
            pc = probe_keys.columns[0]
            pmask = jnp.logical_and(pc.validity, probe_keys.row_mask)
            pv = _monotone_i64(pc.data)
            starts = jnp.minimum(
                jnp.searchsorted(sv, pv, side="left"), nvalid)
            ends = jnp.minimum(
                jnp.searchsorted(sv, pv, side="right"), nvalid)
            counts = jnp.where(pmask, ends - starts, 0)
            if track:
                pinv = jnp.logical_not(pmask)
                ps = jnp.sort(jnp.where(pinv, _I64_MAX, pv))
                pn = jnp.sum(pmask.astype(jnp.int64))
                lo = jnp.minimum(jnp.searchsorted(ps, sv, side="left"), pn)
                hi = jnp.minimum(jnp.searchsorted(ps, sv, side="right"), pn)
                iota = jnp.arange(sv.shape[0], dtype=jnp.int64)
                matched_s = jnp.logical_and(hi > lo, iota < nvalid)
                matched = jnp.zeros(sv.shape[0], dtype=bool) \
                    .at[b_order].set(matched_s)
            else:
                matched = jnp.zeros(sv.shape[0], dtype=bool)
            return starts.astype(jnp.int64), counts.astype(jnp.int64), \
                matched
        return fn

    def _slots(self, build, probe, b_order, starts, counts, out_cap, outer):
        """Common slot math: per-output-slot probe index, build index,
        valid/matched flags."""
        slot_counts = jnp.maximum(counts, 1) if outer else counts
        slot_counts = jnp.where(probe.row_mask, slot_counts, 0)
        cum = jnp.cumsum(slot_counts)
        total = cum[-1]
        offsets = cum - slot_counts
        j = jnp.arange(out_cap, dtype=jnp.int64)
        pi = jnp.searchsorted(cum, j, side="right")
        pi = jnp.clip(pi, 0, probe.capacity - 1)
        k = j - jnp.take(offsets, pi)
        has_match = jnp.take(counts, pi) > 0
        b_sorted_pos = jnp.take(starts, pi) + k
        b_sorted_pos = jnp.clip(b_sorted_pos, 0, build.capacity - 1)
        bi = jnp.take(b_order, b_sorted_pos)
        valid_slot = j < total
        build_matched = jnp.logical_and(valid_slot, has_match)
        return pi.astype(jnp.int32), bi.astype(jnp.int32), valid_slot, \
            build_matched, total

    def expand_fn(self, out_cap: int, how: str):
        """Expand without a residual condition. ``left``/``full`` keep
        unmatched probe rows inline; ``right`` behaves as inner here (its
        unmatched build rows are emitted by leftover_fn at the end)."""
        node = self.node

        def fn(build: DeviceTable, probe: DeviceTable, b_order, starts,
               counts):
            outer = how in ("left", "full")
            pi, bi, valid_slot, build_matched, total = self._slots(
                build, probe, b_order, starts, counts, out_cap, outer)
            pcols = _gather_columns(probe, pi, valid_slot)
            bcols = _gather_columns(build, bi, build_matched)
            out_cols, names = node.assemble(pcols, bcols, build_matched)
            return DeviceTable(tuple(out_cols), valid_slot,
                               total.astype(jnp.int32), tuple(names))
        return fn

    def expand_cond_fn(self, out_cap: int, how: str):
        """Expand WITH a residual condition, outer-correct: candidate pairs
        are inner-expanded, the condition filters pairs, and probe rows
        whose every candidate failed are re-emitted null-padded (left/full)
        — the matched-flag fixup of reference GpuHashJoin.scala:507. Returns
        (pairs_table[, pad_table][, seen_update]) depending on ``how``."""
        node = self.node
        condition = node.condition

        def fn(build: DeviceTable, probe: DeviceTable, b_order, starts,
               counts):
            pi, bi, valid_slot, _, total = self._slots(
                build, probe, b_order, starts, counts, out_cap, outer=False)
            if how in ("left_semi", "left_anti"):
                # pair evaluation only needs the CONDITION's referenced
                # columns — never assemble the full pair table (q21's
                # semi/anti pairs would otherwise gather every payload
                # column per candidate match)
                refs = condition.references()
                lnames = [n for n in node.left.schema.names if n in refs]
                rnames = [n for n in node.right.schema.names if n in refs]
                cols = tuple(
                    [probe.column(n).gather(pi, keep_all_valid=True)
                     .with_validity(
                        jnp.logical_and(
                            jnp.take(probe.column(n).validity, pi),
                            valid_slot)) for n in lnames]
                    + [build.column(n).gather(bi, keep_all_valid=True)
                       .with_validity(
                        jnp.logical_and(
                            jnp.take(build.column(n).validity, bi),
                            valid_slot)) for n in rnames])
                pairs = DeviceTable(cols, valid_slot,
                                    total.astype(jnp.int32),
                                    tuple(lnames + rnames))
                keep = _condition_mask(condition, pairs)
                keep = jnp.logical_and(keep, valid_slot)
                any_pass = jnp.zeros(probe.capacity, dtype=bool) \
                    .at[pi].max(keep, mode="drop")
                keep_rows = jnp.logical_not(any_pass) \
                    if how == "left_anti" else any_pass
                return probe.filter_mask(keep_rows)
            pcols = _gather_columns(probe, pi, valid_slot)
            bcols = _gather_columns(build, bi, valid_slot)
            out_cols, names = node.assemble(pcols, bcols, valid_slot)
            pairs = DeviceTable(tuple(out_cols), valid_slot,
                                total.astype(jnp.int32), tuple(names))
            keep = _condition_mask(condition, pairs)
            pairs = pairs.filter_mask(keep)
            keep = jnp.logical_and(keep, valid_slot)
            any_pass = jnp.zeros(probe.capacity, dtype=bool).at[pi].max(
                keep, mode="drop")
            outs = [pairs]
            if how in ("left", "full"):
                unmatched = jnp.logical_and(probe.row_mask,
                                            jnp.logical_not(any_pass))
                outs.append(node.pad_probe(probe, unmatched))
            if how in ("right", "full"):
                seen_upd = jnp.zeros(build.capacity, dtype=bool).at[bi].max(
                    keep, mode="drop")
                outs.append(seen_upd)
            return tuple(outs)
        return fn

    def semi_mask_fn(self, anti: bool):
        def fn(probe: DeviceTable, counts):
            keep = counts == 0 if anti else counts > 0
            return probe.filter_mask(keep)
        return fn

    def leftover_fn(self):
        """Final right/full emission: build rows no probe row matched,
        null-padded on the probe side."""
        node = self.node

        def fn(build: DeviceTable, seen: jax.Array):
            emit = jnp.logical_and(build.row_mask, jnp.logical_not(seen))
            return node.pad_build(build, emit)
        return fn


class TpuShuffledHashJoinExec(TpuExec):
    """Equi-join: build side = right child, probe side = left child.

    right/full outer track a per-build-row ``seen`` mask across probe
    batches and emit never-matched build rows null-padded at the end —
    sound per partition because the upstream hash exchange gives each
    partition disjoint key ranges (reference GpuHashJoin.scala:507
    HashedExistenceJoinIterator / buildSideTrackerOpt)."""

    SUPPORTED = ("inner", "left", "right", "full", "left_semi", "left_anti")
    EXTRA_METRICS = (M.JOIN_TIME,)

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 left_keys: Sequence[str], right_keys: Sequence[str],
                 how: str, condition: Optional[Expression], merge_keys: bool,
                 min_bucket: Optional[int] = None,
                 batch_bytes: int = 512 * 1024 * 1024):
        super().__init__()
        assert how in self.SUPPORTED, how
        self.left, self.right = left, right
        self.children = (left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.how = how
        self.condition = condition
        self.merge_keys = merge_keys
        self.min_bucket = resolve_min_bucket(min_bucket)
        self.batch_bytes = batch_bytes
        on = self.left_keys if merge_keys else None
        self.schema = _join_schema(left.schema, right.schema, on, how)
        self._kernels = _JoinKernels(self)

    @property
    def num_partitions(self) -> int:
        return self.left.num_partitions

    def node_desc(self):
        return f"{self.how} lkeys={self.left_keys} rkeys={self.right_keys}"

    def plan_signature(self) -> str:
        return (f"Join|{self.how}|{self.left_keys}|{self.right_keys}|"
                f"{self.merge_keys}|{self.condition!r}|"
                f"{self.left.schema!r}|{self.right.schema!r}")

    def _canon(self) -> Tuple["TpuShuffledHashJoinExec", str]:
        """Schema-erased clone + cache key (see aggregate._canon_exec):
        left columns a0..aN, right b0..bM, keys by position. Gather/assemble
        kernels built from the clone are shared by every join with the same
        (how, key positions, merge, column counts); dtype/shape differences
        retrace inside the shared jax.jit wrapper. Residual-condition
        kernels keep name-based keys (conditions reference real names)."""
        if getattr(self, "_canon_cache", None) is not None:
            return self._canon_cache
        lf = list(self.left.schema.fields)
        rf = list(self.right.schema.fields)
        lpos = {f.name: i for i, f in enumerate(lf)}
        rpos = {f.name: i for i, f in enumerate(rf)}
        clone = TpuShuffledHashJoinExec.__new__(TpuShuffledHashJoinExec)
        TpuExec.__init__(clone)
        clone.left = _JoinSchemaOnly(Schema(
            [Field(f"a{i}", f.dtype, f.nullable) for i, f in enumerate(lf)]))
        clone.right = _JoinSchemaOnly(Schema(
            [Field(f"b{i}", f.dtype, f.nullable) for i, f in enumerate(rf)]))
        clone.children = (clone.left, clone.right)
        clone.left_keys = [f"a{lpos[k]}" for k in self.left_keys]
        clone.right_keys = [f"b{rpos[k]}" for k in self.right_keys]
        clone.how = self.how
        clone.condition = None
        clone.merge_keys = self.merge_keys
        clone.min_bucket = self.min_bucket
        clone.batch_bytes = self.batch_bytes
        clone.schema = self.schema
        clone._kernels = _JoinKernels(clone)
        key = (f"JoinC|{self.how}|{[lpos[k] for k in self.left_keys]}|"
               f"{[rpos[k] for k in self.right_keys]}|{self.merge_keys}|"
               f"nl{len(lf)}|nr{len(rf)}")
        self._canon_cache = (clone, key)
        return self._canon_cache

    # -- column assembly (traced inside expand kernel) ------------------------
    def assemble(self, pcols: List[DeviceColumn], bcols: List[DeviceColumn],
                 build_matched: jax.Array, key_from_build: bool = False):
        """``key_from_build`` routes merged ``on=`` key columns from the
        build side — used for right/full leftover rows whose probe side is
        all-null (the coalesce step of the reference's full-outer key
        handling)."""
        lnames = list(self.left.schema.names)
        rnames = list(self.right.schema.names)
        names: List[str] = []
        cols: List[DeviceColumn] = []
        if self.merge_keys:
            for lk, rk in zip(self.left_keys, self.right_keys):
                src = bcols[rnames.index(rk)] if key_from_build \
                    else pcols[lnames.index(lk)]
                cols.append(src)
                names.append(lk)
            skip_l = set(self.left_keys)
            skip_r = set(self.right_keys)
        else:
            skip_l = set()
            skip_r = set()
        for n, c in zip(lnames, pcols):
            if n not in skip_l:
                names.append(n)
                cols.append(c)
        for n, c in zip(rnames, bcols):
            if n not in skip_r:
                names.append(n)
                cols.append(c)
        return cols, names

    # -- null-padded emission (outer-join fixup rows) -------------------------
    def pad_probe(self, probe: DeviceTable, emit: jax.Array) -> DeviceTable:
        """Probe rows with an all-null build side (left/full unmatched)."""
        bcols = [_null_device_column(f.dtype, probe.capacity)
                 for f in self.right.schema]
        pcols = [c.with_validity(jnp.logical_and(c.validity, emit))
                 for c in probe.columns]
        out_cols, names = self.assemble(pcols, bcols,
                                        jnp.zeros(probe.capacity, dtype=bool))
        return DeviceTable(tuple(out_cols), emit,
                           jnp.sum(emit, dtype=jnp.int32), tuple(names))

    def pad_build(self, build: DeviceTable, emit: jax.Array) -> DeviceTable:
        """Build rows with an all-null probe side (right/full leftover)."""
        pcols = [_null_device_column(f.dtype, build.capacity)
                 for f in self.left.schema]
        bcols = [c.with_validity(jnp.logical_and(c.validity, emit))
                 for c in build.columns]
        out_cols, names = self.assemble(pcols, bcols, emit,
                                        key_from_build=True)
        return DeviceTable(tuple(out_cols), emit,
                           jnp.sum(emit, dtype=jnp.int32), tuple(names))

    # -- execution ------------------------------------------------------------
    def _build_table(self, pidx: int) -> DeviceTable:
        from ..memory.retry import with_retry
        batches = list(_device_batches(self.right, pidx))
        if not batches:
            from .aggregate import _empty_device_table
            return _empty_device_table(self.right.schema, self.min_bucket)
        if len(batches) == 1:
            return batches[0]
        # build sides are unsplittable (the probe needs the WHOLE build
        # table in one piece) — spill-only retry, no split escalation
        return with_retry(concat_device_tables, batches,
                          scope="join-build", context=self.node_desc())

    def _max_out_rows(self) -> int:
        """Gather-output row budget derived from the byte budget."""
        row_bytes = 0
        for f in self.schema:
            if isinstance(f.dtype, (dt.StringType, dt.BinaryType)):
                row_bytes += 32  # width varies; assume a modest string
            else:
                row_bytes += f.dtype.np_dtype().itemsize
            row_bytes += 1  # validity
        return max(self.min_bucket, self.batch_bytes // max(row_bytes, 1))

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from .fallback import quarantine_on_failure
        # note-only boundary: the probe needs the whole build table, so a
        # terminal failure can't fall back per-batch — but it quarantines
        with quarantine_on_failure(self):
            for out in self._join_batches(pidx):
                self.account_batch()
                yield out

    def _join_batches(self, pidx: int) -> Iterator[DeviceTable]:
        build = self._build_table(pidx)
        if build.nbytes() > self.batch_bytes:
            yield from self._grace_join(build, pidx)
            return
        build_cap = build.capacity
        handle, own = self._register_build(build)
        del build  # the catalog handle is the owner from here on
        track = self.how in ("right", "full")
        seen_box = [jnp.zeros(build_cap, dtype=bool)] if track else None
        try:
            yield from self._probe_join(
                handle, _device_batches(self.left, pidx), seen_box)
            if track:
                leftover = self._leftover_fn()
                with handle as build:
                    yield leftover(build, seen_box[0])
        finally:
            if own:
                handle.close()

    def _leftover_fn(self):
        """Cached canonical leftover kernel (right/full build-side rows).
        Left-side dtypes go in the key: the null probe columns are built
        from them at trace time."""
        clone, ckey = self._canon()
        lkey = (ckey + "|leftover|"
                + ",".join(repr(f.dtype) for f in self.left.schema.fields))
        fn = cached_jit(lkey, clone._kernels.leftover_fn)
        out_names = tuple(self.schema.names)

        def run(build: DeviceTable, seen) -> DeviceTable:
            return fn(build.canonical(), seen).with_names(out_names)
        return run

    def _register_build(self, build: DeviceTable):
        """-> (SpillableDeviceTable, close_when_done)."""
        from ..memory.catalog import SpillPriorities, get_catalog
        return (get_catalog().register(build, SpillPriorities.ACTIVE_ON_DECK),
                True)

    def _direct_key_ok(self) -> bool:
        """Single-key joins on identical non-nested, non-string dtypes use
        the sort-build-once searchsorted count path."""
        if len(self.left_keys) != 1:
            return False
        lt = self.left.schema.field(self.left_keys[0]).dtype
        rt = self.right.schema.field(self.right_keys[0]).dtype
        bad = (dt.StringType, dt.BinaryType, dt.ArrayType)
        return lt == rt and not isinstance(lt, bad) and not dt.is_d128(lt)

    def _counts_fn(self, track: bool = False):
        """Shared count kernel over key views -> (b_order, starts, counts,
        matched_or_None). One program per key LAYOUT (count of keys +
        direct/general + track), retraced per dtype/capacity inside the
        shared jit."""
        lkeys, rkeys = self.left_keys, self.right_keys
        if self._direct_key_ok():
            cnt = cached_jit(f"JoinC|probeD|t{int(track)}",
                             lambda: self._kernels.probe_count_fn(track))

            def run(build: DeviceTable, probe: DeviceTable):
                b_order, sv, nvalid, _uniq = self._get_prep(build)
                starts, counts, matched = cnt(b_order, sv, nvalid,
                                              _key_view(probe, lkeys))
                return b_order, starts, counts, (matched if track else None)
            return run
        fn = cached_jit(f"JoinC|counts|k{len(lkeys)}",
                        self._kernels.counts_fn)
        matched_fn = cached_jit("JoinC|matched", self._kernels.matched_fn) \
            if track else None

        def run(build: DeviceTable, probe: DeviceTable):
            b_order, starts, counts, bgid, pgid = fn(
                _key_view(build, rkeys), _key_view(probe, lkeys))
            matched = matched_fn(bgid, pgid) if track else None
            return b_order, starts, counts, matched
        return run

    def _get_prep_hash(self, build: DeviceTable):
        """Per-build-table HASH prep (slot table + key array + uniqueness),
        cached like the sorted prep; no lax.sort in the prep program."""
        prep = cached_jit("JoinC|prepH", self._kernels.build_prep_hash_fn)
        lock = self.__dict__.setdefault("_prep_lock",
                                        __import__("threading").Lock())
        with lock:
            hit = self.__dict__.get("_prep_cache_hash")
            if hit is None or hit[0] is not build.row_mask:
                slot_row, bv, unique = prep(_key_view(build,
                                                      self.right_keys))
                pr = self._register_prep_hash(slot_row, bv, unique)
                hit = (build.row_mask, pr)
                old = self.__dict__.get("_prep_cache_hash")
                if old is not None:
                    _close_quietly(old[1][0])
                self.__dict__["_prep_cache_hash"] = hit
        handle, unique = hit[1]
        pt = handle.get()
        cap = pt.capacity // 2
        return pt.columns[0].data, pt.columns[1].data[:cap], unique

    def _register_prep_hash(self, slot_row, bv, unique):
        from ..columnar.device import canonical_names
        from ..memory.catalog import SpillPriorities, get_catalog
        T = slot_row.shape[0]
        bv_padded = jnp.pad(bv, (0, T - bv.shape[0]))
        ones = jnp.ones(T, dtype=bool)
        cols = (DeviceColumn(slot_row, ones, dt.IntegerType(), None),
                DeviceColumn(bv_padded, ones, dt.LongType(), None))
        t = DeviceTable(cols, ones, jnp.asarray(T, jnp.int32),
                        canonical_names(2))
        h = get_catalog().register(t, SpillPriorities.ACTIVE_ON_DECK)
        self._own_spill_handle(h)
        # uniqueness gates the PK fast path: one batched-funnel transfer
        # per build table (cached across probe batches/partitions)
        (uniq,) = resolve_scalars(unique)
        return (h, bool(uniq))

    def _get_prep(self, build: DeviceTable):
        """Per-build-table sorted-key prep: (b_order, sv, nvalid, unique).

        Node-level cache: broadcast joins re-enter _probe_join once per
        probe partition with the SAME build table — the prep must survive
        across those entries. The sorted-key arrays live in a catalog-
        registered spillable so memory pressure can evict them; single
        entry, replaced on build change, race-safe (each thread uses the
        tuple it computed or read, never a second dict lookup). ``unique``
        is host-synced once per build (it gates the PK fast path)."""
        prep = cached_jit("JoinC|prepD", self._kernels.build_prep_fn)
        lock = self.__dict__.setdefault("_prep_lock",
                                        __import__("threading").Lock())
        with lock:
            hit = self.__dict__.get("_prep_cache")
            if hit is None or hit[0] is not build.row_mask:
                pr = self._register_prep(
                    prep(_key_view(build, self.right_keys)))
                hit = (build.row_mask, pr)
                old = self.__dict__.get("_prep_cache")
                if old is not None:
                    _close_quietly(old[1][0])
                self.__dict__["_prep_cache"] = hit
        handle, nvalid, unique = hit[1]
        pt = handle.get()
        return pt.columns[0].data, pt.columns[1].data, nvalid, unique

    def _register_prep(self, pr):
        """(b_order, sv, nvalid, unique) -> (spill handle, nvalid,
        unique_bool): the sorted build-key arrays go through the
        BufferCatalog so memory pressure can evict them like any other
        device buffer; the uniqueness flag syncs to a host bool here (one
        tiny transfer per build table)."""
        from ..columnar.device import canonical_names
        from ..memory.catalog import SpillPriorities, get_catalog
        b_order, sv, nvalid, unique = pr
        cap = sv.shape[0]
        ones = jnp.ones(cap, dtype=bool)
        cols = (DeviceColumn(b_order, ones, dt.LongType(), None),
                DeviceColumn(sv, ones, dt.LongType(), None))
        t = DeviceTable(cols, ones, jnp.asarray(cap, jnp.int32),
                        canonical_names(2))
        h = get_catalog().register(t, SpillPriorities.ACTIVE_ON_DECK)
        self._own_spill_handle(h)
        (uniq,) = resolve_scalars(unique)
        return (h, nvalid, bool(uniq))

    def _probe_join(self, build_handle, probe_batches, seen_box=None
                    ) -> Iterator[DeviceTable]:
        """Join probe batches against one spillable build table.

        ``seen_box`` (right/full) is a one-element list holding the running
        per-build-row matched mask, updated in place across batches.
        """
        has_cond = self.condition is not None
        track = seen_box is not None and not has_cond
        counts_fn = self._counts_fn(track=track)
        pk_eligible = (not has_cond and self._direct_key_ok()
                       and self.how in ("inner", "left", "left_semi",
                                        "left_anti"))
        for probe in probe_batches:
            with self.metrics.timed(M.JOIN_TIME), build_handle as build:
                probe = _co_locate(probe, build)
                if pk_eligible:
                    out_names = tuple(self.schema.names) \
                        if self.how in ("inner", "left") \
                        else tuple(probe.names)
                    clone, ckey = self._canon()
                    out = None
                    if _resolve_join_strategy() == "hash":
                        # sort-free tier: open-addressing slot table.
                        # semi/anti only ask EXISTENCE, so duplicate build
                        # keys are fine (the chain walk finds any
                        # representative); inner/left need uniqueness for
                        # the single-match gather
                        slot_row, bv, unique = self._get_prep_hash(build)
                        if unique or self.how in ("left_semi",
                                                  "left_anti"):
                            fused = cached_jit(
                                ckey + f"|pkh|{self.how}",
                                lambda: clone._kernels
                                .pk_hash_join_fn(self.how))
                            out = fused(build.canonical(),
                                        probe.canonical(),
                                        _key_view(probe, self.left_keys),
                                        slot_row, bv)
                    else:
                        b_order, sv, nvalid, unique = self._get_prep(build)
                        if unique:
                            # FK->PK: counts are 0/1, output fits the
                            # probe capacity — one fused program, no
                            # count sync
                            fused = cached_jit(
                                ckey + f"|pk|{self.how}",
                                lambda: clone._kernels
                                .pk_join_fn(self.how))
                            out = fused(build.canonical(),
                                        probe.canonical(),
                                        _key_view(probe, self.left_keys),
                                        b_order, sv, nvalid)
                    if out is not None:
                        out = out.with_names(out_names)
                        if self.how in ("inner", "left_semi", "left_anti"):
                            # selective joins keep the probe CAPACITY with
                            # a mask; shrink (one int sync) so downstream
                            # sorts/groupbys don't run over dead padding
                            out = shrink_to_fit(out, self.min_bucket)
                        yield out
                        continue
                if seen_box is not None and hasattr(seen_box[0], "devices") \
                        and hasattr(build.row_mask, "devices") \
                        and seen_box[0].devices() != build.row_mask.devices():
                    seen_box[0] = jax.device_put(
                        seen_box[0], next(iter(build.row_mask.devices())))
                b_order, starts, counts, matched = counts_fn(build, probe)
                if matched is not None:
                    seen_box[0] = jnp.logical_or(seen_box[0], matched)
                if self.how in ("left_semi", "left_anti") and not has_cond:
                    anti = self.how == "left_anti"
                    fn = cached_jit(
                        f"JoinC|semi|{anti}",
                        lambda: self._kernels.semi_mask_fn(anti))
                    yield fn(probe.canonical(), counts) \
                        .with_names(probe.names)
                    continue
                outer_slots = self.how in ("left", "full") and not has_cond
                # output capacity is data-dependent: one batched-funnel
                # transfer resolves the slot total (the decision boundary)
                (total,) = resolve_scalars(
                    jnp.sum(jnp.where(
                        probe.row_mask,
                        jnp.maximum(counts, 1) if outer_slots else counts, 0)))
                total = int(total)
                max_out = self._max_out_rows()
                if total > max_out:
                    # oversized gather: emit in probe row windows (reference:
                    # AbstractGpuJoinIterator sub-partitions the gather)
                    yield from self._windowed_expand(build, probe, total,
                                                     max_out, counts_fn,
                                                     seen_box)
                    continue
                out_cap = bucket_rows(max(total, 1), self.min_bucket)
                yield from self._expand_one(build, probe, b_order, starts,
                                            counts, out_cap, seen_box)

    def _expand_one(self, build, probe, b_order, starts, counts, out_cap,
                    seen_box) -> Iterator[DeviceTable]:
        """One expand call on a probe batch/window (post-count)."""
        how = self.how
        out_names = tuple(self.schema.names)
        if self.condition is None:
            # right behaves as inner here; leftover_fn emits its outer rows
            eff = {"right": "inner", "full": "left"}.get(how, how)
            clone, ckey = self._canon()
            expand = cached_jit(
                ckey + f"|expand{out_cap}|{eff}",
                lambda: clone._kernels.expand_fn(out_cap, eff))
            yield expand(build.canonical(), probe.canonical(), b_order,
                         starts, counts).with_names(out_names)
            return
        if how == "inner":
            clone, ckey = self._canon()
            expand = cached_jit(
                ckey + f"|expand{out_cap}|inner",
                lambda: clone._kernels.expand_fn(out_cap, "inner"))
            out = expand(build.canonical(), probe.canonical(), b_order,
                         starts, counts).with_names(out_names)
            cond_fn = cached_jit(self.plan_signature() + "|cond",
                                 lambda: _condition_filter_fn(self.condition))
            yield cond_fn(out)
            return
        fn = cached_jit(self.plan_signature() + f"|condexpand{out_cap}",
                        lambda: self._kernels.expand_cond_fn(out_cap, how))
        res = fn(build, probe, b_order, starts, counts)
        if how in ("left_semi", "left_anti"):
            yield res
            return
        outs = list(res) if isinstance(res, tuple) else [res]
        if how in ("right", "full"):
            seen_upd = outs.pop()  # last element by expand_cond_fn contract
            seen_box[0] = jnp.logical_or(seen_box[0], seen_upd)
        for t in outs:
            yield t

    def _windowed_expand(self, build: DeviceTable, probe: DeviceTable,
                         total: int, max_out: int, counts_fn, seen_box=None
                         ) -> Iterator[DeviceTable]:
        probe = probe.compact()
        (nrows,) = resolve_scalars(probe.num_rows)
        nrows = max(1, int(nrows))
        # size windows by average multiplicity; skewed windows re-split below
        avg_mult = max(1.0, total / nrows)
        wsize = bucket_rows(max(self.min_bucket, int(max_out / avg_mult)),
                            self.min_bucket)
        outer_slots = self.how in ("left", "full") and self.condition is None
        start = 0
        while start < nrows:
            window = slice_rows(probe, start, wsize)
            start += wsize
            b_order, starts, counts, _ = counts_fn(build, window)
            (wtotal,) = resolve_scalars(jnp.sum(jnp.where(
                window.row_mask,
                jnp.maximum(counts, 1) if outer_slots else counts, 0)))
            wtotal = int(wtotal)
            if wtotal == 0 and not outer_slots and self.condition is None \
                    and self.how not in ("left_semi", "left_anti"):
                continue
            if wtotal > 2 * max_out and wsize > self.min_bucket:
                # skewed window: recurse with smaller windows
                yield from self._windowed_expand(build, window, wtotal,
                                                 max_out, counts_fn, seen_box)
                continue
            out_cap = bucket_rows(max(wtotal, 1), self.min_bucket)
            yield from self._expand_one(build, window, b_order, starts,
                                        counts, out_cap, seen_box)

    # -- grace-style sub-partitioned join (build side over budget) -----------
    def _grace_split(self, table: DeviceTable, keys: List[str], n_sub: int
                     ) -> List[DeviceTable]:
        from ..shuffle.manager import device_partition_ids
        pid = device_partition_ids(table, keys, n_sub, seed=_GRACE_SEED)
        return [shrink_to_fit(table.filter_mask(pid == s), self.min_bucket)
                for s in range(n_sub)]

    def _grace_build_parts(self, build: DeviceTable, n_sub: int):
        """-> (list of build-part spill handles, close_when_done)."""
        from ..memory.catalog import SpillPriorities, get_catalog
        catalog = get_catalog()
        return [catalog.register(t, SpillPriorities.INPUT)
                for t in self._grace_split(build, self.right_keys, n_sub)], \
            True

    def _grace_join(self, build: DeviceTable, pidx: int
                    ) -> Iterator[DeviceTable]:
        from ..memory.catalog import SpillPriorities, get_catalog
        catalog = get_catalog()
        n_sub = min(64, max(2, math.ceil(build.nbytes() / self.batch_bytes)))
        build_parts, own_build = self._grace_build_parts(build, n_sub)
        del build
        track = self.how in ("right", "full")
        probe_parts: List[List] = [[] for _ in range(n_sub)]
        try:
            for probe in _device_batches(self.left, pidx):
                parts = self._grace_split(probe, self.left_keys, n_sub)
                # one batched-funnel transfer resolves every bucket's
                # count instead of n_sub per-bucket syncs
                ns = resolve_scalars(*[t.num_rows for t in parts])
                for s, (t, tn) in enumerate(zip(parts, ns)):
                    if int(tn):
                        probe_parts[s].append(
                            catalog.register(t, SpillPriorities.INPUT))
            for s in range(n_sub):
                def sub_batches():
                    for h in probe_parts[s]:
                        with h as t:
                            yield t
                seen_box = None
                if track:
                    with build_parts[s] as bt:
                        seen_box = [jnp.zeros(bt.capacity, dtype=bool)]
                if probe_parts[s] or track:
                    yield from self._probe_join(build_parts[s],
                                                sub_batches(), seen_box)
                if track:
                    # never-probed buckets still owe all their build rows
                    leftover = self._leftover_fn()
                    with build_parts[s] as bt:
                        yield leftover(bt, seen_box[0])
        finally:
            if own_build:
                for h in build_parts:
                    h.close()
            for hs in probe_parts:
                for h in hs:
                    h.close()


class TpuBroadcastHashJoinExec(TpuShuffledHashJoinExec):
    """Build side materialized once across partitions (reference:
    GpuBroadcastHashJoinExec + SerializeConcatHostBuffersDeserializeBatch)."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # broadcasting the build side is unsound when its unmatched rows
        # appear in the output (duplicated per probe partition)
        assert self.how not in ("right", "full"), \
            f"{self.how} join cannot broadcast the right side"
        self._bc_handle = None
        self._bc_grace_parts = None
        self._bc_lock = __import__("threading").Lock()

    def _broadcast_handle(self):
        """Broadcast batch registered once with the BufferCatalog at
        BROADCAST priority — accounted and spillable rather than pinned to
        the exec node for the plan's lifetime. The catalog entry releases
        at query end (release_spill_handles), with a GC-time finalizer
        fallback for plans never explicitly released. The lock keeps
        concurrent (pipelined) probe partitions from double-building.
        Never block on the semaphore while holding it
        (pipeline.exempt_admission invariant)."""
        with self._bc_lock:
            from ..parallel.pipeline import exempt_admission
            with exempt_admission():
                return self._broadcast_handle_locked()

    def _broadcast_handle_locked(self):
        if self._bc_handle is None:
            from ..memory.catalog import SpillPriorities, get_catalog
            batches = []
            for p in range(self.right.num_partitions):  # srtpu: mesh-ok(build-side INPUT drain: collecting the broadcast table's partitions, not per-shard compute)
                batches.extend(_device_batches(self.right, p))
            if not batches:
                from .aggregate import _empty_device_table
                table = _empty_device_table(self.right.schema,
                                            self.min_bucket)
            elif len(batches) == 1:
                table = batches[0]
            else:
                # broadcast build tables are unsplittable: every probe
                # partition needs the whole table — spill-only retry
                from ..memory.retry import with_retry
                table = with_retry(concat_device_tables, batches,
                                   scope="join-build",
                                   context=self.node_desc())
            self._bc_handle = get_catalog().register(
                table, SpillPriorities.BROADCAST)
            self._own_spill_handle(self._bc_handle)
        return self._bc_handle

    def _build_table(self, pidx: int) -> DeviceTable:
        return self._broadcast_handle().get()

    def _register_build(self, build: DeviceTable):
        return self._broadcast_handle(), False

    def _grace_build_parts(self, build: DeviceTable, n_sub: int):
        """Split the broadcast once; reuse the parts for every partition."""
        with self._bc_lock:
            if self._bc_grace_parts is None:
                from ..parallel.pipeline import exempt_admission
                with exempt_admission():
                    parts, _ = super()._grace_build_parts(build, n_sub)
                self._bc_grace_parts = parts
                for h in parts:
                    self._own_spill_handle(h)
            return self._bc_grace_parts, False


def _close_quietly(handle):
    try:
        handle.close()
    except Exception:
        pass  # srtpu: net-ok(best-effort release of an already-consumed spill handle; the data was read before this)


class TpuBroadcastNestedLoopJoinExec(TpuExec):
    """Non-equi / cross join: the right side is broadcast once, the stream
    (left) side crosses it in windows sized so window_rows x build_capacity
    stays under the batch budget (reference:
    GpuBroadcastNestedLoopJoinExec.scala + GpuCartesianProductExec.scala;
    conditions compile into the traced kernel like the reference's AST
    conditions).

    right/full outer consume ALL stream partitions inside partition 0 so
    unmatched build rows are emitted exactly once (the reference instead
    requires the build side opposite the outer side; with a single
    broadcast side this serialization is the sound equivalent).
    """

    SUPPORTED = ("inner", "cross", "left", "right", "full", "left_semi",
                 "left_anti")
    EXTRA_METRICS = (M.JOIN_TIME,)

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, how: str,
                 condition: Optional[Expression], min_bucket: Optional[int] = None,
                 batch_bytes: int = 512 * 1024 * 1024):
        super().__init__()
        assert how in self.SUPPORTED, how
        self.left, self.right = left, right
        self.children = (left, right)
        self.how = how
        self.condition = condition
        self.min_bucket = resolve_min_bucket(min_bucket)
        self.batch_bytes = batch_bytes
        self.schema = _join_schema(left.schema, right.schema, None, how)
        self._bc_handle = None

    @property
    def num_partitions(self) -> int:
        return self.left.num_partitions

    def node_desc(self):
        return f"{self.how} condition={self.condition!r}"

    def plan_signature(self) -> str:
        return (f"BNLJ|{self.how}|{self.condition!r}|"
                f"{self.left.schema!r}|{self.right.schema!r}")

    def _broadcast_handle(self):
        if self._bc_handle is None:
            from ..memory.catalog import SpillPriorities, get_catalog
            batches = []
            for p in range(self.right.num_partitions):  # srtpu: mesh-ok(build-side INPUT drain: collecting the broadcast table's partitions, not per-shard compute)
                batches.extend(_device_batches(self.right, p))
            if not batches:
                from .aggregate import _empty_device_table
                table = _empty_device_table(self.right.schema,
                                            self.min_bucket)
            elif len(batches) == 1:
                table = batches[0]
            else:
                # broadcast build tables are unsplittable: every stream
                # window crosses the whole table — spill-only retry
                from ..memory.retry import with_retry
                table = with_retry(concat_device_tables, batches,
                                   scope="join-build",
                                   context=self.node_desc())
            table = shrink_to_fit(table, self.min_bucket)
            self._bc_handle = get_catalog().register(
                table, SpillPriorities.BROADCAST)
            self._own_spill_handle(self._bc_handle)
        return self._bc_handle

    # -- assembly & padding (stream side plays the probe role) ---------------
    def assemble(self, scols: List[DeviceColumn], bcols: List[DeviceColumn]):
        names = list(self.left.schema.names) + list(self.right.schema.names)
        return list(scols) + list(bcols), names

    def pad_stream(self, stream: DeviceTable, emit: jax.Array) -> DeviceTable:
        bcols = [_null_device_column(f.dtype, stream.capacity)
                 for f in self.right.schema]
        scols = [c.with_validity(jnp.logical_and(c.validity, emit))
                 for c in stream.columns]
        cols, names = self.assemble(scols, bcols)
        return DeviceTable(tuple(cols), emit, jnp.sum(emit, dtype=jnp.int32),
                           tuple(names))

    def pad_build(self, build: DeviceTable, emit: jax.Array) -> DeviceTable:
        scols = [_null_device_column(f.dtype, build.capacity)
                 for f in self.left.schema]
        bcols = [c.with_validity(jnp.logical_and(c.validity, emit))
                 for c in build.columns]
        cols, names = self.assemble(scols, bcols)
        return DeviceTable(tuple(cols), emit, jnp.sum(emit, dtype=jnp.int32),
                           tuple(names))

    # -- kernels --------------------------------------------------------------
    def cross_fn(self, ws: int, how: str):
        """One stream-window x build cross product with traced condition."""
        node = self

        def fn(window: DeviceTable, build: DeviceTable, seen):
            nb = build.capacity
            j = jnp.arange(ws * nb, dtype=jnp.int64)
            si = (j // nb).astype(jnp.int32)
            bi = (j % nb).astype(jnp.int32)
            valid = jnp.logical_and(jnp.take(window.row_mask, si),
                                    jnp.take(build.row_mask, bi))
            scols = _gather_columns(window, si, valid)
            bcols = _gather_columns(build, bi, valid)
            cols, names = node.assemble(scols, bcols)
            pairs = DeviceTable(tuple(cols), valid,
                                jnp.sum(valid, dtype=jnp.int32), tuple(names))
            if node.condition is not None:
                keep = _condition_mask(node.condition, pairs)
            else:
                keep = valid
            pairs = pairs.filter_mask(keep)
            any_pass = jnp.zeros(window.capacity, dtype=bool).at[si].max(
                keep, mode="drop")
            outs = []
            if how in ("inner", "cross", "left", "right", "full"):
                outs.append(pairs)
            if how in ("left", "full"):
                unmatched = jnp.logical_and(window.row_mask,
                                            jnp.logical_not(any_pass))
                outs.append(node.pad_stream(window, unmatched))
            if how == "left_semi":
                outs.append(window.filter_mask(any_pass))
            if how == "left_anti":
                outs.append(window.filter_mask(jnp.logical_not(any_pass)))
            if how in ("right", "full"):
                seen = jnp.logical_or(
                    seen,
                    jnp.zeros(nb, dtype=bool).at[bi].max(keep, mode="drop"))
            return tuple(outs), seen
        return fn

    def leftover_fn(self):
        node = self

        def fn(build: DeviceTable, seen):
            emit = jnp.logical_and(build.row_mask, jnp.logical_not(seen))
            return node.pad_build(build, emit)
        return fn

    # -- execution ------------------------------------------------------------
    def _budget_rows(self) -> int:
        """Cross-product pair-slot budget derived from the byte budget."""
        row_bytes = 0
        for f in self.schema:
            if isinstance(f.dtype, (dt.StringType, dt.BinaryType)):
                row_bytes += 32
            else:
                row_bytes += f.dtype.np_dtype().itemsize
            row_bytes += 1
        return max(self.min_bucket, self.batch_bytes // max(row_bytes, 1))

    def _window_shape(self, build_cap: int):
        """(stream_window_rows, build_window_rows): both sides window so
        stream_ws x build_ws pair slots stay under the budget even when the
        broadcast side alone exceeds it (fixes the reference-scale case
        where GpuBroadcastNestedLoopJoinExec streams the build side too)."""
        budget = self._budget_rows()
        build_ws = bucket_rows(
            min(build_cap, max(self.min_bucket, budget // self.min_bucket)),
            self.min_bucket)
        stream_ws = bucket_rows(max(1, budget // build_ws), self.min_bucket)
        return stream_ws, min(build_ws, bucket_rows(build_cap,
                                                    self.min_bucket))

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from .fallback import quarantine_on_failure
        # note-only boundary: the probe needs the whole build table, so a
        # terminal failure can't fall back per-batch — but it quarantines
        with quarantine_on_failure(self):
            for out in self._join_batches(pidx):
                self.account_batch()
                yield out

    def _join_batches(self, pidx: int) -> Iterator[DeviceTable]:
        track = self.how in ("right", "full")
        if track and pidx != 0:
            return
        handle = self._broadcast_handle()
        with handle as build:
            build_cap = build.capacity
        ws, bws = self._window_shape(build_cap)
        n_bslices = max(1, math.ceil(build_cap / bws))
        semi_like = self.how in ("left_semi", "left_anti")
        # per-build-slice semantics: pairs emit per slice; stream-side
        # outer/semi decisions need the OR across slices, so single-slice
        # keeps the fast path and multi-slice accumulates per window
        fn = cached_jit(self.plan_signature() + f"|cross{ws}x{bws}",
                        lambda: self.cross_fn(ws, self.how))
        # multi-slice variant: pairs only ("right" also threads the seen
        # update for right/full; stream-side fixup happens after all slices)
        pairs_how = "right" if track else (
            "cross" if self.how == "cross" else "inner")
        pairs_fn = cached_jit(
            self.plan_signature() + f"|crosspairs{pairs_how}{ws}x{bws}",
            lambda: self.cross_fn(ws, pairs_how))
        seen_slices = [jnp.zeros(min(bws, build_cap), dtype=bool)
                       for _ in range(n_bslices)] if track else None
        parts = range(self.left.num_partitions) if track else [pidx]
        for sp in parts:
            for batch in _device_batches(self.left, sp):
                batch = batch.compact()
                (nrows,) = resolve_scalars(batch.num_rows)
                nrows = max(0, int(nrows))
                start = 0
                while start < nrows:
                    window = slice_rows(batch, start, ws)
                    start += ws
                    yield from self._cross_window(
                        window, handle, n_bslices, bws, fn, pairs_fn,
                        seen_slices, semi_like)
        if track:
            leftover = cached_jit(self.plan_signature() + "|bnlj_leftover",
                                  self.leftover_fn)
            for bi in range(n_bslices):
                with handle as build:
                    bslice = slice_rows(build, bi * bws, min(bws, build_cap))
                    yield leftover(bslice, seen_slices[bi])

    def _cross_window(self, window, handle, n_bslices, bws, fn, pairs_fn,
                      seen_slices, semi_like) -> Iterator[DeviceTable]:
        track = seen_slices is not None
        if n_bslices == 1:
            with self.metrics.timed(M.JOIN_TIME), handle as build:
                window = _co_locate(window, build)
                outs, seen = fn(window, build, seen_slices[0] if track
                                else jnp.zeros(build.capacity, dtype=bool))
            if track:
                seen_slices[0] = seen
            yield from outs
            return
        # multi-slice: emit inner pairs per slice; accumulate per-stream-row
        # any_pass across slices for outer/semi fixup at the end
        any_pass = jnp.zeros(window.capacity, dtype=bool)
        for bi in range(n_bslices):
            with self.metrics.timed(M.JOIN_TIME), handle as build:
                window = _co_locate(window, build)
                bslice = slice_rows(build, bi * bws,
                                    min(bws, build.capacity))
                outs, seen = pairs_fn(
                    window, bslice,
                    seen_slices[bi] if track
                    else jnp.zeros(bslice.capacity, dtype=bool))
                pairs = outs[0]
                matched = jnp.zeros(window.capacity, dtype=bool)
                if self.how not in ("inner", "cross"):
                    # recompute stream-row matches from the pair mask
                    nb = bslice.capacity
                    si = (jnp.arange(pairs.capacity, dtype=jnp.int32) // nb)
                    matched = jnp.zeros(window.capacity, dtype=bool).at[
                        si].max(pairs.row_mask, mode="drop")
            if track:
                seen_slices[bi] = seen
            any_pass = jnp.logical_or(any_pass, matched)
            if self.how in ("inner", "cross", "left", "right", "full"):
                yield pairs
        if self.how in ("left", "full"):
            unmatched = jnp.logical_and(window.row_mask,
                                        jnp.logical_not(any_pass))
            yield self.pad_stream(window, unmatched)
        elif self.how == "left_semi":
            yield window.filter_mask(any_pass)
        elif self.how == "left_anti":
            yield window.filter_mask(jnp.logical_not(any_pass))


def _condition_filter_fn(condition: Expression):
    def fn(table: DeviceTable) -> DeviceTable:
        ctx = EvalContext.for_device(table)
        c = condition.eval(ctx)
        keep = c.values
        if c.validity is not None:
            keep = jnp.logical_and(keep, c.validity)
        return table.filter_mask(keep)
    return fn


def _device_batches(child: PhysicalPlan, pidx: int) -> Iterator[DeviceTable]:
    assert hasattr(child, "execute_columnar"), \
        f"join child {type(child).__name__} is not columnar (missing transition)"
    return child.execute_columnar(pidx)
