"""TpuGenerateExec — device explode/posexplode over fixed-width arrays.

Reference: GpuGenerateExec.scala:631 (exec rule GenerateExec,
GpuOverrides.scala:3481). TPU-native shape: the output row count is data-
dependent, so each batch syncs one int (the exploded total) to pick a
bucketed output capacity, then a single gather program expands rows —
``src_row = searchsorted(cumsum(counts), k)`` — with no per-row Python.
Map explode and arrays outside the device list layout stay on the host
``CpuGenerateExec`` via TypeSig gating, like the reference's per-type
nesting checks (TypeChecks.scala:166).
"""
from __future__ import annotations

from typing import Iterator, List

import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.device import (DeviceColumn, DeviceTable, bucket_rows,
                               resolve_min_bucket, resolve_scalars)
from ..expr.base import EvalContext
from ..expr.collections import PosExplode
from ..plan.physical import PhysicalPlan
from ..plan.schema import Field, Schema
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuGenerateExec"]


class TpuGenerateExec(TpuExec):
    def __init__(self, child: PhysicalPlan, generator, outer: bool,
                 gen_fields, min_bucket: Optional[int] = None):
        super().__init__()
        self.child = child
        self.children = (child,)
        self.generator = generator
        self.outer = outer
        self.gen_fields = gen_fields
        self.min_bucket = resolve_min_bucket(min_bucket)
        self.schema = Schema(
            list(child.schema.fields)
            + [Field(n, d, nb or outer) for n, d, nb in gen_fields])

    @property
    def fusible(self) -> bool:
        return False        # output capacity is data-dependent

    def node_desc(self) -> str:
        kind = "posexplode" if isinstance(self.generator, PosExplode) \
            else "explode"
        return f"{kind} outer={self.outer}"

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        for batch in self.child_device_batches(pidx):
            with self.metrics.timed(M.OP_TIME):
                # total was already host-resolved for the capacity choice
                # — reuse it for the row metric instead of a second sync
                out, total = self._explode_batch(batch, pidx)
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            self.metrics.add(M.NUM_OUTPUT_ROWS, total)
            yield out

    def _explode_batch(self, batch: DeviceTable, pidx: int):
        ctx = EvalContext.for_device(batch, partition_id=pidx)
        col = self.generator.children[0].eval(ctx)
        cap = batch.capacity
        active = batch.row_mask
        valid = jnp.logical_and(col.valid_mask(ctx), active)
        lens = jnp.where(valid, col.lengths.astype(jnp.int32), 0)
        if self.outer:
            # null/empty arrays still emit one row (with a null element)
            counts = jnp.where(active, jnp.maximum(lens, 1), 0)
        else:
            counts = lens
        # output capacity is data-dependent: one batched-funnel transfer
        # resolves the exploded total (the decision boundary)
        (total,) = resolve_scalars(jnp.sum(counts))
        total = int(total)
        out_cap = bucket_rows(max(total, 1), self.min_bucket)

        cum = jnp.cumsum(counts)
        k = jnp.arange(out_cap, dtype=jnp.int32)
        src = jnp.searchsorted(cum, k, side="right")
        src_c = jnp.clip(src, 0, cap - 1).astype(jnp.int32)
        start = cum[src_c] - counts[src_c]
        eidx = (k - start).astype(jnp.int32)
        row_ok = k < total
        elem_valid = jnp.logical_and(row_ok, eidx < lens[src_c])

        out_cols: List[DeviceColumn] = []
        for c in batch.columns:
            g = c.gather(src_c, keep_all_valid=True)
            out_cols.append(g.with_validity(
                jnp.logical_and(g.validity, row_ok)))
        names = list(batch.names)
        gen_names = [n for n, _, _ in self.gen_fields]
        if isinstance(self.generator, PosExplode):
            out_cols.append(DeviceColumn(
                jnp.where(elem_valid, eidx, 0), elem_valid, dt.INT, None))
        w = col.values.shape[1]
        elem_dt = self.gen_fields[-1][1]
        # gather the source rows of the list matrix, then pick the element
        row_vals = jnp.take(col.values, src_c, axis=0)
        pick = jnp.clip(eidx, 0, w - 1)[:, None]
        evals = jnp.take_along_axis(row_vals, pick, axis=1)[:, 0]
        if col.elem_validity is not None:
            # containsNull arrays: a null element explodes to a null row value
            row_ev = jnp.take(col.elem_validity, src_c, axis=0)
            elem_valid = jnp.logical_and(
                elem_valid, jnp.take_along_axis(row_ev, pick, axis=1)[:, 0])
        evals = jnp.where(elem_valid, evals, jnp.zeros((), evals.dtype))
        out_cols.append(DeviceColumn(evals, elem_valid, elem_dt, None))
        return DeviceTable(tuple(out_cols), row_ok,
                           jnp.asarray(total, jnp.int32),
                           tuple(names + gen_names)), total
