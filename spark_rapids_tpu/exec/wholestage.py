"""Whole-stage fusion: compose adjacent fusible device operators into a single
jitted XLA computation.

The reference executes one cuDF kernel per operator call; on TPU the win is
the opposite — let XLA fuse a project/filter/partial-aggregate chain into one
program so intermediate columns never hit HBM. This is the TPU analogue of
Spark's whole-stage codegen (which the reference replaces with columnar
exec — see GpuExec.scala docs) and is inserted by plan/transitions.py after
lowering.

Input-buffer donation: when the chain CONSUMES its input batch (the batch
is exclusively owned — see exec/transitions.py mark_exclusive: uploads not
retained by the scan cache), the fused program runs with
``donate_argnums=(0,)`` so XLA may reuse the input buffers for the output,
cutting peak HBM per batch roughly in half for projection-shaped chains.
Shared batches (cached uploads, catalog/spill handles, broadcast tables)
never donate. Donated bytes are accounted in the ``donatedBytes`` metric.
"""
from __future__ import annotations

from typing import Iterator, List

import jax

from ..columnar.device import DeviceTable
from ..conf import register_conf
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuWholeStageExec", "fuse_stages", "DONATION_ENABLED",
           "donation_active"]

DONATION_ENABLED = register_conf(
    "spark.rapids.tpu.donation.enabled",
    "Donate exclusively-owned input batches to fused XLA programs "
    "(donate_argnums) so the output can reuse the input's HBM. Only "
    "batches the chain provably consumes are donated (uploads not "
    "retained by the scan device cache); cached/spillable batches are "
    "never donated. No effect on backends without buffer donation "
    "(XLA:CPU).", True)

DONATION_FORCE = register_conf(
    "spark.rapids.tpu.donation.force",
    "Testing only: request donation even on backends that do not "
    "implement it (XLA ignores the request with a warning).", False,
    internal=True)


def donation_active(conf) -> bool:
    """Whether fused stages should compile a donating entry point."""
    if not conf.get(DONATION_ENABLED):
        return False
    if conf.get(DONATION_FORCE):
        return True
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # backend init failure: planning must not die here  # srtpu: degrade-ok(plan-time capability probe, no device work in flight)
        return False


class TpuWholeStageExec(TpuExec):
    """Wraps a linear chain of fusible TpuExecs [bottom, ..., top]."""

    EXTRA_METRICS = (M.PIPELINE_WAIT, M.DONATED_BYTES)

    def __init__(self, chain: List[TpuExec], donate_inputs: bool = False):
        super().__init__()
        assert chain, "empty fusion chain"
        # flatten nested whole-stages: the bottom-up fuse pass wraps inner
        # chains before outer fusible parents are seen, so a parent's chain
        # may contain an already-fused node
        chain = [m for n in chain
                 for m in (n.chain if isinstance(n, TpuWholeStageExec)
                           else [n])]
        self.chain = chain
        self.donate_inputs = donate_inputs
        bottom = chain[0]
        # the producer feeding the chain (transition or other non-fused exec)
        self.source = bottom.children[0]
        self.children = (self.source,)
        self.schema = chain[-1].schema

    @property
    def num_partitions(self) -> int:
        return self.source.num_partitions

    def node_name(self):
        inner = "+".join(type(n).__name__.replace("Tpu", "").replace("Exec", "")
                         for n in self.chain)
        return f"TpuWholeStage[{inner}]"

    def plan_signature(self) -> str:
        return "WS|" + "||".join(n.plan_signature() for n in self.chain)

    def batch_fn(self):
        """Composed chain function — lets an outer fusible parent absorb
        this whole-stage into its own chain (see __init__ flattening)."""
        fns = [n.batch_fn() for n in self.chain]

        def run(table: DeviceTable) -> DeviceTable:
            for f in fns:
                table = f(table)
            return table
        return run

    def host_batch_fn(self):
        """Composed host-engine chain, or None when any member lacks a
        host path — the whole stage then quarantines on terminal failure
        but cannot recover the failing batch."""
        fns = [n.host_batch_fn() for n in self.chain]
        if any(f is None for f in fns):
            return None

        def run(table):
            for f in fns:
                table = f(table)
            return table
        return run

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..memory.retry import split_device_rows, with_retry_split
        from ..parallel.pipeline import maybe_prefetched, stage_name
        from ..utils.compile_cache import cached_jit
        from .transitions import take_exclusive
        chain = self.chain

        def build():
            fns = [n.batch_fn() for n in chain]

            def run(table: DeviceTable) -> DeviceTable:
                for f in fns:
                    table = f(table)
                return table
            return run

        sig = self.plan_signature()
        fused = cached_jit(sig, build)
        donating = cached_jit(sig + "|donate", build,
                              donate_argnums=(0,)) \
            if self.donate_inputs else None
        # stage boundary: the source (typically the upload transition)
        # produces the NEXT batch on a prefetch worker while XLA runs the
        # current one (parallel/pipeline.py)
        source = maybe_prefetched(
            lambda: self.source.execute_columnar(pidx),
            stage=f"source:{stage_name(self.source)}",
            registry=self.metrics)
        def dispatch(b: DeviceTable) -> DeviceTable:
            if donating is not None and take_exclusive(b):
                # nbytes BEFORE the call: donated buffers may be dead
                # the moment dispatch returns
                self.metrics.add(M.DONATED_BYTES, b.nbytes())
                return donating(b)
            return fused(b)

        # degradation boundary: the OOM ladder escalates INSIDE (spill →
        # retry → split); when it terminates — or the failure is a
        # classified non-retryable XLA error — the boundary re-runs the
        # batch through the composed host chain instead of failing the
        # query (exec/fallback.py)
        from .fallback import with_host_fallback
        run = with_host_fallback(
            self,
            lambda b: with_retry_split(dispatch, b,
                                       splitter=split_device_rows,
                                       scope="wholestage",
                                       context=self.node_name()),
            self.host_batch_fn())
        for batch in source:
            with self.metrics.timed(M.OP_TIME):
                # full OOM escalation ladder (memory/retry.py): the chain
                # is row-wise, so halves of the input concat back into the
                # same output. Split halves lose the exclusive flag and
                # dispatch through the non-donating entry.
                out = run(batch)
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            yield out


def fuse_stages(plan, conf=None):
    """Bottom-up pass replacing maximal fusible chains with TpuWholeStageExec.

    A node joins a chain when it is a TpuExec with ``batch_fn() is not None``
    and exactly one child. Chains of length 1 are left alone (plain jit in the
    node itself is equivalent). ``conf`` (when given) decides whether fused
    stages compile a donating entry point (see DONATION_ENABLED).
    """
    from ..plan.physical import PhysicalPlan

    donate = donation_active(conf) if conf is not None else False

    def rebuild(node: PhysicalPlan) -> PhysicalPlan:
        new_children = [rebuild(c) for c in node.children]
        node = _with_children(node, new_children)
        if _fusible(node):
            chain = [node]
            cur = node.children[0] if node.children else None
            while cur is not None and _fusible(cur):
                chain.insert(0, cur)
                cur = cur.children[0] if cur.children else None
            if len(chain) > 1:
                return TpuWholeStageExec(chain, donate_inputs=donate)
        return node

    return rebuild(plan)


def _fusible(node) -> bool:
    return isinstance(node, TpuExec) and len(node.children) == 1 \
        and node.fusible


def _with_children(node, children):
    if list(node.children) == list(children):
        return node
    node.children = tuple(children)
    if hasattr(node, "child") and len(children) == 1:
        node.child = children[0]
    return node
