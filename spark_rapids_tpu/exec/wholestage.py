"""Whole-stage fusion: compose adjacent fusible device operators into a single
jitted XLA computation.

The reference executes one cuDF kernel per operator call; on TPU the win is
the opposite — let XLA fuse a project/filter/partial-aggregate chain into one
program so intermediate columns never hit HBM. This is the TPU analogue of
Spark's whole-stage codegen (which the reference replaces with columnar
exec — see GpuExec.scala docs) and is inserted by plan/transitions.py after
lowering.
"""
from __future__ import annotations

from typing import Iterator, List

import jax

from ..columnar.device import DeviceTable
from ..utils import metrics as M
from .base import TpuExec

__all__ = ["TpuWholeStageExec", "fuse_stages"]


class TpuWholeStageExec(TpuExec):
    """Wraps a linear chain of fusible TpuExecs [bottom, ..., top]."""

    def __init__(self, chain: List[TpuExec]):
        super().__init__()
        assert chain, "empty fusion chain"
        # flatten nested whole-stages: the bottom-up fuse pass wraps inner
        # chains before outer fusible parents are seen, so a parent's chain
        # may contain an already-fused node
        chain = [m for n in chain
                 for m in (n.chain if isinstance(n, TpuWholeStageExec)
                           else [n])]
        self.chain = chain
        bottom = chain[0]
        # the producer feeding the chain (transition or other non-fused exec)
        self.source = bottom.children[0]
        self.children = (self.source,)
        self.schema = chain[-1].schema

    @property
    def num_partitions(self) -> int:
        return self.source.num_partitions

    def node_name(self):
        inner = "+".join(type(n).__name__.replace("Tpu", "").replace("Exec", "")
                         for n in self.chain)
        return f"TpuWholeStage[{inner}]"

    def plan_signature(self) -> str:
        return "WS|" + "||".join(n.plan_signature() for n in self.chain)

    def batch_fn(self):
        """Composed chain function — lets an outer fusible parent absorb
        this whole-stage into its own chain (see __init__ flattening)."""
        fns = [n.batch_fn() for n in self.chain]

        def run(table: DeviceTable) -> DeviceTable:
            for f in fns:
                table = f(table)
            return table
        return run

    def execute_columnar(self, pidx: int) -> Iterator[DeviceTable]:
        from ..utils.compile_cache import cached_jit
        chain = self.chain

        def build():
            fns = [n.batch_fn() for n in chain]

            def run(table: DeviceTable) -> DeviceTable:
                for f in fns:
                    table = f(table)
                return table
            return run

        fused = cached_jit(self.plan_signature(), build)
        for batch in self.source.execute_columnar(pidx):
            with self.metrics.timed(M.OP_TIME):
                out = fused(batch)
            self.metrics.add(M.NUM_OUTPUT_BATCHES, 1)
            yield out


def fuse_stages(plan):
    """Bottom-up pass replacing maximal fusible chains with TpuWholeStageExec.

    A node joins a chain when it is a TpuExec with ``batch_fn() is not None``
    and exactly one child. Chains of length 1 are left alone (plain jit in the
    node itself is equivalent).
    """
    from ..plan.physical import PhysicalPlan

    def rebuild(node: PhysicalPlan) -> PhysicalPlan:
        new_children = [rebuild(c) for c in node.children]
        node = _with_children(node, new_children)
        if _fusible(node):
            chain = [node]
            cur = node.children[0] if node.children else None
            while cur is not None and _fusible(cur):
                chain.insert(0, cur)
                cur = cur.children[0] if cur.children else None
            if len(chain) > 1:
                return TpuWholeStageExec(chain)
        return node

    return rebuild(plan)


def _fusible(node) -> bool:
    return isinstance(node, TpuExec) and len(node.children) == 1 \
        and node.fusible


def _with_children(node, children):
    if list(node.children) == list(children):
        return node
    node.children = tuple(children)
    if hasattr(node, "child") and len(children) == 1:
        node.child = children[0]
    return node
