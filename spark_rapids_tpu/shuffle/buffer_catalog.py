"""Shuffle buffer catalog — device-resident shuffle blocks.

Reference: ShuffleBufferCatalog.scala + RapidsCachingWriter
(RapidsShuffleInternalManagerBase.scala:92-155): written shuffle partitions
stay in the device store as spillable buffers keyed by
(shuffle, map, reduce); readers on the same executor consume them directly
(no serialize/deserialize round trip) and the spill framework migrates them
to host/disk under memory pressure. ``unregisterShuffle`` frees a whole
shuffle's blocks when the stage is done.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..columnar.device import DeviceTable
from ..memory.catalog import SpillPriorities, SpillableDeviceTable, get_catalog

__all__ = ["ShuffleBufferCatalog"]

BlockKey = Tuple[int, int, int]  # (shuffle_id, map_id, reduce_id)


class ShuffleBufferCatalog:
    def __init__(self):
        self._blocks: Dict[BlockKey, SpillableDeviceTable] = {}
        self._lock = threading.Lock()

    def put(self, key: BlockKey, table: DeviceTable) -> SpillableDeviceTable:
        handle = get_catalog().register(table,
                                        SpillPriorities.OUTPUT_FOR_SHUFFLE)
        with self._lock:
            old = self._blocks.get(key)
            self._blocks[key] = handle
        if old is not None:  # map-task re-run overwrites its old output
            old.close()
        return handle

    def get(self, key: BlockKey) -> Optional[SpillableDeviceTable]:
        with self._lock:
            return self._blocks.get(key)

    def has(self, key: BlockKey) -> bool:
        with self._lock:
            return key in self._blocks

    def blocks_for(self, shuffle_id: int) -> List[BlockKey]:
        with self._lock:
            return [k for k in self._blocks if k[0] == shuffle_id]

    def shuffle_ids(self) -> List[int]:
        with self._lock:
            return sorted({k[0] for k in self._blocks})

    def remove_shuffle(self, shuffle_id: int) -> int:
        """Close every block of a finished shuffle (unregisterShuffle)."""
        with self._lock:
            keys = [k for k in self._blocks if k[0] == shuffle_id]
            handles = [self._blocks.pop(k) for k in keys]
        for h in handles:
            h.close()
        return len(handles)

    def stats(self) -> dict:
        with self._lock:
            return {"blocks": len(self._blocks)}
