"""TCP socket shuffle transport — the cross-process tier of the SPI.

Reference mapping (SURVEY §2.7): plays the role of the transport
server/client pair (RapidsShuffleServer.scala:70 serving block data,
RapidsShuffleClient.scala:88 fetching from peers) at the always-works TCP
level; the RDMA/UCX specialization in the reference maps to ICI collectives
(shuffle/ici.py) on TPU, so the socket tier only needs to be correct and
portable, not zero-copy.

Round-3 rework (round-2 weak #4): blocks no longer live as whole ``bytes``
in a dict served in one send —

- published blocks go into a **spill-backed host store**: an in-memory
  budget (``spark.rapids.tpu.shuffle.host.storeBytes``), overflow spills
  oldest-first to local disk files and is served straight from disk
  (the spillable serving behind BufferSendState.scala).
- the server streams **fixed-size windows** of a block (ranged GET),
  never materializing more than a chunk per connection
  (``spark.rapids.tpu.shuffle.tcp.chunkBytes`` ~ WindowedBlockIterator).
- the client fetches blocks through a small worker pool under a
  **receive-inflight byte cap**
  (``spark.rapids.shuffle.transport.maxReceiveInflightBytes`` — the
  reference's throttle, RapidsConf.scala:1064): a block reserves its
  size before its chunks stream in, and the reservation releases when
  the consumer takes the block.

Wire protocol (little-endian), one request per connection:

    request:  magic 'SRTB'|'SRTC' | u8 op | i64 shuffle | i64 map |
              i64 reduce
              (magic SRTC only) | 16s trace_id | u64 parent_span | i64 qid
              (op GET_RANGE only) | i64 offset | i64 max_len
    response: u8 found | u64 total_len | (GET_RANGE only) u64 chunk_len |
              payload
    ops: 1 = GET (whole block), 2 = REMOVE_SHUFFLE, 3 = GET_RANGE

The 'SRTC' magic is the traced variant: a fixed TraceContext header rides
between the base request and any op extension, so the serving side's
spans parent under the requesting query's span in the merged timeline
(``spark.rapids.tpu.trace.distributed.enabled``). Servers accept both
magics — an untraced client talks to a traced server and vice versa.
"""
from __future__ import annotations

import os
import random
import socket
import struct
import tempfile
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Tuple

from ..conf import RapidsConf, _positive, register_conf
from ..utils import faults
from ..utils.tracing import (TRACE_DISTRIBUTED, TraceContext,
                             activate_trace_context, current_trace_context,
                             get_tracer)
from . import telemetry
from .transport import (BlockId, ShuffleFetchFailedException,
                        ShuffleTransport)

__all__ = ["TcpShuffleTransport"]

TCP_CHUNK_BYTES = register_conf(
    "spark.rapids.tpu.shuffle.tcp.chunkBytes",
    "Window size for serving shuffle blocks over the TCP transport: a "
    "block streams in fixed-size chunks instead of one send (reference: "
    "BufferSendState bounce-buffer windows, RapidsShuffleServer.scala:70).",
    1 << 20, checker=lambda v: None if int(v) > 0 else "must be positive")

MAX_RECEIVE_INFLIGHT = register_conf(
    "spark.rapids.shuffle.transport.maxReceiveInflightBytes",
    "Receive-side throttle: total bytes of shuffle blocks in flight "
    "(being fetched or fetched-but-unconsumed) at one time (reference: "
    "RapidsConf.scala:1064).", 64 << 20,
    checker=lambda v: None if int(v) > 0 else "must be positive")

HOST_STORE_BYTES = register_conf(
    "spark.rapids.tpu.shuffle.host.storeBytes",
    "In-memory budget for published shuffle blocks on the TCP transport; "
    "overflow spills oldest-first to local disk and is served from there "
    "(reference: spillable shuffle buffers backing BufferSendState).",
    256 << 20, checker=lambda v: None if int(v) > 0 else "must be positive")

TCP_CONNECT_TIMEOUT = register_conf(
    "spark.rapids.tpu.shuffle.tcp.connectTimeout",
    "Seconds to wait for a TCP connect to a shuffle peer before the "
    "attempt counts as a transient failure (retried with backoff).",
    10.0, checker=_positive("connect timeout"))

TCP_READ_TIMEOUT = register_conf(
    "spark.rapids.tpu.shuffle.tcp.readTimeout",
    "Per-socket-operation read timeout (seconds) on shuffle connections, "
    "client and server side — no socket in the transport blocks forever.",
    30.0, checker=_positive("read timeout"))

TCP_RETRY_ATTEMPTS = register_conf(
    "spark.rapids.tpu.shuffle.tcp.retryAttempts",
    "Attempts per peer for one ranged shuffle request. Transient socket "
    "errors (refused, reset, timeout) are retried with exponential "
    "backoff + jitter; a peer answering 'block not found' is definitive "
    "and never retried (that path stays ShuffleFetchFailedException -> "
    "recompute).",
    4, checker=_positive("retry attempts"))

TCP_RETRY_BACKOFF_MS = register_conf(
    "spark.rapids.tpu.shuffle.tcp.retryBackoffMs",
    "Base backoff (milliseconds) between transient-error retries; grows "
    "exponentially per attempt with +/-50% jitter.",
    50.0, checker=_positive("retry backoff"))

TCP_RETRY_MAX_BACKOFF_MS = register_conf(
    "spark.rapids.tpu.shuffle.tcp.retryMaxBackoffMs",
    "Cap (milliseconds) on the exponential retry backoff.",
    1000.0, checker=_positive("max backoff"))

TCP_MAX_PROVIDER_RETRIES = register_conf(
    "spark.rapids.tpu.shuffle.host.maxProviderRetries",
    "Times a lazy block provider that raised may be re-registered for "
    "another request. Keeping a block requestable after a failed send is "
    "what lets a retrying peer succeed, but a crash-looping provider "
    "must not stay requestable (and pin its inputs) forever.",
    3, checker=_positive("provider retries"))

_MAGIC = b"SRTB"
_MAGIC_TRACED = b"SRTC"
_OP_GET = 1
_OP_REMOVE = 2
_OP_GET_RANGE = 3
_REQ = struct.Struct("<4sBqqq")
_RANGE_EXT = struct.Struct("<qq")
_RESP_HEAD = struct.Struct("<BQ")
_RESP_CHUNK = struct.Struct("<Q")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))  # srtpu: net-ok(every caller sets a read timeout on the socket before handing it here)
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


class _HostBlockStore:
    """Budgeted in-memory block store with oldest-first disk spill."""

    def __init__(self, budget_bytes: int, max_provider_retries: int = 3):
        self._budget = budget_bytes
        self._max_provider_retries = max(1, int(max_provider_retries))
        self._mem: "OrderedDict[BlockId, bytes]" = OrderedDict()
        self._disk: Dict[BlockId, Tuple[str, int]] = {}   # path, length
        self._providers: Dict[BlockId, object] = {}   # lazy payload fns
        self._provider_retries: Dict[BlockId, int] = {}
        self._spilling: set = set()   # victims mid-write, still in _mem
        self._lock = threading.Lock()
        self._mat_inflight: set = set()   # blocks materializing right now
        self._mat_cond = threading.Condition(self._lock)
        self._dir: Optional[str] = None
        self.mem_bytes = 0
        self.spilled_blocks = 0
        self.spilled_bytes = 0

    def _spill_dir(self) -> str:
        if self._dir is None:
            self._dir = tempfile.mkdtemp(prefix="srtpu-shuffle-blocks-")
        return self._dir

    def put(self, block: BlockId, payload: bytes) -> None:
        with self._lock:
            old = self._mem.pop(block, None)
            if old is not None:
                self.mem_bytes -= len(old)
            disk_old = self._disk.pop(block, None)
            self._mem[block] = payload
            self.mem_bytes += len(payload)
            # choose spill victims but KEEP them readable in _mem until
            # their disk entry exists — a concurrent read during the file
            # write must never see the block in neither map
            victims = []
            excess = self.mem_bytes - self._budget
            for b in list(self._mem.keys()):            # oldest first
                if excess <= 0 or \
                        len(self._mem) - len(self._spilling) <= 1:
                    break
                if b in self._spilling or b == block:
                    continue
                self._spilling.add(b)
                victims.append((b, self._mem[b]))
                excess -= len(self._mem[b])
        if disk_old is not None:
            _unlink_quietly(disk_old[0])
        for victim, data in victims:
            path = os.path.join(
                self._spill_dir(),
                f"b{victim[0]}_{victim[1]}_{victim[2]}.blk")
            with open(path, "wb") as f:
                f.write(data)
            with self._lock:
                self._spilling.discard(victim)
                if self._mem.get(victim) is data:   # not replaced/removed
                    self._disk[victim] = (path, len(data))
                    del self._mem[victim]
                    self.mem_bytes -= len(data)
                    self.spilled_blocks += 1
                    self.spilled_bytes += len(data)
                    continue
            _unlink_quietly(path)

    def put_lazy(self, block: BlockId, provider) -> None:
        """Register a deferred payload: ``provider()`` -> bytes runs on the
        first request for this block (DCN tier: blocks stay device-resident
        until a remote peer actually asks — most never serialize)."""
        with self._lock:
            self._providers[block] = provider

    def lazy_depth(self) -> int:
        """Publish-queue depth: lazy providers registered but not yet
        materialized (the shuffle observatory's backpressure signal)."""
        with self._lock:
            return len(self._providers)

    def _materialize(self, block: BlockId) -> None:
        with self._lock:
            # a concurrent materialization of this block: wait for it to
            # land in _mem/_disk instead of reporting the block missing
            while block in self._mat_inflight:
                self._mat_cond.wait()
            provider = self._providers.pop(block, None)
            if provider is None:
                return
            self._mat_inflight.add(block)
        try:
            payload = provider()
        except Exception:
            with self._lock:
                # keep it requestable for a retry, but bounded: a
                # crash-looping provider must not stay registered (and
                # pin its inputs in host memory) forever — after the
                # budget the block simply reports missing, which the
                # fetch path turns into fetch-failed -> recompute
                n = self._provider_retries.get(block, 0) + 1
                self._provider_retries[block] = n
                if n < self._max_provider_retries:
                    self._providers.setdefault(block, provider)
                self._mat_inflight.discard(block)
                self._mat_cond.notify_all()
            raise
        self.put(block, payload)
        with self._lock:
            self._provider_retries.pop(block, None)
            self._mat_inflight.discard(block)
            self._mat_cond.notify_all()

    def length(self, block: BlockId) -> Optional[int]:
        with self._lock:
            pending = block in self._providers \
                or block in self._mat_inflight
        if pending:
            self._materialize(block)
        with self._lock:
            data = self._mem.get(block)
            if data is not None:
                return len(data)
            entry = self._disk.get(block)
            return None if entry is None else entry[1]

    def read(self, block: BlockId, offset: int, n: int) -> Optional[bytes]:
        with self._lock:
            pending = block in self._providers \
                or block in self._mat_inflight
        if pending:
            self._materialize(block)
        with self._lock:
            data = self._mem.get(block)
            entry = self._disk.get(block) if data is None else None
        if data is not None:
            return data[offset:offset + n]
        if entry is None:
            return None
        path, _ = entry
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                return f.read(n)
        except OSError:
            return None

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            for b in [b for b in self._providers if b[0] == shuffle_id]:
                del self._providers[b]
            for b in [b for b in self._provider_retries
                      if b[0] == shuffle_id]:
                del self._provider_retries[b]
            for b in [b for b in self._mem if b[0] == shuffle_id]:
                self.mem_bytes -= len(self._mem.pop(b))
            doomed = [self._disk.pop(b)[0]
                      for b in [b for b in self._disk if b[0] == shuffle_id]]
        for path in doomed:
            _unlink_quietly(path)

    def close(self) -> None:
        with self._lock:
            paths = [p for (p, _) in self._disk.values()]
            self._disk.clear()
            self._mem.clear()
            self.mem_bytes = 0
            spill_dir, self._dir = self._dir, None
        for p in paths:
            _unlink_quietly(p)
        if spill_dir is not None:
            try:
                os.rmdir(spill_dir)
            except OSError:
                pass


def _unlink_quietly(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


class _Turnstile:
    """Orders inflight-budget acquisitions by ticket: ticket k proceeds
    only after tickets < k have acquired (or bailed). Idempotent advance."""

    def __init__(self):
        self._next = 0
        self._cv = threading.Condition()

    def wait_turn(self, ticket: int) -> None:
        with self._cv:
            while self._next < ticket:
                self._cv.wait()

    def advance(self, ticket: int) -> None:
        with self._cv:
            if ticket + 1 > self._next:
                self._next = ticket + 1
                self._cv.notify_all()


class _InflightBudget:
    """Counting byte semaphore for the receive throttle."""

    def __init__(self, limit: int):
        self._limit = limit
        self._used = 0
        self._cv = threading.Condition()
        self.peak = 0

    def acquire(self, n: int) -> None:
        n = min(n, self._limit)  # one oversized block must not deadlock
        with self._cv:
            while self._used + n > self._limit:
                self._cv.wait()
            self._used += n
            self.peak = max(self.peak, self._used)

    def release(self, n: int) -> None:
        n = min(n, self._limit)
        with self._cv:
            self._used -= n
            self._cv.notify_all()


class TcpShuffleTransport(ShuffleTransport):
    def __init__(self, conf: Optional[RapidsConf] = None,
                 host: str = "127.0.0.1", port: int = 0):
        conf = conf or RapidsConf()
        self.chunk_bytes = int(conf.get(TCP_CHUNK_BYTES))
        self._trace_wire = bool(conf.get(TRACE_DISTRIBUTED))
        self._connect_timeout = float(conf.get(TCP_CONNECT_TIMEOUT))
        self._read_timeout = float(conf.get(TCP_READ_TIMEOUT))
        self._retry_attempts = max(1, int(conf.get(TCP_RETRY_ATTEMPTS)))
        self._backoff_s = float(conf.get(TCP_RETRY_BACKOFF_MS)) / 1000.0
        self._max_backoff_s = \
            float(conf.get(TCP_RETRY_MAX_BACKOFF_MS)) / 1000.0
        self._jitter = random.Random()
        #: set at close(): retry backoffs wait on it so shutdown never
        #: has to wait out a backoff schedule
        self._closed = threading.Event()
        self.store = _HostBlockStore(
            int(conf.get(HOST_STORE_BYTES)),
            int(conf.get(TCP_MAX_PROVIDER_RETRIES)))
        self.inflight = _InflightBudget(int(conf.get(MAX_RECEIVE_INFLIGHT)))
        self._lock = threading.Lock()
        self._peers: List[Tuple[str, int]] = []
        self.bytes_published = 0
        self.bytes_fetched = 0
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(32)
        self._closing = False
        self._thread = threading.Thread(target=self._serve,
                                        name="srtpu-shuffle-server",
                                        daemon=True)
        self._thread.start()

    # -- server side ----------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return self._server.getsockname()

    def _serve(self):
        while not self._closing:
            try:
                conn, _ = self._server.accept()  # srtpu: net-ok(the listener blocks until close tears the socket down — an accept deadline would only add spurious wakeups)
            except OSError:
                return  # socket closed
            threading.Thread(target=self._handle, args=(conn,),
                             name="srtpu-shuffle-conn",
                             daemon=True).start()

    def _handle(self, conn: socket.socket):
        try:
            with conn:
                # a stalled or malicious client must not pin a server
                # thread forever
                conn.settimeout(self._read_timeout)
                raw = _recv_exact(conn, _REQ.size)
                magic, op, sid, mid, rid = _REQ.unpack(raw)
                if magic == _MAGIC_TRACED:
                    tctx = TraceContext.unpack(
                        _recv_exact(conn, TraceContext.WIRE.size))
                elif magic == _MAGIC:
                    tctx = None
                else:
                    return
                with activate_trace_context(tctx), \
                        get_tracer().span("shuffle_serve", "shuffle",
                                          op=op, shuffle=sid, map=mid,
                                          reduce=rid):
                    self._serve_request(conn, op, sid, mid, rid)
        except Exception:
            pass  # srtpu: net-ok(a broken client connection must not kill the server; the client side retries or treats the block as missing)

    def _serve_request(self, conn: socket.socket, op: int, sid: int,
                       mid: int, rid: int):
        if op == _OP_REMOVE:
            self.remove_shuffle(sid)
            conn.sendall(_RESP_HEAD.pack(1, 0))
            return
        block = BlockId(sid, mid, rid)
        if op == _OP_GET_RANGE:
            off, max_len = _RANGE_EXT.unpack(
                _recv_exact(conn, _RANGE_EXT.size))
            t0 = telemetry.clock()
            total = self.store.length(block)
            if total is None:
                conn.sendall(_RESP_HEAD.pack(0, 0))
                return
            n = max(0, min(max_len, self.chunk_bytes, total - off))
            payload = self.store.read(block, off, n) or b""
            conn.sendall(_RESP_HEAD.pack(1, total)
                         + _RESP_CHUNK.pack(len(payload)))
            conn.sendall(payload)
            # server half of the transfer: stitched with the client's
            # recv via the SRTC header's trace id + block identity (the
            # first chunk stands for the block)
            tctx = current_trace_context()
            telemetry.note_transfer(
                "transport", "serve", shuffle_id=sid, map_id=mid,
                partition=rid, wire_bytes=len(payload), t0=t0,
                side="send" if (tctx is not None and off == 0) else None,
                trace_id=tctx.trace_id if tctx is not None else None,
                query_id=tctx.query_id if tctx is not None else None)
            return
        # whole-block GET (compat): stream it in windows anyway so
        # the server never materializes more than a chunk per send
        total = self.store.length(block)
        if total is None:
            conn.sendall(_RESP_HEAD.pack(0, 0))
            return
        conn.sendall(_RESP_HEAD.pack(1, total))
        off = 0
        while off < total:
            n = min(self.chunk_bytes, total - off)
            piece = self.store.read(block, off, n)
            if not piece:
                return  # store lost the block mid-stream
            conn.sendall(piece)
            off += len(piece)

    # -- client side ----------------------------------------------------------
    def add_peer(self, host: str, port: int):
        self._peers.append((host, port))

    def _range_from_peer(self, addr: Tuple[str, int], block: BlockId,
                         offset: int,
                         tctx: Optional[TraceContext] = None
                         ) -> Optional[Tuple[int, bytes]]:
        """One ranged request -> (total_len, chunk) or None if absent.

        Transient socket errors (connect refused/reset/timeout) are
        retried with exponential backoff + jitter up to
        ``tcp.retryAttempts``; a live peer answering found=0 is a
        definitive miss and returns immediately — that distinction keeps
        the missing-block path on ShuffleFetchFailedException ->
        recompute while flaky networks just retry. With a TraceContext
        the traced wire variant (magic SRTC) carries it, so the server's
        shuffle_serve span parents under it."""
        if tctx is not None and self._trace_wire:
            head = _REQ.pack(_MAGIC_TRACED, _OP_GET_RANGE, *block) \
                + tctx.pack()
        else:
            head = _REQ.pack(_MAGIC, _OP_GET_RANGE, *block)
        for attempt in range(self._retry_attempts):
            if attempt:
                faults.note_recovery("transport_retries")
                delay = min(self._backoff_s * (2 ** (attempt - 1)),
                            self._max_backoff_s)
                delay *= 0.5 + self._jitter.random()  # +/-50% jitter
                if self._closed.wait(delay):
                    return None  # transport shut down mid-backoff
            try:
                if faults.fire("tcp.connect") not in (None, "delay"):
                    raise ConnectionRefusedError(
                        "injected fault 'tcp.connect'")
                t_conn = telemetry.clock()
                with socket.create_connection(
                        addr, timeout=self._connect_timeout) as s:
                    telemetry.note_transfer(
                        "transport", "connect", shuffle_id=block[0],
                        map_id=block[1], partition=block[2], t0=t_conn,
                        retries=attempt)
                    s.settimeout(self._read_timeout)
                    t_send = telemetry.clock()
                    s.sendall(head
                              + _RANGE_EXT.pack(offset, self.chunk_bytes))
                    telemetry.note_transfer(
                        "transport", "send", shuffle_id=block[0],
                        map_id=block[1], partition=block[2], t0=t_send,
                        wire_bytes=len(head) + _RANGE_EXT.size)
                    if faults.fire("tcp.read") not in (None, "delay"):
                        raise ConnectionResetError(
                            "injected fault 'tcp.read'")
                    t_recv = telemetry.clock()
                    found, total = _RESP_HEAD.unpack(
                        _recv_exact(s, _RESP_HEAD.size))
                    if not found:
                        return None  # definitive miss: peer is up, no block
                    (clen,) = _RESP_CHUNK.unpack(
                        _recv_exact(s, _RESP_CHUNK.size))
                    chunk = _recv_exact(s, clen)
                    # client half: the first chunk carries the stitch key
                    # (trace id + block identity) the server's serve note
                    # pairs with
                    telemetry.note_transfer(
                        "transport", "recv", shuffle_id=block[0],
                        map_id=block[1], partition=block[2], t0=t_recv,
                        wire_bytes=clen, retries=attempt,
                        side="recv" if (tctx is not None and offset == 0)
                        else None,
                        trace_id=tctx.trace_id if tctx is not None
                        else None,
                        query_id=tctx.query_id if tctx is not None
                        else None)
                    return int(total), chunk
            except OSError:
                continue  # transient or dead peer: back off and retry
        faults.note_recovery("transport_giveups")
        return None  # unreachable after retries == block not found here

    def _fetch_remote(self, block: BlockId, turnstile: "_Turnstile",
                      ticket: int,
                      tctx: Optional[TraceContext] = None
                      ) -> Optional[Tuple[bytes, int]]:
        """Assemble a block from a peer chunk by chunk.

        The inflight reservation is acquired in STRICT consumer order via
        the turnstile (ticket = position in the fetch list): ticket k's
        acquire can only ever wait on releases of blocks < k, so the
        budget can never deadlock head-of-line. Returns
        (payload, reserved_bytes) — the caller owns the release. ``tctx``
        is the submitting thread's TraceContext, passed explicitly because
        this runs on a prefetch-pool thread with no ambient context."""
        try:
            for addr in self._peers:
                first = self._range_from_peer(addr, block, 0, tctx=tctx)
                if first is None:
                    continue
                total, chunk = first
                turnstile.wait_turn(ticket)
                self.inflight.acquire(total)
                turnstile.advance(ticket)
                try:
                    parts = [chunk]
                    got = len(chunk)
                    while got < total:
                        nxt = self._range_from_peer(addr, block, got,
                                                    tctx=tctx)
                        if nxt is None or not nxt[1]:
                            break
                        parts.append(nxt[1])
                        got += len(nxt[1])
                    if got != total:
                        self.inflight.release(total)
                        continue  # torn block; try the next peer
                    return b"".join(parts), total
                except BaseException:
                    self.inflight.release(total)
                    raise
            return None
        finally:
            turnstile.advance(ticket)  # idempotent: never block later tickets

    # -- SPI ------------------------------------------------------------------
    def publish(self, block: BlockId, payload: bytes) -> None:
        self.store.put(block, payload)
        with self._lock:
            self.bytes_published += len(payload)

    def fetch(self, blocks: List[BlockId]) -> Iterator[Tuple[BlockId, bytes]]:
        """Local blocks served from the store; remote blocks prefetched by
        a small pool under the receive-inflight cap, yielded in order."""
        local: Dict[BlockId, bool] = {}
        for b in blocks:
            local[b] = self.store.length(b) is not None
        remote = [b for b in blocks if not local[b]]
        pool = ThreadPoolExecutor(max_workers=4,
                                  thread_name_prefix="srtpu-shuffle-fetch") \
            if remote else None
        turnstile = _Turnstile()
        futures = {}
        consumed: set = set()
        # capture the caller's context here: prefetch-pool threads have no
        # ambient thread-local context of their own
        tctx = current_trace_context()
        try:
            for ticket, b in enumerate(remote):
                futures[b] = pool.submit(self._fetch_remote, b, turnstile,
                                         ticket, tctx)
            for b in blocks:
                if local[b]:
                    total = self.store.length(b)
                    payload = self.store.read(b, 0, total) \
                        if total is not None else None
                    if payload is None or len(payload) != total:
                        raise ShuffleFetchFailedException(
                            b, "local block vanished from the store")
                else:
                    res = futures[b].result()
                    consumed.add(b)
                    if res is None:
                        raise ShuffleFetchFailedException(
                            b, f"not found locally or on "
                               f"{len(self._peers)} peers")
                    payload, reserved = res
                    self.inflight.release(reserved)
                with self._lock:
                    self.bytes_fetched += len(payload)
                yield b, payload
        finally:
            # abandoned/errored: reservations of unconsumed prefetches must
            # not leak (they would poison every later fetch) — release as
            # each outstanding future completes
            for b, fut in futures.items():
                if b in consumed:
                    continue
                fut.add_done_callback(self._release_unconsumed)
            if pool is not None:
                pool.shutdown(wait=False)

    def _release_unconsumed(self, fut) -> None:
        try:
            res = fut.result()
        except BaseException:
            return  # worker already released on its error path
        if res is not None:
            self.inflight.release(res[1])

    def remove_shuffle(self, shuffle_id: int) -> None:
        self.store.remove_shuffle(shuffle_id)

    def close(self) -> None:
        self._closing = True
        self._closed.set()  # interrupt any retry backoff in flight
        try:
            self._server.close()
        except OSError:
            pass
        self.store.close()
