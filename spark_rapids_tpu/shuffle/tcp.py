"""TCP socket shuffle transport — the cross-process tier of the SPI.

Reference mapping (SURVEY §2.7): plays the role of the transport
server/client pair (RapidsShuffleServer.scala:70 serving block data,
RapidsShuffleClient.scala:88 fetching from peers) at the always-works TCP
level; the RDMA/UCX specialization in the reference maps to ICI collectives
(shuffle/ici.py) on TPU, so the socket tier only needs to be correct and
portable, not zero-copy.

Design: each executor process owns one ``TcpShuffleTransport``. ``publish``
stores blocks locally; a server thread answers block requests; ``fetch``
serves local blocks directly and asks registered peers for the rest. A block
nobody can produce raises ShuffleFetchFailedException — never silently
skipped.

Wire protocol (little-endian), one request per connection:

    request:  magic 'SRTB' | u8 op | i64 shuffle | i64 map | i64 reduce
    response: u8 found | u64 len | payload
    ops: 1 = GET, 2 = REMOVE_SHUFFLE (shuffle id only; map/reduce ignored)
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from ..conf import RapidsConf
from .transport import (BlockId, ShuffleFetchFailedException,
                        ShuffleTransport)

__all__ = ["TcpShuffleTransport"]

_MAGIC = b"SRTB"
_OP_GET = 1
_OP_REMOVE = 2
_REQ = struct.Struct("<4sBqqq")
_RESP_HEAD = struct.Struct("<BQ")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


class TcpShuffleTransport(ShuffleTransport):
    def __init__(self, conf: Optional[RapidsConf] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self._blocks: Dict[BlockId, bytes] = {}
        self._lock = threading.Lock()
        self._peers: List[Tuple[str, int]] = []
        self.bytes_published = 0
        self.bytes_fetched = 0
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(32)
        self._closing = False
        self._thread = threading.Thread(target=self._serve,
                                        name="srtpu-shuffle-server",
                                        daemon=True)
        self._thread.start()

    # -- server side ----------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return self._server.getsockname()

    def _serve(self):
        while not self._closing:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # socket closed
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket):
        try:
            with conn:
                raw = _recv_exact(conn, _REQ.size)
                magic, op, sid, mid, rid = _REQ.unpack(raw)
                if magic != _MAGIC:
                    return
                if op == _OP_REMOVE:
                    self.remove_shuffle(sid)
                    conn.sendall(_RESP_HEAD.pack(1, 0))
                    return
                with self._lock:
                    payload = self._blocks.get(BlockId(sid, mid, rid))
                if payload is None:
                    conn.sendall(_RESP_HEAD.pack(0, 0))
                else:
                    conn.sendall(_RESP_HEAD.pack(1, len(payload)))
                    conn.sendall(payload)
        except Exception:
            pass  # a broken client connection must not kill the server

    # -- client side ----------------------------------------------------------
    def add_peer(self, host: str, port: int):
        self._peers.append((host, port))

    def _ask_peer(self, addr: Tuple[str, int], block: BlockId,
                  timeout: float = 5.0) -> Optional[bytes]:
        try:
            with socket.create_connection(addr, timeout=timeout) as s:
                s.sendall(_REQ.pack(_MAGIC, _OP_GET, *block))
                found, length = _RESP_HEAD.unpack(
                    _recv_exact(s, _RESP_HEAD.size))
                if not found:
                    return None
                return _recv_exact(s, length)
        except OSError:
            return None  # dead peer == block not found here

    # -- SPI ------------------------------------------------------------------
    def publish(self, block: BlockId, payload: bytes) -> None:
        with self._lock:
            self._blocks[block] = payload
            self.bytes_published += len(payload)

    def fetch(self, blocks: List[BlockId]) -> Iterator[Tuple[BlockId, bytes]]:
        for b in blocks:
            with self._lock:
                payload = self._blocks.get(b)
            if payload is None:
                for addr in self._peers:
                    payload = self._ask_peer(addr, b)
                    if payload is not None:
                        break
            if payload is None:
                raise ShuffleFetchFailedException(
                    b, f"not found locally or on {len(self._peers)} peers")
            self.bytes_fetched += len(payload)
            yield b, payload

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            for b in [b for b in self._blocks if b[0] == shuffle_id]:
                del self._blocks[b]

    def close(self) -> None:
        self._closing = True
        try:
            self._server.close()
        except OSError:
            pass
