"""Shuffle & collective observatory: per-tier transfer telemetry.

ROADMAP item 3 (shuffle and scale-out) had zero measurement: none of
the four shuffle tiers — ICI collectives (shuffle/ici.py), the cached
device-resident tier, host-TCP transport (shuffle/tcp.py) and DCN
(shuffle/dcn.py) — recorded per-transfer phase walls, wire bytes or
queue/backpressure state, so a MULTICHIP timeout was an opaque rc=124.
Theseus (PAPERS.md) argues data movement is *the* bottleneck of a
distributed columnar engine and Thallus specifies exactly the
per-transfer protocol telemetry this module records: every transfer at
the existing chokepoints (manager serialize/publish/fetch/deserialize,
TCP connect/send/recv framing, DCN publish/fetch, the per-device
collective dispatch wall around ``shard_map``) reports into a
process-wide **ShuffleObservatory**.

Cost model mirrors utils/movement.py and utils/faults.py: a module
global ``_OBSERVATORY`` that is ``None`` when disabled, so every hook
pays exactly one global load + is-None check when the observatory is
off (the zero-overhead pin tests/test_shuffle_observatory.py asserts
on). Byte counts may be callables so nothing is computed on the
disabled path.

Each transfer records (shuffle_id, map/reduce partition, tier, phase,
logical vs wire bytes, wall, retries, publish-queue depth) into a
bounded forensics ring plus exact aggregation:

- per-(query, tier) and per-(query, shuffle, tier) rollups with phase
  wall breakdowns — the ``shuffle_summary`` event-log payload;
- **straggler attribution**: per-(shuffle, partition, tier) walls give
  slowest-partition wall vs p50 and the worst triple, extending the v7
  ``shuffle_skew`` rows-based view with measured time;
- **sender/receiver stitching**: the SRTC traced wire header already
  carries a per-query trace id; both halves of one TCP transfer note
  it with the block identity, so ``stitched()`` pairs the client fetch
  wall with the server serve wall for the same block.

Surfacing follows the movement-ledger convention: tools/eventlog.py
writes ONE schema-v12 ``shuffle_summary`` record per query (null when
off) on success AND error paths; ``shuffle_telemetry_stats()`` feeds
the stats registry so statusd ``/metrics`` gauges, per-query event-log
stats deltas and the history sentinel's shuffle-wall gate come free.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ..conf import register_conf

__all__ = [
    "ShuffleObservatory",
    "TIERS",
    "configure_shuffle_telemetry",
    "reset_shuffle_telemetry",
    "active",
    "clock",
    "note_transfer",
    "drain_ring",
    "query_summary",
    "shuffle_telemetry_stats",
]

SHUFFLE_TELEMETRY_ENABLED = register_conf(
    "spark.rapids.tpu.shuffle.telemetry.enabled",
    "Enable the shuffle & collective observatory "
    "(shuffle/telemetry.py): every transfer on every shuffle tier "
    "(ici/local/cached/transport/dcn) is recorded with phase walls, "
    "logical vs wire bytes, retries and publish-queue depth; TCP "
    "sender/receiver halves are stitched via the SRTC trace header and "
    "each query's event log carries a shuffle_summary record with "
    "straggler attribution. When false (the default) every hook "
    "compiles down to a single module-constant check and nothing is "
    "recorded.",
    False)

SHUFFLE_TELEMETRY_RING_SIZE = register_conf(
    "spark.rapids.tpu.shuffle.telemetry.ringSize",
    "Bounded capacity of the shuffle observatory's raw-event forensics "
    "ring. Oldest events drop first; the per-(query, shuffle, tier) "
    "aggregation is exact regardless of ring occupancy.",
    4096,
    checker=lambda v: None if int(v) > 0 else "must be positive")


#: the transfer fabrics a note may attribute to — "ici" collective
#: all-to-all, "local" single-device exchange, "cached" device-resident
#: catalog blocks, "transport" host-TCP (incl. in-process transports),
#: "dcn" cross-slice data-center network
TIERS = ("ici", "local", "cached", "transport", "dcn")

#: keys of the per-query / process-wide totals dict — one place so the
#: event-log record, the stats source and the tests agree on the shape
TOTAL_KEYS = ("transfers", "logical_bytes", "wire_bytes", "retries",
              "stitched")


def _zero_totals() -> Dict[str, Any]:
    t: Dict[str, Any] = {k: 0 for k in TOTAL_KEYS}
    t["wall_s"] = 0.0
    t["max_queue_depth"] = 0
    return t


def _zero_agg() -> Dict[str, Any]:
    return {"count": 0, "logical_bytes": 0, "wire_bytes": 0,
            "wall_s": 0.0, "retries": 0, "max_queue_depth": 0,
            "phases": {}}


class ShuffleObservatory:
    """Process-wide ledger of shuffle/collective transfers.

    Raw events land in a bounded ring (forensics: the exact transfer
    sequence, dumped into MULTICHIP timeout diagnostics); exact
    aggregation is kept per (query, tier) and per (query, shuffle,
    tier), with per-(shuffle, partition, tier) walls for straggler
    attribution. All state is lock-guarded — hooks fire from pipeline
    workers, the TCP server thread, fetch pools and the query thread
    concurrently."""

    def __init__(self, ring_size: int = 4096):
        self.ring_size = int(ring_size)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.ring_size)
        self._totals = _zero_totals()
        # (tier, phase) -> agg, process-wide
        self._agg: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # query_id -> {"totals", "tiers", "shuffles", "partitions"}
        self._queries: Dict[Any, Dict[str, Any]] = {}
        # (trace_id, shuffle, map, partition) -> {"send": e, "recv": e}
        self._stitch: Dict[Tuple, Dict[str, Dict[str, Any]]] = {}
        self._stitched: List[Dict[str, Any]] = []

    # -- recording --------------------------------------------------------
    def note(self, tier: str, phase: str,
             shuffle_id: Any = None, map_id: Any = None,
             partition: Any = None,
             logical_bytes: Union[int, Callable[[], int]] = 0,
             wire_bytes: Union[int, Callable[[], int]] = 0,
             t0: float = 0.0, retries: int = 0, queue_depth: int = 0,
             trace_id: Any = None, side: Optional[str] = None,
             query_id: Any = None) -> None:
        """Record one transfer (or one phase of one). ``query_id``
        overrides node-context attribution for hooks running off the
        query thread (the TCP server half passes the traced header's
        qid). ``side`` ("send"/"recv") + ``trace_id`` + block identity
        stitch the two halves of one wire transfer."""
        wall = (time.perf_counter() - t0) if t0 else 0.0
        logical = int(logical_bytes() if callable(logical_bytes)
                      else logical_bytes)
        wire = int(wire_bytes() if callable(wire_bytes) else wire_bytes)
        operator = None
        if query_id is None:
            from ..utils import node_context
            ctx = node_context.current()
            operator = ctx.name if ctx is not None else None
            query_id = ctx.query_id if ctx is not None else None
        entry = {
            "ts": time.time(),
            "tier": tier,
            "phase": phase,
            "shuffle_id": shuffle_id,
            "map_id": map_id,
            "partition": partition,
            "logical_bytes": logical,
            "wire_bytes": wire,
            "wall_s": wall,
            "retries": int(retries),
            "queue_depth": int(queue_depth),
            "query_id": query_id,
            "operator": operator,
            "trace_id": trace_id,
            "side": side,
        }
        with self._lock:
            self._ring.append(entry)
            self._fold_totals(self._totals, entry)
            self._fold_agg(self._agg.setdefault((tier, phase),
                                                _zero_agg()), entry)
            q = self._queries.get(query_id)
            if q is None:
                q = self._queries[query_id] = {
                    "totals": _zero_totals(), "tiers": {},
                    "shuffles": {}, "partitions": {}}
            self._fold_totals(q["totals"], entry)
            self._fold_agg(q["tiers"].setdefault(tier, _zero_agg()),
                           entry)
            if shuffle_id is not None:
                self._fold_agg(
                    q["shuffles"].setdefault((shuffle_id, tier),
                                             _zero_agg()), entry)
            if shuffle_id is not None and partition is not None \
                    and wall > 0.0:
                pk = (shuffle_id, partition, tier)
                q["partitions"][pk] = \
                    q["partitions"].get(pk, 0.0) + wall
            if trace_id is not None and side in ("send", "recv"):
                self._fold_stitch(entry)

    @staticmethod
    def _fold_totals(totals: Dict[str, Any], entry: Dict) -> None:
        totals["transfers"] += 1
        totals["logical_bytes"] += entry["logical_bytes"]
        totals["wire_bytes"] += entry["wire_bytes"]
        totals["retries"] += entry["retries"]
        totals["wall_s"] += entry["wall_s"]
        if entry["queue_depth"] > totals["max_queue_depth"]:
            totals["max_queue_depth"] = entry["queue_depth"]

    @staticmethod
    def _fold_agg(a: Dict[str, Any], entry: Dict) -> None:
        a["count"] += 1
        a["logical_bytes"] += entry["logical_bytes"]
        a["wire_bytes"] += entry["wire_bytes"]
        a["wall_s"] += entry["wall_s"]
        a["retries"] += entry["retries"]
        if entry["queue_depth"] > a["max_queue_depth"]:
            a["max_queue_depth"] = entry["queue_depth"]
        ph = a["phases"]
        ph[entry["phase"]] = ph.get(entry["phase"], 0.0) \
            + entry["wall_s"]

    def _fold_stitch(self, entry: Dict) -> None:
        """Pair the two halves of one wire transfer on (trace id, block
        identity). Caller holds the lock."""
        key = (entry["trace_id"], entry["shuffle_id"],
               entry["map_id"], entry["partition"])
        halves = self._stitch.setdefault(key, {})
        halves[entry["side"]] = entry
        if "send" in halves and "recv" in halves:
            send, recv = halves["send"], halves["recv"]
            self._stitched.append({
                "trace_id": entry["trace_id"],
                "shuffle_id": entry["shuffle_id"],
                "map_id": entry["map_id"],
                "partition": entry["partition"],
                "send_tier": send["tier"],
                "send_wall_s": send["wall_s"],
                "send_bytes": send["wire_bytes"],
                "recv_wall_s": recv["wall_s"],
                "recv_bytes": recv["wire_bytes"],
            })
            del self._stitch[key]
            self._totals["stitched"] += 1
            q = self._queries.get(entry["query_id"])
            if q is not None:
                q["totals"]["stitched"] += 1

    # -- reads ------------------------------------------------------------
    def drain_ring(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
            return out

    def totals(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._totals)

    def stitched(self) -> List[Dict[str, Any]]:
        """Completed sender/receiver pairs (both halves seen)."""
        with self._lock:
            return list(self._stitched)

    def tier_aggregate(self) -> List[Dict[str, Any]]:
        """Process-wide per-(tier, phase) rows, heaviest wall first."""
        with self._lock:
            rows = [{"tier": tier, "phase": phase,
                     **{k: v for k, v in a.items() if k != "phases"}}
                    for (tier, phase), a in self._agg.items()]
        rows.sort(key=lambda r: (-r["wall_s"], -r["wire_bytes"],
                                 r["tier"], r["phase"]))
        return rows

    @staticmethod
    def _straggler(partitions: Dict[Tuple, float]) -> Optional[Dict]:
        """Slowest-partition wall vs p50 over the per-(shuffle,
        partition, tier) walls — the measured-time extension of the v7
        rows-based ``shuffle_skew`` view."""
        if not partitions:
            return None
        walls = sorted(partitions.values())
        p50 = walls[len(walls) // 2]
        worst_key = max(partitions, key=lambda k: partitions[k])
        slowest = partitions[worst_key]
        return {
            "slowest_wall_s": slowest,
            "p50_wall_s": p50,
            "skew": (slowest / p50) if p50 > 0 else 0.0,
            "worst": {"shuffle_id": worst_key[0],
                      "partition": worst_key[1],
                      "tier": worst_key[2],
                      "wall_s": slowest},
        }

    def query_summary(self, query_id: Any,
                      drain: bool = True) -> Dict[str, Any]:
        """The per-query ``shuffle_summary`` payload: totals plus
        per-tier and per-(shuffle, tier) breakdowns (wall-heavy first)
        and straggler attribution. A query that shuffled nothing gets a
        zero summary — the event-log record set stays stable whether or
        not data moved."""
        with self._lock:
            q = (self._queries.pop(query_id, None) if drain
                 else self._queries.get(query_id))
        if q is None:
            return {"totals": _zero_totals(), "tiers": [],
                    "shuffles": [], "straggler": None}
        tiers = [{"tier": tier, **a, "phases": dict(a["phases"])}
                 for tier, a in q["tiers"].items()]
        tiers.sort(key=lambda r: (-r["wall_s"], -r["wire_bytes"],
                                  r["tier"]))
        shuffles = [{"shuffle_id": sid, "tier": tier,
                     **{k: v for k, v in a.items() if k != "phases"}}
                    for (sid, tier), a in q["shuffles"].items()]
        shuffles.sort(key=lambda r: (-r["wall_s"], -r["wire_bytes"],
                                     str(r["shuffle_id"]), r["tier"]))
        return {"totals": dict(q["totals"]), "tiers": tiers,
                "shuffles": shuffles,
                "straggler": self._straggler(q["partitions"])}


# ---------------------------------------------------------------------------
# module-level observatory: None when disabled (the zero-overhead pin)
# ---------------------------------------------------------------------------
_OBSERVATORY: Optional[ShuffleObservatory] = None


def clock() -> float:
    """Hook-side timestamp: perf_counter when the observatory is on,
    0.0 (= "don't time") when off. One global load + is-None check on
    the disabled path."""
    if _OBSERVATORY is None:
        return 0.0
    return time.perf_counter()


def note_transfer(tier: str, phase: str,
                  shuffle_id: Any = None, map_id: Any = None,
                  partition: Any = None,
                  logical_bytes: Union[int, Callable[[], int]] = 0,
                  wire_bytes: Union[int, Callable[[], int]] = 0,
                  t0: float = 0.0, retries: int = 0,
                  queue_depth: int = 0, trace_id: Any = None,
                  side: Optional[str] = None,
                  query_id: Any = None) -> None:
    """Hot-path transfer hook. Disabled: one global load + is-None
    check (the zero-overhead pin)."""
    if _OBSERVATORY is None:
        return
    _OBSERVATORY.note(tier, phase, shuffle_id=shuffle_id, map_id=map_id,
                      partition=partition, logical_bytes=logical_bytes,
                      wire_bytes=wire_bytes, t0=t0, retries=retries,
                      queue_depth=queue_depth, trace_id=trace_id,
                      side=side, query_id=query_id)


def configure_shuffle_telemetry(conf) -> Optional[ShuffleObservatory]:
    """Install (or clear) the process-wide observatory from a
    RapidsConf (TpuSession.__init__ chokepoint — the most recent
    session wins)."""
    global _OBSERVATORY
    if not conf.get(SHUFFLE_TELEMETRY_ENABLED):
        _OBSERVATORY = None
        return None
    _OBSERVATORY = ShuffleObservatory(
        int(conf.get(SHUFFLE_TELEMETRY_RING_SIZE)))
    return _OBSERVATORY


def reset_shuffle_telemetry() -> None:
    global _OBSERVATORY
    _OBSERVATORY = None


def active() -> Optional[ShuffleObservatory]:
    return _OBSERVATORY


def drain_ring() -> List[Dict[str, Any]]:
    obs = _OBSERVATORY
    return obs.drain_ring() if obs is not None else []


def query_summary(query_id: Any,
                  drain: bool = True) -> Optional[Dict[str, Any]]:
    """Per-query shuffle summary for the event log; None when the
    observatory is off (the v12 record's null-payload convention)."""
    obs = _OBSERVATORY
    if obs is None:
        return None
    return obs.query_summary(query_id, drain=drain)


def shuffle_telemetry_stats() -> Dict[str, Any]:
    """Stats-registry source: process-wide transfer totals, flattened
    as ``shuffle_telemetry_*`` gauges on /metrics and per-query
    event-log stats deltas (the history sentinel's shuffle-wall gate
    reads ``shuffle_telemetry_wall_s``). Empty when the observatory is
    off."""
    obs = _OBSERVATORY
    if obs is None:
        return {}
    t = obs.totals()
    t["wall_s"] = round(t["wall_s"], 6)
    return t
