"""Cross-process broadcast materialization.

Reference mapping: GpuBroadcastExchangeExec.scala:336-345 — the driver
builds the broadcast relation ONCE on its thread pool, serializes it to
host buffers, and every executor re-materializes from those bytes
(SerializeConcatHostBuffersDeserializeBatch). Here the serialized build
side is published through the shuffle transport under a reserved shuffle
id, so ProcessCluster workers fetch-and-upload instead of re-executing
the build-side plan per process (the round-2 gap: each worker rebuilt).

Flow:
    designated builder (driver or one worker):
        table = build_fn(); publish(serialize(table)); use it
    every other worker:
        fetch bytes -> deserialize -> DeviceTable.from_host -> catalog
        (BROADCAST spill priority, evicted last)
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..columnar.device import DeviceTable, resolve_min_bucket
from . import telemetry
from .serializer import deserialize_table, serialize_table
from .transport import BlockId, ShuffleFetchFailedException, ShuffleTransport

__all__ = ["BroadcastManager", "BROADCAST_SHUFFLE_ID"]

#: reserved shuffle-id namespace for broadcast blocks (never a real shuffle)
BROADCAST_SHUFFLE_ID = -1


class BroadcastManager:
    """Per-executor broadcast cache backed by the shuffle transport."""

    def __init__(self, transport: ShuffleTransport, catalog=None,
                 min_bucket: Optional[int] = None):
        self.transport = transport
        self.catalog = catalog
        self.min_bucket = resolve_min_bucket(min_bucket)
        self._handles: Dict[int, object] = {}   # bcast_id -> spill handle
        self._lock = threading.Lock()
        self.builds = 0          # local build-side executions (test hook)
        self.fetches = 0         # re-materializations from peers

    @staticmethod
    def block_of(bcast_id: int) -> BlockId:
        return BlockId(BROADCAST_SHUFFLE_ID, bcast_id, 0)

    def publish(self, bcast_id: int, table: DeviceTable) -> None:
        """Builder side: serialize once and make it fetchable by peers."""
        t0 = telemetry.clock()
        payload = serialize_table(table.to_host())
        telemetry.note_transfer(
            "transport", "serialize", shuffle_id=BROADCAST_SHUFFLE_ID,
            map_id=bcast_id, partition=0, t0=t0,
            logical_bytes=lambda: table.nbytes(),
            wire_bytes=len(payload))
        t1 = telemetry.clock()
        self.transport.publish(self.block_of(bcast_id), payload)
        telemetry.note_transfer(
            "transport", "publish", shuffle_id=BROADCAST_SHUFFLE_ID,
            map_id=bcast_id, partition=0, t0=t1,
            wire_bytes=len(payload))

    def build_and_publish(self, bcast_id: int,
                          build_fn: Callable[[], DeviceTable]) -> DeviceTable:
        with self._lock:
            h = self._handles.get(bcast_id)
        if h is not None:
            return h.get()
        table = build_fn()
        self.builds += 1
        self.publish(bcast_id, table)  # srtpu: shuffle-ok(BroadcastManager.publish itself notes the serialize and publish phases)
        return self._cache(bcast_id, table)

    def get(self, bcast_id: int) -> DeviceTable:
        """Consumer side: local cache, else fetch + re-materialize."""
        with self._lock:
            h = self._handles.get(bcast_id)
        if h is not None:
            return h.get()
        t0 = telemetry.clock()
        for bid, payload in self.transport.fetch([self.block_of(bcast_id)]):
            self.fetches += 1
            telemetry.note_transfer(
                "transport", "fetch", shuffle_id=BROADCAST_SHUFFLE_ID,
                map_id=bcast_id, partition=0, t0=t0,
                wire_bytes=len(payload))
            t1 = telemetry.clock()
            host = deserialize_table(payload)
            table = DeviceTable.from_host(host, self.min_bucket)
            telemetry.note_transfer(
                "transport", "deserialize",
                shuffle_id=BROADCAST_SHUFFLE_ID, map_id=bcast_id,
                partition=0, t0=t1,
                logical_bytes=lambda: host.nbytes())
            return self._cache(bcast_id, table)
        raise ShuffleFetchFailedException(
            self.block_of(bcast_id), "broadcast block unavailable")

    def get_or_build(self, bcast_id: int,
                     build_fn: Optional[Callable[[], DeviceTable]] = None
                     ) -> DeviceTable:
        """Fetch if any peer (or the driver) already built it, else build
        locally and publish — the fallback when no designated builder."""
        try:
            return self.get(bcast_id)
        except ShuffleFetchFailedException:
            if build_fn is None:
                raise
            return self.build_and_publish(bcast_id, build_fn)

    def _cache(self, bcast_id: int, table: DeviceTable) -> DeviceTable:
        if self.catalog is not None:
            from ..memory.catalog import SpillPriorities
            h = self.catalog.register(table, SpillPriorities.BROADCAST)
            with self._lock:
                self._handles[bcast_id] = h
            return h.get()

        class _Plain:
            def __init__(self, t):
                self._t = t

            def get(self):
                return self._t
        with self._lock:
            self._handles[bcast_id] = _Plain(table)
        return table

    def close(self) -> None:
        with self._lock:
            handles, self._handles = list(self._handles.values()), {}
        for h in handles:
            close = getattr(h, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass  # srtpu: net-ok(best-effort handle release during broadcast teardown; nothing reads these buffers again)
