"""ICI mesh-collective exchange — the accelerated shuffle tier.

Reference mapping (SURVEY §2.7 / §5): the UCX RDMA transport
(shuffle-plugin/.../UCX.scala:69) moves partitioned batches executor-to-
executor over NVLink/IB. The TPU-native equivalent keeps exchanges ON DEVICE:
rows live as one mesh-sharded DeviceTable; a hash-partition kernel + a single
``jax.lax.all_to_all`` over the ``dp`` axis re-homes every row across ICI
links inside one XLA program — no host staging, no serialization.

Static-shape contract: all_to_all needs equal per-destination quotas. The
caller may pass ``quota`` (slots per source-destination pair, from a prior
count pass — exec/exchange.py does this) to right-size the intermediate;
without it each shard reserves ``local_capacity`` slots per destination
(worst case, an n_devices× blowup kept only as the safe default).

Works under ``shard_map`` on any mesh — real ICI on TPU pods, XLA-emulated on
the CPU test mesh (tests/conftest.py).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import List

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.shard_compat import shard_map

from ..columnar.device import (DeviceColumn, DeviceTable,
                               stable_counting_order)
from ..utils import movement
from . import telemetry
from .manager import device_partition_ids

__all__ = ["ici_all_to_all_exchange", "shard_table", "unshard_table",
           "clear_exchange_programs"]

# movement-observatory site identity (utils/movement.py SITES)
_MOVE_UNSHARD = "spark_rapids_tpu/shuffle/ici.py::unshard_table"


def shard_table(table: DeviceTable, mesh: Mesh, axis: str = "dp"
                ) -> DeviceTable:
    """Place a DeviceTable row-sharded over the mesh axis."""
    n = mesh.shape[axis]
    assert table.capacity % n == 0, \
        f"capacity {table.capacity} not divisible by mesh axis {n}"
    sharding = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())

    cols = jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), table.columns)
    return DeviceTable(cols,
                       jax.device_put(table.row_mask, sharding),
                       jax.device_put(table.num_rows, rep), table.names)


def unshard_table(table: DeviceTable) -> DeviceTable:
    # ONE bulk device_get of the whole (columns, mask) leaf pytree — the
    # PR-18 funnel shape — instead of one blocking np.asarray round trip
    # per column plane; the ledger sees a single D2H crossing
    t0 = movement.clock()
    host_cols, host_mask = jax.device_get(  # srtpu: sync-ok(deliberate unshard gather: one bulk host materialization at the shuffle boundary)
        (table.columns, table.row_mask))
    movement.note_d2h(
        _MOVE_UNSHARD,
        lambda: sum(a.nbytes for a in
                    jax.tree_util.tree_leaves((host_cols, host_mask))),
        t0)
    cols = jax.tree_util.tree_map(jnp.asarray, host_cols)
    mask = jnp.asarray(host_mask)
    return DeviceTable(cols, mask, jnp.sum(mask, dtype=jnp.int32), table.names)


# Exchange programs are AOT-compiled (lower + compile) and cached by
# their semantic key so repeated same-shape exchanges reuse the
# executable instead of re-tracing a fresh ``jax.jit`` closure per call,
# and so the one-time XLA compile can be timed SEPARATELY from the
# collective dispatch (the ``compile`` vs ``dispatch`` phase split in the
# shuffle observatory — a cold cache must not read as shuffle wall).
# Bounded LRU: shapes are bucketed upstream (quota bucketing,
# exec/exchange.py), so a handful of entries covers a whole run.
_PROGRAMS: "OrderedDict[tuple, object]" = OrderedDict()
_PROGRAMS_MAX = 64


def clear_exchange_programs() -> None:
    """Drop cached exchange executables (test hygiene: compiled-program
    caches accumulate per shape family, tests/conftest.py)."""
    _PROGRAMS.clear()


def _program_key(table: DeviceTable, key_names: List[str], mesh: Mesh,
                 axis: str, quota: int | None) -> tuple:
    leaves, treedef = jax.tree_util.tree_flatten(table.columns)
    return (tuple(str(d) for d in mesh.devices.flat), axis, quota,
            tuple(table.names), tuple(key_names), str(treedef),
            tuple((l.shape, str(l.dtype)) for l in leaves),
            (table.row_mask.shape, str(table.row_mask.dtype)))


def ici_all_to_all_exchange(table: DeviceTable, key_names: List[str],
                            mesh: Mesh, axis: str = "dp",
                            quota: int | None = None,
                            telemetry_sid: int | None = None
                            ) -> DeviceTable:
    """Hash-exchange a row-sharded table so rows with equal keys land on the
    same shard, as one jitted shard_map program (collectives over ICI).

    ``quota`` is the per-(source, destination) slot count; it MUST be >= the
    max rows any shard sends to any destination (callers size it from a count
    pass; undersizing would drop rows). Defaults to local capacity (always
    safe). Returns a row-sharded table with per-shard capacity n * quota
    (padding masked off)."""
    n = mesh.shape[axis]
    names = table.names

    # the column tuple is a pytree whose leaves are the per-column planes
    # (data/validity/lengths/elem_validity + struct children, recursively)
    # — tree_map applies the scatter + all_to_all to every plane uniformly
    def local(columns, mask):
        cap = mask.shape[0]
        q = cap if quota is None else min(quota, cap)
        local_tbl = DeviceTable(columns, mask,
                                jnp.sum(mask, dtype=jnp.int32), names)
        pid = device_partition_ids(local_tbl, key_names, n)
        pid = jnp.where(mask, pid, n)  # park inactive rows past the end
        order = stable_counting_order(pid, n + 1)
        sorted_pid = jnp.take(pid, order)
        iota = jnp.arange(cap, dtype=jnp.int32)
        start = jnp.searchsorted(sorted_pid,
                                 jnp.arange(n, dtype=sorted_pid.dtype))
        dst = jnp.clip(sorted_pid, 0, n - 1).astype(jnp.int32)
        k = iota - jnp.take(start, dst).astype(jnp.int32)
        ok = sorted_pid < n

        def xform(x):
            xs = jnp.take(x, order, axis=0)
            buckets = jnp.zeros((n, q) + xs.shape[1:], dtype=xs.dtype)
            fill = jnp.where(ok.reshape((-1,) + (1,) * (xs.ndim - 1)), xs,
                             jnp.zeros_like(xs))
            scattered = buckets.at[dst, k].set(fill, mode="drop")
            return jax.lax.all_to_all(scattered, axis, 0, 0, tiled=True) \
                .reshape((n * q,) + x.shape[1:])

        slot_mask = jnp.zeros((n, q), dtype=bool).at[dst, k].set(
            ok, mode="drop")
        out_mask = jax.lax.all_to_all(slot_mask, axis, 0, 0,
                                      tiled=True).reshape(n * q)
        out_cols = jax.tree_util.tree_map(xform, columns)
        return out_cols, out_mask

    key = _program_key(table, key_names, mesh, axis, quota)
    prog = _PROGRAMS.get(key)
    if prog is None:
        col_specs = jax.tree_util.tree_map(lambda _: P(axis), table.columns)
        fn = jax.jit(shard_map(local, mesh=mesh,
                               in_specs=(col_specs, P(axis)),
                               out_specs=(col_specs, P(axis)), check=False))
        # one-time lower + XLA compile, timed as its own observatory
        # phase: folding it into ``dispatch`` would read cold caches as
        # shuffle wall and trip the sentinel's shuffle-wall gate
        t0 = telemetry.clock()
        prog = fn.lower(table.columns, table.row_mask).compile()
        telemetry.note_transfer("ici", "compile", shuffle_id=telemetry_sid,
                                t0=t0, queue_depth=n)
        _PROGRAMS[key] = prog
        while len(_PROGRAMS) > _PROGRAMS_MAX:
            _PROGRAMS.popitem(last=False)
    else:
        _PROGRAMS.move_to_end(key)
    # collective dispatch wall: dispatch of the all-to-all over n devices
    # (compile is its own phase above); wire bytes are the padded sharded
    # input actually crossing ICI links (vs the pre-padding logical bytes
    # the exchange exec notes at enqueue)
    t0 = telemetry.clock()
    out_cols, mask = prog(table.columns, table.row_mask)
    telemetry.note_transfer("ici", "dispatch", shuffle_id=telemetry_sid,
                            t0=t0, queue_depth=n,
                            wire_bytes=lambda: table.nbytes())
    total = jnp.sum(mask, dtype=jnp.int32)
    return DeviceTable(tuple(out_cols), mask, total, names)
