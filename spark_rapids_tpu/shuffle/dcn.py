"""DCN (cross-host) accelerated shuffle tier — design + mocked transport.

Reference mapping: the UCX shuffle plugin (shuffle-plugin/.../UCX.scala:69,
UCXShuffleTransport.scala:47) moves shuffle blocks executor-to-executor
device-to-device over NVLink/IB/RoCE with bounce-buffer pools and a TCP
management handshake. On TPU pods the equivalent fabric story has three
tiers:

1. **ICI** (intra-slice): already first-class — the planner-reachable
   all-to-all exchange (shuffle/ici.py + exec/exchange.py) runs as XLA
   collectives inside one jitted program. No transport code at all; the
   compiler owns the links. This replaces UCX for everything inside a
   slice, which is where the reference's NVLink tier lived.
2. **DCN** (cross-slice, same pod network): multi-slice jax meshes expose
   DCN to XLA through the SAME collectives — a mesh axis that crosses
   slices makes `all_to_all`/`ppermute` ride DCN automatically. The
   production path is therefore *mesh shape*, not a socket transport:
   `Mesh(devices.reshape(n_slices, chips_per_slice), ("dcn", "ici"))`
   with the exchange partitioned over both axes. `dryrun_multichip`
   exercises exactly this program shape on virtual devices.
3. **Fallback / task-parallel tier** (this module's SPI): when executors
   run as independent processes (ProcessCluster — the Spark-task model),
   cross-host blocks must move through an explicit transport. The TCP
   tier (shuffle/tcp.py) ships host bytes; THIS module is the
   accelerated analogue, keeping payloads as device arrays end to end
   and staging device->device (host memory never holds a serialized
   copy). Real hardware would back `_link_transfer` with
   jax.device_put over DCN-visible devices or a PJRT cross-host copy;
   the in-process mock preserves the exact SPI surface, device
   residency, and accounting so the planner/manager integration and the
   failure semantics are testable without a pod
   (the reference tests its UCX protocol with mocked transports the
   same way, RapidsShuffleTestHelper.scala:53-132).

Mock semantics:
- every `MockDcnFabric` is a registry of named "hosts"; each host owns a
  `DcnShuffleTransport` bound to a jax device.
- `publish` keeps the DeviceTable resident on the owner's device (via
  the catalog at shuffle priority, so it stays spillable).
- `fetch` locates the block on a peer host and moves it with
  `jax.device_put` onto the consumer's device — a device-to-device copy
  path with per-link byte accounting (`fabric.link_bytes`) and an
  injectable failure hook for fetch-failed testing.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax

from ..columnar.device import DeviceTable
from ..utils import faults
from ..utils.tracing import current_trace_context, get_tracer
from . import telemetry
from .transport import BlockId, ShuffleFetchFailedException

__all__ = ["MockDcnFabric", "DcnShuffleTransport",
           "TcpDcnShuffleTransport"]


class MockDcnFabric:
    """In-process stand-in for the cross-slice network: a registry of
    hosts plus per-link transfer accounting."""

    def __init__(self):
        self.hosts: Dict[str, "DcnShuffleTransport"] = {}
        self.link_bytes: Dict[Tuple[str, str], int] = {}
        self.transfers = 0
        self._lock = threading.Lock()
        #: test hook: raise/drop on specific transfers (failure injection)
        self.fault: Optional[Callable[[str, str, BlockId], None]] = None

    def attach(self, name: str, transport: "DcnShuffleTransport"):
        with self._lock:
            self.hosts[name] = transport

    def transfer(self, src: str, dst: str, block: BlockId,
                 table: DeviceTable, device) -> DeviceTable:
        if self.fault is not None:
            self.fault(src, dst, block)
        with get_tracer().span("dcn_transfer", "shuffle", src=src, dst=dst,
                               shuffle=block[0], map=block[1]):
            moved = jax.device_put(table, device)
        nbytes = table.nbytes()
        with self._lock:
            self.link_bytes[(src, dst)] = \
                self.link_bytes.get((src, dst), 0) + nbytes
            self.transfers += 1
        return moved


class DcnShuffleTransport:
    """Device-resident shuffle transport over a (mock) DCN fabric.

    Unlike the byte-oriented ShuffleTransport SPI, blocks here are
    DeviceTables: publish keeps them on-device (catalog-registered,
    spillable), fetch lands them on the consumer's device without a host
    serialization round trip."""

    def __init__(self, fabric: MockDcnFabric, host_name: str,
                 device=None, catalog=None):
        self.fabric = fabric
        self.host_name = host_name
        self.device = device if device is not None else jax.devices()[0]
        self.catalog = catalog
        self._blocks: Dict[BlockId, object] = {}   # handle or table
        self._lock = threading.Lock()
        fabric.attach(host_name, self)

    # -- publish/lookup -------------------------------------------------------
    def publish_table(self, block: BlockId, table: DeviceTable) -> None:
        entry: object = table
        if self.catalog is not None:
            from ..memory.catalog import SpillPriorities
            entry = self.catalog.register(
                table, SpillPriorities.OUTPUT_FOR_SHUFFLE)
        with self._lock:
            self._blocks[block] = entry

    def _local(self, block: BlockId) -> Optional[DeviceTable]:
        with self._lock:
            entry = self._blocks.get(block)
        if entry is None:
            return None
        return entry.get() if hasattr(entry, "get") else entry

    # -- fetch ----------------------------------------------------------------
    def fetch_tables(self, blocks: List[BlockId]
                     ) -> Iterator[Tuple[BlockId, DeviceTable]]:
        for b in blocks:
            local = self._local(b)
            if local is not None:
                yield b, local
                continue
            found = False
            for name, host in list(self.fabric.hosts.items()):
                if name == self.host_name:
                    continue
                remote = host._local(b)
                if remote is None:
                    continue
                yield b, self.fabric.transfer(  # srtpu: shuffle-ok(in-process mock fabric hop with its own link_bytes accounting; the real DCN tier TcpDcnShuffleTransport notes the observatory)
                    name, self.host_name, b, remote, self.device)
                found = True
                break
            if not found:
                raise ShuffleFetchFailedException(
                    b, f"block not on any of {len(self.fabric.hosts)} "
                       "DCN hosts")

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            doomed = [b for b in self._blocks if b[0] == shuffle_id]
            entries = [self._blocks.pop(b) for b in doomed]
        for e in entries:
            close = getattr(e, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass  # srtpu: net-ok(best-effort handle release while dropping a finished shuffle; the blocks are dead either way)

    def close(self) -> None:
        self.remove_all()

    def remove_all(self) -> None:
        with self._lock:
            sids = {b[0] for b in self._blocks}
        for sid in sids:
            self.remove_shuffle(sid)


class TcpDcnShuffleTransport:
    """REAL cross-process DCN-tier transport (round-4 VERDICT item 9):
    device-resident at both ends, host-staged only at the wire.

    Same surface as DcnShuffleTransport but peers are other PROCESSES
    (ProcessCluster workers — the Spark-task model), reached through the
    chunked spill-backed TCP fabric (shuffle/tcp.py) exactly as the
    reference's UCX transport pairs device tables with a TCP/active-message
    wire (UCXShuffleTransport.scala:47). Serialization is LAZY: a published
    block stays a spillable device table until some peer actually requests
    it, then it downloads + serializes once into the TCP block store."""

    def __init__(self, conf=None, device=None, catalog=None,
                 codec: str = "lz4"):
        from ..conf import RapidsConf
        from .tcp import TcpShuffleTransport
        conf = conf or RapidsConf()
        self.tcp = TcpShuffleTransport(conf)
        self.device = device if device is not None else jax.devices()[0]
        self.catalog = catalog
        self.codec = codec
        self._blocks: Dict[BlockId, object] = {}
        self._lock = threading.Lock()
        self.bytes_wired = 0

    # -- wiring ---------------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return self.tcp.address

    def add_peer(self, host: str, port: int) -> None:
        self.tcp.add_peer(host, port)

    # -- publish/fetch --------------------------------------------------------
    def publish_table(self, block: BlockId, table: DeviceTable) -> None:
        action = faults.fire("dcn.publish")
        if action is not None and action != "delay":
            raise faults.FaultInjectedError("dcn.publish", action)
        entry: object = table
        if self.catalog is not None:
            from ..memory.catalog import SpillPriorities
            entry = self.catalog.register(
                table, SpillPriorities.OUTPUT_FOR_SHUFFLE)
        with self._lock:
            self._blocks[block] = entry
        t0 = telemetry.clock()
        self.tcp.store.put_lazy(block, lambda: self._serialize(block))
        telemetry.note_transfer(
            "dcn", "enqueue", shuffle_id=block[0], map_id=block[1],
            partition=block[2], t0=t0,
            logical_bytes=lambda: table.nbytes(),
            queue_depth=self.tcp.store.lazy_depth())

    def _serialize(self, block: BlockId) -> bytes:
        from .serializer import serialize_table
        table = self._local(block)
        if table is None:
            raise ShuffleFetchFailedException(
                block, "published table vanished before serialization")
        # runs on the TCP server thread under the REQUESTING query's
        # TraceContext (the SRTC wire header activated it), so this span
        # parents under the remote query span in the merged timeline
        t0 = telemetry.clock()
        with get_tracer().span("dcn_serialize", "shuffle",
                               shuffle=block[0], map=block[1]):
            payload = serialize_table(table.to_host(), codec=self.codec)
        tctx = current_trace_context()
        telemetry.note_transfer(
            "dcn", "serialize", shuffle_id=block[0], map_id=block[1],
            partition=block[2], t0=t0,
            logical_bytes=lambda: table.nbytes(),
            wire_bytes=len(payload),
            queue_depth=self.tcp.store.lazy_depth(),
            query_id=tctx.query_id if tctx is not None else None)
        with self._lock:
            self.bytes_wired += len(payload)
        return payload

    def _local(self, block: BlockId) -> Optional[DeviceTable]:
        with self._lock:
            entry = self._blocks.get(block)
        if entry is None:
            return None
        return entry.get() if hasattr(entry, "get") else entry

    def fetch_tables(self, blocks: List[BlockId]
                     ) -> Iterator[Tuple[BlockId, DeviceTable]]:
        from .serializer import deserialize_table

        from ..columnar.device import DeviceTable as _DT
        local = [b for b in blocks if self._local(b) is not None]
        remote = [b for b in blocks if b not in set(local)]
        for b in local:
            yield b, self._local(b)
        if not remote:
            return
        action = faults.fire("dcn.fetch")
        if action is not None and action != "delay":
            raise faults.FaultInjectedError("dcn.fetch", action)
        t_fetch = telemetry.clock()
        for b, payload in self.tcp.fetch(remote):
            telemetry.note_transfer(
                "dcn", "fetch", shuffle_id=b[0], map_id=b[1],
                partition=b[2], wire_bytes=len(payload), t0=t_fetch,
                queue_depth=len(remote))
            t_des = telemetry.clock()
            with get_tracer().span("dcn_fetch", "shuffle",
                                   shuffle=b[0], map=b[1],
                                   bytes=len(payload)):
                host = deserialize_table(payload)
                table = _DT.from_host(host)
                if self.device is not None:
                    table = jax.device_put(table, self.device)
            telemetry.note_transfer(
                "dcn", "deserialize", shuffle_id=b[0], map_id=b[1],
                partition=b[2], t0=t_des,
                logical_bytes=lambda: table.nbytes())
            yield b, table
            t_fetch = telemetry.clock()

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            doomed = [b for b in self._blocks if b[0] == shuffle_id]
            entries = [self._blocks.pop(b) for b in doomed]
        for e in entries:
            close = getattr(e, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass  # srtpu: net-ok(best-effort handle release while dropping a finished shuffle; the blocks are dead either way)
        self.tcp.remove_shuffle(shuffle_id)

    def close(self) -> None:
        with self._lock:
            sids = {b[0] for b in self._blocks}
        for sid in sids:
            self.remove_shuffle(sid)
        self.tcp.close()
