"""Host columnar batch serialization (reference: GpuColumnarBatchSerializer +
JCudfSerialization host-buffer table format + TableCompressionCodec.scala).

Framed binary format (little-endian):

    magic 'SRTT' | u32 version | u32 codec | u64 payload_len | payload

payload (possibly compressed) = a pickle-free header (JSON) + raw column
buffers. Strings are serialized as concatenated UTF-8 + int32 offsets (dense),
not the device fixed-width layout — wire size matters more than device layout
here. A C++ serializer can swap in underneath without format change.
"""
from __future__ import annotations

import io
import json
import struct
import zlib
from typing import List, Optional

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.host import HostColumn, HostTable

__all__ = ["serialize_table", "deserialize_table", "CODECS"]

_MAGIC = b"SRTT"
_VERSION = 3  # v3: nested columns ship as embedded Arrow IPC streams

CODECS = {"none": 0, "zlib": 1, "lz4": 2}
_CODEC_BY_ID = {v: k for k, v in CODECS.items()}


def default_codec() -> str:
    """lz4 via the native library when built (reference: nvcomp LZ4 is the
    default shuffle codec, RapidsConf.scala:1156-1168); zlib otherwise."""
    from .. import native
    return "lz4" if native.available() else "zlib"


def _dtype_tag(d: dt.DataType) -> str:
    if isinstance(d, (dt.ArrayType, dt.StructType, dt.MapType)):
        # nested columns take the Arrow IPC branch in serialize_table —
        # offsets + child buffers, the JCudfSerialization nested layout
        return "arrow"
    if isinstance(d, dt.DecimalType):
        return f"decimal({d.precision},{d.scale})"
    return d.simple_name


def _tag_dtype(tag: str) -> dt.DataType:
    if tag.startswith("decimal("):
        p, s = tag[8:-1].split(",")
        return dt.DecimalType(int(p), int(s))
    table = {
        "boolean": dt.BOOLEAN, "tinyint": dt.BYTE, "smallint": dt.SHORT,
        "int": dt.INT, "bigint": dt.LONG, "float": dt.FLOAT,
        "double": dt.DOUBLE, "string": dt.STRING, "binary": dt.BINARY,
        "date": dt.DATE, "timestamp": dt.TIMESTAMP, "null": dt.NULL,
    }
    return table[tag]


def serialize_table(table: HostTable, codec: str = "none") -> bytes:
    buf = io.BytesIO()
    n = table.num_rows
    header = {"n": n, "cols": []}
    payloads: List[bytes] = []
    for name, col in zip(table.names, table.columns):
        entry = {"name": name, "dtype": _dtype_tag(col.dtype),
                 "has_validity": col.validity is not None}
        if isinstance(col.dtype, (dt.ArrayType, dt.StructType, dt.MapType)):
            # nested encoding = one-column Arrow IPC stream (offsets + child
            # buffers; validity rides inside the arrow array). Reference:
            # JCudfSerialization writes nested via offset+child buffers.
            import pyarrow as pa
            # HostColumn.to_arrow already nullifies masked rows
            arr = col.to_arrow()
            batch = pa.record_batch([arr], names=[name])
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, batch.schema) as w:
                w.write_batch(batch)
            blob = sink.getvalue().to_pybytes()
            entry["has_validity"] = False  # nulls live in the arrow stream
            entry["nbytes"] = [len(blob)]
            payloads.append(blob)
            header["cols"].append(entry)
            continue
        if isinstance(col.dtype, (dt.StringType, dt.BinaryType)):
            encoded = [v.encode("utf-8") if isinstance(v, str) else bytes(v)
                       for v in col.values]
            offsets = np.zeros(n + 1, dtype=np.int32)
            for i, b in enumerate(encoded):
                offsets[i + 1] = offsets[i] + len(b)
            blob = b"".join(encoded)
            entry["nbytes"] = [offsets.nbytes, len(blob)]
            payloads.append(offsets.tobytes())
            payloads.append(blob)
        else:
            data = np.ascontiguousarray(col.values)
            entry["np"] = data.dtype.str
            entry["nbytes"] = [data.nbytes]
            payloads.append(data.tobytes())
        if col.validity is not None:
            v = np.packbits(col.validity)
            entry["validity_nbytes"] = v.nbytes
            payloads.append(v.tobytes())
        header["cols"].append(entry)
    hj = json.dumps(header).encode()
    body = struct.pack("<I", len(hj)) + hj + b"".join(payloads)
    raw_len = len(body)
    if codec == "zlib":
        body = zlib.compress(body, level=1)
    elif codec == "lz4":
        from .. import native
        body = native.lz4_compress(body)
    buf.write(_MAGIC)
    buf.write(struct.pack("<II", _VERSION, CODECS[codec]))
    buf.write(struct.pack("<QQ", len(body), raw_len))
    buf.write(body)
    return buf.getvalue()


def deserialize_table(data: bytes) -> HostTable:
    assert data[:4] == _MAGIC, "bad magic"
    version, codec_id = struct.unpack_from("<II", data, 4)
    assert version == _VERSION, version
    length, raw_len = struct.unpack_from("<QQ", data, 12)
    body = data[28:28 + length]
    codec = _CODEC_BY_ID[codec_id]
    if codec == "zlib":
        body = zlib.decompress(body)
    elif codec == "lz4":
        from .. import native
        body = native.lz4_decompress(body, raw_len)
    (hlen,) = struct.unpack_from("<I", body, 0)
    header = json.loads(body[4:4 + hlen])
    pos = 4 + hlen
    n = header["n"]
    names, cols = [], []
    for entry in header["cols"]:
        if entry["dtype"] == "arrow":
            import pyarrow as pa
            from ..columnar.host import HostColumn as _HC
            (blen,) = entry["nbytes"]
            blob = body[pos:pos + blen]
            pos += blen
            with pa.ipc.open_stream(blob) as reader:
                batch = reader.read_all()
            names.append(entry["name"])
            cols.append(_HC.from_arrow(batch.column(0)))
            continue
        d = _tag_dtype(entry["dtype"])
        if isinstance(d, (dt.StringType, dt.BinaryType)):
            olen, blen = entry["nbytes"]
            offsets = np.frombuffer(body, dtype=np.int32, count=n + 1,
                                    offset=pos)
            pos += olen
            blob = body[pos:pos + blen]
            pos += blen
            vals = np.empty(n, dtype=object)
            for i in range(n):
                raw = blob[offsets[i]:offsets[i + 1]]
                vals[i] = raw.decode("utf-8") if isinstance(d, dt.StringType) \
                    else bytes(raw)
        else:
            (nbytes,) = entry["nbytes"]
            vals = np.frombuffer(body, dtype=np.dtype(entry["np"]), count=n,
                                 offset=pos).copy()
            pos += nbytes
        validity = None
        if entry["has_validity"]:
            vb = np.frombuffer(body, dtype=np.uint8,
                               count=entry["validity_nbytes"], offset=pos)
            pos += entry["validity_nbytes"]
            validity = np.unpackbits(vb)[:n].astype(bool)
        names.append(entry["name"])
        cols.append(HostColumn(d, vals, validity))
    return HostTable(names, cols)
