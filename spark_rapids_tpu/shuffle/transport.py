"""Shuffle transport SPI (reference: shuffle/RapidsShuffleTransport.scala:303
— connections, transaction lifecycle, bounce-buffer throttling; implementation
loaded reflectively by class name at :545-569 so alternative transports drop
in without a hard dependency, exactly like the optional UCX jar).

``LocalShuffleTransport`` is the in-process default. A multi-host DCN/ICI
transport implements the same three methods; tests drive the protocol with a
mock transport (reference test strategy SURVEY §4.2).
"""
from __future__ import annotations

import importlib
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from ..conf import RapidsConf, SHUFFLE_TRANSPORT_CLASS, register_conf

MAX_INFLIGHT_BYTES = register_conf(
    "spark.rapids.shuffle.maxMetadataSize",
    "Throttle: max in-flight fetched bytes per reader (reference: "
    "maxReceiveInflightBytes, RapidsConf.scala:1064).", 1024 * 1024 * 1024)

__all__ = ["BlockId", "ShuffleTransport", "LocalShuffleTransport",
           "ShuffleFetchFailedException", "load_transport"]


class BlockId(Tuple[int, int, int]):
    """(shuffle_id, map_id, reduce_id) — reference: ShuffleBlockId."""

    def __new__(cls, shuffle_id: int, map_id: int, reduce_id: int):
        return super().__new__(cls, (shuffle_id, map_id, reduce_id))


class ShuffleFetchFailedException(Exception):
    """A shuffle block could not be fetched (reference:
    RapidsShuffleFetchFailedException -> Spark stage retry,
    shuffle/RapidsShuffleIterator.scala:191,371). A missing block must FAIL
    LOUDLY — silently skipping it would produce a silently wrong answer."""

    def __init__(self, block: BlockId, detail: str = ""):
        self.block = block
        super().__init__(
            f"shuffle block (shuffle={block[0]}, map={block[1]}, "
            f"reduce={block[2]}) could not be fetched"
            + (f": {detail}" if detail else ""))


class ShuffleTransport:
    """SPI: store blocks on the 'server' side, fetch from the 'client'.

    ``fetch`` MUST raise ShuffleFetchFailedException for any requested block
    it cannot produce — never skip."""

    def publish(self, block: BlockId, payload: bytes) -> None:
        raise NotImplementedError

    def fetch(self, blocks: List[BlockId]) -> Iterator[Tuple[BlockId, bytes]]:
        raise NotImplementedError

    def remove_shuffle(self, shuffle_id: int) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LocalShuffleTransport(ShuffleTransport):
    """In-process block store (the 'boring fallback' tier of SURVEY §5)."""

    def __init__(self, conf: Optional[RapidsConf] = None):
        self._blocks: Dict[BlockId, bytes] = {}
        self._lock = threading.Lock()
        self.bytes_published = 0
        self.bytes_fetched = 0

    def publish(self, block: BlockId, payload: bytes) -> None:
        with self._lock:
            self._blocks[block] = payload
            self.bytes_published += len(payload)

    def fetch(self, blocks: List[BlockId]) -> Iterator[Tuple[BlockId, bytes]]:
        for b in blocks:
            with self._lock:
                payload = self._blocks.get(b)
            if payload is None:
                raise ShuffleFetchFailedException(b, "not in local store")
            self.bytes_fetched += len(payload)
            yield b, payload

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            for b in [b for b in self._blocks if b[0] == shuffle_id]:
                del self._blocks[b]


def load_transport(conf: RapidsConf) -> ShuffleTransport:
    """Reflective load by class name (reference: RapidsShuffleTransport.scala:545)."""
    clsname = conf.get(SHUFFLE_TRANSPORT_CLASS)
    module, _, name = clsname.rpartition(".")
    cls = getattr(importlib.import_module(module), name)
    return cls(conf)
