"""Shuffle manager: device-side partition slicing + transport-backed exchange
(reference: RapidsShuffleInternalManagerBase.scala — RapidsCachingWriter at
:92-155, RapidsShuffleIterator / RapidsShuffleClient on the read side; and
GpuPartitioning.sliceInternalOnGpu, GpuPartitioning.scala:49,130).

Write path per map partition:
  device batch -> device hash kernel assigns reduce partition per row
  -> one compact-by-partition sort -> slice per reduce partition (host loop
     over bucketed slices) -> serialize (+codec) -> transport.publish
Read path per reduce partition:
  transport.fetch -> deserialize -> host-concat (GpuShuffleCoalesceExec
  analogue) -> upload as one device batch.

A heartbeat registry stands in for the executor discovery control plane
(reference: RapidsShuffleHeartbeatManager.scala).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.device import DeviceTable, stable_counting_order
from ..columnar.host import HostTable
from ..conf import RapidsConf, SHUFFLE_COMPRESSION_CODEC, register_conf
from ..memory.stores import SpillCorruptionError
from ..utils import faults, movement
from ..utils.tracing import get_tracer
from . import telemetry
from .serializer import deserialize_table, serialize_table
from .transport import BlockId, ShuffleTransport, load_transport

__all__ = ["ShuffleManager", "HeartbeatManager", "device_partition_ids",
           "shuffle_stats"]

# process-wide shuffle counters (all ShuffleManager instances fold in here;
# feeds utils.metrics.StatsRegistry and the per-query event-log deltas)
_STATS_LOCK = threading.Lock()
_STATS = {
    "blocks_published": 0, "bytes_published": 0,
    "blocks_fetched": 0, "bytes_fetched": 0,
    "writes_cached_tier": 0, "writes_transport_tier": 0,
    "reads_cached_tier": 0, "reads_transport_tier": 0,
}


def _bump(**kv) -> None:
    with _STATS_LOCK:
        for k, v in kv.items():
            _STATS[k] += v


def shuffle_stats() -> Dict[str, int]:
    """Blocks/bytes written+fetched and which tier served them (cached
    device-resident vs transport)."""
    with _STATS_LOCK:
        return dict(_STATS)


SHUFFLE_CACHE_WRITES = register_conf(
    "spark.rapids.tpu.shuffle.cacheWrites",
    "Cache written shuffle partitions in the device store as spillable "
    "buffers (reference: RapidsCachingWriter + ShuffleBufferCatalog): same-"
    "process readers consume them with no serialize/upload round trip. "
    "'auto' enables it for the in-process transport only; 'on'/'off' force.",
    "auto",
    checker=lambda v: None if v in ("auto", "on", "off")
    else f"must be one of auto/on/off, got {v!r}")


# movement-ledger funnel names (see utils/movement.py SITES)
_MOVE_WRITE_TRANSPORT = ("spark_rapids_tpu/shuffle/manager.py"
                         "::ShuffleManager._write_partition_transport")
_MOVE_WRITE_CACHED = ("spark_rapids_tpu/shuffle/manager.py"
                      "::ShuffleManager._write_partition_cached")
_MOVE_READ_CACHED = ("spark_rapids_tpu/shuffle/manager.py"
                     "::ShuffleManager._read_partition_cached")
_MOVE_READ_UPLOAD = ("spark_rapids_tpu/shuffle/manager.py"
                     "::ShuffleManager.read_partition")


def _partition_order(pids, num_parts: int):
    """Stable group-by-partition permutation. The sort-free counting
    order materializes an O(rows x parts) one-hot, so it only pays off
    for small partition counts; larger fan-outs keep the argsort (same
    memory as before the sort-free rework)."""
    if num_parts + 1 <= 32:
        return stable_counting_order(pids, num_parts + 1)
    return jnp.argsort(pids, stable=True)


_MURMUR_C1 = np.uint32(0x85EBCA6B)
_MURMUR_C2 = np.uint32(0xC2B2AE35)


def _fmix_device(x: jax.Array) -> jax.Array:
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(_MURMUR_C1)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(_MURMUR_C2)
    x = x ^ (x >> 16)
    return x


def _string_key_hash(col) -> jax.Array:
    """Width-independent hash of a fixed-width string column.

    Bytes past each row's length are zero-padded by construction
    (columnar/device.py from_host), and words fully past the length are
    masked out, so the result does not depend on the batch's padded width —
    the same key hashes identically across batches (required for shuffle
    write/read agreement, like cudf's string murmur in the reference)."""
    data, lengths = col.data, col.lengths
    cap, w = data.shape
    k = jnp.zeros(cap, dtype=jnp.uint32)
    for start in range(0, w, 8):
        chunk = data[:, start:start + 8]
        word = jnp.zeros((cap,), dtype=jnp.uint64)
        for j in range(chunk.shape[1]):
            word = word | (chunk[:, j].astype(jnp.uint64)
                           << jnp.uint64(8 * (7 - j)))
        kw = _fmix_device((word & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
                          ^ (word >> jnp.uint64(32)).astype(jnp.uint32)
                          ^ jnp.uint32(start + 1))
        overlaps = lengths > start
        k = k ^ jnp.where(overlaps, kw, jnp.uint32(0))
    return k ^ _fmix_device(lengths.astype(jnp.uint32))


def device_partition_ids(table: DeviceTable, key_names: List[str],
                         num_parts: int, seed: int = 42) -> jax.Array:
    """Per-row reduce-partition ids; bitwise-identical to the host
    murmur-style partitioner (plan/physical.py murmur_hash_columns) for
    fixed-width types so host and device paths agree on placement. String
    keys use a device-only width-independent hash (consistent across the
    all-device shuffle write/read paths; host/device placement agreement is
    not required for strings because placement never crosses engines)."""
    h = jnp.full(table.capacity, jnp.uint32(seed), dtype=jnp.uint32)
    for name in key_names:
        k = _column_key_hash(table.column(name))
        h = h ^ k
        h = h * jnp.uint32(5) + jnp.uint32(0xE6546B64)
    return (h % jnp.uint32(num_parts)).astype(jnp.int32)


def _column_key_hash(col) -> jax.Array:
    """Per-row u32 hash of one key column; struct keys fold their field
    hashes (recursively), null rows/fields hash to 0."""
    from ..columnar import dtypes as _dt
    if isinstance(col.dtype, _dt.StructType):
        k = jnp.zeros(col.capacity, dtype=jnp.uint32)
        for i, child in enumerate(col.children):
            ck = _column_key_hash(child)
            k = k ^ _fmix_device(ck ^ jnp.uint32(i + 1))
            k = k * jnp.uint32(5) + jnp.uint32(0xE6546B64)
        return jnp.where(col.validity, k, jnp.uint32(0))
    v = col.data
    if col.lengths is not None:  # string/binary
        k = _string_key_hash(col)
    elif v.ndim == 2:  # decimal128 two-limb columns: fold both limbs
        hi = v[:, 0].view(jnp.uint64)
        lo = v[:, 1].view(jnp.uint64)
        bits = hi ^ (lo * jnp.uint64(0x9E3779B97F4A7C15))
        k = (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32) \
            ^ (bits >> jnp.uint64(32)).astype(jnp.uint32)
    elif v.dtype == jnp.bool_:
        k = v.astype(jnp.uint32)
    elif jnp.issubdtype(v.dtype, jnp.floating):
        bits = v.astype(jnp.float64).view(jnp.uint64)
        k = (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32) \
            ^ (bits >> jnp.uint64(32)).astype(jnp.uint32)
    else:
        bits = v.astype(jnp.int64).view(jnp.uint64)
        k = (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32) \
            ^ (bits >> jnp.uint64(32)).astype(jnp.uint32)
    k = _fmix_device(k)
    return jnp.where(col.validity, k, jnp.uint32(0))


class HeartbeatManager:
    """Executor registration/heartbeat control plane (reference:
    Plugin.scala:149-161 + RapidsShuffleHeartbeatManager.scala)."""

    def __init__(self, timeout_s: float = 60.0):
        self._peers: Dict[int, float] = {}
        self._lock = threading.Lock()
        self.timeout_s = timeout_s

    def register(self, executor_id: int):
        self.heartbeat(executor_id)

    def heartbeat(self, executor_id: int):
        with self._lock:
            self._peers[executor_id] = time.monotonic()

    def live_peers(self) -> List[int]:
        now = time.monotonic()
        with self._lock:
            return sorted(e for e, t in self._peers.items()
                          if now - t < self.timeout_s)

    def expire(self):
        now = time.monotonic()
        with self._lock:
            for e in [e for e, t in self._peers.items()
                      if now - t >= self.timeout_s]:
                del self._peers[e]


class ShuffleManager:
    def __init__(self, conf: Optional[RapidsConf] = None,
                 transport: Optional[ShuffleTransport] = None):
        self.conf = conf or RapidsConf()
        self.transport = transport or load_transport(self.conf)
        from .serializer import default_codec
        self.codec = self.conf.get(SHUFFLE_COMPRESSION_CODEC)
        if self.codec not in ("none", "zlib"):
            # lz4 needs the native library; zstd isn't shipped — both degrade
            # to the best available codec
            self.codec = default_codec()
        self._ids = itertools.count()
        # v7 skew telemetry: per-shuffle reduce-partition row/byte
        # distribution, accumulated across map tasks on both write tiers
        # from counts the write paths already compute (bounds diff +
        # published block sizes). Instance state: shuffle ids are
        # per-manager, so a process-wide map would alias id 0 across
        # managers with different partition counts.
        self._skew_lock = threading.Lock()
        self._skew: Dict[int, Dict[str, List[int]]] = {}
        self.heartbeats = HeartbeatManager()
        from .buffer_catalog import ShuffleBufferCatalog
        self.buffer_catalog = ShuffleBufferCatalog()
        mode = self.conf.get(SHUFFLE_CACHE_WRITES)
        if mode == "auto":
            from .transport import LocalShuffleTransport
            self.cache_writes = isinstance(self.transport,
                                           LocalShuffleTransport)
        else:
            self.cache_writes = mode == "on"

    def new_shuffle_id(self) -> int:
        return next(self._ids)

    def _bump_skew(self, shuffle_id: int, part_rows, part_bytes) -> None:
        with self._skew_lock:
            entry = self._skew.setdefault(
                shuffle_id, {"rows": [0] * len(part_rows),
                             "bytes": [0] * len(part_bytes)})
            for p, r in enumerate(part_rows):
                entry["rows"][p] += int(r)
            for p, b in enumerate(part_bytes):
                entry["bytes"][p] += int(b)

    def shuffle_skew_stats(self, shuffle_id: int) -> Optional[Dict]:
        """The v7 ``shuffle_skew`` payload for one shuffle's write-side
        distribution (min/p50/max/imbalance over reduce partitions), or
        None for an unknown/unwritten shuffle id."""
        with self._skew_lock:
            entry = self._skew.get(shuffle_id)
            if entry is None:
                return None
            from ..utils.metrics import build_skew_record
            return build_skew_record(entry["rows"], entry["bytes"])

    def unregister_shuffle(self, shuffle_id: int) -> None:
        """Free a finished shuffle's blocks in BOTH stores — device-resident
        catalog buffers and transport payloads (reference:
        unregisterShuffle releasing the ShuffleBufferCatalog's buffers).
        Callers own the shuffle lifecycle: invoke when the consuming stage
        has fully drained the reduce partitions."""
        self.buffer_catalog.remove_shuffle(shuffle_id)
        with self._skew_lock:
            self._skew.pop(shuffle_id, None)
        try:
            self.transport.remove_shuffle(shuffle_id)
        except NotImplementedError:
            pass

    def unregister_all(self) -> None:
        """Executor shutdown: free every cached shuffle block."""
        for sid in self.buffer_catalog.shuffle_ids():
            self.buffer_catalog.remove_shuffle(sid)

    # -- write side -----------------------------------------------------------
    def write_partition(self, shuffle_id: int, map_id: int,
                        batches: Iterator[DeviceTable], key_names: List[str],
                        num_parts: int) -> List[int]:
        """Slice + publish one map task's output; returns bytes per block.

        EVERY (map, reduce) block is published, including empty ones — the
        reader treats a missing block as a fetch failure (reference: Spark's
        MapStatus records every block; RapidsShuffleIterator fails loudly on
        a miss rather than guessing it was empty).

        With ``cache_writes`` the slices stay DEVICE-resident in the shuffle
        buffer catalog (RapidsCachingWriter): no download, no serialization;
        same-process readers concat the device blocks directly and the spill
        framework owns the memory."""
        action = faults.fire("shuffle.publish")
        if action is not None and action != "delay":
            raise faults.FaultInjectedError("shuffle.publish", action)
        if self.cache_writes:
            with get_tracer().span("shuffle_write", "shuffle", tier="cached",
                                   shuffle=shuffle_id, map=map_id):
                return self._write_partition_cached(
                    shuffle_id, map_id, batches, key_names, num_parts)
        with get_tracer().span("shuffle_write", "shuffle", tier="transport",
                               shuffle=shuffle_id, map=map_id):
            return self._write_partition_transport(
                shuffle_id, map_id, batches, key_names, num_parts)

    def _write_partition_transport(self, shuffle_id: int, map_id: int,
                                   batches: Iterator[DeviceTable],
                                   key_names: List[str],
                                   num_parts: int) -> List[int]:
        merged: List[List[HostTable]] = [[] for _ in range(num_parts)]
        part_rows = np.zeros(num_parts, dtype=np.int64)
        schema_host: Optional[HostTable] = None
        for batch in batches:
            pids = device_partition_ids(batch, key_names, num_parts)
            pids = jnp.where(batch.row_mask, pids, num_parts)  # park inactive
            order = _partition_order(pids, num_parts)
            sorted_tbl = DeviceTable(
                tuple(c.gather(order, keep_all_valid=True)
                      for c in batch.columns),
                jnp.take(batch.row_mask, order), batch.num_rows, batch.names)
            t0 = movement.clock()
            sorted_pids = np.asarray(jnp.take(pids, order))  # srtpu: sync-ok(count pass: partition-id vector only, 4B/row, before the bulk download)
            movement.note_d2h(_MOVE_WRITE_TRANSPORT, sorted_pids.nbytes, t0)
            bounds = np.searchsorted(sorted_pids, np.arange(num_parts + 1))
            part_rows += np.diff(bounds)
            host = sorted_tbl.to_host()  # single download, dense prefix
            schema_host = host
            for p in range(num_parts):
                lo, hi = int(bounds[p]), int(bounds[p + 1])
                if hi > lo:
                    merged[p].append(host.slice(lo, hi - lo))
        def publish(p: int) -> int:
            if merged[p]:
                table = HostTable.concat(merged[p])
            elif schema_host is not None:
                table = schema_host.slice(0, 0)
            else:  # map task saw no batches at all: typed-empty marker
                table = HostTable([], [])
            t0 = telemetry.clock()
            payload = serialize_table(table, self.codec)
            telemetry.note_transfer(
                "transport", "serialize", shuffle_id=shuffle_id,
                map_id=map_id, partition=p, t0=t0,
                logical_bytes=lambda: table.nbytes(),
                wire_bytes=len(payload))
            t1 = telemetry.clock()
            self.transport.publish(BlockId(shuffle_id, map_id, p), payload)
            telemetry.note_transfer(
                "transport", "publish", shuffle_id=shuffle_id,
                map_id=map_id, partition=p, t0=t1,
                wire_bytes=len(payload))
            return len(payload)

        # parallel map-side writes: per-block concat+serialize (+codec) is
        # pure CPU work; the transport guards its own store
        from ..parallel.pipeline import parallel_map
        sizes = parallel_map(publish, range(num_parts),
                             stage="shuffle_serialize")
        _bump(blocks_published=num_parts, bytes_published=sum(sizes),
              writes_transport_tier=1)
        self._bump_skew(shuffle_id, part_rows, sizes)
        return sizes

    def _write_partition_cached(self, shuffle_id: int, map_id: int,
                                batches: Iterator[DeviceTable],
                                key_names: List[str],
                                num_parts: int) -> List[int]:
        """Device-resident write path (RapidsCachingWriter analogue)."""
        from ..columnar.device import bucket_rows, concat_device_tables

        def gather_window(tbl: DeviceTable, lo: int, hi: int) -> DeviceTable:
            # explicit gather (NOT slice_rows: its start clamp would shift
            # windows whose bucketed length overruns the capacity)
            length = bucket_rows(max(hi - lo, 1), 256)  # srtpu: bucket-ok(cached-block slice quantum: 256 keys the window kernels independently of the session ladder, so reader and writer agree on stored shard shapes)
            idx = jnp.clip(lo + jnp.arange(length, dtype=jnp.int32),
                           0, tbl.capacity - 1)
            mask = jnp.arange(length, dtype=jnp.int32) < (hi - lo)
            cols = tuple(c.gather(idx, keep_all_valid=True).with_validity(
                jnp.take(c.validity, idx) & mask) for c in tbl.columns)
            return DeviceTable(cols, mask, jnp.int32(hi - lo), tbl.names)

        per_part: List[List[DeviceTable]] = [[] for _ in range(num_parts)]
        part_rows = np.zeros(num_parts, dtype=np.int64)
        schema_tbl: Optional[DeviceTable] = None
        for batch in batches:
            pids = device_partition_ids(batch, key_names, num_parts)
            pids = jnp.where(batch.row_mask, pids, num_parts)
            order = _partition_order(pids, num_parts)
            sorted_tbl = DeviceTable(
                tuple(c.gather(order, keep_all_valid=True)
                      for c in batch.columns),
                jnp.take(batch.row_mask, order), batch.num_rows, batch.names)
            schema_tbl = sorted_tbl
            # count download only (4B/row), like the ICI exchange count pass
            t0 = movement.clock()
            sorted_pids = np.asarray(jnp.take(pids, order))  # srtpu: sync-ok(count pass: partition-id vector only, 4B/row; slices stay on device)
            movement.note_d2h(_MOVE_WRITE_CACHED, sorted_pids.nbytes, t0)
            bounds = np.searchsorted(sorted_pids, np.arange(num_parts + 1))
            part_rows += np.diff(bounds)
            for p in range(num_parts):
                lo, hi = int(bounds[p]), int(bounds[p + 1])
                if hi > lo:
                    per_part[p].append(gather_window(sorted_tbl, lo, hi))
        sizes = [0] * num_parts
        for p in range(num_parts):
            if per_part[p]:
                table = concat_device_tables(per_part[p], 256)  # srtpu: bucket-ok(stored cached-tier blocks share the 256-row write quantum above; readers re-bucket to their own ladder)
            elif schema_tbl is not None:
                table = gather_window(schema_tbl, 0, 0)
            else:  # map task saw no batches at all
                table = DeviceTable((), jnp.zeros(0, dtype=bool),
                                    jnp.int32(0), ())
            t0 = telemetry.clock()
            self.buffer_catalog.put((shuffle_id, map_id, p), table)
            sizes[p] = table.nbytes()
            telemetry.note_transfer(
                "cached", "publish", shuffle_id=shuffle_id,
                map_id=map_id, partition=p, t0=t0,
                logical_bytes=sizes[p], wire_bytes=sizes[p])
        _bump(blocks_published=num_parts, bytes_published=sum(sizes),
              writes_cached_tier=1)
        self._bump_skew(shuffle_id, part_rows, sizes)
        return sizes

    # -- read side ------------------------------------------------------------
    def read_partition(self, shuffle_id: int, num_maps: int, reduce_id: int,
                       min_bucket: Optional[int] = None,
                       recompute=None) -> Iterator[DeviceTable]:
        """Fetch + coalesce + upload one reduce partition.

        A missing block raises ShuffleFetchFailedException. When a
        ``recompute(map_id)`` hook is provided (the stage-retry analogue —
        reference: RapidsShuffleFetchFailedException -> Spark recomputes the
        map task from lineage), it is invoked once for the failed map and the
        fetch retried before giving up."""
        from .transport import ShuffleFetchFailedException
        if self.cache_writes:
            yield from self._read_partition_cached(
                shuffle_id, num_maps, reduce_id, min_bucket, recompute)
            return
        blocks = [BlockId(shuffle_id, m, reduce_id) for m in range(num_maps)]
        tables: List[HostTable] = []
        fetched_bytes = 0
        pending = list(blocks)
        retried = set()
        with get_tracer().span("shuffle_fetch", "shuffle", tier="transport",
                               shuffle=shuffle_id, reduce=reduce_id,
                               maps=num_maps):
            while pending:
                try:
                    if faults.fire("shuffle.fetch") not in (None, "delay"):
                        # injected through the REAL failure type so the
                        # recompute-once machinery below recovers it
                        raise ShuffleFetchFailedException(
                            pending[0], "injected fault 'shuffle.fetch'")
                    t_fetch = telemetry.clock()
                    for bid, payload in self.transport.fetch(pending):
                        telemetry.note_transfer(
                            "transport", "fetch", shuffle_id=shuffle_id,
                            map_id=bid[1], partition=reduce_id,
                            wire_bytes=len(payload), t0=t_fetch,
                            retries=1 if bid[1] in retried else 0,
                            queue_depth=len(pending))
                        t_des = telemetry.clock()
                        host = deserialize_table(payload)
                        telemetry.note_transfer(
                            "transport", "deserialize",
                            shuffle_id=shuffle_id, map_id=bid[1],
                            partition=reduce_id, t0=t_des,
                            logical_bytes=lambda: host.nbytes())
                        tables.append(host)
                        fetched_bytes += len(payload)
                        pending = pending[pending.index(bid) + 1:]
                        t_fetch = telemetry.clock()
                    break
                except ShuffleFetchFailedException as e:
                    map_id = e.block[1]
                    get_tracer().instant(
                        "shuffle_fetch_failed", "shuffle",
                        shuffle=shuffle_id, map=map_id, reduce=reduce_id,
                        retry=recompute is not None and map_id not in retried)
                    if recompute is None or map_id in retried:
                        raise
                    retried.add(map_id)
                    faults.note_recovery("shuffle_recomputes")
                    with get_tracer().span("shuffle_recompute", "shuffle",
                                           shuffle=shuffle_id, map=map_id):
                        recompute(map_id)
                    pending = pending[pending.index(e.block):]
        _bump(blocks_fetched=len(tables), bytes_fetched=fetched_bytes,
              reads_transport_tier=1)
        non_empty = [t for t in tables if t.num_columns and t.num_rows]
        if not non_empty:
            # all blocks empty: match the cached tier — yield a zero-row
            # table with the schema when any schema-bearing block exists
            schema_t = next((t for t in tables if t.num_columns), None)
            if schema_t is not None:
                yield DeviceTable.from_host(schema_t.slice(0, 0), min_bucket)
            return
        # host-side coalesce then single upload (GpuShuffleCoalesceExec)
        merged = HostTable.concat(non_empty)
        t0 = movement.clock()
        dtb = DeviceTable.from_host(merged, min_bucket)
        movement.note_h2d(_MOVE_READ_UPLOAD, dtb.nbytes, t0, origin=merged)
        yield dtb

    def _read_partition_cached(self, shuffle_id: int, num_maps: int,
                               reduce_id: int, min_bucket: int,
                               recompute=None) -> Iterator[DeviceTable]:
        """Catalog-backed read: blocks never left the device (or come back
        via the spill framework); a miss is a fetch failure with the same
        recompute-once semantics as the transport path."""
        from ..columnar.device import concat_device_tables
        from .transport import ShuffleFetchFailedException
        parts: List[DeviceTable] = []
        schema_holder: Optional[DeviceTable] = None
        fetched_bytes = 0
        with get_tracer().span("shuffle_fetch", "shuffle", tier="cached",
                               shuffle=shuffle_id, reduce=reduce_id,
                               maps=num_maps):
            tables: List[DeviceTable] = []
            for m in range(num_maps):
                key = (shuffle_id, m, reduce_id)
                handle = self.buffer_catalog.get(key)
                if handle is not None and \
                        faults.fire("shuffle.fetch") not in (None, "delay"):
                    handle = None  # injected miss: exercises the same
                    # recompute path a genuinely lost block takes
                if handle is None and recompute is not None:
                    get_tracer().instant(
                        "shuffle_fetch_failed", "shuffle",
                        shuffle=shuffle_id, map=m, reduce=reduce_id,
                        retry=True)
                    faults.note_recovery("shuffle_recomputes")
                    with get_tracer().span("shuffle_recompute", "shuffle",
                                           shuffle=shuffle_id, map=m):
                        recompute(m)
                    handle = self.buffer_catalog.get(key)
                if handle is None:
                    raise ShuffleFetchFailedException(
                        BlockId(shuffle_id, m, reduce_id),
                        "block not in the shuffle buffer catalog")
                try:
                    t = handle.get()
                except SpillCorruptionError as e:
                    # a corrupt disk-spilled block is recoverable the same
                    # way a lost remote block is: recompute the map output
                    # (put() overwrites and closes the corrupt handle)
                    get_tracer().instant(
                        "shuffle_fetch_failed", "shuffle",
                        shuffle=shuffle_id, map=m, reduce=reduce_id,
                        retry=recompute is not None)
                    if recompute is None:
                        raise ShuffleFetchFailedException(
                            BlockId(shuffle_id, m, reduce_id),
                            f"spilled block corrupt: {e}")
                    faults.note_recovery("shuffle_recomputes")
                    with get_tracer().span("shuffle_recompute", "shuffle",
                                           shuffle=shuffle_id, map=m):
                        recompute(m)
                    fresh = self.buffer_catalog.get(key)
                    if fresh is None:
                        raise ShuffleFetchFailedException(
                            BlockId(shuffle_id, m, reduce_id),
                            "block missing after corruption recompute")
                    try:
                        t = fresh.get()
                    except SpillCorruptionError as e2:
                        raise ShuffleFetchFailedException(
                            BlockId(shuffle_id, m, reduce_id),
                            f"spilled block corrupt after recompute: {e2}")
                nb = t.nbytes()
                telemetry.note_transfer(
                    "cached", "fetch", shuffle_id=shuffle_id,
                    map_id=m, partition=reduce_id,
                    logical_bytes=nb, wire_bytes=nb)
                fetched_bytes += nb
                if t.num_columns:
                    tables.append(t)
            # ONE bulk D2H of all block row counts instead of a blocking
            # 4-byte round trip per map block (ROADMAP item 1)
            t0 = movement.clock()
            counts = jax.device_get(  # srtpu: sync-ok(batched count sync, 4B per block once per reduce partition)
                [t.num_rows for t in tables])
            movement.note_d2h(_MOVE_READ_CACHED, 4 * len(tables), t0)
            for t, cnt in zip(tables, counts):
                if int(cnt):
                    parts.append(t)
                elif schema_holder is None:
                    schema_holder = t
        _bump(blocks_fetched=num_maps, bytes_fetched=fetched_bytes,
              reads_cached_tier=1)
        if parts:
            yield concat_device_tables(parts, min_bucket)
        elif schema_holder is not None:
            # all blocks empty: yield a zero-row table with the schema so
            # this tier matches the transport tier's empty-partition shape;
            # re-bucket to the READER's min_bucket (the stored block keeps
            # the map-side write capacity, a one-off shape downstream)
            yield DeviceTable.from_host(
                schema_holder.to_host().slice(0, 0), min_bucket)
