"""supported_ops.md generator (reference: SupportedOpsDocs in
TypeChecks.scala:1638, which emits the 17.7k-line docs/supported_ops.md).

Walks the live rule registries so the document can never drift from the
planner: every exec/expression rule row shows its per-type support matrix
(S supported / PS partial with note / NS not supported), the per-op conf
kill switch, and the rule's note. Regenerate:
``python -m spark_rapids_tpu.tools.supported_ops``.
"""
from __future__ import annotations

import os
from typing import List

from ..columnar.dtypes import TypeEnum, TypeSig
from ..columnar import dtypes as dt

__all__ = ["supported_ops_markdown", "write_supported_ops"]

# one concrete probe type per TypeEnum column
_PROBE = {
    TypeEnum.BOOLEAN: dt.BOOLEAN, TypeEnum.BYTE: dt.BYTE,
    TypeEnum.SHORT: dt.SHORT, TypeEnum.INT: dt.INT, TypeEnum.LONG: dt.LONG,
    TypeEnum.FLOAT: dt.FLOAT, TypeEnum.DOUBLE: dt.DOUBLE,
    TypeEnum.STRING: dt.STRING, TypeEnum.BINARY: dt.BINARY,
    TypeEnum.DATE: dt.DATE, TypeEnum.TIMESTAMP: dt.TIMESTAMP,
    TypeEnum.NULL: dt.NULL, TypeEnum.DECIMAL: dt.DecimalType(10, 2),
    TypeEnum.ARRAY: dt.ArrayType(dt.LONG),
    TypeEnum.STRUCT: dt.StructType((dt.StructField("f", dt.LONG),)),
    TypeEnum.MAP: dt.MapType(dt.LONG, dt.LONG),
}


def _cell(sig: TypeSig, enum: str) -> str:
    probe = _PROBE[enum]
    if sig.is_supported(probe):
        note = sig.note_for(probe)
        return "PS" if note else "S"
    return "NS"


def _sig_row(sig: TypeSig) -> List[str]:
    return [_cell(sig, e) for e in TypeEnum.ALL]


def _notes_of(sig: TypeSig) -> List[str]:
    out = []
    for e in TypeEnum.ALL:
        note = sig.note_for(_PROBE[e])
        if note and sig.is_supported(_PROBE[e]):
            out.append(f"{e}: {note}")
    return out


def supported_ops_markdown() -> str:
    # imports trigger rule registration (aqe adds the stage-reader rules;
    # importing both keeps the doc deterministic regardless of what else
    # the process already loaded)
    from ..plan import aqe, overrides  # noqa: F401
    from ..plan.meta import EXEC_RULES, EXPR_RULES

    header = "| op | conf key | " + " | ".join(TypeEnum.ALL) + " | notes |"
    rule = "|" + "---|" * (len(TypeEnum.ALL) + 3)
    lines = [
        "<!-- Generated from the live rule registries — DO NOT EDIT. "
        "Regenerate: python -m spark_rapids_tpu.tools.supported_ops -->",
        "# Supported operators and expressions",
        "",
        "`S` = supported, `PS` = partial (see note), `NS` = not supported.",
        "Each op can be force-disabled by setting its conf key to `false` "
        "(reference: the auto-derived `spark.rapids.sql.exec.*` / "
        "`expression.*` keys of GpuOverrides.scala:211-303).",
        "",
        "## Execs",
        "",
        header, rule,
    ]
    for cls in sorted(EXEC_RULES, key=lambda c: c.__name__):
        r = EXEC_RULES[cls]
        notes = _notes_of(r.output_sig)
        if r.note:
            notes.insert(0, r.note)
        lines.append(
            f"| {cls.__name__.replace('Cpu', '')} | `{r.conf_key}` | "
            + " | ".join(_sig_row(r.output_sig))
            + " | " + "; ".join(notes) + " |")
    lines += ["", "## Expressions", "", header, rule]
    for cls in sorted(EXPR_RULES, key=lambda c: c.__name__):
        r = EXPR_RULES[cls]
        notes = _notes_of(r.sig)
        if r.note:
            notes.insert(0, r.note)
        lines.append(
            f"| {cls.__name__} | `{r.conf_key}` | "
            + " | ".join(_sig_row(r.sig))
            + " | " + "; ".join(notes) + " |")
    lines.append("")
    return "\n".join(lines)


def write_supported_ops(path: str = None) -> str:
    if path is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "docs", "supported_ops.md")
    text = supported_ops_markdown()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    return path


if __name__ == "__main__":
    print(write_supported_ops())
