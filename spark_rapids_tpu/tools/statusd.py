"""HTTP status endpoints for the live health subsystem.

The Spark live-UI analogue, cut down to what a load balancer and an
operator actually poll (ROADMAP north star: serve heavy traffic — a
fleet needs a liveness probe per process):

- ``GET /healthz`` — liveness JSON; **503** while the watchdog considers
  the engine stalled (work in flight, no progress past
  ``spark.rapids.tpu.health.stallTimeout``), 200 otherwise. Load
  balancers key off the status code alone.
- ``GET /metrics`` — the process StatsRegistry as Prometheus text
  exposition 0.0.4 (utils/metrics.py), same payload
  ``prometheus_text()`` returns programmatically. Includes the memory
  flight recorder's per-operator HBM gauges
  (``spark_rapids_tpu_memprof_operator_live_bytes_<Op>``, plus
  peak/leak/postmortem counters from utils/memprof.py) and — when
  ``spark.rapids.tpu.movement.enabled`` is on — the movement ledger's
  transfer gauges (``spark_rapids_tpu_movement_d2h_bytes``,
  ``..._h2d_bytes``, ``..._blocking_count``, ``..._round_trips``,
  ``..._wall_s`` from utils/movement.py), and — when
  ``spark.rapids.tpu.shuffle.telemetry.enabled`` is on — the shuffle
  observatory's per-tier transfer gauges
  (``spark_rapids_tpu_shuffle_telemetry_transfers``,
  ``..._logical_bytes``, ``..._wire_bytes``, ``..._wall_s``,
  ``..._retries``, ``..._stitched``, ``..._max_queue_depth`` from
  shuffle/telemetry.py), which the federation endpoints re-export per
  process.
- ``GET /status`` — the full live JSON snapshot
  (``HealthMonitor.snapshot()``): semaphore holders/waiters, pipeline
  queue depths + in-flight task ages, HBM watermarks, the memory
  flight recorder's live/peak holders-by-operator attribution, active
  operator contexts, recent watermark history.
- ``GET /federation`` — JSON scrape summary over every registered peer
  process (ProcessCluster workers / remote status daemons): per-peer
  reachability + sample counts.
- ``GET /federation/metrics`` — ONE Prometheus text page combining the
  driver's registry with every peer's, each sample tagged with a
  ``process="<name>"`` label so worker counters never collide with the
  driver's (the federation view a fleet scraper ingests; reference:
  Prometheus federation's ``honor_labels`` pattern).

stdlib ``http.server`` only (no new dependencies); a
``ThreadingHTTPServer`` on 127.0.0.1 whose serve loop runs on a
``tpu-health-httpd`` daemon thread — ``StatusServer.stop()`` (from
``session.close()``) shuts it down, which the no-leaked-threads test
asserts. Port 0 binds an ephemeral port (``StatusServer.port`` reports
the bound one) so tests and multi-session hosts never collide.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

__all__ = ["StatusServer", "MetricsFederation", "label_prometheus_text"]


def label_prometheus_text(text: str, process: str) -> str:
    """Tag every sample line of a Prometheus 0.0.4 text page with a
    ``process="<name>"`` label (comments/HELP/TYPE lines pass through) so
    pages from several processes can concatenate without name
    collisions."""
    esc = process.replace("\\", "\\\\").replace('"', '\\"')
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        metric, sep, rest = line.partition(" ")
        if "{" in metric:
            metric = metric.replace("{", '{process="%s",' % esc, 1)
        else:
            metric = metric + '{process="%s"}' % esc
        out.append(metric + sep + rest)
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")


class MetricsFederation:
    """Aggregates peer-process metrics into the driver's status daemon.

    Two kinds of peers, same scrape surface:

    - ``register_url(name, url)`` — an HTTP ``/metrics`` endpoint
      (another process's StatusServer);
    - ``register_puller(name, fn)`` — a zero-arg callable returning
      Prometheus text (e.g. ``ProcessCluster.run_on(w,
      metrics_text_task)`` — workers don't run HTTP servers, the task
      queue IS their scrape transport).

    ``prometheus_text()`` returns one combined page: the local registry
    first, then every peer, each sample labelled ``process="<name>"``."""

    def __init__(self, local_name: str = "driver"):
        self.local_name = local_name
        self._peers: "dict" = {}
        self._lock = threading.Lock()

    def register_url(self, name: str, url: str) -> None:
        with self._lock:
            self._peers[name] = ("url", url)

    def register_puller(self, name: str, fn) -> None:
        with self._lock:
            self._peers[name] = ("puller", fn)

    def register_cluster(self, cluster) -> None:
        """One puller per live ProcessCluster worker, scraped through the
        cluster's task queues (no worker-side HTTP server needed)."""
        from ..parallel.runtime import metrics_text_task
        for w, p in enumerate(cluster.procs):
            if not p.is_alive():
                continue
            self.register_puller(
                f"worker-{w}",
                lambda w=w: cluster.run_on(w, metrics_text_task))

    def unregister(self, name: str) -> None:
        with self._lock:
            self._peers.pop(name, None)

    def peers(self) -> "dict":
        with self._lock:
            return dict(self._peers)

    def _pull(self, kind: str, target, timeout_s: float) -> str:
        if kind == "url":
            from urllib.request import urlopen
            with urlopen(target, timeout=timeout_s) as resp:
                return resp.read().decode("utf-8")
        return target()

    def scrape(self, timeout_s: float = 2.0) -> "dict":
        """name -> {"ok", "samples"|"error"} for every registered peer
        (the /federation JSON body). A dead peer is reported, never
        raised — federation must degrade, not 500."""
        out = {}
        for name, (kind, target) in sorted(self.peers().items()):
            try:
                text = self._pull(kind, target, timeout_s)
                samples = sum(1 for ln in text.splitlines()
                              if ln and not ln.startswith("#"))
                out[name] = {"ok": True, "kind": kind, "samples": samples}
            except Exception as e:  # noqa: BLE001 — report, don't fail
                out[name] = {"ok": False, "kind": kind, "error": str(e)}
        return out

    def prometheus_text(self, timeout_s: float = 2.0) -> str:
        from ..utils.metrics import get_stats
        pages = [label_prometheus_text(get_stats().prometheus_text(),
                                       self.local_name)]
        for name, (kind, target) in sorted(self.peers().items()):
            try:
                text = self._pull(kind, target, timeout_s)
            except Exception as e:  # noqa: BLE001
                pages.append(f"# federation scrape of {name} FAILED: "
                             f"{e}\n")
                continue
            pages.append(f"# federated from {name}\n"
                         + label_prometheus_text(text, name))
        return "\n".join(pages)


class _StatusHandler(BaseHTTPRequestHandler):
    server_version = "spark-rapids-tpu-statusd"

    def log_message(self, fmt, *args):  # no stderr chatter per request
        pass

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        monitor = self.server.monitor  # type: ignore[attr-defined]
        path = urlparse(self.path).path.rstrip("/") or "/"
        try:
            if path == "/healthz":
                if not monitor.ticking():
                    # no monitor thread (health.port set without
                    # health.enabled): sample on the probe itself so the
                    # 503-while-stalled contract still holds; no
                    # heartbeat — liveness polls must not flood the log
                    monitor.tick(emit_heartbeat=False)
                # cheap probe path: no full snapshot() — load balancers
                # poll this every few seconds
                body = {
                    "status": "stalled" if monitor.stalled else "ok",
                    "uptime_s": round(monitor.uptime_s(), 3),
                    "stalls_detected": monitor.stalls_detected,
                    "last_progress_age_s": round(
                        monitor.last_progress_age_s(), 3),
                }
                self._send(503 if monitor.stalled else 200,
                           json.dumps(body), "application/json")
            elif path == "/metrics":
                from ..utils.metrics import get_stats
                self._send(200, get_stats().prometheus_text(),
                           "text/plain; version=0.0.4")
            elif path == "/status":
                self._send(200,
                           json.dumps(monitor.snapshot(), default=str),
                           "application/json")
            elif path == "/federation":
                fed = self.server.federation  # type: ignore[attr-defined]
                body = {"local": fed.local_name,
                        "peers": fed.scrape()}
                self._send(200, json.dumps(body), "application/json")
            elif path == "/federation/metrics":
                fed = self.server.federation  # type: ignore[attr-defined]
                self._send(200, fed.prometheus_text(),
                           "text/plain; version=0.0.4")
            elif path == "/quarantine":
                # degradation state: live quarantine entries + the
                # fallback/deadline counters (exec/fallback.py,
                # utils/deadline.py) — what an operator checks when
                # explain() starts showing "quarantined:" reasons
                from ..exec.fallback import (fallback_stats,
                                             quarantine_entries)
                from ..utils.deadline import deadline_stats
                body = {"fallback": fallback_stats(),
                        "deadline": deadline_stats(),
                        "quarantine": quarantine_entries()}
                self._send(200, json.dumps(body, default=str),
                           "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "not found",
                     "endpoints": ["/healthz", "/metrics", "/status",
                                   "/quarantine", "/federation",
                                   "/federation/metrics"]}),
                    "application/json")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response

    def _send(self, code: int, body: str, ctype: str) -> None:
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class StatusServer:
    """Background HTTP server bound to 127.0.0.1 serving one monitor's
    snapshots. Request handling is threaded (daemon threads), so /healthz
    answers even while a long /status snapshot or a query runs."""

    def __init__(self, monitor, port: int = 0, host: str = "127.0.0.1",
                 federation: Optional[MetricsFederation] = None):
        self._httpd = ThreadingHTTPServer((host, port), _StatusHandler)
        self._httpd.daemon_threads = True
        self._httpd.monitor = monitor  # type: ignore[attr-defined]
        self.federation = federation or MetricsFederation()
        self._httpd.federation = self.federation  # type: ignore[attr-defined]
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "StatusServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="tpu-health-httpd")
            self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        t, self._thread = self._thread, None
        if t is None:
            return
        self._httpd.shutdown()
        t.join(timeout=timeout_s)
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
