"""HTTP status endpoints for the live health subsystem.

The Spark live-UI analogue, cut down to what a load balancer and an
operator actually poll (ROADMAP north star: serve heavy traffic — a
fleet needs a liveness probe per process):

- ``GET /healthz`` — liveness JSON; **503** while the watchdog considers
  the engine stalled (work in flight, no progress past
  ``spark.rapids.tpu.health.stallTimeout``), 200 otherwise. Load
  balancers key off the status code alone.
- ``GET /metrics`` — the process StatsRegistry as Prometheus text
  exposition 0.0.4 (utils/metrics.py), same payload
  ``prometheus_text()`` returns programmatically.
- ``GET /status`` — the full live JSON snapshot
  (``HealthMonitor.snapshot()``): semaphore holders/waiters, pipeline
  queue depths + in-flight task ages, HBM watermarks, active operator
  contexts, recent watermark history.

stdlib ``http.server`` only (no new dependencies); a
``ThreadingHTTPServer`` on 127.0.0.1 whose serve loop runs on a
``tpu-health-httpd`` daemon thread — ``StatusServer.stop()`` (from
``session.close()``) shuts it down, which the no-leaked-threads test
asserts. Port 0 binds an ephemeral port (``StatusServer.port`` reports
the bound one) so tests and multi-session hosts never collide.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

__all__ = ["StatusServer"]


class _StatusHandler(BaseHTTPRequestHandler):
    server_version = "spark-rapids-tpu-statusd"

    def log_message(self, fmt, *args):  # no stderr chatter per request
        pass

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        monitor = self.server.monitor  # type: ignore[attr-defined]
        path = urlparse(self.path).path.rstrip("/") or "/"
        try:
            if path == "/healthz":
                if not monitor.ticking():
                    # no monitor thread (health.port set without
                    # health.enabled): sample on the probe itself so the
                    # 503-while-stalled contract still holds; no
                    # heartbeat — liveness polls must not flood the log
                    monitor.tick(emit_heartbeat=False)
                # cheap probe path: no full snapshot() — load balancers
                # poll this every few seconds
                body = {
                    "status": "stalled" if monitor.stalled else "ok",
                    "uptime_s": round(monitor.uptime_s(), 3),
                    "stalls_detected": monitor.stalls_detected,
                    "last_progress_age_s": round(
                        monitor.last_progress_age_s(), 3),
                }
                self._send(503 if monitor.stalled else 200,
                           json.dumps(body), "application/json")
            elif path == "/metrics":
                from ..utils.metrics import get_stats
                self._send(200, get_stats().prometheus_text(),
                           "text/plain; version=0.0.4")
            elif path == "/status":
                self._send(200,
                           json.dumps(monitor.snapshot(), default=str),
                           "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "not found",
                     "endpoints": ["/healthz", "/metrics", "/status"]}),
                    "application/json")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response

    def _send(self, code: int, body: str, ctype: str) -> None:
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class StatusServer:
    """Background HTTP server bound to 127.0.0.1 serving one monitor's
    snapshots. Request handling is threaded (daemon threads), so /healthz
    answers even while a long /status snapshot or a query runs."""

    def __init__(self, monitor, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, port), _StatusHandler)
        self._httpd.daemon_threads = True
        self._httpd.monitor = monitor  # type: ignore[attr-defined]
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "StatusServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="tpu-health-httpd")
            self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        t, self._thread = self._thread, None
        if t is None:
            return
        self._httpd.shutdown()
        t.join(timeout=timeout_s)
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
