"""TPC-H workload support: data generator + query definitions.

The reference ships benchmark workloads (mortgage ETL, NDS) rather than a
generator; BASELINE.md's ladder starts at TPC-H Q6 @ SF10. This module
generates TPC-H-shaped data (numpy, seeded) and defines queries against the
DataFrame API. Prices are double (not decimal) matching the common
benchmarking simplification; row counts follow the spec scale factors.
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa

__all__ = ["gen_lineitem", "gen_orders", "gen_customer", "gen_part",
           "gen_supplier", "gen_nation", "gen_region", "q6", "q1", "q3"]

_EPOCH_1992 = 8035   # days from unix epoch to 1992-01-01
_DATE_RANGE = 2557   # ~7 years of ship dates


def gen_lineitem(sf: float, seed: int = 0, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(6_000_000 * sf)
    rng = np.random.default_rng(seed)
    orderkey = rng.integers(1, max(int(1_500_000 * sf), n // 4 + 1) * 4 + 1, size=n)
    partkey = rng.integers(1, max(int(200_000 * sf), 1) + 1, size=n)
    suppkey = rng.integers(1, max(int(10_000 * sf), 1) + 1, size=n)
    quantity = rng.integers(1, 51, size=n).astype(np.float64)
    extendedprice = np.round(rng.uniform(900.0, 105_000.0, size=n), 2)
    discount = np.round(rng.integers(0, 11, size=n) * 0.01, 2)
    tax = np.round(rng.integers(0, 9, size=n) * 0.01, 2)
    shipdate = (_EPOCH_1992 + rng.integers(0, _DATE_RANGE, size=n)).astype(np.int32)
    commitdate = shipdate + rng.integers(-30, 31, size=n).astype(np.int32)
    receiptdate = shipdate + rng.integers(1, 31, size=n).astype(np.int32)
    returnflag = rng.choice(np.array(["A", "N", "R"]), size=n)
    linestatus = np.where(shipdate > _EPOCH_1992 + 1460, "O", "F")
    shipmode = rng.choice(np.array(
        ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]), size=n)
    return pa.table({
        "l_orderkey": pa.array(orderkey, type=pa.int64()),
        "l_partkey": pa.array(partkey, type=pa.int64()),
        "l_suppkey": pa.array(suppkey, type=pa.int64()),
        "l_quantity": pa.array(quantity),
        "l_extendedprice": pa.array(extendedprice),
        "l_discount": pa.array(discount),
        "l_tax": pa.array(tax),
        "l_returnflag": pa.array(returnflag),
        "l_linestatus": pa.array(linestatus),
        "l_shipdate": pa.array(shipdate, type=pa.int32()).cast(pa.date32()),
        "l_commitdate": pa.array(commitdate, type=pa.int32()).cast(pa.date32()),
        "l_receiptdate": pa.array(receiptdate, type=pa.int32()).cast(pa.date32()),
        "l_shipmode": pa.array(shipmode),
    })


def gen_orders(sf: float, seed: int = 1, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(1_500_000 * sf)
    rng = np.random.default_rng(seed)
    orderkey = np.arange(1, n + 1, dtype=np.int64) * 4
    custkey = rng.integers(1, max(int(150_000 * sf), n // 10 + 1) + 1, size=n)
    totalprice = np.round(rng.uniform(850.0, 560_000.0, size=n), 2)
    orderdate = (_EPOCH_1992 + rng.integers(0, _DATE_RANGE - 151, size=n)
                 ).astype(np.int32)
    orderstatus = rng.choice(np.array(["F", "O", "P"]), size=n)
    orderpriority = rng.choice(np.array(
        ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]), size=n)
    shippriority = np.zeros(n, dtype=np.int32)
    return pa.table({
        "o_orderkey": pa.array(orderkey),
        "o_custkey": pa.array(custkey, type=pa.int64()),
        "o_orderstatus": pa.array(orderstatus),
        "o_totalprice": pa.array(totalprice),
        "o_orderdate": pa.array(orderdate, type=pa.int32()).cast(pa.date32()),
        "o_orderpriority": pa.array(orderpriority),
        "o_shippriority": pa.array(shippriority),
    })


def gen_customer(sf: float, seed: int = 2, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(150_000 * sf)
    rng = np.random.default_rng(seed)
    custkey = np.arange(1, n + 1, dtype=np.int64)
    nationkey = rng.integers(0, 25, size=n).astype(np.int64)
    acctbal = np.round(rng.uniform(-999.99, 9999.99, size=n), 2)
    mktsegment = rng.choice(np.array(
        ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]),
        size=n)
    return pa.table({
        "c_custkey": pa.array(custkey),
        "c_nationkey": pa.array(nationkey),
        "c_acctbal": pa.array(acctbal),
        "c_mktsegment": pa.array(mktsegment),
    })


def gen_part(sf: float, seed: int = 3, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(200_000 * sf)
    rng = np.random.default_rng(seed)
    return pa.table({
        "p_partkey": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "p_size": pa.array(rng.integers(1, 51, size=n).astype(np.int32)),
        "p_retailprice": pa.array(np.round(rng.uniform(900, 2000, size=n), 2)),
        "p_brand": pa.array(rng.choice(
            np.array([f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]),
            size=n)),
        "p_container": pa.array(rng.choice(np.array(
            ["SM CASE", "SM BOX", "MED BAG", "LG JAR", "JUMBO PKG"]), size=n)),
    })


def gen_supplier(sf: float, seed: int = 4, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(10_000 * sf)
    rng = np.random.default_rng(seed)
    return pa.table({
        "s_suppkey": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "s_nationkey": pa.array(rng.integers(0, 25, size=n).astype(np.int64)),
        "s_acctbal": pa.array(np.round(rng.uniform(-999.99, 9999.99, size=n), 2)),
    })


def gen_nation() -> pa.Table:
    return pa.table({
        "n_nationkey": pa.array(np.arange(25, dtype=np.int64)),
        "n_regionkey": pa.array((np.arange(25) % 5).astype(np.int64)),
        "n_name": pa.array([f"NATION_{i:02d}" for i in range(25)]),
    })


def gen_region() -> pa.Table:
    return pa.table({
        "r_regionkey": pa.array(np.arange(5, dtype=np.int64)),
        "r_name": pa.array(["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDEAST"]),
    })


# ---------------------------------------------------------------------------
# Queries (DataFrame API). Dates passed as days-since-epoch ints compared
# against date columns via casts.
# ---------------------------------------------------------------------------
_D_1994_01_01 = 8766
_D_1995_01_01 = 9131
_D_1998_09_02 = 10471
_D_1995_03_15 = 9204


def q6(lineitem_df):
    """TPC-H Q6: forecast revenue change (scan+filter+sum, BASELINE ladder #1)."""
    from ..expr.functions import col, lit, sum as fsum
    from ..columnar import dtypes as dt
    sd = col("l_shipdate").cast(dt.INT)
    return (lineitem_df
            .filter((sd >= lit(_D_1994_01_01)) & (sd < lit(_D_1995_01_01))
                    & (col("l_discount") >= lit(0.05))
                    & (col("l_discount") <= lit(0.07))
                    & (col("l_quantity") < lit(24.0)))
            .agg(fsum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q1(lineitem_df):
    """TPC-H Q1: pricing summary report (grouped agg over most of lineitem)."""
    from ..expr.functions import avg, col, count_star, lit, sum as fsum
    from ..columnar import dtypes as dt
    sd = col("l_shipdate").cast(dt.INT)
    disc_price = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    charge = disc_price * (lit(1.0) + col("l_tax"))
    return (lineitem_df
            .filter(sd <= lit(_D_1998_09_02))
            .group_by("l_returnflag", "l_linestatus")
            .agg(fsum(col("l_quantity")).alias("sum_qty"),
                 fsum(col("l_extendedprice")).alias("sum_base_price"),
                 fsum(disc_price).alias("sum_disc_price"),
                 fsum(charge).alias("sum_charge"),
                 avg(col("l_quantity")).alias("avg_qty"),
                 avg(col("l_extendedprice")).alias("avg_price"),
                 avg(col("l_discount")).alias("avg_disc"),
                 count_star().alias("count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q3(lineitem_df, orders_df, customer_df):
    """TPC-H Q3: shipping priority (join-heavy)."""
    from ..expr.functions import col, lit, sum as fsum
    from ..columnar import dtypes as dt
    od = col("o_orderdate").cast(dt.INT)
    sd = col("l_shipdate").cast(dt.INT)
    cust = customer_df.filter(col("c_mktsegment") == lit("BUILDING"))
    orders = orders_df.filter(od < lit(_D_1995_03_15))
    li = lineitem_df.filter(sd > lit(_D_1995_03_15))
    joined = (cust.join(orders, condition=(col("c_custkey") == col("o_custkey")))
                  .join(li, condition=(col("o_orderkey") == col("l_orderkey"))))
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (joined.group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(fsum(rev).alias("revenue"))
            .sort(col("revenue").desc())
            .limit(10))
