"""TPC-H workload support: data generator + all 22 query definitions.

The reference ships benchmark workloads (mortgage ETL, NDS) rather than a
generator; BASELINE.md's ladder runs TPC-H Q6 @ SF10 then the full 22-query
suite. This module generates TPC-H-shaped data (numpy, seeded, dbgen-flavored
value domains) and defines every query against the DataFrame API. Prices are
double (not decimal) matching the common benchmarking simplification; row
counts follow the spec scale factors.

Scalar subqueries are expressed the way a DataFrame-API user writes them:
aggregate to a one-row frame and cross-join it back (stays one lazy plan on
both engines). EXISTS / NOT EXISTS become left-semi / left-anti joins
(reference: GpuBroadcastHashJoinExec left_semi/left_anti support,
sql-plugin GpuHashJoin.scala).
"""
from __future__ import annotations

import numpy as np
import pyarrow as pa

__all__ = ["gen_lineitem", "gen_orders", "gen_customer", "gen_part",
           "gen_supplier", "gen_partsupp", "gen_nation", "gen_region",
           "gen_all", "QUERIES", "TABLE_GENERATORS",
           ] + [f"q{i}" for i in range(1, 23)]

_EPOCH_1992 = 8035   # days from unix epoch to 1992-01-01
_DATE_RANGE = 2557   # ~7 years of ship dates

_WORDS = np.array([
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
    "light", "lime", "linen", "magenta", "maroon", "medium", "metallic",
    "midnight", "mint", "misty", "moccasin", "navajo", "navy", "olive",
    "orange", "orchid", "pale", "papaya", "peach", "peru", "pink", "plum",
    "powder", "puff", "purple", "red", "rose", "rosy", "royal", "saddle",
    "salmon", "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow",
    "spring", "steel", "tan", "thistle", "tomato", "turquoise", "violet",
    "wheat", "white", "yellow"])

_FILLER = np.array([
    "carefully", "quickly", "furiously", "slyly", "blithely", "ironic",
    "regular", "final", "bold", "pending", "express", "silent", "even",
    "unusual", "daring", "idle", "busy", "brave", "quiet", "ruthless",
    "deposits", "requests", "packages", "accounts", "instructions", "theodolites",
    "foxes", "pinto", "beans", "dependencies", "platelets", "excuses", "ideas",
    "sheaves", "asymptotes", "dugouts", "sauternes", "warthogs", "courts"])


def _sentences(rng: np.random.Generator, n: int, words: int = 6,
               special: "tuple[str, float] | None" = None) -> np.ndarray:
    """Vectorized random comment strings from a pre-built pool of 128; with
    probability ``special[1]`` a row gets a pool entry embedding
    ``special[0]`` (a '<a>%<b>' two-word wildcard phrase)."""
    pool = np.array([" ".join(rng.choice(_FILLER, words)) for _ in range(128)])
    out = rng.choice(pool, size=n)
    if special is not None:
        phrase, prob = special
        a, b = phrase.split("%")
        hit = rng.random(n) < prob
        mid = rng.choice(_FILLER, n)
        out = np.where(hit, np.char.add(np.char.add(a + " ", mid), " " + b), out)
    return out


def decimal_lineitem(table: pa.Table) -> pa.Table:
    """Money/quantity columns re-typed to DECIMAL(12,2) — Spark's TPC-H
    schema semantics (the reference runs these as DECIMAL_128 intermediates:
    decimalExpressions.scala; sum/avg states exceed 18 digits)."""
    out = table
    for name in ("l_quantity", "l_extendedprice", "l_discount", "l_tax"):
        i = out.schema.get_field_index(name)
        out = out.set_column(
            i, name, out.column(name).cast(pa.decimal128(12, 2)))
    return out


def gen_lineitem(sf: float, seed: int = 0, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(6_000_000 * sf)
    rng = np.random.default_rng(seed)
    # key domains follow the spec ratios; when ``rows`` overrides the scale
    # they derive from n ALONE so referential integrity with the sibling
    # tables' gen_all(tiny=True) row counts is preserved (orders=n/4,
    # part=n/25, supplier=n/120 — the _TINY_ROWS ratios)
    if rows is not None:
        n_ord, n_part, n_supp = max(n // 4, 1), max(n // 25, 1), max(n // 120, 1)
    else:
        n_ord, n_part, n_supp = (max(int(1_500_000 * sf), 1),
                                 max(int(200_000 * sf), 1),
                                 max(int(10_000 * sf), 1))
    orderkey = rng.integers(1, n_ord + 1, size=n) * 4
    partkey = rng.integers(1, n_part + 1, size=n)
    suppkey = rng.integers(1, n_supp + 1, size=n)
    linenumber = rng.integers(1, 8, size=n).astype(np.int32)
    quantity = rng.integers(1, 51, size=n).astype(np.float64)
    extendedprice = np.round(rng.uniform(900.0, 105_000.0, size=n), 2)
    discount = np.round(rng.integers(0, 11, size=n) * 0.01, 2)
    tax = np.round(rng.integers(0, 9, size=n) * 0.01, 2)
    shipdate = (_EPOCH_1992 + rng.integers(0, _DATE_RANGE, size=n)).astype(np.int32)
    commitdate = shipdate + rng.integers(-30, 31, size=n).astype(np.int32)
    receiptdate = shipdate + rng.integers(1, 31, size=n).astype(np.int32)
    returnflag = rng.choice(np.array(["A", "N", "R"]), size=n)
    linestatus = np.where(shipdate > _EPOCH_1992 + 1460, "O", "F")
    shipmode = rng.choice(np.array(
        ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]), size=n)
    shipinstruct = rng.choice(np.array(
        ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]), size=n)
    return pa.table({
        "l_orderkey": pa.array(orderkey, type=pa.int64()),
        "l_partkey": pa.array(partkey, type=pa.int64()),
        "l_suppkey": pa.array(suppkey, type=pa.int64()),
        "l_linenumber": pa.array(linenumber, type=pa.int32()),
        "l_quantity": pa.array(quantity),
        "l_extendedprice": pa.array(extendedprice),
        "l_discount": pa.array(discount),
        "l_tax": pa.array(tax),
        "l_returnflag": pa.array(returnflag),
        "l_linestatus": pa.array(linestatus),
        "l_shipdate": pa.array(shipdate, type=pa.int32()).cast(pa.date32()),
        "l_commitdate": pa.array(commitdate, type=pa.int32()).cast(pa.date32()),
        "l_receiptdate": pa.array(receiptdate, type=pa.int32()).cast(pa.date32()),
        "l_shipinstruct": pa.array(shipinstruct),
        "l_shipmode": pa.array(shipmode),
    })


def gen_orders(sf: float, seed: int = 1, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(1_500_000 * sf)
    rng = np.random.default_rng(seed)
    orderkey = np.arange(1, n + 1, dtype=np.int64) * 4
    n_cust = max(n // 5, 1) if rows is not None else max(int(150_000 * sf), 1)
    custkey = rng.integers(1, n_cust + 1, size=n)
    totalprice = np.round(rng.uniform(850.0, 560_000.0, size=n), 2)
    orderdate = (_EPOCH_1992 + rng.integers(0, _DATE_RANGE - 151, size=n)
                 ).astype(np.int32)
    orderstatus = rng.choice(np.array(["F", "O", "P"]), size=n)
    orderpriority = rng.choice(np.array(
        ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]), size=n)
    shippriority = np.zeros(n, dtype=np.int32)
    comment = _sentences(rng, n, special=("special%requests", 0.05))
    return pa.table({
        "o_orderkey": pa.array(orderkey),
        "o_custkey": pa.array(custkey, type=pa.int64()),
        "o_orderstatus": pa.array(orderstatus),
        "o_totalprice": pa.array(totalprice),
        "o_orderdate": pa.array(orderdate, type=pa.int32()).cast(pa.date32()),
        "o_orderpriority": pa.array(orderpriority),
        "o_shippriority": pa.array(shippriority),
        "o_comment": pa.array(comment),
    })


def gen_customer(sf: float, seed: int = 2, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(150_000 * sf)
    rng = np.random.default_rng(seed)
    custkey = np.arange(1, n + 1, dtype=np.int64)
    nationkey = rng.integers(0, 25, size=n).astype(np.int64)
    acctbal = np.round(rng.uniform(-999.99, 9999.99, size=n), 2)
    mktsegment = rng.choice(np.array(
        ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]),
        size=n)
    # phone country code = nationkey + 10 (dbgen rule) -> Q22 substring codes
    p1 = rng.integers(100, 1000, size=n).astype("U3")
    p2 = rng.integers(100, 1000, size=n).astype("U3")
    p3 = rng.integers(1000, 10000, size=n).astype("U4")
    phone = (nationkey + 10).astype("U2")
    for part in ("-", p1, "-", p2, "-", p3):
        phone = np.char.add(phone, part)
    return pa.table({
        "c_custkey": pa.array(custkey),
        "c_name": pa.array(np.char.add("Customer#", custkey.astype("U9"))),
        "c_address": pa.array(_sentences(rng, n, words=3)),
        "c_nationkey": pa.array(nationkey),
        "c_phone": pa.array(phone),
        "c_acctbal": pa.array(acctbal),
        "c_mktsegment": pa.array(mktsegment),
        "c_comment": pa.array(_sentences(rng, n)),
    })


_TYPE_1 = np.array(["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"])
_TYPE_2 = np.array(["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"])
_TYPE_3 = np.array(["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"])
_CONT_1 = np.array(["SM", "MED", "LG", "JUMBO", "WRAP"])
_CONT_2 = np.array(["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"])


def gen_part(sf: float, seed: int = 3, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(200_000 * sf)
    rng = np.random.default_rng(seed)
    name = rng.choice(_WORDS, size=(n, 5))
    p_name = name[:, 0]
    for i in range(1, 5):
        p_name = np.char.add(np.char.add(p_name, " "), name[:, i])
    p_type = np.char.add(np.char.add(
        np.char.add(rng.choice(_TYPE_1, n), " "),
        np.char.add(rng.choice(_TYPE_2, n), " ")), rng.choice(_TYPE_3, n))
    container = np.char.add(np.char.add(rng.choice(_CONT_1, n), " "),
                            rng.choice(_CONT_2, n))
    mfgr_id = rng.integers(1, 6, size=n)
    brand = np.char.add(np.char.add("Brand#", mfgr_id.astype("U1")),
                        rng.integers(1, 6, size=n).astype("U1"))
    return pa.table({
        "p_partkey": pa.array(np.arange(1, n + 1, dtype=np.int64)),
        "p_name": pa.array(p_name),
        "p_mfgr": pa.array(np.char.add("Manufacturer#", mfgr_id.astype("U1"))),
        "p_brand": pa.array(brand),
        "p_type": pa.array(p_type),
        "p_size": pa.array(rng.integers(1, 51, size=n).astype(np.int32)),
        "p_container": pa.array(container),
        "p_retailprice": pa.array(np.round(rng.uniform(900, 2000, size=n), 2)),
        "p_comment": pa.array(_sentences(rng, n, words=3)),
    })


def gen_supplier(sf: float, seed: int = 4, rows: int | None = None) -> pa.Table:
    n = rows if rows is not None else int(10_000 * sf)
    rng = np.random.default_rng(seed)
    suppkey = np.arange(1, n + 1, dtype=np.int64)
    nationkey = rng.integers(0, 25, size=n).astype(np.int64)
    phone = np.char.add((nationkey + 10).astype("U2"), "-555-0100")
    return pa.table({
        "s_suppkey": pa.array(suppkey),
        "s_name": pa.array(np.char.add("Supplier#", suppkey.astype("U9"))),
        "s_address": pa.array(_sentences(rng, n, words=3)),
        "s_nationkey": pa.array(nationkey),
        "s_phone": pa.array(phone),
        "s_acctbal": pa.array(np.round(rng.uniform(-999.99, 9999.99, size=n), 2)),
        "s_comment": pa.array(_sentences(
            rng, n, special=("Customer%Complaints", 0.05))),
    })


def gen_partsupp(sf: float, seed: int = 5, rows: int | None = None) -> pa.Table:
    """4 suppliers per part (dbgen layout); ps_suppkey spread deterministically
    so (ps_partkey, ps_suppkey) pairs are unique."""
    n_part = max((rows // 4) if rows is not None else int(200_000 * sf), 1)
    # supplier domain tracks gen_all's tiny ratios (supplier = partsupp/19.2)
    n_supp = max(round(rows / 19.2), 4) if rows is not None \
        else max(int(10_000 * sf), 4)
    rng = np.random.default_rng(seed)
    partkey = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
    i = np.tile(np.arange(4, dtype=np.int64), n_part)
    suppkey = ((partkey - 1 + i * max(n_supp // 4, 1)) % n_supp) + 1
    n = len(partkey)
    return pa.table({
        "ps_partkey": pa.array(partkey),
        "ps_suppkey": pa.array(suppkey),
        "ps_availqty": pa.array(rng.integers(1, 10_000, size=n).astype(np.int32)),
        "ps_supplycost": pa.array(np.round(rng.uniform(1.0, 1000.0, size=n), 2)),
        "ps_comment": pa.array(_sentences(rng, n)),
    })


_NATIONS = [  # (key, name, regionkey) — dbgen nation table
    (0, "ALGERIA", 0), (1, "ARGENTINA", 1), (2, "BRAZIL", 1), (3, "CANADA", 1),
    (4, "EGYPT", 4), (5, "ETHIOPIA", 0), (6, "FRANCE", 3), (7, "GERMANY", 3),
    (8, "INDIA", 2), (9, "INDONESIA", 2), (10, "IRAN", 4), (11, "IRAQ", 4),
    (12, "JAPAN", 2), (13, "JORDAN", 4), (14, "KENYA", 0), (15, "MOROCCO", 0),
    (16, "MOZAMBIQUE", 0), (17, "PERU", 1), (18, "CHINA", 2), (19, "ROMANIA", 3),
    (20, "SAUDI ARABIA", 4), (21, "VIETNAM", 2), (22, "RUSSIA", 3),
    (23, "UNITED KINGDOM", 3), (24, "UNITED STATES", 1)]


def gen_nation() -> pa.Table:
    return pa.table({
        "n_nationkey": pa.array([k for k, _, _ in _NATIONS], type=pa.int64()),
        "n_name": pa.array([n for _, n, _ in _NATIONS]),
        "n_regionkey": pa.array([r for _, _, r in _NATIONS], type=pa.int64()),
    })


def gen_region() -> pa.Table:
    return pa.table({
        "r_regionkey": pa.array(np.arange(5, dtype=np.int64)),
        "r_name": pa.array(["AFRICA", "AMERICA", "ASIA", "EUROPE",
                            "MIDDLE EAST"]),
    })


TABLE_GENERATORS = {
    "lineitem": gen_lineitem, "orders": gen_orders, "customer": gen_customer,
    "part": gen_part, "supplier": gen_supplier, "partsupp": gen_partsupp,
    "nation": lambda sf, **kw: gen_nation(), "region": lambda sf, **kw: gen_region(),
}

_TINY_ROWS = {"lineitem": 3000, "orders": 750, "customer": 150, "part": 120,
              "supplier": 25, "partsupp": 480}


def gen_all(sf: float, tiny: bool = False) -> "dict[str, pa.Table]":
    """All 8 tables; ``tiny=True`` caps row counts for unit tests."""
    out = {}
    for name, g in TABLE_GENERATORS.items():
        if name in ("nation", "region"):
            out[name] = g(sf)
        elif tiny:
            out[name] = g(sf, rows=_TINY_ROWS[name])
        else:
            out[name] = g(sf)
    return out


# ---------------------------------------------------------------------------
# Queries (DataFrame API). Dates compared as days-since-epoch ints via casts.
# Each query function takes a dict of DataFrames keyed by table name.
# ---------------------------------------------------------------------------
_D = {
    "1993-01-01": 8401, "1993-07-01": 8582, "1993-10-01": 8674,
    "1994-01-01": 8766, "1995-01-01": 9131, "1995-03-15": 9204,
    "1995-09-01": 9374, "1995-10-01": 9404, "1996-01-01": 9496,
    "1996-04-01": 9587, "1996-12-31": 9861, "1997-01-01": 9862,
    "1998-09-02": 10471,
}


def _f():
    from ..expr import functions as F
    return F


def _dt():
    from ..columnar import dtypes as dt
    return dt


def q1(t):
    """TPC-H Q1: pricing summary report (reference workload: grouped agg)."""
    F = _f()
    col, lit = F.col, F.lit
    sd = col("l_shipdate").cast(_dt().INT)
    disc_price = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    charge = disc_price * (lit(1.0) + col("l_tax"))
    return (t["lineitem"]
            .filter(sd <= lit(_D["1998-09-02"]))
            .group_by("l_returnflag", "l_linestatus")
            .agg(F.sum(col("l_quantity")).alias("sum_qty"),
                 F.sum(col("l_extendedprice")).alias("sum_base_price"),
                 F.sum(disc_price).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.avg(col("l_quantity")).alias("avg_qty"),
                 F.avg(col("l_extendedprice")).alias("avg_price"),
                 F.avg(col("l_discount")).alias("avg_disc"),
                 F.count_star().alias("count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q1_decimal(t):
    """Q1 over DECIMAL(12,2) money columns: disc_price is decimal(26,4),
    charge decimal(38,6), their sums decimal(36,4)/decimal(38,6) — the
    DECIMAL_128 device tier end-to-end (expr/decimal128.py; reference:
    decimalExpressions.scala)."""
    import decimal as _dec
    F = _f()
    col, lit = F.col, F.lit
    sd = col("l_shipdate").cast(_dt().INT)
    one = lit(_dec.Decimal("1.00"))
    disc_price = col("l_extendedprice") * (one - col("l_discount"))
    charge = disc_price * (one + col("l_tax"))
    return (t["lineitem"]
            .filter(sd <= lit(_D["1998-09-02"]))
            .group_by("l_returnflag", "l_linestatus")
            .agg(F.sum(col("l_quantity")).alias("sum_qty"),
                 F.sum(col("l_extendedprice")).alias("sum_base_price"),
                 F.sum(disc_price).alias("sum_disc_price"),
                 F.sum(charge).alias("sum_charge"),
                 F.count_star().alias("count_order"))
            .sort("l_returnflag", "l_linestatus"))


def q6_decimal(t):
    """Q6 over DECIMAL(12,2): revenue = sum(price * disc) as decimal(35,4)."""
    import decimal as _dec
    F = _f()
    col, lit = F.col, F.lit
    sd = col("l_shipdate").cast(_dt().INT)
    return (t["lineitem"]
            .filter((sd >= lit(_D["1994-01-01"])) & (sd < lit(_D["1995-01-01"]))
                    & (col("l_discount") >= lit(_dec.Decimal("0.05")))
                    & (col("l_discount") <= lit(_dec.Decimal("0.07")))
                    & (col("l_quantity") < lit(_dec.Decimal("24.00"))))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q2(t):
    """TPC-H Q2: minimum-cost supplier (correlated min subquery -> groupby +
    re-join)."""
    F = _f()
    col, lit = F.col, F.lit
    base = (t["part"]
            .filter((col("p_size") == lit(15)) & col("p_type").endswith("BRASS"))
            .join(t["partsupp"], condition=col("p_partkey") == col("ps_partkey"))
            .join(t["supplier"], condition=col("ps_suppkey") == col("s_suppkey"))
            .join(t["nation"], condition=col("s_nationkey") == col("n_nationkey"))
            .join(t["region"], condition=col("n_regionkey") == col("r_regionkey"))
            .filter(col("r_name") == lit("EUROPE")))
    mincost = (base.group_by("p_partkey")
               .agg(F.min(col("ps_supplycost")).alias("min_sc"))
               .select(col("p_partkey").alias("mc_partkey"), col("min_sc")))
    return (base.join(mincost,
                      condition=(col("p_partkey") == col("mc_partkey"))
                      & (col("ps_supplycost") == col("min_sc")))
            .select("s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
                    "s_address", "s_phone", "s_comment")
            .sort(col("s_acctbal").desc(), col("n_name").asc(),
                  col("s_name").asc(), col("p_partkey").asc())
            .limit(100))


def q3(t):
    """TPC-H Q3: shipping priority (join-heavy)."""
    F = _f()
    col, lit = F.col, F.lit
    od = col("o_orderdate").cast(_dt().INT)
    sd = col("l_shipdate").cast(_dt().INT)
    cust = t["customer"].filter(col("c_mktsegment") == lit("BUILDING"))
    orders = t["orders"].filter(od < lit(_D["1995-03-15"]))
    li = t["lineitem"].filter(sd > lit(_D["1995-03-15"]))
    joined = (cust.join(orders, condition=(col("c_custkey") == col("o_custkey")))
                  .join(li, condition=(col("o_orderkey") == col("l_orderkey"))))
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (joined.group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(F.sum(rev).alias("revenue"))
            .sort(col("revenue").desc(), col("o_orderdate").asc())
            .limit(10))


def q4(t):
    """TPC-H Q4: order priority checking (EXISTS -> left-semi join)."""
    F = _f()
    col, lit = F.col, F.lit
    od = col("o_orderdate").cast(_dt().INT)
    li = t["lineitem"].select(
        col("l_orderkey").alias("lk"),
        (col("l_commitdate").cast(_dt().INT)
         < col("l_receiptdate").cast(_dt().INT)).alias("late"))
    return (t["orders"]
            .filter((od >= lit(_D["1993-07-01"])) & (od < lit(_D["1993-10-01"])))
            .join(li.filter(col("late")), how="left_semi",
                  condition=col("o_orderkey") == col("lk"))
            .group_by("o_orderpriority")
            .agg(F.count_star().alias("order_count"))
            .sort("o_orderpriority"))


def q5(t):
    """TPC-H Q5: local supplier volume (6-way join)."""
    F = _f()
    col, lit = F.col, F.lit
    od = col("o_orderdate").cast(_dt().INT)
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (t["customer"]
            .join(t["orders"], condition=col("c_custkey") == col("o_custkey"))
            .filter((od >= lit(_D["1994-01-01"])) & (od < lit(_D["1995-01-01"])))
            .join(t["lineitem"], condition=col("o_orderkey") == col("l_orderkey"))
            .join(t["supplier"],
                  condition=(col("l_suppkey") == col("s_suppkey"))
                  & (col("c_nationkey") == col("s_nationkey")))
            .join(t["nation"], condition=col("s_nationkey") == col("n_nationkey"))
            .join(t["region"], condition=col("n_regionkey") == col("r_regionkey"))
            .filter(col("r_name") == lit("ASIA"))
            .group_by("n_name")
            .agg(F.sum(rev).alias("revenue"))
            .sort(col("revenue").desc()))


def q6(t):
    """TPC-H Q6: forecast revenue change (scan+filter+sum, BASELINE ladder #1)."""
    F = _f()
    col, lit = F.col, F.lit
    sd = col("l_shipdate").cast(_dt().INT)
    return (t["lineitem"]
            .filter((sd >= lit(_D["1994-01-01"])) & (sd < lit(_D["1995-01-01"]))
                    & (col("l_discount") >= lit(0.05))
                    & (col("l_discount") <= lit(0.07))
                    & (col("l_quantity") < lit(24.0)))
            .agg(F.sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue")))


def q7(t):
    """TPC-H Q7: volume shipping (nation self-pair, year extraction)."""
    F = _f()
    col, lit = F.col, F.lit
    sd = col("l_shipdate").cast(_dt().INT)
    n1 = t["nation"].select(col("n_nationkey").alias("n1_key"),
                            col("n_name").alias("supp_nation"))
    n2 = t["nation"].select(col("n_nationkey").alias("n2_key"),
                            col("n_name").alias("cust_nation"))
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (t["supplier"]
            .join(t["lineitem"], condition=col("s_suppkey") == col("l_suppkey"))
            .filter((sd >= lit(_D["1995-01-01"])) & (sd <= lit(_D["1996-12-31"])))
            .join(t["orders"], condition=col("o_orderkey") == col("l_orderkey"))
            .join(t["customer"], condition=col("c_custkey") == col("o_custkey"))
            .join(n1, condition=col("s_nationkey") == col("n1_key"))
            .join(n2, condition=col("c_nationkey") == col("n2_key"))
            .filter(((col("supp_nation") == lit("FRANCE"))
                     & (col("cust_nation") == lit("GERMANY")))
                    | ((col("supp_nation") == lit("GERMANY"))
                       & (col("cust_nation") == lit("FRANCE"))))
            .with_column("l_year", F.year(col("l_shipdate")))
            .with_column("volume", rev)
            .group_by("supp_nation", "cust_nation", "l_year")
            .agg(F.sum(col("volume")).alias("revenue"))
            .sort("supp_nation", "cust_nation", "l_year"))


def q8(t):
    """TPC-H Q8: national market share (conditional aggregate ratio)."""
    F = _f()
    col, lit, when = F.col, F.lit, F.when
    od = col("o_orderdate").cast(_dt().INT)
    n1 = t["nation"].select(col("n_nationkey").alias("n1_key"),
                            col("n_regionkey").alias("n1_region"))
    n2 = t["nation"].select(col("n_nationkey").alias("n2_key"),
                            col("n_name").alias("nation"))
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (t["part"]
            .filter(col("p_type") == lit("ECONOMY ANODIZED STEEL"))
            .join(t["lineitem"], condition=col("p_partkey") == col("l_partkey"))
            .join(t["supplier"], condition=col("l_suppkey") == col("s_suppkey"))
            .join(t["orders"], condition=col("l_orderkey") == col("o_orderkey"))
            .filter((od >= lit(_D["1995-01-01"])) & (od <= lit(_D["1996-12-31"])))
            .join(t["customer"], condition=col("o_custkey") == col("c_custkey"))
            .join(n1, condition=col("c_nationkey") == col("n1_key"))
            .join(t["region"], condition=col("n1_region") == col("r_regionkey"))
            .filter(col("r_name") == lit("AMERICA"))
            .join(n2, condition=col("s_nationkey") == col("n2_key"))
            .with_column("o_year", F.year(col("o_orderdate")))
            .with_column("volume", rev)
            .with_column("brazil_volume",
                         when(col("nation") == lit("BRAZIL"), col("volume"))
                         .otherwise(lit(0.0)))
            .group_by("o_year")
            .agg(F.sum(col("brazil_volume")).alias("num"),
                 F.sum(col("volume")).alias("den"))
            .with_column("mkt_share", col("num") / col("den"))
            .select("o_year", "mkt_share")
            .sort("o_year"))


def q9(t):
    """TPC-H Q9: product type profit measure."""
    F = _f()
    col, lit = F.col, F.lit
    amount = (col("l_extendedprice") * (lit(1.0) - col("l_discount"))
              - col("ps_supplycost") * col("l_quantity"))
    return (t["part"]
            .filter(col("p_name").contains("green"))
            .join(t["lineitem"], condition=col("p_partkey") == col("l_partkey"))
            .join(t["supplier"], condition=col("l_suppkey") == col("s_suppkey"))
            .join(t["partsupp"],
                  condition=(col("ps_suppkey") == col("l_suppkey"))
                  & (col("ps_partkey") == col("l_partkey")))
            .join(t["orders"], condition=col("l_orderkey") == col("o_orderkey"))
            .join(t["nation"], condition=col("s_nationkey") == col("n_nationkey"))
            .with_column("o_year", F.year(col("o_orderdate")))
            .with_column("amount", amount)
            .group_by("n_name", "o_year")
            .agg(F.sum(col("amount")).alias("sum_profit"))
            .sort(col("n_name").asc(), col("o_year").desc()))


def q10(t):
    """TPC-H Q10: returned item reporting (top 20 customers)."""
    F = _f()
    col, lit = F.col, F.lit
    od = col("o_orderdate").cast(_dt().INT)
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (t["customer"]
            .join(t["orders"], condition=col("c_custkey") == col("o_custkey"))
            .filter((od >= lit(_D["1993-10-01"])) & (od < lit(_D["1994-01-01"])))
            .join(t["lineitem"], condition=col("o_orderkey") == col("l_orderkey"))
            .filter(col("l_returnflag") == lit("R"))
            .join(t["nation"], condition=col("c_nationkey") == col("n_nationkey"))
            .group_by("c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                      "c_address", "c_comment")
            .agg(F.sum(rev).alias("revenue"))
            .sort(col("revenue").desc(), col("c_custkey").asc())
            .limit(20))


def q11(t):
    """TPC-H Q11: important stock identification (global-scalar HAVING via
    scalar subquery, the SQL formulation's shape)."""
    F = _f()
    col, lit = F.col, F.lit
    base = (t["partsupp"]
            .join(t["supplier"], condition=col("ps_suppkey") == col("s_suppkey"))
            .join(t["nation"], condition=col("s_nationkey") == col("n_nationkey"))
            .filter(col("n_name") == lit("GERMANY"))
            .with_column("value", col("ps_supplycost")
                         * col("ps_availqty").cast(_dt().DOUBLE)))
    total = F.scalar_subquery(base.agg(F.sum(col("value")).alias("tv")))
    return (base.group_by("ps_partkey")
            .agg(F.sum(col("value")).alias("value"))
            .filter(col("value") > total * lit(0.0001))
            .select("ps_partkey", "value")
            .sort(col("value").desc(), col("ps_partkey").asc()))


def q12(t):
    """TPC-H Q12: shipping modes and order priority (conditional counts)."""
    F = _f()
    col, lit, when = F.col, F.lit, F.when
    rd = col("l_receiptdate").cast(_dt().INT)
    cd = col("l_commitdate").cast(_dt().INT)
    sd = col("l_shipdate").cast(_dt().INT)
    high = when(col("o_orderpriority").isin("1-URGENT", "2-HIGH"), lit(1)) \
        .otherwise(lit(0))
    low = when(col("o_orderpriority").isin("1-URGENT", "2-HIGH"), lit(0)) \
        .otherwise(lit(1))
    return (t["lineitem"]
            .filter(col("l_shipmode").isin("MAIL", "SHIP")
                    & (cd < rd) & (sd < cd)
                    & (rd >= lit(_D["1994-01-01"])) & (rd < lit(_D["1995-01-01"])))
            .join(t["orders"], condition=col("l_orderkey") == col("o_orderkey"))
            .with_column("high", high).with_column("low", low)
            .group_by("l_shipmode")
            .agg(F.sum(col("high")).alias("high_line_count"),
                 F.sum(col("low")).alias("low_line_count"))
            .sort("l_shipmode"))


def q13(t):
    """TPC-H Q13: customer distribution (left outer join + double grouping)."""
    F = _f()
    col = F.col
    orders = (t["orders"]
              .filter(~col("o_comment").like("%special%requests%"))
              .select(col("o_custkey").alias("ok_custkey"), col("o_orderkey")))
    return (t["customer"]
            .join(orders, how="left",
                  condition=col("c_custkey") == col("ok_custkey"))
            .group_by("c_custkey")
            .agg(F.count(col("o_orderkey")).alias("c_count"))
            .group_by("c_count")
            .agg(F.count_star().alias("custdist"))
            .sort(col("custdist").desc(), col("c_count").desc()))


def q14(t):
    """TPC-H Q14: promotion effect (conditional ratio over one month)."""
    F = _f()
    col, lit, when = F.col, F.lit, F.when
    sd = col("l_shipdate").cast(_dt().INT)
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (t["lineitem"]
            .filter((sd >= lit(_D["1995-09-01"])) & (sd < lit(_D["1995-10-01"])))
            .join(t["part"], condition=col("l_partkey") == col("p_partkey"))
            .with_column("rev", rev)
            .with_column("promo", when(col("p_type").startswith("PROMO"),
                                       col("rev")).otherwise(lit(0.0)))
            .agg(F.sum(col("promo")).alias("promo_rev"),
                 F.sum(col("rev")).alias("total_rev"))
            .with_column("promo_revenue",
                         lit(100.0) * col("promo_rev") / col("total_rev"))
            .select("promo_revenue"))


def q15(t):
    """TPC-H Q15: top supplier (max-scalar via scalar subquery)."""
    F = _f()
    col, lit = F.col, F.lit
    sd = col("l_shipdate").cast(_dt().INT)
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    revenue = (t["lineitem"]
               .filter((sd >= lit(_D["1996-01-01"])) & (sd < lit(_D["1996-04-01"])))
               .with_column("rev", rev)
               .group_by("l_suppkey")
               .agg(F.sum(col("rev")).alias("total_revenue")))
    maxrev = F.scalar_subquery(
        revenue.agg(F.max(col("total_revenue")).alias("max_revenue")))
    return (t["supplier"]
            .join(revenue, condition=col("s_suppkey") == col("l_suppkey"))
            .filter(col("total_revenue") == maxrev)
            .select("s_suppkey", "s_name", "s_address", "s_phone",
                    "total_revenue")
            .sort("s_suppkey"))


def q16(t):
    """TPC-H Q16: parts/supplier relationship (NOT IN -> left-anti, count
    distinct via dedup + count)."""
    F = _f()
    col, lit = F.col, F.lit
    bad_supp = (t["supplier"]
                .filter(col("s_comment").like("%Customer%Complaints%"))
                .select(col("s_suppkey").alias("bad_key")))
    return (t["partsupp"]
            .join(t["part"], condition=col("ps_partkey") == col("p_partkey"))
            .filter((col("p_brand") != lit("Brand#45"))
                    & ~col("p_type").startswith("MEDIUM POLISHED")
                    & col("p_size").isin(49, 14, 23, 45, 19, 3, 36, 9))
            .join(bad_supp, how="left_anti",
                  condition=col("ps_suppkey") == col("bad_key"))
            .select("p_brand", "p_type", "p_size", "ps_suppkey")
            .distinct()
            .group_by("p_brand", "p_type", "p_size")
            .agg(F.count_star().alias("supplier_cnt"))
            .sort(col("supplier_cnt").desc(), col("p_brand").asc(),
                  col("p_type").asc(), col("p_size").asc()))


def q17(t):
    """TPC-H Q17: small-quantity-order revenue (correlated avg subquery)."""
    F = _f()
    col, lit = F.col, F.lit
    avgq = (t["lineitem"].group_by("l_partkey")
            .agg(F.avg(col("l_quantity")).alias("aq"))
            .select(col("l_partkey").alias("aq_partkey"),
                    (lit(0.2) * col("aq")).alias("qty_limit")))
    return (t["lineitem"]
            .join(t["part"], condition=col("l_partkey") == col("p_partkey"))
            .filter((col("p_brand") == lit("Brand#23"))
                    & (col("p_container") == lit("MED BOX")))
            .join(avgq, condition=col("l_partkey") == col("aq_partkey"))
            .filter(col("l_quantity") < col("qty_limit"))
            .agg(F.sum(col("l_extendedprice")).alias("sum_price"))
            .with_column("avg_yearly", col("sum_price") / lit(7.0))
            .select("avg_yearly"))


def q18(t):
    """TPC-H Q18: large volume customer (HAVING -> filter over grouped agg,
    IN -> left-semi)."""
    F = _f()
    col, lit = F.col, F.lit
    big = (t["lineitem"].group_by("l_orderkey")
           .agg(F.sum(col("l_quantity")).alias("sum_qty"))
           .filter(col("sum_qty") > lit(300.0))
           .select(col("l_orderkey").alias("big_key")))
    return (t["customer"]
            .join(t["orders"], condition=col("c_custkey") == col("o_custkey"))
            .join(big, how="left_semi",
                  condition=col("o_orderkey") == col("big_key"))
            .join(t["lineitem"], condition=col("o_orderkey") == col("l_orderkey"))
            .group_by("c_name", "c_custkey", "o_orderkey", "o_orderdate",
                      "o_totalprice")
            .agg(F.sum(col("l_quantity")).alias("sum_qty"))
            .sort(col("o_totalprice").desc(), col("o_orderdate").asc(),
                  col("o_orderkey").asc())
            .limit(100))


def q19(t):
    """TPC-H Q19: discounted revenue (disjunctive join predicate)."""
    F = _f()
    col, lit = F.col, F.lit
    qty = col("l_quantity")
    sz = col("p_size")
    c1 = ((col("p_brand") == lit("Brand#12"))
          & col("p_container").isin("SM CASE", "SM BOX", "SM PACK", "SM PKG")
          & (qty >= lit(1.0)) & (qty <= lit(11.0))
          & (sz >= lit(1)) & (sz <= lit(5)))
    c2 = ((col("p_brand") == lit("Brand#23"))
          & col("p_container").isin("MED BAG", "MED BOX", "MED PKG", "MED PACK")
          & (qty >= lit(10.0)) & (qty <= lit(20.0))
          & (sz >= lit(1)) & (sz <= lit(10)))
    c3 = ((col("p_brand") == lit("Brand#34"))
          & col("p_container").isin("LG CASE", "LG BOX", "LG PACK", "LG PKG")
          & (qty >= lit(20.0)) & (qty <= lit(30.0))
          & (sz >= lit(1)) & (sz <= lit(15)))
    rev = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    return (t["lineitem"]
            .filter(col("l_shipmode").isin("AIR", "AIR REG")
                    & (col("l_shipinstruct") == lit("DELIVER IN PERSON")))
            .join(t["part"], condition=col("p_partkey") == col("l_partkey"))
            .filter(c1 | c2 | c3)
            .agg(F.sum(rev).alias("revenue")))


def q20(t):
    """TPC-H Q20: potential part promotion (nested IN -> stacked semi joins)."""
    F = _f()
    col, lit = F.col, F.lit
    sd = col("l_shipdate").cast(_dt().INT)
    qty = (t["lineitem"]
           .filter((sd >= lit(_D["1994-01-01"])) & (sd < lit(_D["1995-01-01"])))
           .group_by("l_partkey", "l_suppkey")
           .agg(F.sum(col("l_quantity")).alias("sq"))
           .select(col("l_partkey").alias("lq_partkey"),
                   col("l_suppkey").alias("lq_suppkey"),
                   (lit(0.5) * col("sq")).alias("half_qty")))
    forest = (t["part"].filter(col("p_name").startswith("forest"))
              .select(col("p_partkey").alias("fp_key")))
    ps = (t["partsupp"]
          .join(forest, how="left_semi",
                condition=col("ps_partkey") == col("fp_key"))
          .join(qty, how="left_semi",
                condition=(col("ps_partkey") == col("lq_partkey"))
                & (col("ps_suppkey") == col("lq_suppkey"))
                & (col("ps_availqty").cast(_dt().DOUBLE) > col("half_qty")))
          .select(col("ps_suppkey").alias("ok_supp")))
    return (t["supplier"]
            .join(ps, how="left_semi", condition=col("s_suppkey") == col("ok_supp"))
            .join(t["nation"], condition=col("s_nationkey") == col("n_nationkey"))
            .filter(col("n_name") == lit("CANADA"))
            .select("s_name", "s_address")
            .sort("s_name"))


def q21(t):
    """TPC-H Q21: suppliers who kept orders waiting (EXISTS + NOT EXISTS with
    non-equi residuals -> semi/anti joins)."""
    F = _f()
    col, lit = F.col, F.lit
    late = (col("l_receiptdate").cast(_dt().INT)
            > col("l_commitdate").cast(_dt().INT))
    l2 = t["lineitem"].select(col("l_orderkey").alias("l2_orderkey"),
                              col("l_suppkey").alias("l2_suppkey"))
    l3 = (t["lineitem"].filter(late)
          .select(col("l_orderkey").alias("l3_orderkey"),
                  col("l_suppkey").alias("l3_suppkey")))
    return (t["supplier"]
            .join(t["lineitem"].filter(late),
                  condition=col("s_suppkey") == col("l_suppkey"))
            .join(t["orders"], condition=col("o_orderkey") == col("l_orderkey"))
            .filter(col("o_orderstatus") == lit("F"))
            .join(t["nation"], condition=col("s_nationkey") == col("n_nationkey"))
            .filter(col("n_name") == lit("SAUDI ARABIA"))
            .join(l2, how="left_semi",
                  condition=(col("l_orderkey") == col("l2_orderkey"))
                  & (col("l2_suppkey") != col("l_suppkey")))
            .join(l3, how="left_anti",
                  condition=(col("l_orderkey") == col("l3_orderkey"))
                  & (col("l3_suppkey") != col("l_suppkey")))
            .group_by("s_name")
            .agg(F.count_star().alias("numwait"))
            .sort(col("numwait").desc(), col("s_name").asc())
            .limit(100))


def q22(t):
    """TPC-H Q22: global sales opportunity (substring country codes, global
    avg via scalar subquery, NOT EXISTS -> anti join)."""
    F = _f()
    col, lit = F.col, F.lit
    codes = ("13", "31", "23", "29", "30", "18", "17")
    cust = (t["customer"]
            .with_column("cntrycode", F.substring(col("c_phone"), 1, 2))
            .filter(col("cntrycode").isin(*codes)))
    avg_bal = F.scalar_subquery(
        cust.filter(col("c_acctbal") > lit(0.0))
            .agg(F.avg(col("c_acctbal")).alias("avg_bal")))
    ord_keys = t["orders"].select(col("o_custkey").alias("ord_custkey"))
    return (cust
            .filter(col("c_acctbal") > avg_bal)
            .join(ord_keys, how="left_anti",
                  condition=col("c_custkey") == col("ord_custkey"))
            .group_by("cntrycode")
            .agg(F.count_star().alias("numcust"),
                 F.sum(col("c_acctbal")).alias("totacctbal"))
            .sort("cntrycode"))


QUERIES = {f"q{i}": globals()[f"q{i}"] for i in range(1, 23)}


def build_dataframes(sess, tables: "dict[str, pa.Table]",
                     num_partitions: int = 1):
    return {name: sess.create_dataframe(tbl, num_partitions=num_partitions)
            for name, tbl in tables.items()}
