"""Compare two runs: event logs or bench result JSONs.

Reference: the plugin tools' CompareApplications
(tools/.../profiling/CompareApplications.scala) lines up several Spark
event logs and reports matching SQL IDs / stage durations side by side so
a regression can be localized to an operator, not just a query. Same job
here, over our own JSONL event logs (tools/eventlog.py) or two ``bench.py``
result JSONs:

- queries align by query id (the workloads are assumed to be the same
  script run twice — exactly the BENCH_rNN trajectory use case);
- operators align by (name, occurrence-index) within a query, which is
  stable across runs of the same plan even when node ids shift;
- per-operator wall/rows deltas plus per-query counter deltas (compile
  cache, upload cache, shuffle tiers, spill, semaphore) with regression
  flags: candidate slower than baseline by more than ``threshold``
  (relative) AND ``min_seconds`` (absolute floor, so microsecond noise on
  trivial operators doesn't flag);
- critical-path category deltas when both runs carry a breakdown
  (schema-v5 event logs / traced bench JSONs): a query whose sync-wait
  fraction grew by more than 5 percentage points flags even when its
  total wall time did NOT regress — the composition shifted toward the
  ROADMAP-item-1 bottleneck and the next scale-up will pay for it;
- per-query memory deltas when both runs carry the flight recorder's
  numbers (schema-v6 ``memory_summary`` / bench ``peak_hbm_bytes``):
  peak HBM and spilled bytes diff side by side, and a candidate whose
  peak grew by more than ``MEM_PEAK_FLAG_FRAC`` (10%) flags a
  peak-memory regression — also independent of wall time, since a run
  can get faster by holding more HBM and pay later in spills/OOM;
- per-query transfer-byte deltas when both runs carry the data-movement
  ledger's numbers (schema-v11 ``movement_summary`` / bench
  ``d2h_bytes``+``h2d_bytes``): D2H/H2D bytes and round trips diff side
  by side, and a candidate whose transfer bytes grew past
  ``MOVE_BYTES_FLAG_FRAC`` (10%) and ``MOVE_BYTES_FLAG_MIN`` flags a
  transfer-byte regression — the same wall-orthogonal logic: a plan
  change that bounces batches through the host can hide inside an
  unchanged total on a fast PCI link and still sink the scale-up;
- per-query shuffle deltas when both runs carry the shuffle
  observatory's numbers (schema-v12 ``shuffle_summary`` totals / bench
  ``shuffle_wall_s``+``wire_bytes``): wall measurably spent inside
  transfer phases and bytes actually crossing the wire diff side by
  side, and a candidate whose shuffle wall grew past
  ``SHUFFLE_WALL_FLAG_FRAC`` (+ the 50 ms floor) or whose wire bytes
  grew past the byte gate flags a shuffle regression — pipeline
  overlap hides a slower tier inside flat query wall, and serializer
  changes inflate wire bytes without touching logical bytes.

CLI: ``python -m spark_rapids_tpu.tools.compare A B [--threshold 0.2]``
where A/B are event-log JSONL paths or bench summary JSONs.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

__all__ = ["OpDelta", "QueryDelta", "CompareReport", "compare_event_logs",
           "compare_bench_results", "compare_apps",
           "critical_path_fractions", "critical_path_delta",
           "memory_delta", "movement_delta", "shuffle_delta",
           "CP_FRAC_FLAG_PP",
           "MEM_PEAK_FLAG_FRAC", "MEM_PEAK_FLAG_MIN_BYTES",
           "MOVE_BYTES_FLAG_FRAC", "MOVE_BYTES_FLAG_MIN",
           "SHUFFLE_WALL_FLAG_FRAC", "SHUFFLE_WALL_FLAG_MIN_S",
           "SYNC_WAIT_GATE_FRAC"]

#: category-fraction growth (candidate minus baseline) that flags a
#: critical-path regression: 5 percentage points
CP_FRAC_FLAG_PP = 0.05

#: relative peak-HBM growth (candidate over baseline) that flags a
#: memory regression: 10%
MEM_PEAK_FLAG_FRAC = 0.10

#: absolute peak-HBM growth floor for the memory gate: tiny queries jitter
#: past 10% run-to-run (bucket rounding, warm-cache layout), so a relative
#: gate alone makes the history sentinel cry wolf on clean back-to-back
#: runs — both conditions must hold, like the sentinel's count gates
MEM_PEAK_FLAG_MIN_BYTES = 1 << 20


#: relative transfer-byte growth (candidate over baseline) that flags a
#: movement regression: 10%, same shape as the peak-HBM gate
MOVE_BYTES_FLAG_FRAC = 0.10

#: absolute transfer-byte growth floor for the movement gate — shape
#: buckets round batch capacities, so tiny queries jitter in bytes
#: run-to-run; both conditions must hold, like the memory gate
MOVE_BYTES_FLAG_MIN = 1 << 20

#: relative shuffle-transfer-wall growth (candidate over baseline) that
#: flags a shuffle regression: 10%, same shape as the byte gates
SHUFFLE_WALL_FLAG_FRAC = 0.10

#: absolute shuffle-wall growth floor (50 ms) — tiny transfers jitter
#: with scheduler noise, so both conditions must hold
SHUFFLE_WALL_FLAG_MIN_S = 0.05

#: ABSOLUTE sync-wait ceiling for the candidate run: a query spending
#: more than 10% of its wall blocked on device->host syncs fails the
#: async-first budget regardless of how the baseline did — this is a
#: gate on the candidate, not a delta, so a regression that was already
#: present in the baseline still flags. The violation names the
#: heaviest movement-ledger funnel (bench "sync_top_site") so the fix
#: starts at a file:symbol, not a number.
SYNC_WAIT_GATE_FRAC = 0.10


def movement_delta(mv_a: Optional[Dict], mv_b: Optional[Dict],
                   flag_frac: float = MOVE_BYTES_FLAG_FRAC,
                   flag_min_bytes: int = MOVE_BYTES_FLAG_MIN
                   ) -> Tuple[Dict[str, float], List[str]]:
    """(deltas B - A, flagged keys) from two per-query movement dicts
    ({"d2h_bytes", "h2d_bytes", "round_trips"}, from a v11 event log's
    movement_summary totals or a bench JSON's movement fields). Empty
    when either run lacks the numbers — ledger off must not flag. A
    byte direction growing past ``flag_frac`` AND ``flag_min_bytes``
    flags; new round trips (baseline had none) always flag."""
    if not mv_a or not mv_b:
        return {}, []
    keys = ("d2h_bytes", "h2d_bytes", "round_trips")
    deltas = {k: float(mv_b.get(k) or 0) - float(mv_a.get(k) or 0)
              for k in keys}
    flagged = []
    for k in ("d2h_bytes", "h2d_bytes"):
        a = float(mv_a.get(k) or 0)
        b = float(mv_b.get(k) or 0)
        if a > 0 and b > a * (1.0 + flag_frac) and b - a >= flag_min_bytes:
            flagged.append(k)
    if not float(mv_a.get("round_trips") or 0) \
            and float(mv_b.get("round_trips") or 0):
        flagged.append("round_trips")
    return deltas, flagged


def shuffle_delta(sh_a: Optional[Dict], sh_b: Optional[Dict],
                  flag_frac: float = SHUFFLE_WALL_FLAG_FRAC,
                  flag_min_s: float = SHUFFLE_WALL_FLAG_MIN_S,
                  flag_min_bytes: int = MOVE_BYTES_FLAG_MIN
                  ) -> Tuple[Dict[str, float], List[str]]:
    """(deltas B - A, flagged keys) from two per-query shuffle dicts
    ({"shuffle_wall_s", "wire_bytes"}, from a v12 event log's
    shuffle_summary totals or a bench JSON's shuffle fields). Empty
    when either run lacks the numbers — telemetry off must not flag.
    Shuffle wall growing past ``flag_frac`` AND ``flag_min_s`` flags
    "shuffle_wall_s"; wire bytes growing past ``flag_frac`` AND
    ``flag_min_bytes`` flags "wire_bytes"."""
    if not sh_a or not sh_b:
        return {}, []
    keys = ("shuffle_wall_s", "wire_bytes")
    deltas = {k: float(sh_b.get(k) or 0) - float(sh_a.get(k) or 0)
              for k in keys}
    flagged = []
    floors = {"shuffle_wall_s": flag_min_s, "wire_bytes": flag_min_bytes}
    for k in keys:
        a = float(sh_a.get(k) or 0)
        b = float(sh_b.get(k) or 0)
        if a > 0 and b > a * (1.0 + flag_frac) and b - a >= floors[k]:
            flagged.append(k)
    return deltas, flagged


def memory_delta(mem_a: Optional[Dict], mem_b: Optional[Dict],
                 flag_frac: float = MEM_PEAK_FLAG_FRAC,
                 flag_min_bytes: int = MEM_PEAK_FLAG_MIN_BYTES
                 ) -> Tuple[Dict[str, float], List[str]]:
    """(byte deltas B - A, flagged keys) from two per-query memory dicts
    ({"peak_bytes", "spill_bytes"}, from a v6 event log's memory_summary
    or a bench JSON's per-query fields). Empty when either run lacks the
    numbers — profiling off must not flag. Peak HBM growing past
    ``flag_frac`` AND ``flag_min_bytes`` flags "peak_bytes" (the
    >10%%-and-≥1MiB peak-memory gate)."""
    if not mem_a or not mem_b:
        return {}, []
    deltas = {k: float(mem_b.get(k) or 0) - float(mem_a.get(k) or 0)
              for k in ("peak_bytes", "spill_bytes")}
    flagged = []
    peak_a = float(mem_a.get("peak_bytes") or 0)
    peak_b = float(mem_b.get("peak_bytes") or 0)
    if (peak_a > 0 and peak_b > peak_a * (1.0 + flag_frac)
            and peak_b - peak_a >= flag_min_bytes):
        flagged.append("peak_bytes")
    return deltas, flagged


def critical_path_fractions(cp: Optional[Dict]) -> Optional[Dict]:
    """Category -> fraction-of-wall from a critical-path dict
    (tools/trace.py ``CriticalPath.to_dict()`` or the trimmed bench form
    with only ``categories_s`` + ``total_s``)."""
    if not cp:
        return None
    if cp.get("fractions"):
        return dict(cp["fractions"])
    total = float(cp.get("total_s", 0.0))
    if total <= 0:
        return None
    return {k: float(v) / total
            for k, v in cp.get("categories_s", {}).items()}


def critical_path_delta(cp_a: Optional[Dict], cp_b: Optional[Dict],
                        flag_pp: float = CP_FRAC_FLAG_PP
                        ) -> Tuple[Dict[str, float], List[str]]:
    """(fraction deltas B - A, categories whose share grew > flag_pp).
    Empty when either run lacks a breakdown — absence of tracing must
    not flag."""
    fa = critical_path_fractions(cp_a)
    fb = critical_path_fractions(cp_b)
    if fa is None or fb is None:
        return {}, []
    deltas = {k: round(fb.get(k, 0.0) - fa.get(k, 0.0), 4)
              for k in sorted(set(fa) | set(fb))}
    flagged = sorted(k for k, v in deltas.items() if v > flag_pp)
    return deltas, flagged


@dataclasses.dataclass
class OpDelta:
    """One aligned operator's baseline-vs-candidate numbers. ``query_id``
    is an int for event logs, a "phase:qN" label for bench comparisons."""
    query_id: "int | str"
    name: str
    occurrence: int
    wall_a: float
    wall_b: float
    rows_a: int
    rows_b: int
    regressed: bool = False
    only_in: str = ""  # "a"/"b" when the op exists in one run only

    @property
    def delta_s(self) -> float:
        return self.wall_b - self.wall_a

    @property
    def ratio(self) -> float:
        return self.wall_b / self.wall_a if self.wall_a > 0 else float("inf")


@dataclasses.dataclass
class QueryDelta:
    query_id: "int | str"
    wall_a: float
    wall_b: float
    regressed: bool
    ops: List[OpDelta]
    metric_deltas: Dict[str, float]  # candidate minus baseline counters
    #: critical-path fraction deltas (B - A) per category, when both
    #: runs carried a breakdown
    cp_deltas: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: categories whose share of the query wall grew > CP_FRAC_FLAG_PP
    cp_flagged: List[str] = dataclasses.field(default_factory=list)
    #: memory byte deltas (B - A): peak_bytes + spill_bytes, when both
    #: runs carried the memory flight recorder's numbers
    mem_deltas: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: ["peak_bytes"] when the candidate's peak HBM grew past
    #: MEM_PEAK_FLAG_FRAC — the memory-regression gate
    mem_flagged: List[str] = dataclasses.field(default_factory=list)
    #: the baseline's absolute memory numbers (for % rendering)
    mem_base: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: movement deltas (B - A): d2h/h2d bytes + round trips, when both
    #: runs carried the data-movement ledger's numbers (schema v11)
    move_deltas: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: byte directions grown past MOVE_BYTES_FLAG_FRAC (+ floor), or
    #: "round_trips" when the candidate bounces batches the baseline kept
    #: device-resident — the transfer-byte regression gate
    move_flagged: List[str] = dataclasses.field(default_factory=list)
    #: the baseline's absolute movement numbers (for % rendering)
    move_base: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: candidate sync-wait fraction when it exceeds SYNC_WAIT_GATE_FRAC
    #: (None otherwise) — the absolute async-first budget gate
    sync_gate_frac: Optional[float] = None
    #: the heaviest movement-ledger funnel during the candidate run
    #: (bench "sync_top_site"); where a sync_gate violation points
    sync_top_site: str = ""
    #: shuffle deltas (B - A): transfer wall + wire bytes, when both
    #: runs carried the shuffle observatory's numbers (schema v12)
    shuffle_deltas: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    #: keys grown past SHUFFLE_WALL_FLAG_FRAC (+ their floors) — the
    #: shuffle-regression gate
    shuffle_flagged: List[str] = dataclasses.field(default_factory=list)
    #: the baseline's absolute shuffle numbers (for % rendering)
    shuffle_base: Dict[str, float] = dataclasses.field(
        default_factory=dict)

    @property
    def delta_s(self) -> float:
        return self.wall_b - self.wall_a

    @property
    def ratio(self) -> float:
        return self.wall_b / self.wall_a if self.wall_a > 0 else float("inf")


@dataclasses.dataclass
class CompareReport:
    label_a: str
    label_b: str
    queries: List[QueryDelta]
    threshold: float
    only_in_a: List[int] = dataclasses.field(default_factory=list)
    only_in_b: List[int] = dataclasses.field(default_factory=list)

    def regressions(self) -> List[OpDelta]:
        return [op for q in self.queries for op in q.ops if op.regressed]

    def regressed_queries(self) -> List[QueryDelta]:
        return [q for q in self.queries if q.regressed]

    def critical_path_regressions(self) -> List[QueryDelta]:
        """Queries whose critical-path COMPOSITION regressed (a category's
        share grew past the flag threshold) — orthogonal to wall-time
        regressions; a query can flag here while getting faster."""
        return [q for q in self.queries if q.cp_flagged]

    def memory_regressions(self) -> List[QueryDelta]:
        """Queries whose peak HBM grew past MEM_PEAK_FLAG_FRAC — also
        orthogonal to wall time: a query can get faster by holding more
        memory, and the next scale-up pays in spills/OOM."""
        return [q for q in self.queries if q.mem_flagged]

    def movement_regressions(self) -> List[QueryDelta]:
        """Queries whose host<->device transfer bytes grew past
        MOVE_BYTES_FLAG_FRAC (or that started round-tripping batches) —
        orthogonal to wall time like the memory gate: extra transfers
        hide on a fast link and sink the scale-up."""
        return [q for q in self.queries if q.move_flagged]

    def shuffle_regressions(self) -> List[QueryDelta]:
        """Queries whose shuffle transfer wall or wire bytes grew past
        SHUFFLE_WALL_FLAG_FRAC (+ floors) — orthogonal to wall time:
        pipeline overlap hides a slower shuffle tier inside flat query
        wall until the tier saturates at scale."""
        return [q for q in self.queries if q.shuffle_flagged]

    def sync_wait_violations(self) -> List[QueryDelta]:
        """Queries whose CANDIDATE run spent more than
        SYNC_WAIT_GATE_FRAC of wall blocked on device->host syncs — an
        absolute budget, not a delta, so debt the baseline already
        carried still fails; each violation names the heaviest
        movement-ledger funnel to fix first."""
        return [q for q in self.queries if q.sync_gate_frac is not None]

    def summary(self) -> str:
        lines = [f"compare: A={self.label_a}  B={self.label_b}  "
                 f"(threshold {self.threshold:.0%}; positive delta = "
                 "B slower)"]
        for q in self.queries:
            flag = "  ** REGRESSED" if q.regressed else ""
            lines.append(f"query {q.query_id}: "
                         f"A={q.wall_a:.4f}s B={q.wall_b:.4f}s "
                         f"delta={q.delta_s:+.4f}s "
                         f"({q.ratio:.2f}x){flag}")
            lines.append(f"  {'op':<40}{'A_s':>9}{'B_s':>9}"
                         f"{'delta_s':>10}{'rows_B':>12}")
            for op in q.ops:
                mark = " **" if op.regressed else \
                    (f" [only {op.only_in}]" if op.only_in else "")
                lines.append(f"  {op.name[:39]:<40}{op.wall_a:>9.4f}"
                             f"{op.wall_b:>9.4f}{op.delta_s:>+10.4f}"
                             f"{op.rows_b:>12}{mark}")
            hot = sorted((k for k, v in q.metric_deltas.items() if v),
                         key=lambda k: -abs(q.metric_deltas[k]))[:8]
            if hot:
                lines.append("  counter deltas (B - A): " + ", ".join(
                    f"{k}={q.metric_deltas[k]:+g}" for k in hot))
            if q.cp_deltas:
                moved = sorted((k for k, v in q.cp_deltas.items() if v),
                               key=lambda k: -abs(q.cp_deltas[k]))[:6]
                if moved:
                    lines.append(
                        "  critical-path share deltas (B - A): " + ", ".join(
                            f"{k}={q.cp_deltas[k]:+.1%}" for k in moved))
                if q.cp_flagged:
                    lines.append(
                        "  ** CRITICAL-PATH REGRESSION: "
                        + ", ".join(f"{k} share +{q.cp_deltas[k]:.1%}"
                                    for k in q.cp_flagged))
            if q.mem_deltas:
                parts = []
                for k in sorted(q.mem_deltas):
                    v = q.mem_deltas[k]
                    base = q.mem_base.get(k, 0.0)
                    pct = f" ({v / base:+.1%})" if base > 0 else ""
                    parts.append(f"{k}={v:+.0f}B{pct}")
                lines.append("  memory deltas (B - A): " + ", ".join(parts))
                if q.mem_flagged:
                    lines.append(
                        "  ** PEAK-MEMORY REGRESSION: "
                        + ", ".join(
                            f"{k} +{q.mem_deltas[k] / q.mem_base[k]:.1%}"
                            if q.mem_base.get(k) else f"{k} grew"
                            for k in q.mem_flagged)
                        + f" (gate {MEM_PEAK_FLAG_FRAC:.0%})")
            if q.move_deltas:
                parts = []
                for k in sorted(q.move_deltas):
                    v = q.move_deltas[k]
                    base = q.move_base.get(k, 0.0)
                    pct = f" ({v / base:+.1%})" if base > 0 else ""
                    unit = "" if k == "round_trips" else "B"
                    parts.append(f"{k}={v:+.0f}{unit}{pct}")
                lines.append("  movement deltas (B - A): "
                             + ", ".join(parts))
                if q.move_flagged:
                    lines.append(
                        "  ** TRANSFER-BYTE REGRESSION: "
                        + ", ".join(
                            f"{k} +{q.move_deltas[k] / q.move_base[k]:.1%}"
                            if q.move_base.get(k) else f"{k} grew"
                            for k in q.move_flagged)
                        + f" (gate {MOVE_BYTES_FLAG_FRAC:.0%})")
            if q.shuffle_deltas:
                parts = []
                for k in sorted(q.shuffle_deltas):
                    v = q.shuffle_deltas[k]
                    base = q.shuffle_base.get(k, 0.0)
                    pct = f" ({v / base:+.1%})" if base > 0 else ""
                    unit = "s" if k.endswith("_s") else "B"
                    parts.append(f"{k}={v:+.4g}{unit}{pct}")
                lines.append("  shuffle deltas (B - A): "
                             + ", ".join(parts))
                if q.shuffle_flagged:
                    lines.append(
                        "  ** SHUFFLE REGRESSION: "
                        + ", ".join(
                            f"{k} +{q.shuffle_deltas[k] / q.shuffle_base[k]:.1%}"
                            if q.shuffle_base.get(k) else f"{k} grew"
                            for k in q.shuffle_flagged)
                        + f" (gate {SHUFFLE_WALL_FLAG_FRAC:.0%})")
            if q.sync_gate_frac is not None:
                site = q.sync_top_site or "(no ledger attribution)"
                lines.append(
                    f"  ** SYNC-WAIT GATE: {q.sync_gate_frac:.1%} of "
                    f"wall blocked on device->host syncs (budget "
                    f"{SYNC_WAIT_GATE_FRAC:.0%}) — heaviest funnel: "
                    f"{site}")
        if self.only_in_a:
            lines.append(f"queries only in A: {self.only_in_a}")
        if self.only_in_b:
            lines.append(f"queries only in B: {self.only_in_b}")
        n_reg = len(self.regressions())
        lines.append(f"{n_reg} regressed operator(s), "
                     f"{len(self.regressed_queries())} regressed query(ies), "
                     f"{len(self.critical_path_regressions())} "
                     "critical-path regression(s), "
                     f"{len(self.memory_regressions())} "
                     "peak-memory regression(s), "
                     f"{len(self.movement_regressions())} "
                     "transfer-byte regression(s), "
                     f"{len(self.shuffle_regressions())} "
                     "shuffle regression(s), "
                     f"{len(self.sync_wait_violations())} "
                     "sync-wait gate violation(s)")
        return "\n".join(lines)


def _op_key_counts(nodes: List[Dict]) -> List[Tuple[Tuple[str, int], Dict]]:
    """Stable (name, occurrence) keys in node order."""
    seen: Dict[str, int] = {}
    out = []
    for n in nodes:
        idx = seen.get(n["name"], 0)
        seen[n["name"]] = idx + 1
        out.append(((n["name"], idx), n))
    return out


def _query_memory(q) -> Optional[Dict]:
    """Per-query memory numbers from a replay's v6 ``memory_summary``:
    peak HBM bytes + total bytes its operators spilled. None pre-v6 or
    with profiling off."""
    ms = getattr(q, "memory_summary", None)
    if not ms:
        return None
    per_op = ms.get("per_operator") or {}
    return {"peak_bytes": int(ms.get("peak_bytes") or 0),
            "spill_bytes": sum(int(d.get("spilled_bytes") or 0)
                               for d in per_op.values())}


def _query_movement(q) -> Optional[Dict]:
    """Per-query transfer numbers from a replay's v11 ``movement_summary``
    totals. None pre-v11 or with the ledger off."""
    mv = getattr(q, "movement_summary", None)
    if not mv:
        return None
    t = mv.get("totals") or {}
    return {"d2h_bytes": int(t.get("d2h_bytes") or 0),
            "h2d_bytes": int(t.get("h2d_bytes") or 0),
            "round_trips": int(t.get("round_trips") or 0)}


def _query_shuffle(q) -> Optional[Dict]:
    """Per-query shuffle numbers from a replay's v12 ``shuffle_summary``
    totals. None pre-v12 or with telemetry off."""
    sh = getattr(q, "shuffle_summary", None)
    if not sh:
        return None
    t = sh.get("totals") or {}
    return {"shuffle_wall_s": float(t.get("wall_s") or 0.0),
            "wire_bytes": int(t.get("wire_bytes") or 0)}


def compare_apps(app_a, app_b, threshold: float = 0.2,
                 min_seconds: float = 0.001) -> CompareReport:
    """Compare two loaded ``AppReplay``s (tools/eventlog.py)."""
    qids_a, qids_b = set(app_a.queries), set(app_b.queries)
    queries: List[QueryDelta] = []
    for qid in sorted(qids_a & qids_b):
        qa, qb = app_a.queries[qid], app_b.queries[qid]
        ops_a = dict(_op_key_counts(qa.nodes))
        ops_b = dict(_op_key_counts(qb.nodes))
        ops: List[OpDelta] = []
        for key in list(ops_a) + [k for k in ops_b if k not in ops_a]:
            na, nb = ops_a.get(key), ops_b.get(key)
            wall_a = na["wall_s"] if na else 0.0
            wall_b = nb["wall_s"] if nb else 0.0
            regressed = (na is not None and nb is not None
                         and wall_b > wall_a * (1.0 + threshold)
                         and wall_b - wall_a >= min_seconds)
            ops.append(OpDelta(
                qid, key[0], key[1], wall_a, wall_b,
                na["rows"] if na else 0, nb["rows"] if nb else 0,
                regressed=regressed,
                only_in="a" if nb is None else ("b" if na is None else "")))
        stats_delta = {k: qb.stats.get(k, 0) - qa.stats.get(k, 0)
                       for k in set(qa.stats) | set(qb.stats)
                       if isinstance(qa.stats.get(k, 0), (int, float))
                       and isinstance(qb.stats.get(k, 0), (int, float))}
        q_regressed = (qb.wall_s > qa.wall_s * (1.0 + threshold)
                       and qb.wall_s - qa.wall_s >= min_seconds)
        cp_deltas, cp_flagged = critical_path_delta(
            getattr(qa, "critical_path", None),
            getattr(qb, "critical_path", None))
        mem_a, mem_b = _query_memory(qa), _query_memory(qb)
        mem_deltas, mem_flagged = memory_delta(mem_a, mem_b)
        mv_a, mv_b = _query_movement(qa), _query_movement(qb)
        move_deltas, move_flagged = movement_delta(mv_a, mv_b)
        sh_a, sh_b = _query_shuffle(qa), _query_shuffle(qb)
        sh_deltas, sh_flagged = shuffle_delta(sh_a, sh_b)
        queries.append(QueryDelta(qid, qa.wall_s, qb.wall_s,
                                  q_regressed, ops, stats_delta,
                                  cp_deltas, cp_flagged,
                                  mem_deltas, mem_flagged,
                                  {k: float(v) for k, v in
                                   (mem_a or {}).items()},
                                  move_deltas, move_flagged,
                                  {k: float(v) for k, v in
                                   (mv_a or {}).items()},
                                  shuffle_deltas=sh_deltas,
                                  shuffle_flagged=sh_flagged,
                                  shuffle_base={k: float(v) for k, v in
                                                (sh_a or {}).items()}))
    return CompareReport(app_a.app_id or app_a.path,
                         app_b.app_id or app_b.path, queries, threshold,
                         sorted(qids_a - qids_b), sorted(qids_b - qids_a))


def compare_event_logs(path_a: str, path_b: str, threshold: float = 0.2,
                       min_seconds: float = 0.001) -> CompareReport:
    """Load two JSONL event logs and align them (A = baseline,
    B = candidate)."""
    from .eventlog import load_event_log
    return compare_apps(load_event_log(path_a), load_event_log(path_b),
                        threshold, min_seconds)


def _bench_memory(entry: Dict) -> Optional[Dict]:
    """Per-query memory numbers from a bench JSON entry (bench.py writes
    peak_hbm_bytes + spill_bytes when BENCH_MEMPROF is on)."""
    if "peak_hbm_bytes" not in entry:
        return None
    return {"peak_bytes": int(entry.get("peak_hbm_bytes") or 0),
            "spill_bytes": int(entry.get("spill_bytes") or 0)}


def _bench_movement(entry: Dict) -> Optional[Dict]:
    """Per-query transfer numbers from a bench JSON entry (bench.py
    writes d2h_bytes/h2d_bytes/round_trips when the movement ledger is
    on)."""
    if "d2h_bytes" not in entry:
        return None
    return {"d2h_bytes": int(entry.get("d2h_bytes") or 0),
            "h2d_bytes": int(entry.get("h2d_bytes") or 0),
            "round_trips": int(entry.get("round_trips") or 0)}


def _bench_shuffle(entry: Dict) -> Optional[Dict]:
    """Per-query shuffle numbers from a bench JSON entry (bench.py
    writes shuffle_wall_s/shuffle_wall_frac/wire_bytes when shuffle
    telemetry is on)."""
    if "shuffle_wall_s" not in entry:
        return None
    return {"shuffle_wall_s": float(entry.get("shuffle_wall_s") or 0.0),
            "wire_bytes": int(entry.get("wire_bytes") or 0)}


def compare_bench_results(path_a: str, path_b: str, threshold: float = 0.2,
                          min_seconds: float = 0.001) -> CompareReport:
    """Compare two ``bench.py`` per-query result JSONs (the
    BENCH_partial.json shape, with smoke/tpch sections): device seconds as
    single-op queries so the same report/flagging machinery applies."""
    with open(path_a, encoding="utf-8") as f:
        a = json.load(f)
    with open(path_b, encoding="utf-8") as f:
        b = json.load(f)
    # phases compare separately: smoke and tpch both name queries q1/q6
    # but run at different scale factors — merging would shadow the smoke
    # entries (or diff incomparable numbers when one run lacks a phase)
    queries: List[QueryDelta] = []
    only_a: List = []
    only_b: List = []
    for phase in ("smoke", "tpch"):
        qs_a = a.get(phase, {})
        qs_b = b.get(phase, {})
        names = sorted(set(qs_a) & set(qs_b),
                       key=lambda n: int(n.lstrip("q"))
                       if n.lstrip("q").isdigit() else 0)
        only_a.extend(f"{phase}:{n}" for n in sorted(set(qs_a) - set(qs_b)))
        only_b.extend(f"{phase}:{n}" for n in sorted(set(qs_b) - set(qs_a)))
        for name in names:
            label = f"{phase}:{name}"
            wall_a = float(qs_a[name].get("dev_s", 0.0))
            wall_b = float(qs_b[name].get("dev_s", 0.0))
            regressed = (wall_a > 0 and wall_b > wall_a * (1.0 + threshold)
                         and wall_b - wall_a >= min_seconds)
            deltas = {k: float(qs_b[name].get(k, 0))
                      - float(qs_a[name].get(k, 0))
                      for k in ("dev_s", "cpu_s", "compile_s", "speedup",
                                "sync_wait_frac")
                      if k in qs_a[name] or k in qs_b[name]}
            cp_deltas, cp_flagged = critical_path_delta(
                qs_a[name].get("critical_path"),
                qs_b[name].get("critical_path"))
            mem_a = _bench_memory(qs_a[name])
            mem_b = _bench_memory(qs_b[name])
            mem_deltas, mem_flagged = memory_delta(mem_a, mem_b)
            mv_a = _bench_movement(qs_a[name])
            mv_b = _bench_movement(qs_b[name])
            move_deltas, move_flagged = movement_delta(mv_a, mv_b)
            sh_a = _bench_shuffle(qs_a[name])
            sh_b = _bench_shuffle(qs_b[name])
            sh_deltas, sh_flagged = shuffle_delta(sh_a, sh_b)
            # absolute sync-wait budget on the CANDIDATE run: > 10% of
            # wall blocked on syncs fails even if the baseline was just
            # as bad; the heaviest ledger funnel gives the fix a target
            frac_b = qs_b[name].get("sync_wait_frac")
            gate_frac = (float(frac_b)
                         if frac_b is not None
                         and float(frac_b) > SYNC_WAIT_GATE_FRAC
                         else None)
            queries.append(QueryDelta(
                label, wall_a, wall_b, regressed,
                [OpDelta(label, name, 0, wall_a, wall_b, 0, 0,
                         regressed=regressed)], deltas,
                cp_deltas, cp_flagged,
                mem_deltas, mem_flagged,
                {k: float(v) for k, v in (mem_a or {}).items()},
                move_deltas, move_flagged,
                {k: float(v) for k, v in (mv_a or {}).items()},
                sync_gate_frac=gate_frac,
                sync_top_site=str(qs_b[name].get("sync_top_site") or ""),
                shuffle_deltas=sh_deltas, shuffle_flagged=sh_flagged,
                shuffle_base={k: float(v) for k, v in
                              (sh_a or {}).items()}))
    return CompareReport(path_a, path_b, queries, threshold,
                         only_a, only_b)


def _sniff(path: str) -> str:
    """Classify an input file: "bench" (one JSON object with smoke/tpch
    per-query sections, i.e. BENCH_partial.json shape), "eventlog" (JSONL
    from tools/eventlog.py), or "unknown". Note the round driver's
    BENCH_rNN.json wrappers hold only the summary metric — no per-query
    data to compare — so they classify as unknown."""
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except json.JSONDecodeError:
        # multi-line JSONL fails a full-file parse; check the first record
        try:
            with open(path, encoding="utf-8") as f:
                first = json.loads(f.readline())
            return "eventlog" if isinstance(first, dict) and "event" in first \
                else "unknown"
        except (json.JSONDecodeError, OSError):
            return "unknown"
    except OSError:
        return "unknown"
    if isinstance(obj, dict):
        if "tpch" in obj or "smoke" in obj:
            return "bench"
        if "event" in obj:
            return "eventlog"  # degenerate single-record log
    return "unknown"


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="Compare two event logs or bench result JSONs "
                    "(A = baseline, B = candidate)")
    ap.add_argument("log_a")
    ap.add_argument("log_b")
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="relative slowdown that flags a regression")
    ap.add_argument("--min-seconds", type=float, default=0.001,
                    help="absolute slowdown floor for flagging")
    args = ap.parse_args(argv)
    kinds = {_sniff(args.log_a), _sniff(args.log_b)}
    if "unknown" in kinds:
        ap.error(
            "inputs must both be event logs (JSONL from "
            "spark.rapids.tpu.eventLog.dir) or both bench summaries with "
            "per-query sections (BENCH_partial.json / bench event sink); "
            "round wrapper files like BENCH_rNN.json carry only the "
            "summary metric and cannot be compared per operator")
    if len(kinds) > 1:
        ap.error("cannot compare an event log against a bench summary")
    if kinds == {"bench"}:
        report = compare_bench_results(args.log_a, args.log_b,
                                       args.threshold, args.min_seconds)
    else:
        report = compare_event_logs(args.log_a, args.log_b, args.threshold,
                                    args.min_seconds)
    print(report.summary())
    return 1 if report.regressions() \
        or report.critical_path_regressions() \
        or report.memory_regressions() \
        or report.movement_regressions() \
        or report.shuffle_regressions() else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
