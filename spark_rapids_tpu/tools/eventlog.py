"""Event-log persistence + post-hoc replay.

Reference: the plugin tools replay *Spark event logs* into profiling and
qualification reports (tools/.../profiling/Profiler.scala:32,436 and
EventLogPathProcessor) — the whole point is analyzing a run after the fact.
This framework owns its runtime, so it writes its own event log: one JSONL
file per session (``spark.rapids.tpu.eventLog.dir``), one record per event:

- ``app_start``: conf snapshot
- ``query_start``: query id + plan tree
- ``node``: one per physical operator — name/desc/depth/parent, wall time,
  rows/batches, first/last activity offsets, operator metrics snapshot
  (schema v3: the snapshot carries the per-node byte/compile/spill
  attribution — upload/download bytes, shuffle bytes, xla cache hits and
  misses, compile seconds, spill bytes)
- ``kernel`` (schema v3): one per XLA program the query touched — plan
  signature, owning node, compile wall, HLO cost / memory analysis
  (utils/compile_cache.py kernel table)
- ``heartbeat`` (schema v4): periodic live-engine sample from the health
  monitor (utils/health.py) — HBM used/peak/limit, semaphore
  holders/waiters, pipeline queue depths + in-flight tasks, progress age
  and the watchdog's stalled verdict; written from the monitor thread
  (the writer is locked), so ``tools/diagnose.py`` can rank stall
  windows and flag queries that heartbeated into OOM territory
- ``query_end``: wall time, spill/semaphore deltas, AQE events, per-query
  process-counter deltas; schema v5 adds ``trace_id`` (the distributed
  TraceContext minted for the query, also on ``query_start``) and
  ``critical_path`` (the per-category wall-time attribution computed
  from this process's tracer spans — tools/trace.py)
- ``memory_summary`` (schema v6): one per query (success AND error
  paths) — the memory flight recorder's per-operator peak/live HBM
  aggregation, peak-holder attribution and retained-buffer leak scan
  (utils/memprof.py ``query_end``); ``summary`` is null when profiling
  is off. v6 also adds ``peak_device_bytes`` to ``node`` records.
- ``oom_postmortem`` (schema v6): one per OOM the catalog hit during the
  query — context, ranked holders-by-operator, live/peak bytes and the
  path of the full ``oom-<ts>.txt`` report (the record omits the report
  text; the file carries it)
- ``shuffle_skew`` (schema v7): one per exchange node that materialized
  during the query — the per-output-partition row/byte distribution
  (min/p50/max/mean, imbalance ratio = max/mean, per-partition row
  counts) computed from counts the exchange tiers already gather in
  bulk; the partition-level telemetry ROADMAP items 3–4 consume and
  the history server's regression sentinel watches
- ``fault`` (schema v8): one per injected-fault fire drained from the
  fault-injection framework (utils/faults.py) — point, action and the
  per-point fire/evaluation ordinals; absent entirely when injection is
  off (the common case)
- ``recovery`` (schema v8): ONE per query (success AND error paths) —
  the per-query delta of the recovery ledger (worker deaths/respawns,
  task resubmissions, transport retries, shuffle recomputes, spill
  corruptions...); the ``recovery`` payload is null when the query saw
  no recovery activity, so the record set per query is stable whether
  or not faults fired
- ``movement_summary`` (schema v11): ONE per query (success AND error
  paths) — the data-movement ledger's per-query aggregation
  (utils/movement.py): total D2H/H2D bytes and counts, blocking vs.
  deferred syncs, detected round trips (downloaded then re-uploaded
  within the query), and the per-(site, operator) breakdown keyed by the
  same funnel names srtpu-analyze's sync baseline tracks; the
  ``movement`` payload is null when the ledger is off (the default), so
  the per-query record set is stable either way
- ``app_end``

``load_event_log`` replays a file into ``AppReplay``: per-query summaries,
aggregated operator hot list, HealthCheck warnings, a timeline SVG, and a
plan DOT graph — the Profiler.scala report set, rebuilt from our log.
``tools/diagnose.py`` consumes the same replay for the ranked bottleneck
report (the AutoTuner analogue).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from ..conf import register_conf

__all__ = ["EventLogWriter", "load_event_log", "AppReplay", "QueryReplay",
           "EVENT_LOG_DIR", "SCHEMA_VERSION", "RECORD_TYPES"]

# Event-record schema version. Bump ONLY with a migration note in
# docs/observability.md; tests/test_observability.py pins the current value
# and the per-record required-key sets so replay/compare tooling can rely
# on old logs staying loadable. v12: shuffle_summary records — ONE per
# query, the shuffle observatory's per-query aggregation of every
# transfer on every shuffle tier (shuffle/telemetry.py): per-tier and
# per-(shuffle, tier) bytes/wall/phase breakdowns, stitched TCP
# sender/receiver counts and straggler attribution (slowest-partition
# wall vs p50); null payload when the observatory is off.
# (v11 added movement_summary records — ONE per
# query, the data-movement ledger's per-query aggregation of every
# host<->device crossing (utils/movement.py): per-site and per-operator
# bytes/wall/counts plus round-trip detections; null payload when the
# ledger is off; v10 added fallback records — one per batch a
# device operator re-executed through the host engine after a terminal
# device failure (exec/fallback.py): operator + failure class + bytes
# moved each way + host wall time; v9 added oom_retry records — one per
# retry scope that engaged the device-OOM escalation ladder
# (memory/retry.py): spill → retry → split-and-retry, with the
# attempt/split/spilled-bytes counts and the recovered/failed outcome;
# v8 added fault/recovery records — per-fire injection telemetry plus an
# always-written per-query recovery-ledger delta; v7 added shuffle_skew
# records; v6 added memory_summary/oom_postmortem records and
# peak_device_bytes on node records.)
SCHEMA_VERSION = 12

# The event-record schema registry: every record type a writer may emit,
# mapped to the schema version that introduced it. srtpu-analyze's
# ``eventlog`` checker statically verifies that each
# ``write({"event": ...})`` call site across the package names a
# registered type, and that no registered type claims a version above
# SCHEMA_VERSION — adding a record type without bumping the version (and
# the docs/observability.md migration note) is flagged at analyze time.
RECORD_TYPES: Dict[str, int] = {
    "app_start": 1,
    "query_start": 1,
    "node": 1,
    "query_end": 1,
    "app_end": 1,
    "kernel": 3,
    "heartbeat": 4,
    "memory_summary": 6,
    "oom_postmortem": 6,
    "shuffle_skew": 7,
    "fault": 8,
    "recovery": 8,
    "oom_retry": 9,
    "fallback": 10,
    "movement_summary": 11,
    "shuffle_summary": 12,
}

#: health_check flags a query whose critical-path ``sync_wait`` fraction
#: exceeds this (v11) — past it, host<->device synchronization is the
#: dominant cost and the movement ledger's site ranking is the worklist
SYNC_WAIT_WARN_FRAC = 0.4

#: health_check flags a shuffle straggler when the slowest partition's
#: measured transfer wall exceeds the p50 by this factor (v12) AND the
#: absolute wall clears ``SHUFFLE_STRAGGLER_WARN_WALL_S`` — tiny queries
#: have noisy ratios, so both gates must fire
SHUFFLE_STRAGGLER_WARN_SKEW = 4.0
SHUFFLE_STRAGGLER_WARN_WALL_S = 0.05

EVENT_LOG_DIR = register_conf(
    "spark.rapids.tpu.eventLog.dir",
    "Directory for the session event log (JSONL; one file per session). "
    "Empty disables logging. Spark's spark.eventLog.dir analogue — feeds "
    "the replay tools (tools/eventlog.py load_event_log and "
    "tools/compare.py).", "")


class EventLogWriter:
    """Append-only JSONL writer; one per session."""

    def __init__(self, directory: str, app_id: str, conf_snapshot: Dict):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"{app_id}.jsonl")
        self._f = open(self.path, "a", encoding="utf-8")
        self._query_seq = 0
        # v4: the health monitor thread appends heartbeats while the query
        # thread writes node/query records — serialize whole lines
        self._lock = threading.Lock()
        self.write({"event": "app_start", "app_id": app_id,
                    "schema_version": SCHEMA_VERSION,
                    "ts": time.time(), "conf": conf_snapshot})

    def write(self, record: Dict) -> None:
        line = json.dumps(record) + "\n"
        with self._lock:
            self._f.write(line)
            self._f.flush()

    def write_heartbeat(self, record: Dict) -> None:
        """One schema-v4 heartbeat record (utils/health.py supplies the
        flat sample dict; event type + wall-clock stamp added here)."""
        self.write({"event": "heartbeat", "ts": time.time(), **record})  # srtpu: eventlog-ok(health.py sample dicts are flat metric counters and never carry an event key)

    def next_query_id(self) -> int:
        self._query_seq += 1
        return self._query_seq

    def run_query(self, plan, collect_fn):
        """Instrument ``plan``, run ``collect_fn()``, persist the events."""
        from ..memory.catalog import get_catalog
        from ..memory.semaphore import get_semaphore
        from ..utils.compile_cache import kernel_seq, kernels_since
        from ..utils.metrics import StatsRegistry, get_stats
        from ..utils.tracing import (activate_trace_context, get_tracer,
                                     mint_trace_context)
        from .profiler import instrument_plan

        qid = self.next_query_id()
        # v5: one TraceContext per query — the identity every process
        # boundary (ProcessCluster envelope, shuffle wire header) carries
        # so worker spans merge under this query's timeline
        tctx = mint_trace_context(query_id=qid)
        epoch = time.perf_counter()
        stats: List = []
        from ..plan.aqe import AdaptiveExec
        if isinstance(plan, AdaptiveExec):
            # AQE finalizes lazily: each stage segment + the final segment
            # get instrumented as the adaptive loop creates them
            plan._instrument_hook = \
                lambda p: instrument_plan(p, epoch, into=stats,
                                          query_id=qid)
        else:
            instrument_plan(plan, epoch, into=stats, query_id=qid)
        cat = get_catalog()
        sem = get_semaphore()
        registry = get_stats()
        spill_before = dict(cat.spill_count)
        wait_before = sem.total_wait_time
        counters_before = registry.collect()
        kseq_before = kernel_seq()
        from ..utils import faults
        recovery_before = faults.recovery_counters()
        self.write({"event": "query_start", "query_id": qid,
                    "ts": time.time(), "trace_id": tctx.trace_id,
                    "plan": plan.tree_string()})
        t0 = time.perf_counter()
        try:
            with activate_trace_context(tctx), \
                    get_tracer().span("query", "query", query_id=qid):
                result = collect_fn()
        except Exception as e:
            # v6: the OOM that killed the query (if any) queued a
            # postmortem in the flight recorder — persist it, and the leak
            # scan, before the error record propagates
            self._write_memory_records(qid)
            # v8: whatever recovery the runtime managed BEFORE giving up
            # (retries, recomputes, respawns) is exactly the forensics a
            # failed query needs — write it on the error path too
            self._write_fault_records(qid, recovery_before)
            # v9: ditto for the OOM-retry ladder — the scopes that
            # retried/split before the query died are the postmortem trail
            self._write_oom_retry_records(qid)
            # v10: host fallbacks completed before the query died anyway
            self._write_fallback_records(qid)
            # v11: whatever the query moved across the PCI boundary before
            # failing is exactly where a timeout/OOM forensics starts
            self._write_movement_records(qid)
            # v12: ditto for shuffle transfers — a query that died mid
            # exchange leaves the straggler/backpressure trail here
            self._write_shuffle_records(qid)
            self.write({"event": "query_end", "query_id": qid,
                        "ts": time.time(), "trace_id": tctx.trace_id,
                        "wall_s": time.perf_counter() - t0,
                        "error": f"{type(e).__name__}: {e}"})
            raise
        wall = time.perf_counter() - t0
        # close plan-owned spill handles (shuffle/broadcast outputs)
        # BEFORE the leak scan in _write_memory_records below: the plan is
        # single-use and its outputs release at query end by design — only
        # what remains after this is a real leak
        plan.release_spill_handles()
        # v6: per-node peak HBM from the flight recorder (keys match the
        # node ids instrument_plan assigned; {} when profiling is off)
        from ..utils.memprof import active as memprof_active
        mp = memprof_active()
        node_peaks = mp.node_peaks(qid) if mp is not None else {}
        for ns in stats:
            self.write({"event": "node", "query_id": qid,
                        "node_id": ns.node_id, "parent_id": ns.parent_id,
                        "name": ns.name, "desc": ns.desc, "depth": ns.depth,
                        "wall_s": ns.wall_s, "rows": ns.rows,
                        "batches": ns.batches, "t_first": ns.t_first,
                        "t_last": ns.t_last,
                        "peak_device_bytes": node_peaks.get(ns.node_id, 0),
                        "metrics": _node_metrics(ns)})
        # schema v7: per-exchange output-partition row/byte distribution.
        # Exchange nodes (both tiers + the host fallback) accumulate the
        # per-partition counts they already gather during materialize and
        # expose them via shuffle_skew(); one record per exchange that
        # actually materialized in this query.
        for ns in stats:
            skew = _node_shuffle_skew(ns)
            if skew is not None:
                self.write({**skew, "event": "shuffle_skew",
                            "query_id": qid, "node_id": ns.node_id,
                            "name": ns.name})
        # schema v3: one kernel record per XLA program this query touched
        # (compile wall + cost/memory analysis keyed back to node ids)
        for entry in kernels_since(kseq_before):
            entry.pop("last_touch", None)
            # the record's query_id is THIS query (the entry's own
            # query_id field records where the program first compiled)
            self.write({**entry, "event": "kernel", "query_id": qid,
                        "first_query_id": entry.get("query_id")})
        self._write_memory_records(qid)
        self._write_fault_records(qid, recovery_before)
        self._write_oom_retry_records(qid)
        self._write_fallback_records(qid)
        self._write_movement_records(qid)
        self._write_shuffle_records(qid)
        aqe_events: List[str] = list(getattr(plan, "events", []))
        self.write({
            "event": "query_end", "query_id": qid, "ts": time.time(),
            "trace_id": tctx.trace_id,
            "critical_path": _query_critical_path(tctx.trace_id),
            "wall_s": wall, "final_plan": plan.tree_string(),
            "aqe_events": aqe_events,
            "spill_count": {str(k): v - spill_before.get(k, 0)
                            for k, v in cat.spill_count.items()},
            "semaphore_wait_s": sem.total_wait_time - wait_before,
            # per-query deltas of every process-wide counter: compile cache,
            # upload cache, shuffle tiers, catalog spills/OOM, semaphore —
            # the attribution BENCH needs (VERDICT layer-11 gap)
            "stats": StatsRegistry.delta(registry.collect(),
                                         counters_before),
        })
        return result

    def _write_memory_records(self, qid: int) -> None:
        """v6: drain queued oom_postmortem records, then run the flight
        recorder's query-end leak scan and write ONE memory_summary
        (``summary`` is null when profiling is off, so the record set per
        query is stable either way)."""
        from ..utils.memprof import active as memprof_active
        mp = memprof_active()
        summary = None
        if mp is not None:
            for pm in mp.drain_postmortems():
                rec = {k: v for k, v in pm.items() if k != "report"}
                self.write({**rec, "event": "oom_postmortem",
                            "query_id": qid})
            summary = mp.query_end(qid)
        self.write({"event": "memory_summary", "query_id": qid,
                    "ts": time.time(), "summary": summary})

    def _write_fault_records(self, qid: int,
                             before: Dict[str, int]) -> None:
        """v8: drain the injector's fire records (one ``fault`` record
        each; none when injection is off — the common case) and write
        ONE ``recovery`` record whose payload is the per-query delta of
        the recovery ledger. ``recovery`` is null when the query saw no
        recovery activity, so the per-query record set is identical
        whether or not faults are enabled."""
        from ..utils import faults
        for fr in faults.drain_fault_records():
            self.write({**fr, "event": "fault", "query_id": qid,
                        "ts": time.time()})
        after = faults.recovery_counters()
        delta = {k: after.get(k, 0) - before.get(k, 0)
                 for k in after if after.get(k, 0) != before.get(k, 0)}
        self.write({"event": "recovery", "query_id": qid,
                    "ts": time.time(), "recovery": delta or None})

    def _write_oom_retry_records(self, qid: int) -> None:
        """v9: drain the OOM-retry ladder's per-scope records (one
        ``oom_retry`` record per retry scope that saw at least one retry
        or split; none in the common no-pressure case)."""
        from ..memory.retry import drain_oom_retry_records
        for rr in drain_oom_retry_records():
            self.write({**rr, "event": "oom_retry", "query_id": qid})

    def _write_movement_records(self, qid: int) -> None:
        """v11: write ONE ``movement_summary`` record — the data-movement
        ledger's per-query aggregation of every host<->device crossing
        (utils/movement.py). ``movement`` is null when the ledger is off
        (the default), so the per-query record set is stable either way."""
        from ..utils import movement
        self.write({"event": "movement_summary", "query_id": qid,
                    "ts": time.time(),
                    "movement": movement.query_summary(qid)})

    def _write_shuffle_records(self, qid: int) -> None:
        """v12: write ONE ``shuffle_summary`` record — the shuffle
        observatory's per-query aggregation of every transfer on every
        shuffle tier (shuffle/telemetry.py), with straggler attribution.
        ``shuffle`` is null when the observatory is off (the default),
        so the per-query record set is stable either way."""
        from ..shuffle import telemetry
        self.write({"event": "shuffle_summary", "query_id": qid,
                    "ts": time.time(),
                    "shuffle": telemetry.query_summary(qid)})

    def _write_fallback_records(self, qid: int) -> None:
        """v10: drain the degradation layer's completed-fallback records
        (one ``fallback`` record per batch re-executed through the host
        engine; none in the healthy-device common case)."""
        from ..exec.fallback import drain_fallback_records
        for fr in drain_fallback_records():
            self.write({**fr, "event": "fallback", "query_id": qid})

    def close(self) -> None:
        self.write({"event": "app_end", "ts": time.time()})
        self._f.close()


def _query_critical_path(trace_id: str) -> Optional[Dict]:
    """The per-category wall-time breakdown of the query just run,
    computed from THIS process's tracer spans (v5 query_end payload).
    None when tracing is off or the query span was dropped from the
    ring — never raises (trace math must not fail a query)."""
    from ..utils.tracing import get_tracer
    tracer = get_tracer()
    if not tracer.enabled:
        return None
    try:
        from .trace import critical_path_from_tracer
        cp = critical_path_from_tracer(tracer, trace_id)
        return None if cp is None else cp.to_dict()
    except Exception:  # pragma: no cover — defensive
        return None


def _node_metrics(ns) -> Dict:
    """Snapshot the live node's operator metrics (TpuExec registries) —
    the same rule QueryProfile uses (tools/profiler.py)."""
    from .profiler import registry_snapshot
    return registry_snapshot(getattr(ns, "_node", None))


def _node_shuffle_skew(ns) -> Optional[Dict]:
    """The live node's accumulated per-partition distribution (v7), or
    None for non-exchange nodes / exchanges that never materialized.
    Never raises — skew telemetry must not fail a query."""
    node = getattr(ns, "_node", None)
    fn = getattr(node, "shuffle_skew", None)
    if fn is None:
        return None
    try:
        return fn()
    except Exception:  # pragma: no cover — defensive
        return None


# ---------------------------------------------------------------------------
# Replay
# ---------------------------------------------------------------------------
class QueryReplay:
    def __init__(self, qid: int):
        self.query_id = qid
        self.plan: str = ""
        self.final_plan: str = ""
        self.wall_s: float = 0.0
        self.error: Optional[str] = None
        self.nodes: List[Dict] = []
        self.kernels: List[Dict] = []  # v3: per-XLA-program records
        self.aqe_events: List[str] = []
        self.spill_count: Dict = {}
        self.semaphore_wait_s: float = 0.0
        self.stats: Dict = {}  # per-query process-counter deltas
        # v4: wall-clock window (query_start.ts .. query_end.ts) so
        # app-level heartbeats can be attributed to the running query
        self.ts_start: float = 0.0
        self.ts_end: float = 0.0
        # v5: distributed-trace identity + critical-path attribution
        self.trace_id: str = ""
        self.critical_path: Optional[Dict] = None
        # v6: memory flight recorder — per-operator HBM attribution +
        # leak scan (None for pre-v6 logs or profiling off), and any OOM
        # postmortems the query hit
        self.memory_summary: Optional[Dict] = None
        self.oom_postmortems: List[Dict] = []
        # v7: per-exchange output-partition row/byte distribution records
        # (empty for pre-v7 logs or queries with no materialized exchange)
        self.shuffle_skew: List[Dict] = []
        # v8: fault-injection + recovery telemetry — ``recovery`` is the
        # per-query recovery-ledger delta (None for pre-v8 logs AND for
        # queries that needed no recovery), ``faults`` the injected-fire
        # records (empty when injection is off)
        self.recovery: Optional[Dict] = None
        self.faults: List[Dict] = []
        # v9: device-OOM retry-ladder records — one per retry scope that
        # retried or split (empty for pre-v9 logs and unpressured queries)
        self.oom_retries: List[Dict] = []
        # v10: host-fallback records — one per batch re-executed through
        # the host engine (empty for pre-v10 logs and healthy devices)
        self.fallbacks: List[Dict] = []
        # v11: data-movement ledger aggregation — per-site/per-operator
        # host<->device bytes, wall, blocking counts and round trips
        # (None for pre-v11 logs AND when the ledger is off)
        self.movement_summary: Optional[Dict] = None
        # v12: shuffle observatory aggregation — per-tier/per-shuffle
        # transfer bytes, walls, retries and straggler attribution
        # (None for pre-v12 logs AND when shuffle telemetry is off)
        self.shuffle_summary: Optional[Dict] = None

    def heartbeats_in_window(self, heartbeats: List[Dict]) -> List[Dict]:
        """App heartbeats whose timestamp falls inside this query's run
        (v4; empty for pre-v4 logs — ts_start is 0)."""
        if not self.ts_start:
            return []
        end = self.ts_end or float("inf")
        return [h for h in heartbeats
                if self.ts_start <= h.get("ts", 0.0) <= end]

    def summary(self) -> str:
        lines = [f"query {self.query_id}: wall={self.wall_s:.4f}s"
                 + (f" ERROR {self.error}" if self.error else ""),
                 f"{'op':<44}{'time_s':>9}{'rows':>12}{'batches':>9}"]
        for n in self.nodes:
            label = ("  " * n["depth"] + n["name"])[:43]
            lines.append(f"{label:<44}{n['wall_s']:>9.4f}{n['rows']:>12}"
                         f"{n['batches']:>9}")
        if self.aqe_events:
            lines.append("aqe: " + "; ".join(self.aqe_events))
        return "\n".join(lines)

    def timeline_svg(self) -> str:
        """One bar per operator from first to last activity — the
        reference profiler's generateTimeline analogue."""
        nodes = [n for n in self.nodes if n["batches"] > 0]
        if not nodes:
            return "<svg xmlns='http://www.w3.org/2000/svg'/>"
        t_max = max(max(n["t_last"] for n in nodes), self.wall_s, 1e-9)
        row_h, label_w, width = 22, 260, 900
        height = row_h * (len(nodes) + 1) + 10
        scale = (width - label_w - 20) / t_max
        parts = [f"<svg xmlns='http://www.w3.org/2000/svg' "
                 f"width='{width}' height='{height}' "
                 f"font-family='monospace' font-size='11'>"]
        for i, n in enumerate(sorted(nodes, key=lambda x: x["t_first"])):
            y = 5 + i * row_h
            x0 = label_w + n["t_first"] * scale
            w = max(1.0, (n["t_last"] - n["t_first"]) * scale)
            label = ("  " * n["depth"] + n["name"])[:38]
            parts.append(f"<text x='4' y='{y + 14}'>{label}</text>")
            parts.append(
                f"<rect x='{x0:.1f}' y='{y + 3}' width='{w:.1f}' "
                f"height='{row_h - 8}' fill='#4C78A8'>"
                f"<title>{n['name']}: {n['wall_s']:.4f}s, "
                f"{n['rows']} rows</title></rect>")
        axis_y = 5 + len(nodes) * row_h + 12
        parts.append(f"<text x='{label_w}' y='{axis_y}'>0s</text>")
        parts.append(f"<text x='{width - 60}' y='{axis_y}'>"
                     f"{t_max:.3f}s</text>")
        parts.append("</svg>")
        return "".join(parts)

    def to_dot(self) -> str:
        """Graphviz DOT of the executed plan with per-node metrics
        (reference: GenerateDot.scala)."""
        lines = ["digraph plan {", "  node [shape=box fontname=monospace];"]
        for n in self.nodes:
            label = (f"{n['name']}\\n{n['desc'][:40]}\\n"
                     f"{n['wall_s']:.4f}s  {n['rows']} rows")
            lines.append(f"  n{n['node_id']} [label=\"{label}\"];")
        for n in self.nodes:
            if n["parent_id"] >= 0:
                lines.append(f"  n{n['node_id']} -> n{n['parent_id']};")
        lines.append("}")
        return "\n".join(lines)


class AppReplay:
    def __init__(self, path: str):
        self.path = path
        self.app_id: str = ""
        self.schema_version: int = 1  # logs predating the field
        self.conf: Dict = {}
        self.queries: Dict[int, QueryReplay] = {}
        self.heartbeats: List[Dict] = []  # v4: app-level monitor samples

    def query(self, qid: int) -> QueryReplay:
        return self.queries[qid]

    def summary(self) -> str:
        lines = [f"app {self.app_id}: {len(self.queries)} queries"]
        for q in self.queries.values():
            lines.append(f"  q{q.query_id}: {q.wall_s:.4f}s"
                         + (" ERROR" if q.error else ""))
        hot: Dict[str, float] = {}
        for q in self.queries.values():
            for n in q.nodes:
                hot[n["name"]] = hot.get(n["name"], 0.0) + n["wall_s"]
        lines.append("hottest operators:")
        for name, t in sorted(hot.items(), key=lambda kv: -kv[1])[:10]:
            lines.append(f"  {name:<40}{t:>9.4f}s")
        return "\n".join(lines)

    def health_check(self) -> List[str]:
        warnings = []
        for q in self.queries.values():
            if q.error:
                warnings.append(f"q{q.query_id} failed: {q.error}")
            if any(q.spill_count.values()):
                warnings.append(
                    f"q{q.query_id}: device memory pressure "
                    f"(spills {q.spill_count})")
            if q.wall_s > 0 and q.semaphore_wait_s > 0.25 * q.wall_s:
                warnings.append(
                    f"q{q.query_id}: semaphore wait is "
                    f"{q.semaphore_wait_s / q.wall_s:.0%} of wall time")
            compile_s = q.stats.get("compile_cache_compile_seconds", 0.0)
            if q.wall_s > 0 and compile_s > 0.5 * q.wall_s:
                warnings.append(
                    f"q{q.query_id}: XLA compile is "
                    f"{compile_s / q.wall_s:.0%} of wall time — cold compile "
                    "cache (warm up or enable the persistent cache)")
            if q.stats.get("catalog_oom_callback_errors", 0):
                warnings.append(
                    f"q{q.query_id}: OOM cache-drop callbacks raised "
                    "(see catalog diagnostics)")
            ms = q.memory_summary or {}
            if ms.get("leaked_bytes"):
                warnings.append(
                    f"q{q.query_id}: {len(ms.get('leaked_buffers', []))} "
                    f"buffer(s) still registered after query end "
                    f"({ms['leaked_bytes']} bytes leaked — top holder: "
                    f"{ms['leaked_buffers'][0]['operator']})")
            for pm in q.oom_postmortems:
                warnings.append(
                    f"q{q.query_id}: OOM postmortem — {pm.get('context')}"
                    + (f" (report: {pm['path']})" if pm.get("path")
                       else ""))
            if q.recovery:
                detail = ", ".join(f"{k}={v}"
                                   for k, v in sorted(q.recovery.items()))
                warnings.append(
                    f"q{q.query_id}: recovered from failures ({detail})"
                    + (" — faults were injected" if q.faults else ""))
            # v9: a scope that had to split repeatedly is running batches
            # far above what HBM can hold — a split storm
            storm = [r for r in q.oom_retries if r.get("splits", 0) >= 2]
            if storm:
                worst = max(storm, key=lambda r: r.get("splits", 0))
                warnings.append(
                    f"q{q.query_id}: OOM split storm — scope "
                    f"'{worst.get('scope')}' split {worst['splits']}x "
                    "(lower spark.rapids.sql.batchSizeBytes so batches "
                    "fit HBM without retry-time splitting)")
            # v10: batches that had to re-execute on the host engine —
            # correct results, but the device path is failing for that
            # operator and each batch pays a download/upload round trip
            if q.fallbacks:
                ops = sorted({f.get("operator", "?") for f in q.fallbacks})
                down = sum(f.get("bytes_down", 0) for f in q.fallbacks)
                warnings.append(
                    f"q{q.query_id}: {len(q.fallbacks)} batch(es) fell "
                    f"back to the host engine ({', '.join(ops)}; "
                    f"{down} bytes downloaded) — repeated failures "
                    "quarantine the operator to host at plan time")
            # v11: the query spent most of its wall blocked on host<->
            # device synchronization — the data-movement observatory's
            # per-site ranking says which funnel to make non-blocking
            cp = q.critical_path or {}
            sync_frac = cp.get("sync_wait_frac", 0.0) or 0.0
            if sync_frac > SYNC_WAIT_WARN_FRAC:
                msg = (f"q{q.query_id}: sync wait is {sync_frac:.0%} of "
                       "wall time — host<->device crossings dominate")
                mv = q.movement_summary or {}
                sites = mv.get("sites") or []
                if sites:
                    top = sites[0]
                    msg += (f" (heaviest site: {top.get('site')} — "
                            f"{top.get('bytes', 0)} bytes, "
                            f"{top.get('count', 0)} crossings)")
                else:
                    msg += (" (enable spark.rapids.tpu.movement.enabled "
                            "for per-site attribution)")
                warnings.append(msg)
            mvt = (q.movement_summary or {}).get("totals") or {}
            if mvt.get("round_trips"):
                warnings.append(
                    f"q{q.query_id}: {mvt['round_trips']} batch(es) made a "
                    "host round trip (downloaded then re-uploaded within "
                    "the query) — keep them device-resident or cache the "
                    "shuffle on device")
            # v12: shuffle observatory — measured per-partition transfer
            # walls expose stragglers that row-count skew records can't
            # (a balanced partition on a slow link still stalls the stage)
            sh = q.shuffle_summary or {}
            st = sh.get("straggler") or {}
            if ((st.get("skew") or 0.0) >= SHUFFLE_STRAGGLER_WARN_SKEW
                    and (st.get("slowest_wall_s") or 0.0)
                    >= SHUFFLE_STRAGGLER_WARN_WALL_S):
                worst = st.get("worst") or {}
                warnings.append(
                    f"q{q.query_id}: shuffle straggler — slowest partition "
                    f"wall {st['slowest_wall_s']:.3f}s vs p50 "
                    f"{st['p50_wall_s']:.3f}s ({st['skew']:.1f}x; shuffle "
                    f"{worst.get('shuffle_id')} partition "
                    f"{worst.get('partition')} on the {worst.get('tier')} "
                    "tier) — repartition or salt the hot keys")
            sht = sh.get("totals") or {}
            if sht.get("retries"):
                warnings.append(
                    f"q{q.query_id}: {sht['retries']} shuffle transfer "
                    "retrie(s) — peers answered late or died; check "
                    "transport-tier backpressure (max publish-queue depth "
                    f"{sht.get('max_queue_depth', 0)})")
        stalled = [h for h in self.heartbeats if h.get("stalled")]
        if stalled:
            age = max(h.get("last_progress_age_s", 0.0) for h in stalled)
            warnings.append(
                f"watchdog: {len(stalled)} heartbeat(s) reported a stalled "
                f"engine (max no-progress age {age:.1f}s) — see the "
                "stall-<ts>.txt forensics reports")
        return warnings


def load_event_log(path: str) -> AppReplay:
    app = AppReplay(path)
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            ev = rec.get("event")
            if ev == "app_start":
                app.app_id = rec.get("app_id", "")
                app.schema_version = rec.get("schema_version", 1)
                app.conf = rec.get("conf", {})
            elif ev == "query_start":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.plan = rec.get("plan", "")
                q.ts_start = rec.get("ts", 0.0)
                q.trace_id = rec.get("trace_id", "")
            elif ev == "heartbeat":
                app.heartbeats.append(rec)
            elif ev == "node":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.nodes.append(rec)
            elif ev == "kernel":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.kernels.append(rec)
            elif ev == "memory_summary":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.memory_summary = rec.get("summary")
            elif ev == "oom_postmortem":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.oom_postmortems.append(rec)
            elif ev == "shuffle_skew":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.shuffle_skew.append(rec)
            elif ev == "fault":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.faults.append(rec)
            elif ev == "recovery":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.recovery = rec.get("recovery")
            elif ev == "oom_retry":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.oom_retries.append(rec)
            elif ev == "fallback":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.fallbacks.append(rec)
            elif ev == "movement_summary":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.movement_summary = rec.get("movement")
            elif ev == "shuffle_summary":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.shuffle_summary = rec.get("shuffle")
            elif ev == "query_end":
                q = app.queries.setdefault(rec["query_id"],
                                           QueryReplay(rec["query_id"]))
                q.wall_s = rec.get("wall_s", 0.0)
                q.error = rec.get("error")
                q.ts_end = rec.get("ts", 0.0)
                q.trace_id = rec.get("trace_id", q.trace_id)
                q.critical_path = rec.get("critical_path")
                q.final_plan = rec.get("final_plan", "")
                q.aqe_events = rec.get("aqe_events", [])
                q.spill_count = rec.get("spill_count", {})
                q.semaphore_wait_s = rec.get("semaphore_wait_s", 0.0)
                q.stats = rec.get("stats", {})
    return app
