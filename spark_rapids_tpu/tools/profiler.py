"""Profiling tool: per-operator wall time + runtime health report.

Reference: tools/ ProfileMain / Profiler (tools/.../profiling/Profiler.scala:
32,436) — replays Spark event logs into executor/app/SQL-metric reports plus
a HealthCheck. Standalone equivalent: wrap a live plan execution, time every
physical node, and fold in the runtime's own health signals (spill counts,
semaphore waits) — the data the reference mines from event logs, captured at
the source instead.
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional

from ..conf import RapidsConf

__all__ = ["profile_query", "QueryProfile", "NodeStats", "instrument_plan",
           "registry_snapshot", "snapshot_node_metrics",
           "compute_self_times", "finalize_self_times"]


@dataclasses.dataclass
class NodeStats:
    name: str
    desc: str
    depth: int
    node_id: int = 0
    parent_id: int = -1
    wall_s: float = 0.0
    rows: int = 0
    batches: int = 0
    t_first: float = 0.0   # offset of first activity from query start
    t_last: float = 0.0    # offset of last activity
    # operator-metric snapshot (the node's MetricRegistry), captured after
    # the run by snapshot_node_metrics(); lands in event-log node records
    metrics: Dict = dataclasses.field(default_factory=dict)

    @property
    def self_s(self) -> float:
        """Wall time minus child production (set by finalize_self_times)."""
        return getattr(self, "_self_s", self.wall_s)


@dataclasses.dataclass
class QueryProfile:
    nodes: List[NodeStats]
    total_s: float
    spill: Dict
    semaphore: Dict
    # per-query deltas of the process-wide StatsRegistry counters: compile
    # cache, upload cache, shuffle tiers, catalog spills/OOM, semaphore —
    # one report with every subsystem's signal
    stats: Dict = dataclasses.field(default_factory=dict)
    # kernel-table entries this query touched (utils/compile_cache.py):
    # per-program compile wall + XLA cost/memory analysis, node-attributed
    kernels: List[Dict] = dataclasses.field(default_factory=list)

    TIMELINE_WIDTH = 20

    def _timeline(self, n: NodeStats) -> str:
        """Activity window of one operator as an ASCII bar over the query
        wall — column-aligned bars make operator overlap (pipelining vs
        serialization) visible at a glance."""
        w = self.TIMELINE_WIDTH
        if self.total_s <= 0 or n.batches == 0 or n.t_last < n.t_first:
            return " " * w
        lo = int(round(min(n.t_first, self.total_s) / self.total_s * w))
        hi = int(round(min(n.t_last, self.total_s) / self.total_s * w))
        lo = min(lo, w - 1)
        hi = max(hi, lo + 1)
        return "." * lo + "=" * (hi - lo) + "." * (w - hi)

    def summary(self) -> str:
        lines = [f"total wall time: {self.total_s:.4f}s", "",
                 f"{'op':<44}{'time_s':>9}{'rows':>12}{'batches':>9}"
                 f"  {'timeline':<{self.TIMELINE_WIDTH}}"]
        for n in self.nodes:
            label = ("  " * n.depth + n.name)[:43]
            lines.append(f"{label:<44}{n.wall_s:>9.4f}{n.rows:>12}"
                         f"{n.batches:>9}  {self._timeline(n)}")
        lines.append("")
        lines.append(f"spill: {self.spill}")
        lines.append(f"semaphore: {self.semaphore}")
        if self.stats:
            lines.append("counters (this query):")
            for k in sorted(self.stats):
                v = self.stats[k]
                if v:
                    lines.append(f"  {k:<44}{v}")
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps({
            "total_s": self.total_s,
            "nodes": [dataclasses.asdict(n) for n in self.nodes],
            "spill": self.spill,
            "semaphore": self.semaphore,
            "stats": self.stats,
            "kernels": self.kernels,
        })

    def health_check(self) -> List[str]:
        """Reference: HealthCheck — flag suspicious signals."""
        warnings = []
        if self.spill.get("spill_count"):
            sc = self.spill["spill_count"]
            if any(sc.values()):
                warnings.append(
                    f"device memory pressure: spills occurred ({sc}) — "
                    "consider a larger pool or smaller batch size")
        wait = self.semaphore.get("total_wait_time", 0.0)
        if self.total_s > 0 and wait > 0.25 * self.total_s:
            warnings.append(
                f"semaphore wait is {wait / self.total_s:.0%} of wall time — "
                "tasks are serialized on the chip; lower parallelism or raise "
                "concurrentGpuTasks")
        slowest = max(self.nodes, key=lambda n: n.wall_s, default=None)
        if slowest and self.total_s > 0 and slowest.wall_s > 0.8 * self.total_s:
            warnings.append(
                f"{slowest.name} dominates ({slowest.wall_s:.2f}s) — "
                "check its explain tagging for fallback reasons")
        compile_s = self.stats.get("compile_cache_compile_seconds", 0.0)
        if self.total_s > 0 and compile_s > 0.5 * self.total_s:
            warnings.append(
                f"XLA compile is {compile_s / self.total_s:.0%} of wall "
                "time — cold compile cache (warm up, or check for shape-"
                "bucket churn recompiling per batch)")
        if self.stats.get("catalog_oom_callback_errors", 0):
            warnings.append(
                "OOM cache-drop callbacks raised during this query — "
                "cached device bytes may not have been released "
                "(see catalog diagnostics)")
        return warnings


def instrument_plan(plan, epoch: Optional[float] = None,
                    annotate: bool = False,
                    into: Optional[List[NodeStats]] = None,
                    query_id: Optional[int] = None) -> List[NodeStats]:
    """Wrap every physical node's ``execute``/``execute_columnar`` in timers
    (shared by the live profiler and the event-log writer). ``annotate``
    additionally scopes each node's work in a
    ``jax.profiler.TraceAnnotation`` so XLA trace captures show query
    operators by name — the NvtxWithMetrics analogue (reference:
    NvtxWithMetrics.scala). ``into`` appends to an existing stats list with
    continuing node ids (AQE instruments each stage segment as it forms).
    ``query_id`` flows into the node-context scopes so process services
    (the compile-cache kernel table) can record which query first drove
    them."""
    stats: List[NodeStats] = [] if into is None else into
    if epoch is None:
        epoch = time.perf_counter()

    def wrap(node, depth: int, parent: int):
        ns = NodeStats(type(node).__name__,
                       getattr(node, "node_desc", lambda: "")(), depth,
                       node_id=len(stats), parent_id=parent)
        ns._node = node  # live reference for metric snapshots (not serialized)
        stats.append(ns)
        # wrap exactly one method per node: device execs route execute()
        # through execute_columnar(), so wrapping both would double-count
        from ..exec.base import TpuExec
        attrs = ("execute_columnar",) if isinstance(node, TpuExec) \
            else ("execute",)
        for attr in attrs:
            fn = getattr(node, attr, None)
            if fn is None:
                continue

            def timed(pidx, _fn=fn, _ns=ns, _node=node):
                import contextlib

                from ..utils import metrics as M
                from ..utils.node_context import node_scope
                from ..utils.tracing import get_tracer
                tracer = get_tracer()
                reg = getattr(_node, "metrics", None)
                scope = contextlib.nullcontext()
                if annotate:
                    import jax.profiler
                    scope = jax.profiler.TraceAnnotation(
                        f"{_ns.name}[{pidx}]")
                it = _fn(pidx)
                t0 = time.perf_counter()
                if not _ns.batches:
                    _ns.t_first = t0 - epoch
                try:
                    with scope:
                        while True:
                            # the node-context scope brackets each RESUME of
                            # the node's generator frame: process services
                            # (compile cache, spill path) attribute work to
                            # the innermost node driving them. A child
                            # resumed within pushes itself deeper, so the
                            # top of stack is always the executing node.
                            with node_scope(_ns.node_id, _ns.name, reg,
                                            query_id=query_id):
                                try:
                                    batch = next(it)
                                except StopIteration:
                                    break
                            now = time.perf_counter()
                            _ns.wall_s += now - t0
                            _ns.t_last = now - epoch
                            _ns.batches += 1
                            rows = int(batch.num_rows)
                            _ns.rows += rows
                            # operator-batch span: one complete event per
                            # batch produced (the query->stage->task->
                            # operator level of the span hierarchy)
                            tracer.complete(_ns.name, "operator", t0,
                                            now - t0, partition=pidx,
                                            rows=rows)
                            if reg is not None and hasattr(reg, "observe"):
                                reg.observe(M.BATCH_ROWS_HISTOGRAM, rows)
                            yield batch
                            t0 = time.perf_counter()
                finally:
                    now = time.perf_counter()
                    _ns.wall_s += now - t0
                    _ns.t_last = now - epoch

            setattr(node, attr, timed)

        # materializing nodes (exchanges) may be driven directly via
        # _materialize() by the AQE loop (plan/aqe.py materialize_stage)
        # instead of through their generator — time that path too, but
        # skip when re-entered from this node's own instrumented generator
        # (the generator timer already covers it)
        mat = getattr(node, "_materialize", None)
        if callable(mat):
            def timed_mat(_fn=mat, _ns=ns, _node=node):
                from ..utils.node_context import current, node_scope
                ctx = current()
                if ctx is not None and ctx.node_id == _ns.node_id:
                    return _fn()  # inside our own timed generator
                reg = getattr(_node, "metrics", None)
                t0 = time.perf_counter()
                if not _ns.batches and not _ns.wall_s:
                    _ns.t_first = t0 - epoch
                try:
                    with node_scope(_ns.node_id, _ns.name, reg,
                                    query_id=query_id):
                        return _fn()
                finally:
                    now = time.perf_counter()
                    _ns.wall_s += now - t0
                    _ns.t_last = now - epoch

            setattr(node, "_materialize", timed_mat)
        me = ns.node_id
        for c in node.children:
            wrap(c, depth + 1, me)

    wrap(plan, 0, -1)
    return stats


def registry_snapshot(node) -> Dict:
    """A node's operator-metric snapshot with zero values dropped — the
    ONE filtering rule shared by the event-log node records and
    QueryProfile, so both report identical metrics for the same query."""
    reg = getattr(node, "metrics", None)
    if reg is None or not hasattr(reg, "snapshot"):
        return {}
    return {k: v for k, v in reg.snapshot().items() if v}


def snapshot_node_metrics(stats: List[NodeStats]) -> None:
    """Fold each live node's MetricRegistry into its NodeStats (call after
    the run)."""
    for ns in stats:
        ns.metrics = registry_snapshot(getattr(ns, "_node", None))


def compute_self_times(nodes) -> Dict[int, float]:
    """Per-node SELF time (wall minus direct children's wall), keyed by
    node_id. ``nodes`` are NodeStats or event-log node dicts.

    An operator's timed window includes pulling from its children (the
    generators nest), so wall_s alone over-attributes upstream cost; self
    time is the ONE attribution rule EXPLAIN ANALYZE percentages and the
    diagnose tool both rank by."""
    def get(n, k, default=0.0):
        # dicts may come from old event logs with keys missing
        return n.get(k, default) if isinstance(n, dict) else getattr(n, k)

    child_wall: Dict[int, float] = {}
    for n in nodes:
        parent = get(n, "parent_id", -1)
        if parent >= 0:
            child_wall[parent] = child_wall.get(parent, 0.0) \
                + get(n, "wall_s")
    return {get(n, "node_id"):
            max(0.0, get(n, "wall_s") - child_wall.get(get(n, "node_id"),
                                                       0.0))
            for n in nodes}


def finalize_self_times(stats: List[NodeStats]) -> None:
    """Attach ``self_s`` to each NodeStats (see compute_self_times)."""
    self_s = compute_self_times(stats)
    for ns in stats:
        ns._self_s = self_s[ns.node_id]


def profile_query(df, device: Optional[bool] = None,
                  xla_trace_dir: Optional[str] = None) -> QueryProfile:
    """Execute ``df.collect(device=...)`` with every physical node's
    ``execute``/``execute_columnar`` wrapped in timers. With
    ``xla_trace_dir`` the whole execution also runs under
    ``jax.profiler.trace`` with per-operator TraceAnnotations, producing a
    TensorBoard-loadable XLA trace."""
    from ..memory.catalog import get_catalog
    from ..memory.semaphore import get_semaphore
    from ..utils.compile_cache import kernel_seq, kernels_since
    from ..utils.memprof import active as memprof_active
    from ..utils.metrics import StatsRegistry, get_stats
    from ..utils.tracing import get_tracer

    plan = df.session._physical(df.logical, device)
    annotate = xla_trace_dir is not None
    stats: List[NodeStats] = []
    epoch = time.perf_counter()
    from ..plan.aqe import AdaptiveExec
    if isinstance(plan, AdaptiveExec):
        # AQE finalizes lazily: instrument each stage segment + the final
        # segment as the adaptive loop creates them
        plan._instrument_hook = \
            lambda p: instrument_plan(p, epoch, annotate, into=stats)
    else:
        instrument_plan(plan, epoch, annotate, into=stats)
    # snapshot the process-global counters so the report shows THIS query's
    # deltas, not lifetime totals
    cat = get_catalog()
    sem = get_semaphore()
    registry = get_stats()
    spill_before = dict(cat.spill_count)
    bytes_before = dict(cat.spilled_bytes)
    wait_before = sem.total_wait_time
    acq_before = sem.acquire_count
    counters_before = registry.collect()
    kseq_before = kernel_seq()
    # profiled runs share query_id=None in the node contexts — drop any
    # stale per-operator memory aggregation from a previous profile so
    # node_peaks() below reflects only THIS run
    mp = memprof_active()
    if mp is not None:
        mp.begin_query(None)

    if xla_trace_dir is not None:
        import jax.profiler
        t0 = time.perf_counter()
        with jax.profiler.trace(xla_trace_dir), \
                get_tracer().span("query", "query", profiled=True):
            plan.collect()
        total = time.perf_counter() - t0
    else:
        t0 = time.perf_counter()
        with get_tracer().span("query", "query", profiled=True):
            plan.collect()
        total = time.perf_counter() - t0

    spill = {
        "spill_count": {str(k): v - spill_before.get(k, 0)
                        for k, v in cat.spill_count.items()},
        "spilled_bytes": {str(k): v - bytes_before.get(k, 0)
                          for k, v in cat.spilled_bytes.items()},
    }
    # single-use profiled plan: close its spill-registered outputs now
    # (same query-end release the session collect path performs)
    plan.release_spill_handles()
    semaphore = {"total_wait_time": sem.total_wait_time - wait_before,
                 "acquire_count": sem.acquire_count - acq_before}
    counters = StatsRegistry.delta(registry.collect(), counters_before)
    snapshot_node_metrics(stats)
    # fold per-node peak HBM from the memory flight recorder into the
    # metric snapshots: EXPLAIN ANALYZE renders it as the peakDevMemory
    # column (plan/meta.py render order)
    if mp is not None:
        from ..utils.metrics import PEAK_DEVICE_MEMORY
        peaks = mp.node_peaks(None)
        for ns in stats:
            if peaks.get(ns.node_id):
                ns.metrics[PEAK_DEVICE_MEMORY] = peaks[ns.node_id]
    finalize_self_times(stats)
    return QueryProfile(stats, total, spill, semaphore, counters,
                        kernels=kernels_since(kseq_before))
