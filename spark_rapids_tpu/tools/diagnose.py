"""Auto-diagnosis: ranked bottleneck report + conf suggestions.

Reference: the plugin's AutoTuner (tools/.../tuning/AutoTuner.scala) mines
profiling output into concrete ``spark.rapids.*`` recommendations. Here the
input is our own event log (tools/eventlog.py, schema v3+): per-node
wall times and metric snapshots, kernel records (XLA compile wall + cost
analysis per plan signature), per-query process-counter deltas, and —
on v6 logs — the memory flight recorder's per-query ``memory_summary``
(leaked buffers, peak-HBM holders, spill churn) and any
``oom_postmortem`` records. The
output names, for every query, the top bottleneck (node, metric) pairs —
"q1: 61% in ShuffleExchangeExec host serialization" — each with the conf
knob that addresses it.

CLI::

    python -m spark_rapids_tpu.tools.diagnose <eventlog.jsonl | dir> \
        [--top N] [--json] [--out report.txt]

Programmatic: ``diagnose_path(path)`` / ``diagnose_app(AppReplay)`` return
a ``DiagnoseReport`` (``.summary()``, ``.to_json()``, ``.findings``).
"""
from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
from typing import Dict, List, Optional

from ..utils import metrics as M

__all__ = ["Finding", "QueryDiagnosis", "DiagnoseReport", "diagnose_app",
           "diagnose_path", "main"]

# minimum share of query wall before a signal counts as a bottleneck
_FRACTION_FLOOR = 0.02


@dataclasses.dataclass
class Finding:
    """One (node, metric) bottleneck with an actionable suggestion."""
    node: str              # operator name, or "(query)" for query-level
    node_id: Optional[int]
    metric: str            # which signal ranked it (wall, xlaCompileTime...)
    seconds: float         # attributed seconds (0 when not time-based)
    fraction: float        # share of query wall, ranking key
    detail: str
    suggestion: str

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class QueryDiagnosis:
    query_id: int
    wall_s: float
    findings: List[Finding]
    #: the query_end critical-path breakdown (schema v5; None pre-v5)
    critical_path: Optional[Dict] = None
    #: the movement_summary payload (schema v11; None pre-v11 / ledger off)
    movement: Optional[Dict] = None
    #: the shuffle_summary payload (schema v12; None pre-v12 /
    #: telemetry off)
    shuffle: Optional[Dict] = None

    def top(self, n: int = 3) -> List[Finding]:
        return self.findings[:n]


class DiagnoseReport:
    def __init__(self, path: str, queries: List[QueryDiagnosis]):
        self.path = path
        self.queries = queries

    def summary(self, top: int = 3) -> str:
        lines = [f"== diagnose: {os.path.basename(self.path)} =="]
        if not self.queries:
            lines.append("no completed queries in the log")
        for q in self.queries:
            lines.append(f"q{q.query_id} (wall {q.wall_s:.4f}s) "
                         f"top bottlenecks:")
            if not q.findings:
                lines.append("  no signal above the reporting floor")
            for rank, f in enumerate(q.top(top), 1):
                pct = f"{100.0 * f.fraction:.0f}%"
                lines.append(f"  {rank}. ({f.node}, {f.metric}) {pct} of "
                             f"wall — {f.detail}")
                lines.append(f"     suggest: {f.suggestion}")
        lines.extend(_sync_debt_lines(self._measured_sync()))
        lines.extend(_movement_lines(self._measured_movement()))
        return "\n".join(lines)

    def _measured_sync(self) -> Optional[Dict]:
        """Aggregate measured critical-path sync-wait over the report's
        queries (schema-v5 logs) — the dynamic number the static
        sync-site inventory is ranked against. None pre-v5."""
        sync_s = wall_s = 0.0
        counted = 0
        for q in self.queries:
            cp = q.critical_path
            if not cp:
                continue
            counted += 1
            wall_s += float(cp.get("total_s", q.wall_s))
            sync_s += float((cp.get("categories_s") or {})
                            .get("sync_wait", 0.0))
        if not counted:
            return None
        return {"queries": counted, "sync_wait_s": sync_s,
                "wall_s": wall_s,
                "sync_wait_frac": sync_s / wall_s if wall_s > 0 else 0.0}

    def _measured_movement(self) -> List[Dict]:
        """Measured per-site movement cost aggregated over the report's
        queries (schema-v11 logs with the ledger on), each site joined
        onto its srtpu-analyze sync baseline keys (``path::rule::symbol``)
        — the static<->runtime join: the baseline says WHERE the sticky
        sync debt lives, these rows say what each site measurably COSTS
        in wall and bytes. Empty for pre-v11 logs / ledger off."""
        agg: Dict[str, Dict] = {}
        for q in self.queries:
            for s in (q.movement or {}).get("sites") or []:
                a = agg.setdefault(s.get("site", "?"), {
                    "site": s.get("site", "?"),
                    "direction": s.get("direction"),
                    "count": 0, "bytes": 0, "wall_s": 0.0,
                    "blocking_count": 0, "round_trips": 0})
                for k in ("count", "bytes", "blocking_count",
                          "round_trips"):
                    a[k] += int(s.get(k) or 0)
                a["wall_s"] += float(s.get("wall_s") or 0.0)
        if not agg:
            return []
        try:
            from .analyze import load_baseline
            base_keys = set(load_baseline().get("counts") or {})
        except Exception:
            base_keys = set()
        from ..utils import movement as _movement
        rows: List[Dict] = []
        for site, a in agg.items():
            info = _movement.site_info(site)
            keys = list(info.baseline_keys) if info is not None else []
            in_base = sorted(k for k in keys if k in base_keys)
            a["baseline_keys"] = keys
            a["baselined_debt"] = in_base
            # a funnel whose keys sit in the committed baseline is sticky
            # sync debt with a measured price tag; one whose keys are all
            # sync-ok-suppressed is deliberate; keyless sites (uploads)
            # are deferred transfers, not syncs
            a["status"] = ("baselined sync debt" if in_base
                           else "suppressed (deliberate sync)" if keys
                           else "deferred transfer")
            a["suggestion"] = info.hint if info is not None else ""
            a["wall_s"] = round(a["wall_s"], 6)
            rows.append(a)
        rows.sort(key=lambda r: (-r["wall_s"], -r["bytes"], r["site"]))
        return rows

    def to_json(self, top: int = 3) -> str:
        return json.dumps({
            "path": self.path,
            "queries": [{
                "query_id": q.query_id, "wall_s": q.wall_s,
                "findings": [f.to_dict() for f in q.top(top)],
                "critical_path": q.critical_path,
                "movement": q.movement,
                "shuffle": q.shuffle,
            } for q in self.queries],
            "sync_debt": _sync_debt_info(),
            "measured_sync": self._measured_sync(),
            "measured_movement": self._measured_movement(),
        }, indent=1)


def _sync_debt_info() -> Dict:
    """The srtpu-analyze baseline's sync inventory (see tools/analyze):
    which FILES statically carry blocking-sync debt. Cross-referencing it
    against the dynamic findings above ranks ROADMAP-item-1 work — an
    operator with a hot pipelineWait/d2h signal whose source file is near
    the top of this inventory is the highest-leverage fix. {} when no
    baseline is committed (never fails the report)."""
    try:
        from .analyze import baseline_summary
        return baseline_summary()
    except Exception:
        return {}


def _sync_debt_lines(measured: Optional[Dict] = None) -> List[str]:
    info = _sync_debt_info()
    checks = (info.get("summary") or {}).get("checks") or {}
    sync = checks.get("sync")
    if not sync:
        return []
    initial = (info.get("initial_inventory") or {}).get("sync")
    head = (f"static sync-site debt (srtpu-analyze baseline): "
            f"{sync.get('total', 0)} site(s), hot={sync.get('hot', 0)} "
            f"warm={sync.get('warm', 0)}")
    if initial:
        head += f" (initial inventory {initial})"
    lines = [head]
    if measured:
        # the critical-path measurement closes the static/dynamic loop:
        # the inventory says WHERE the blocking syncs live, the traced
        # critical path says how much wall they actually COST
        lines.append(
            f"  measured critical-path sync wait: "
            f"{measured['sync_wait_s']:.4f}s over "
            f"{measured['queries']} traced query(ies) "
            f"({measured['sync_wait_frac']:.1%} of wall) — the dynamic "
            "cost of the sites in this inventory")
    top = (info.get("summary") or {}).get("top_sync_files") or []
    if top:
        lines.append("  top hot-sync files: " + ", ".join(
            f"{t['path'].rsplit('/', 1)[-1]}({t['hot_syncs']})"
            for t in top[:5]))
        lines.append("  operators above with pipelineWait / d2h signals "
                     "that live in these files are the ROADMAP item 1 "
                     "targets (python -m spark_rapids_tpu.tools.analyze "
                     "for exact lines)")
    return lines




def _movement_lines(rows: List[Dict]) -> List[str]:
    """The "data movement" section: the movement ledger's measured
    per-site ranking, each row cross-referenced to its srtpu-analyze
    baseline keys, heaviest wall first."""
    if not rows:
        return []
    lines = ["data movement (measured, movement ledger):"]
    for r in rows[:8]:
        lines.append(
            f"  {r['site']}: {r['wall_s']:.4f}s wall, {r['bytes']} bytes "
            f"over {r['count']} crossing(s), {r['blocking_count']} "
            f"blocking [{r['status']}]")
        if r.get("baselined_debt"):
            lines.append("    baseline keys: "
                         + ", ".join(r["baselined_debt"]))
        if r.get("suggestion"):
            lines.append(f"    suggest: {r['suggestion']}")
    trips = sum(r.get("round_trips", 0) for r in rows)
    if trips:
        lines.append(f"  {trips} host round trip(s) detected — batches "
                     "downloaded then re-uploaded within one query")
    return lines


def _node_suggestion(name: str, metrics: Dict) -> str:
    """Knob for a node whose SELF time dominates, by operator family."""
    if "ShuffleExchange" in name and not name.startswith("Tpu"):
        return ("host-staged shuffle serializes through the CPU — attach a "
                "device mesh (spark.rapids.tpu.shuffle.mode=ici) or keep "
                "single-chip exchanges device-resident (mode=local)")
    if "LocalExchange" in name or "TpuShuffleExchange" in name:
        return ("device exchange dominates — raise spark.rapids.tpu."
                "shuffle.exchangeChunkRows to amortize collectives, or "
                "lower spark.rapids.tpu.shuffle.partitions")
    if "HostToDevice" in name:
        return ("upload-bound — enable spark.rapids.tpu.scan.deviceCache."
                "enabled / raise its maxBytes so repeated scans stay "
                "device-resident; larger spark.rapids.sql.batchSizeBytes "
                "amortizes per-batch upload overhead")
    if "DeviceToHost" in name:
        return ("download-bound — keep more of the plan on device (check "
                "explain('tpu') for fallback reasons above this node)")
    if name.startswith("Cpu") or "ArrowEval" in name:
        return ("operator fell back to the host engine — run "
                "df.explain('tpu') and address its NOT_ON_GPU reasons, or "
                "accept the fallback")
    if "Sort" in name:
        return ("sort-bound — raise spark.rapids.sql.batchSizeBytes to "
                "stay in the single-batch sort path, or pre-partition so "
                "each partition sorts less data")
    if "Join" in name:
        return ("join-bound — check the build side fits the batch budget "
                "(spark.rapids.sql.batchSizeBytes); a broadcast-eligible "
                "small side avoids the shuffle entirely")
    if "Aggregate" in name:
        return ("aggregate-bound — more input partitions parallelize the "
                "partial pass; check batchRows histograms for tiny batches")
    return ("dominant operator — profile_query(df) / df.explain('analyze') "
            "for its per-batch breakdown")


def _shape_blind(signature: str) -> str:
    """A plan signature with every numeral collapsed — signatures sharing
    a blind form differ only in shape (capacities, widths, chunk sizes)."""
    return re.sub(r"\d+", "#", signature or "")


def _bucket_churn_findings(per_node: Dict[str, List[Dict]],
                           wall: float) -> List[Finding]:
    """Per operator: groups of kernel-table signatures that differ only
    in shape. One group with >1 member means the same traced computation
    compiled more than once because batches arrived at different
    capacities — a shape-bucket-policy miss (the canonical ladder exists
    so one compiled kernel serves every partition)."""
    out: List[Finding] = []
    for name, entries in per_node.items():
        groups: Dict[str, List[Dict]] = {}
        for e in entries:
            groups.setdefault(_shape_blind(e.get("signature", "")),
                              []).append(e)
        churned = {b: es for b, es in groups.items() if len(es) > 1}
        if not churned:
            continue
        sigs = sum(len(es) for es in churned.values())
        compile_s = sum(e.get("compile_s", 0.0)
                        for es in churned.values() for e in es)
        shapes = max(len(es) for es in churned.values())
        out.append(Finding(
            node=name, node_id=next(iter(churned.values()))[0].get("node_id"),
            metric="bucketChurn", seconds=compile_s,
            fraction=max(_FRACTION_FLOOR,
                         compile_s / wall if wall else 0.0),
            detail=f"bucket churn: {sigs} signatures in "
                   f"{len(churned)} numeral-blind group(s) (worst group "
                   f"spans {shapes} variants, {compile_s:.2f}s compiling) "
                   f"— signatures differing only in numeric literals, "
                   f"typically capacities the bucket ladder should have "
                   f"collapsed (plan parameters like LIMIT n also match)",
            suggestion="if the variants are shapes, raise spark.rapids."
                       "tpu.shapeBuckets.minRows (or batchRowsMinBucket) "
                       "or raise shapeBuckets.maxWasteFrac back toward "
                       "0.5 — extra ladder rungs trade padding for "
                       "exactly this recompile churn"))
    return out


#: heartbeat device_used / device_limit fraction above which a query is
#: "in OOM territory" — spills/OOM are one bad batch away
_HBM_PRESSURE_FLOOR = 0.9


def _heartbeat_findings(q, heartbeats, wall: float) -> List[Finding]:
    """v4 live-health signals: stall windows the watchdog flagged while
    this query ran, and queries that heartbeated into OOM territory."""
    hbs = q.heartbeats_in_window(heartbeats) \
        if hasattr(q, "heartbeats_in_window") else []
    findings: List[Finding] = []
    stalled = [h for h in hbs if h.get("stalled")]
    if stalled:
        age = max(h.get("last_progress_age_s", 0.0) for h in stalled)
        findings.append(Finding(
            node="(query)", node_id=None, metric="stall",
            seconds=age, fraction=min(1.0, age / wall) if wall else 1.0,
            detail=f"watchdog stall window: {len(stalled)} heartbeat(s) "
                   f"with zero engine progress (max no-progress age "
                   f"{age:.1f}s)",
            suggestion="read the stall-<ts>.txt forensics report "
                       "(spark.rapids.tpu.health.reportDir) — it names "
                       "the semaphore holder thread and its stack; a "
                       "holder blocked on host work should release via "
                       "task_scope/release_all"))
    pressured = [h for h in hbs
                 if h.get("device_limit_bytes", 0)
                 and h.get("device_used_bytes", 0)
                 >= _HBM_PRESSURE_FLOOR * h["device_limit_bytes"]]
    if pressured:
        worst = max(pressured,
                    key=lambda h: h["device_used_bytes"]
                    / h["device_limit_bytes"])
        frac_used = worst["device_used_bytes"] / worst["device_limit_bytes"]
        findings.append(Finding(
            node="(query)", node_id=None, metric="hbmPressure",
            seconds=0.0,
            fraction=max(_FRACTION_FLOOR,
                         frac_used - _HBM_PRESSURE_FLOOR),
            detail=f"heartbeated into OOM territory: HBM at "
                   f"{frac_used:.0%} of the pool limit on "
                   f"{len(pressured)} of {len(hbs)} heartbeats",
            suggestion="lower spark.rapids.sql.batchSizeBytes or raise "
                       "spark.rapids.memory.gpu.allocFraction before "
                       "this becomes a spill storm or an OOM"))
    return findings


#: critical-path category -> actionable knob (the span-DAG analogue of
#: _node_suggestion); "other" and device_compute below the floor stay
#: silent — compute dominating the path is the HEALTHY profile
_CP_SUGGESTIONS = {
    "sync_wait": (
        "blocking device->host sync on the critical path — the measured "
        "ROADMAP item 1 cost; the static sync-site inventory at the end "
        "of this report names the files to fix"),
    "shuffle_transfer": (
        "shuffle dominates the path — attach a mesh "
        "(spark.rapids.tpu.shuffle.mode=ici) or enable cached writes so "
        "blocks stay device-resident"),
    "compile": (
        "XLA compile on the critical path — persist the compile tier "
        "(spark.rapids.tpu.compile.cacheDir) or raise "
        "batchRowsMinBucket to collapse shape buckets"),
    "semaphore_wait": (
        "tasks serialized on the device semaphore — raise "
        "spark.rapids.sql.concurrentGpuTasks or lower task parallelism"),
    "pipeline_queue_idle": (
        "the pipeline starved — raise "
        "spark.rapids.tpu.pipeline.prefetchDepth or speed up the "
        "producing stage"),
    "h2d_upload": (
        "host->device upload on the path — enable "
        "spark.rapids.tpu.scan.deviceCache.* so re-scanned batches skip "
        "the upload"),
    "spill": (
        "spill I/O on the path — raise "
        "spark.rapids.memory.gpu.allocFraction or lower "
        "spark.rapids.sql.batchSizeBytes"),
    "memory_pressure": (
        "spill-restore round-trips / OOM recovery on the path — the "
        "memory flight recorder's per-operator holders (this query's "
        "memory findings above, or /status \"memory\") name which "
        "operator pins the HBM that forces them; raise "
        "spark.rapids.memory.gpu.allocFraction or shrink that "
        "operator's batches"),
}


def _memory_findings(q, wall: float) -> List[Finding]:
    """v6 memory flight-recorder signals (utils/memprof.py): buffers
    leaked past query end, the operators holding HBM at the query's peak
    watermark, per-operator spill churn, and any OOM postmortems
    recorded while the query ran."""
    findings: List[Finding] = []
    ms = getattr(q, "memory_summary", None) or {}

    leaked = int(ms.get("leaked_bytes") or 0)
    if leaked:
        leaks = ms.get("leaked_buffers") or []
        worst = leaks[0] if leaks else {}
        findings.append(Finding(
            node=worst.get("operator") or "(query)",
            node_id=worst.get("node_id"),
            metric="leakedBytes", seconds=0.0, fraction=_FRACTION_FLOOR,
            detail=f"{len(leaks)} buffer(s) / {leaked} bytes still "
                   f"registered at query end — retained HBM that the "
                   f"next query pays for",
            suggestion="a buffer outlived its query — close spillable "
                       "handles (task_scope / SpillableDeviceTable) on "
                       "the named operator; srtpu-analyze memtrack finds "
                       "construction sites that never register"))

    peak = int(ms.get("peak_bytes") or 0)
    holders = ms.get("peak_holders") or {}
    if peak and holders:
        ranked = sorted(holders.items(), key=lambda kv: -kv[1])[:3]
        top_op, top_bytes = ranked[0]
        share = top_bytes / peak if peak else 0.0
        if share >= 0.5:
            detail = (f"held {share:.0%} of the query's peak HBM "
                      f"watermark ({top_bytes} of {peak} bytes)")
            if len(ranked) > 1:
                detail += " — next: " + ", ".join(
                    f"{op}={b}" for op, b in ranked[1:])
            findings.append(Finding(
                node=top_op, node_id=None, metric="peakHbmShare",
                seconds=0.0, fraction=_FRACTION_FLOOR,
                detail=detail,
                suggestion="this operator sets the memory high-water "
                           "mark — shrink its batches (spark.rapids.sql."
                           "batchSizeBytes) or spill its output eagerly "
                           "before it forces neighbours out"))

    per_op = ms.get("per_operator") or {}
    churn = sorted(((op, int(d.get("spilled_bytes") or 0))
                    for op, d in per_op.items()
                    if d.get("spilled_bytes")), key=lambda t: -t[1])
    if churn:
        total = sum(b for _, b in churn)
        op, b = churn[0]
        findings.append(Finding(
            node=op, node_id=None, metric="spillChurn",
            seconds=0.0, fraction=_FRACTION_FLOOR,
            detail=f"spill churn: {b} of {total} bytes spilled this "
                   f"query were this operator's buffers "
                   f"({len(churn)} operator(s) spilled)",
            suggestion="its buffers bounce between tiers — pin fewer of "
                       "them (smaller batches) or raise spark.rapids."
                       "memory.gpu.allocFraction so they stay resident"))

    for pm in getattr(q, "oom_postmortems", []) or []:
        # holders is a ranked {operator: bytes} mapping (insertion order
        # = rank); the first key is the top holder at failure time
        top_op = next(iter(pm.get("holders") or {}), None)
        findings.append(Finding(
            node=top_op or "(query)",
            node_id=None, metric="oomPostmortem",
            seconds=0.0, fraction=1.0,
            detail=f"device OOM at {pm.get('live_bytes', 0)} live bytes "
                   f"(peak {pm.get('peak_bytes', 0)}): "
                   f"{pm.get('context', '')[:120]}",
            suggestion=f"read the postmortem ({pm.get('path', '?')}) — "
                       "it ranks holders by operator, spill-tier "
                       "occupancy, and the last lifecycle events before "
                       "the failure"))
    return findings


def _critical_path_findings(cp: Optional[Dict],
                            wall: float) -> List[Finding]:
    if not cp or wall <= 0:
        return []
    out: List[Finding] = []
    for cat, sec in (cp.get("categories_s") or {}).items():
        suggest = _CP_SUGGESTIONS.get(cat)
        if suggest is None:
            continue  # device_compute / other: not actionable debt
        frac = float(sec) / wall
        if frac < _FRACTION_FLOOR:
            continue
        out.append(Finding(
            node="(critical-path)", node_id=None,
            metric=f"criticalPath.{cat}", seconds=float(sec),
            fraction=frac,
            detail=f"{cat} holds {frac:.0%} of the traced critical path "
                   f"({float(sec):.4f}s of {wall:.4f}s wall)",
            suggestion=suggest))
    return out


#: the async-first sync-wait budget: a query blocking more than 10% of
#: its wall on device->host syncs violates the ISSUE-18 acceptance
#: floor regardless of composition deltas — an absolute gate, matching
#: tools/compare.py SYNC_WAIT_GATE_FRAC
_SYNC_WAIT_GATE_FRAC = 0.10


def _sync_wait_gate_findings(cp: Optional[Dict], mv: Optional[Dict],
                             wall: float) -> List[Finding]:
    """The async-first budget gate: traced sync_wait over 10% of wall is
    a violation (not just a ranked share), and the finding names the
    heaviest movement-ledger funnel so the fix starts at a file:symbol.
    Needs the tracer's critical path; the ledger only sharpens the
    attribution."""
    if not cp or wall <= 0:
        return []
    sync_s = float((cp.get("categories_s") or {}).get("sync_wait", 0.0))
    frac = sync_s / wall
    if frac <= _SYNC_WAIT_GATE_FRAC:
        return []
    sites = (mv or {}).get("sites") or []
    top = max(sites, key=lambda s: float(s.get("wall_s") or 0.0)) \
        if sites else None
    where = (f"heaviest ledger funnel: {top['site']} "
             f"({float(top.get('wall_s') or 0.0):.4f}s, "
             f"{top.get('bytes', 0)} bytes)") if top else \
        "movement ledger off — enable spark.rapids.tpu.movement.enabled " \
        "for funnel attribution"
    return [Finding(
        node="(query)", node_id=None, metric="syncWaitGate",
        seconds=sync_s, fraction=frac,
        detail=f"sync wait holds {frac:.1%} of wall "
               f"({sync_s:.4f}s of {wall:.4f}s) — over the "
               f"{_SYNC_WAIT_GATE_FRAC:.0%} async-first budget; {where}",
        suggestion="route the named funnel through the batched "
                   "resolve_scalars / to_host_batched endpoints "
                   "(columnar/device.py) or defer the decision the sync "
                   "feeds — spark.rapids.tpu.async.enabled=false "
                   "localizes the stall for bisection")]


#: a partition holding more than 2x the mean rows of its exchange is
#: skewed enough to flag — the straggler partition alone bounds the
#: stage's wall time, so past 2x half the fleet idles behind it
_SKEW_FLAG_IMBALANCE = 2.0


def _skew_findings(q) -> List[Finding]:
    """v7 shuffle_skew records: exchanges whose output-partition row
    distribution is imbalanced past ``_SKEW_FLAG_IMBALANCE``. Surfaces
    the worst (exchange node, partition) pair per record — the straggler
    every downstream task waits on."""
    findings: List[Finding] = []
    for rec in getattr(q, "shuffle_skew", []) or []:
        rows = rec.get("rows") or {}
        imbalance = float(rows.get("imbalance") or 1.0)
        if imbalance <= _SKEW_FLAG_IMBALANCE:
            continue
        per_part = rec.get("per_partition_rows") or []
        worst_part = (max(range(len(per_part)), key=per_part.__getitem__)
                      if per_part else -1)
        findings.append(Finding(
            node=rec.get("name", "(exchange)"),
            node_id=rec.get("node_id"),
            metric="shuffleSkew", seconds=0.0,
            # rank among other findings by how lopsided the exchange is:
            # at 2x the straggler doubles the stage, at 4x quadruples it
            fraction=min(1.0, imbalance / 10.0),
            detail=f"partition {worst_part} holds {rows.get('max', 0)} "
                   f"rows vs p50 {rows.get('p50', 0)} across "
                   f"{rec.get('partitions', 0)} partitions "
                   f"({imbalance:.1f}x the mean) — every downstream task "
                   f"waits on it",
            suggestion="skewed partition key — raise spark.rapids.tpu."
                       "shuffle.partitions to dilute the hot key, "
                       "repartition on a higher-cardinality key, or "
                       "coalesce+rebalance upstream; salting the key "
                       "splits a single hot group"))
    return findings


def _recovery_findings(q) -> List[Finding]:
    """v8 recovery records: the query finished, but only because the
    runtime recovered from failures along the way — worker deaths,
    transport retries, shuffle recomputes, corrupted spill files. The
    result is correct; the latency and the underlying fault are the
    signal. Null/absent ``recovery`` (the healthy common case) emits
    nothing."""
    rec = getattr(q, "recovery", None) or {}
    if not any(rec.values()):
        return []
    findings: List[Finding] = []
    injected = bool(getattr(q, "faults", []))
    detail = ", ".join(f"{k}={v}" for k, v in sorted(rec.items()) if v)
    if rec.get("worker_deaths") or rec.get("task_resubmissions"):
        findings.append(Finding(
            node="(query)", node_id=None, metric="workerRecovery",
            seconds=0.0, fraction=min(1.0, 0.2 * rec.get(
                "worker_deaths", rec.get("task_resubmissions", 1))),
            detail=f"worker failures recovered mid-query ({detail})",
            suggestion="injected chaos — expected" if injected else
                       "workers died mid-query; check worker logs/rlimits "
                       "and spark.rapids.tpu.task.maxWorkerRespawns — "
                       "each respawn re-pays session + compile warmup"))
    if rec.get("transport_retries") or rec.get("transport_giveups"):
        findings.append(Finding(
            node="(query)", node_id=None, metric="transportRetries",
            seconds=0.0, fraction=min(1.0, 0.05 * rec.get(
                "transport_retries", 1)),
            detail=f"shuffle transport retried ({detail})",
            suggestion="injected chaos — expected" if injected else
                       "flaky shuffle network — each retry backs off up "
                       "to shuffle.tcp.retryMaxBackoffMs; check peer "
                       "liveness and raise retryAttempts only if the "
                       "fabric is genuinely lossy"))
    if rec.get("spill_corruptions"):
        findings.append(Finding(
            node="(query)", node_id=None, metric="spillCorruption",
            seconds=0.0, fraction=min(1.0, 0.25 * rec["spill_corruptions"]),
            detail=f"spilled blocks failed CRC32 on restore ({detail})",
            suggestion="injected chaos — expected" if injected else
                       "disk returned corrupt spill bytes — recompute "
                       "saved the query but the storage device is "
                       "suspect; check the spill dir's filesystem/disk "
                       "health (memory.disk.checksum caught this)"))
    if rec.get("shuffle_recomputes") and not findings:
        findings.append(Finding(
            node="(query)", node_id=None, metric="shuffleRecompute",
            seconds=0.0, fraction=min(1.0, 0.1 * rec["shuffle_recomputes"]),
            detail=f"shuffle blocks recomputed from lineage ({detail})",
            suggestion="injected chaos — expected" if injected else
                       "missing shuffle blocks recomputed — upstream "
                       "stages re-ran; check for evicted/removed "
                       "map outputs"))
    return findings


def _retry_findings(q) -> List[Finding]:
    """v9 oom_retry records: the query survived device OOM, but every
    retry re-pays the failed dispatch and every split halves the batch
    (re-paying compile for the half shape). Rank by how hard the ladder
    had to work; a split storm means batches are sized far above what
    HBM can hold under the current concurrency."""
    records = getattr(q, "oom_retries", []) or []
    if not records:
        return []
    injected = bool(getattr(q, "faults", []))
    findings: List[Finding] = []
    retries = sum(r.get("attempts", 0) for r in records)
    splits = sum(r.get("splits", 0) for r in records)
    spilled = sum(r.get("spilled_bytes", 0) for r in records)
    scopes = ", ".join(sorted({r.get("scope", "?") for r in records}))
    if splits >= 2:
        worst = max(records, key=lambda r: r.get("splits", 0))
        findings.append(Finding(
            node="(query)", node_id=None, metric="oomSplitStorm",
            seconds=0.0, fraction=min(1.0, 0.25 * splits),
            detail=f"split-and-retry storm: {splits} splits across "
                   f"scopes [{scopes}] (worst: '{worst.get('scope')}' "
                   f"x{worst.get('splits', 0)})",
            suggestion="injected chaos — expected" if injected else
                       "batches repeatedly halved to fit HBM — lower "
                       "spark.rapids.sql.batchSizeBytes (cheaper than "
                       "retry-time splitting, which re-pays the failed "
                       "dispatch plus a compile per half shape) or lower "
                       "spark.rapids.sql.concurrentGpuTasks"))
    elif retries or splits:
        findings.append(Finding(
            node="(query)", node_id=None, metric="oomRetries",
            seconds=0.0, fraction=min(1.0, 0.1 * (retries + splits)),
            detail=f"device OOM recovered: {retries} retries, {splits} "
                   f"splits, {spilled} bytes spilled (scopes [{scopes}])",
            suggestion="injected chaos — expected" if injected else
                       "HBM pressure forced spill-and-retry — raise "
                       "spark.rapids.memory.gpu.allocFraction headroom, "
                       "lower spark.rapids.sql.batchSizeBytes, or lower "
                       "spark.rapids.sql.concurrentGpuTasks"))
    return findings


def _fallback_findings(q) -> List[Finding]:
    """Schema-v10 host-fallback records: batches that terminally failed
    on the device and re-executed through the host engine. Correct
    results, but each batch pays download + host execute + upload."""
    fallbacks = getattr(q, "fallbacks", []) or []
    if not fallbacks:
        return []
    injected = bool(getattr(q, "faults", []))
    ops = sorted({f.get("operator", "?") for f in fallbacks})
    classes = sorted({f.get("failure_class", "?") for f in fallbacks})
    down = sum(f.get("bytes_down", 0) for f in fallbacks)
    wall = sum(f.get("wall_s", 0.0) for f in fallbacks)
    return [Finding(
        node="(query)", node_id=None, metric="hostFallbacks",
        seconds=wall, fraction=min(1.0, 0.2 * len(fallbacks)),
        detail=f"{len(fallbacks)} batch(es) re-executed on the host "
               f"engine: operators [{', '.join(ops)}], failure classes "
               f"[{', '.join(classes)}], {down} bytes downloaded",
        suggestion="injected chaos — expected" if injected else
                   "the device path is failing terminally for these "
                   "operators — repeated failures quarantine them to "
                   "host at plan time (see explain); inspect the "
                   "fallback records' failure_class to decide whether "
                   "to fix the operator or disable it via "
                   "spark.rapids.sql.exec.* ahead of the quarantine")]


def _movement_findings(q, wall: float) -> List[Finding]:
    """Schema-v11 movement_summary records: the data-movement ledger's
    per-query aggregation. A round trip (batch downloaded then
    re-uploaded within the query) is the prime async-first target; a
    single funnel holding a measurable share of wall is the next."""
    mv = getattr(q, "movement_summary", None) or {}
    totals = mv.get("totals") or {}
    findings: List[Finding] = []
    rt = int(totals.get("round_trips") or 0)
    if rt:
        findings.append(Finding(
            node="(query)", node_id=None, metric="hostRoundTrips",
            seconds=0.0, fraction=min(1.0, 0.1 * rt),
            detail=f"{rt} batch(es) made a host round trip (downloaded "
                   f"then re-uploaded within the query; "
                   f"{totals.get('d2h_bytes', 0)} bytes D2H / "
                   f"{totals.get('h2d_bytes', 0)} bytes H2D total)",
            suggestion="device residency lost mid-plan — keep the "
                       "intermediate on device (cached shuffle writes, "
                       "device-resident exchange) instead of bouncing it "
                       "through host memory"))
    if wall <= 0:
        return findings
    from ..utils import movement as _movement
    for s in (mv.get("sites") or []):
        sec = float(s.get("wall_s") or 0.0)
        frac = sec / wall
        if frac < _FRACTION_FLOOR:
            continue
        info = _movement.site_info(s.get("site", ""))
        findings.append(Finding(
            node=s.get("site", "?").split("::")[-1], node_id=None,
            metric="movementWall", seconds=sec, fraction=frac,
            detail=f"{s.get('direction')} funnel moved "
                   f"{s.get('bytes', 0)} bytes over {s.get('count', 0)} "
                   f"crossing(s) ({s.get('blocking_count', 0)} blocking) "
                   f"— {sec:.4f}s of wall",
            suggestion=info.hint if info is not None else
                       "un-ledgered crossing — route it through a "
                       "utils/movement.py funnel for attribution"))
    return findings


#: measured-wall straggler gate: the slowest (shuffle, partition, tier)
#: triple must exceed the p50 partition wall by this factor AND clear
#: the absolute floor below before it flags — complements the v7
#: row-count skew records, which can't see a balanced partition
#: crawling on a slow link
_STRAGGLER_FLAG_SKEW = 4.0
_STRAGGLER_FLAG_WALL_S = 0.05


def _shuffle_findings(q, wall: float) -> List[Finding]:
    """Schema-v12 shuffle_summary records: the shuffle observatory's
    per-query aggregation. A measured-time straggler (one partition's
    transfer wall far above the p50) bounds the stage no matter how
    balanced the row counts look; retries and deep publish queues are
    transport-tier backpressure."""
    sh = getattr(q, "shuffle_summary", None) or {}
    findings: List[Finding] = []
    st = sh.get("straggler") or {}
    skew = float(st.get("skew") or 0.0)
    slowest = float(st.get("slowest_wall_s") or 0.0)
    if skew >= _STRAGGLER_FLAG_SKEW and slowest >= _STRAGGLER_FLAG_WALL_S:
        worst = st.get("worst") or {}
        findings.append(Finding(
            node="(query)", node_id=None, metric="shuffleStraggler",
            seconds=slowest,
            fraction=min(1.0, slowest / wall) if wall > 0
            else _FRACTION_FLOOR,
            detail=f"slowest shuffle partition took {slowest:.4f}s vs "
                   f"p50 {float(st.get('p50_wall_s') or 0.0):.4f}s "
                   f"({skew:.1f}x) — shuffle {worst.get('shuffle_id')} "
                   f"partition {worst.get('partition')} on the "
                   f"{worst.get('tier')} tier; the stage waits on it",
            suggestion="measured-time straggler — repartition on a "
                       "higher-cardinality key or salt the hot key to "
                       "split the heavy partition; if row counts are "
                       "balanced (no shuffleSkew finding), the slow "
                       "link/peer itself is the suspect"))
    totals = sh.get("totals") or {}
    retries = int(totals.get("retries") or 0)
    depth = int(totals.get("max_queue_depth") or 0)
    if retries:
        findings.append(Finding(
            node="(query)", node_id=None, metric="shuffleBackpressure",
            seconds=0.0, fraction=min(1.0, 0.05 * retries),
            detail=f"{retries} shuffle transfer retr(y/ies), max "
                   f"publish-queue depth {depth} — peers answered late "
                   "or the map side outran the reducers",
            suggestion="transport-tier backpressure — check peer "
                       "liveness; raise shuffle.tcp.retryAttempts only "
                       "if the fabric is genuinely lossy, and lower map "
                       "parallelism if the publish queue keeps growing"))
    return findings


def _diagnose_query(q, heartbeats=None) -> Optional[QueryDiagnosis]:
    wall = getattr(q, "wall_s", 0.0)
    if wall <= 0 or getattr(q, "error", None):
        return None
    from .profiler import compute_self_times
    nodes = q.nodes
    findings: List[Finding] = []
    self_s = compute_self_times(nodes)

    # 1. per-node self wall time — the primary (node, metric) ranking
    for n in nodes:
        s = self_s.get(n["node_id"], 0.0)
        frac = s / wall
        if frac < _FRACTION_FLOOR:
            continue
        metrics = n.get("metrics") or {}
        findings.append(Finding(
            node=n["name"], node_id=n["node_id"], metric="wall",
            seconds=s, fraction=frac,
            detail=f"self time {s:.4f}s over {n.get('batches', 0)} batches "
                   f"/ {n.get('rows', 0)} rows",
            suggestion=_node_suggestion(n["name"], metrics)))

    # 2. per-node attributed signals from the metric snapshots
    for n in nodes:
        metrics = n.get("metrics") or {}
        for key, label, suggest in (
            (M.COMPILE_TIME, "XLA compile",
             "warm the query first or enable the persistent compilation "
             "cache (jax_compilation_cache_dir); recurring compiles mean "
             "shape churn — raise spark.rapids.tpu.batchRowsMinBucket"),
            (M.UPLOAD_TIME, "host->device upload",
             "enable/raise spark.rapids.tpu.scan.deviceCache.* so "
             "re-scanned batches skip the upload"),
            (M.DOWNLOAD_TIME, "device->host download",
             "keep downstream operators on device (check explain('tpu'))"),
            (M.SHUFFLE_PARTITION_TIME, "host shuffle partitioning",
             "attach a mesh (spark.rapids.tpu.shuffle.mode=ici) or force "
             "the device-local tier (mode=local)"),
            (M.PIPELINE_WAIT, "pipeline stall (starved prefetch queue)",
             "the upstream stage cannot keep this operator fed — raise "
             "spark.rapids.tpu.pipeline.prefetchDepth / taskPool, or "
             "speed up the producing stage (see its own findings); check "
             "the prefetchQueueDepth histogram: p50 of 0 means the "
             "producer is the bottleneck"),
        ):
            v = metrics.get(key, 0.0)
            if isinstance(v, dict):
                continue
            frac = v / wall
            if frac >= _FRACTION_FLOOR:
                detail = f"{label} {v:.4f}s inside this node"
                if key == M.PIPELINE_WAIT:
                    depth = metrics.get(M.PREFETCH_QUEUE_DEPTH)
                    if isinstance(depth, dict) and depth.get("count"):
                        detail += (f" (queue depth p50="
                                   f"{depth.get('p50', 0):.0f} over "
                                   f"{depth['count']} polls)")
                findings.append(Finding(
                    node=n["name"], node_id=n["node_id"], metric=key,
                    seconds=v, fraction=frac,
                    detail=detail,
                    suggestion=suggest))
        spilled = metrics.get(M.SPILL_BYTES, 0)
        if not isinstance(spilled, dict) and spilled:
            findings.append(Finding(
                node=n["name"], node_id=n["node_id"], metric=M.SPILL_BYTES,
                seconds=0.0, fraction=_FRACTION_FLOOR,
                detail=f"{spilled} bytes spilled while this node ran",
                suggestion="raise spark.rapids.memory.gpu.allocFraction or "
                           "lower spark.rapids.sql.batchSizeBytes"))

    # 3. recompile churn from the kernel table: many unique signatures
    # landing on one operator = per-shape recompiles
    per_node: Dict[str, List[Dict]] = {}
    for k in getattr(q, "kernels", []):
        name = k.get("node_name") or "(unattributed)"
        per_node.setdefault(name, []).append(k)
    for name, entries in per_node.items():
        compiles = sum(e.get("compiles", 0) for e in entries)
        compile_s = sum(e.get("compile_s", 0.0) for e in entries)
        frac = compile_s / wall
        if compiles >= 4 and frac >= _FRACTION_FLOOR:
            findings.append(Finding(
                node=name, node_id=entries[0].get("node_id"),
                metric="recompiles", seconds=compile_s, fraction=frac,
                detail=f"dominated by recompiles: {len(entries)} unique "
                       f"signatures / {compiles} compiles "
                       f"({compile_s:.2f}s) for 1 operator",
                suggestion="shape-bucket churn — raise spark.rapids.tpu."
                           "batchRowsMinBucket so batch capacities collapse "
                           "onto fewer buckets"))

    # 3b. bucket churn: kernel-table signatures for one operator that are
    # IDENTICAL once numerals are stripped compiled the same computation
    # for different shapes — direct evidence the shape-bucket policy
    # failed to collapse this operator's partitions onto one capacity
    findings.extend(_bucket_churn_findings(per_node, wall))

    # 4. query-level process-counter deltas (v2-compatible: works without
    # node metrics or kernel records)
    stats = getattr(q, "stats", {}) or {}
    compile_s = stats.get("compile_cache_compile_seconds", 0.0)
    if compile_s / wall >= 0.3:
        findings.append(Finding(
            node="(query)", node_id=None, metric="xlaCompileSeconds",
            seconds=compile_s, fraction=compile_s / wall,
            detail=f"XLA compile is {compile_s / wall:.0%} of wall — cold "
                   "compile cache",
            suggestion="warm up once per session or persist compiles "
                       "(jax_compilation_cache_dir)"))
    sem_wait = getattr(q, "semaphore_wait_s", 0.0)
    if sem_wait / wall >= 0.25:
        findings.append(Finding(
            node="(query)", node_id=None, metric=M.SEMAPHORE_WAIT_TIME,
            seconds=sem_wait, fraction=sem_wait / wall,
            detail=f"semaphore wait is {sem_wait / wall:.0%} of wall — "
                   "tasks serialized on the chip",
            suggestion="raise spark.rapids.sql.concurrentGpuTasks or lower "
                       "task parallelism"))
    if any((getattr(q, "spill_count", {}) or {}).values()):
        findings.append(Finding(
            node="(query)", node_id=None, metric="spills",
            seconds=0.0, fraction=_FRACTION_FLOOR,
            detail=f"device memory pressure (spills {q.spill_count})",
            suggestion="raise spark.rapids.memory.gpu.allocFraction, lower "
                       "spark.rapids.sql.batchSizeBytes, or raise "
                       "spark.rapids.memory.host.spillStorageSize"))

    # 5. live-health heartbeats (schema v4): stall windows + HBM pressure
    findings.extend(_heartbeat_findings(q, heartbeats or [], wall))

    # 6. critical-path attribution (schema v5): measured category costs
    # from the traced span DAG — unlike the per-node signals above these
    # sum to the whole query wall, so a category that dominates here IS
    # the bottleneck, not merely a contributor
    cp = getattr(q, "critical_path", None)
    findings.extend(_critical_path_findings(cp, wall))

    # 7. memory flight recorder (schema v6): leaks, peak-HBM holders,
    # per-operator spill churn, OOM postmortems
    findings.extend(_memory_findings(q, wall))

    # 8. partition skew (schema v7): exchanges whose output partitions
    # are row-imbalanced past 2x — the straggler partition that bounds
    # the downstream stage
    findings.extend(_skew_findings(q))

    # 9. recovery ledger (schema v8): the query survived failures —
    # worker deaths, transport retries, corrupt spills — rank what the
    # runtime had to absorb
    findings.extend(_recovery_findings(q))

    # 10. OOM retry ladder (schema v9): retries, splits, and split storms
    # the query absorbed to stay under HBM
    findings.extend(_retry_findings(q))

    # 11. host fallbacks (schema v10): batches the degradation layer
    # re-executed on the host engine after terminal device failures
    findings.extend(_fallback_findings(q))

    # 12. data-movement ledger (schema v11): round-trip batches and the
    # funnels whose measured crossings hold a share of the query wall
    findings.extend(_movement_findings(q, wall))

    # 13. the async-first budget gate: sync wait past 10% of wall is a
    # hard violation, attributed to the heaviest movement-ledger funnel
    findings.extend(_sync_wait_gate_findings(
        cp, getattr(q, "movement_summary", None), wall))

    # 14. shuffle observatory (schema v12): measured-time stragglers and
    # transport-tier backpressure from the per-tier transfer telemetry
    findings.extend(_shuffle_findings(q, wall))

    findings.sort(key=lambda f: -f.fraction)
    return QueryDiagnosis(q.query_id, wall, findings, critical_path=cp,
                          movement=getattr(q, "movement_summary", None),
                          shuffle=getattr(q, "shuffle_summary", None))


def diagnose_app(app, path: str = "") -> DiagnoseReport:
    """Diagnose a loaded AppReplay (tools/eventlog.py)."""
    queries = []
    heartbeats = getattr(app, "heartbeats", [])
    for qid in sorted(app.queries):
        d = _diagnose_query(app.queries[qid], heartbeats)
        if d is not None:
            queries.append(d)
    return DiagnoseReport(path or getattr(app, "path", ""), queries)


def diagnose_path(path: str) -> DiagnoseReport:
    """Diagnose one event-log JSONL file."""
    from .eventlog import load_event_log
    return diagnose_app(load_event_log(path), path)


def _expand_paths(args: List[str]) -> List[str]:
    out: List[str] = []
    for a in args:
        if os.path.isdir(a):
            out.extend(sorted(glob.glob(os.path.join(a, "*.jsonl"))))
        else:
            out.append(a)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.diagnose",
        description="Ranked bottleneck report from an event log "
                    "(AutoTuner analogue)")
    ap.add_argument("paths", nargs="+",
                    help="event-log .jsonl file(s) or directories of them")
    ap.add_argument("--top", type=int, default=3,
                    help="findings reported per query (default 3)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable JSON instead of text")
    ap.add_argument("--out", default="",
                    help="also write the report to this file")
    ns = ap.parse_args(argv)
    paths = _expand_paths(ns.paths)
    if not paths:
        print("no event logs found")
        return 2
    chunks = []
    for p in paths:
        rep = diagnose_path(p)
        chunks.append(rep.to_json(ns.top) if ns.json
                      else rep.summary(ns.top))
    text = "\n\n".join(chunks)
    print(text)
    if ns.out:
        with open(ns.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
