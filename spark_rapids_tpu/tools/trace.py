"""Distributed-trace collector: merge per-process Chrome traces onto one
timeline + per-query critical-path attribution.

Each process (driver and every ProcessCluster worker) records spans into
its own tracer ring (utils/tracing.py) against its own
``time.perf_counter()`` epoch. Cross-process identity travels as a
TraceContext (trace_id, parent span id, query_id) inside task envelopes
and the SRTC shuffle wire header, so a worker span's ``parent_span_id``
points at a driver span — but the TIMESTAMPS live in per-process clock
domains. This module puts them on one timeline:

1. every tracer snapshots ``epoch_unix = time.time()`` at the same
   instant as its perf_counter epoch, anchoring relative timestamps to
   that process's wall clock;
2. ProcessCluster estimates each worker's wall-clock offset against the
   driver with an NTP-style min-RTT handshake (the "clock" envelope
   kind) and stamps ``clock_offset_s`` into collected traces;
3. ``merge_process_traces`` shifts every event by
   ``(epoch_unix - clock_offset_s) - driver_epoch`` so all spans share
   the driver's timebase, assigns deterministic pids, and emits one
   Perfetto-loadable Chrome trace with per-process metadata rows.

Critical-path attribution walks the merged span DAG for one trace_id:
each span's SELF time (its duration minus the union of its children's
intervals, clipped to the parent) is attributed to a category —
device compute, sync wait, shuffle transfer, compile, semaphore wait,
pipeline-queue idle, ... — so the categories sum to exactly the root
query span's wall time. The ranked path is the greedy longest chain
root -> leaf, the place an optimiser should look first (reference: the
qualification/profiling tool ranks stages by task time the same way,
tools/qualification in the plugin repo).

CLI::

    python -m spark_rapids_tpu.tools.trace merge <dir-or-files...> \
        [-o merged.json] [--trace-id HEX]
    python -m spark_rapids_tpu.tools.trace critical-path <merged.json> \
        [--trace-id HEX]
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["merge_process_traces", "load_process_traces",
           "critical_path", "critical_path_from_tracer", "CriticalPath",
           "CATEGORY_BY_CAT", "span_category"]

# ---------------------------------------------------------------------------
# category attribution: tracer cat -> critical-path bucket
# ---------------------------------------------------------------------------
#: tracer category -> critical-path cost bucket. Structural spans
#: (query/stage/task) fall through to "other": their SELF time is
#: scheduling/driver overhead not owned by any subsystem.
CATEGORY_BY_CAT: Dict[str, str] = {
    "operator": "device_compute",
    "compile": "compile",
    "semaphore": "semaphore_wait",
    "shuffle": "shuffle_transfer",
    "pipeline": "pipeline_queue_idle",
    "download": "sync_wait",      # blocking D2H sync (ROADMAP item 1)
    "upload": "h2d_upload",
    "spill": "spill",
    # spill-restore + OOM-recovery spans (memory/catalog.py) — time the
    # query lost to HBM pressure, distinct from proactive spill writes
    "memory": "memory_pressure",
}


def span_category(cat: str) -> str:
    return CATEGORY_BY_CAT.get(cat, "other")


# ---------------------------------------------------------------------------
# loading + merging
# ---------------------------------------------------------------------------
def load_process_traces(sources: Iterable[str]) -> List[dict]:
    """Load per-process Chrome trace dicts from files and/or directories
    (directories contribute every ``trace-*.json`` inside, sorted)."""
    paths: List[str] = []
    for src in sources:
        if os.path.isdir(src):
            paths.extend(sorted(
                os.path.join(src, n) for n in os.listdir(src)
                if n.startswith("trace-") and n.endswith(".json")))
        else:
            paths.append(src)
    traces = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            traces.append(json.load(f))
    return traces


def _proc_key(trace: dict) -> Tuple[int, str]:
    od = trace.get("otherData", {})
    role = od.get("role", "")
    # driver first, then workers by name — deterministic merge order no
    # matter what order the files were read in
    return (0 if role == "driver" else 1, str(od.get("process_name", "")))


def merge_process_traces(traces: List[dict],
                         trace_id: Optional[str] = None) -> dict:
    """Merge per-process Chrome traces into ONE Perfetto-loadable trace.

    - clock alignment: every event is shifted into the driver's wall
      clock using the process's ``epoch_unix`` anchor and its
      ``clock_offset_s`` handshake estimate (driver offset = 0);
    - deterministic pids: processes are sorted (driver first, then by
      process name) and numbered 1..N, with ``process_name`` /
      ``process_sort_index`` metadata rows;
    - drop accounting: a process whose window dropped events gets a
      ``trace_truncated`` instant at the front of its row and a
      ``truncated`` flag in ``otherData.processes`` — a merged timeline
      never silently hides a wrapped ring;
    - ``trace_id`` filters to one query's span DAG (metadata rows are
      kept only for processes that still contribute events).
    """
    ordered = sorted(traces, key=_proc_key)
    ref_epoch = None
    for t in ordered:
        od = t.get("otherData", {})
        if od.get("role") == "driver" and "epoch_unix" in od:
            ref_epoch = float(od["epoch_unix"])
            break
    if ref_epoch is None and ordered:
        ref_epoch = float(
            ordered[0].get("otherData", {}).get("epoch_unix", 0.0))

    events: List[dict] = []
    processes: List[dict] = []
    for idx, t in enumerate(ordered):
        od = t.get("otherData", {})
        pid = idx + 1
        name = str(od.get("process_name", f"process-{pid}"))
        offset = float(od.get("clock_offset_s", 0.0))
        epoch = float(od.get("epoch_unix", ref_epoch or 0.0))
        # worker wall = epoch + ts; driver-clock equivalent subtracts the
        # estimated (worker_wall - driver_wall) offset
        shift_us = ((epoch - offset) - (ref_epoch or 0.0)) * 1e6
        dropped = int(od.get("dropped_events", 0))
        kept: List[dict] = []
        for ev in t.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue  # re-emitted below with merged pids
            if trace_id is not None \
                    and ev.get("args", {}).get("trace_id") != trace_id:
                continue
            out = dict(ev)
            out["pid"] = pid
            out["ts"] = round(float(ev.get("ts", 0.0)) + shift_us, 3)
            kept.append(out)
        if trace_id is not None and not kept:
            continue  # process contributed nothing to this query
        first_ts = min((e["ts"] for e in kept), default=0.0)
        if dropped > 0:
            kept.append({
                "name": "trace_truncated", "cat": "health", "ph": "i",
                "ts": round(first_ts, 3), "pid": pid, "tid": 0, "s": "p",
                "args": {"dropped_events": dropped,
                         "process_name": name}})
        events.extend(kept)
        processes.append({
            "pid": pid, "process_name": name,
            "role": od.get("role", "unknown"),
            "clock_offset_s": offset, "epoch_unix": epoch,
            "dropped_events": dropped, "truncated": dropped > 0,
            "events": len(kept)})
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": name}})
        events.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"sort_index": idx}})

    events.sort(key=lambda e: (e.get("ph") != "M", e.get("pid", 0),
                               e.get("ts", 0.0), e.get("name", "")))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "spark-rapids-tpu",
            "merged": True,
            "clock_aligned": True,
            "reference_epoch_unix": ref_epoch or 0.0,
            "trace_id_filter": trace_id,
            "processes": processes,
            "truncated_processes": [p["process_name"] for p in processes
                                    if p["truncated"]],
        },
    }


# ---------------------------------------------------------------------------
# critical-path attribution
# ---------------------------------------------------------------------------
class _Span:
    __slots__ = ("name", "cat", "ts", "dur", "span_id", "parent_id", "pid")

    def __init__(self, name, cat, ts, dur, span_id, parent_id, pid):
        self.name = name
        self.cat = cat
        self.ts = ts
        self.dur = dur
        self.span_id = span_id
        self.parent_id = parent_id
        self.pid = pid

    @property
    def end(self):
        return self.ts + self.dur


class CriticalPath:
    """Per-query wall-time attribution over the merged span DAG.

    ``categories`` maps cost bucket -> seconds of SELF time summed over
    the tree (children clipped to parents). With serial children the
    buckets sum to the root span's wall time exactly (coverage 1.0);
    concurrent children (parallel partition drains) overlap in wall
    time, so coverage reads as parallel busy time and can exceed 1.0 —
    either way the acceptance bar is coverage >= 0.95. ``ranked_path``
    is the greedy longest chain from the query root to a leaf."""

    def __init__(self, trace_id: str, total_s: float,
                 categories: Dict[str, float],
                 ranked_path: List[Dict],
                 span_count: int):
        self.trace_id = trace_id
        self.total_s = total_s
        self.categories = categories
        self.ranked_path = ranked_path
        self.span_count = span_count

    @property
    def sync_wait_frac(self) -> float:
        if self.total_s <= 0:
            return 0.0
        return self.categories.get("sync_wait", 0.0) / self.total_s

    @property
    def coverage(self) -> float:
        """Fraction of the root wall time the categories account for:
        1.0 by construction for serial children, above 1.0 when sibling
        spans ran concurrently (parallel busy time). The acceptance bar
        is >= 0.95."""
        if self.total_s <= 0:
            return 0.0
        return sum(self.categories.values()) / self.total_s

    def to_dict(self) -> Dict:
        total = self.total_s
        fractions = {k: (v / total if total > 0 else 0.0)
                     for k, v in self.categories.items()}
        return {
            "trace_id": self.trace_id,
            "total_s": round(total, 6),
            "span_count": self.span_count,
            "categories_s": {k: round(v, 6)
                             for k, v in sorted(self.categories.items())},
            "fractions": {k: round(v, 4)
                          for k, v in sorted(fractions.items())},
            "sync_wait_frac": round(self.sync_wait_frac, 4),
            "coverage": round(self.coverage, 4),
            "ranked_path": self.ranked_path,
        }

    def render(self) -> str:
        lines = [f"critical path for trace {self.trace_id} "
                 f"({self.total_s * 1e3:.2f} ms wall, "
                 f"{self.span_count} spans)"]
        total = self.total_s or 1.0
        for cat, sec in sorted(self.categories.items(),
                               key=lambda kv: -kv[1]):
            lines.append(f"  {cat:<22} {sec * 1e3:10.3f} ms "
                         f"{100.0 * sec / total:6.2f}%")
        lines.append("  ranked path (longest chain):")
        for i, hop in enumerate(self.ranked_path):
            lines.append(f"    {'  ' * i}{hop['name']} "
                         f"[{hop['category']}] "
                         f"{hop['dur_s'] * 1e3:.3f} ms")
        return "\n".join(lines)


def _extract_spans(events: Iterable[dict],
                   trace_id: Optional[str]) -> List[_Span]:
    spans = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args", {})
        sid = args.get("span_id")
        if sid is None:
            continue
        if trace_id is not None and args.get("trace_id") != trace_id:
            continue
        spans.append(_Span(ev.get("name", "?"), ev.get("cat", "misc"),
                           float(ev.get("ts", 0.0)),
                           float(ev.get("dur", 0.0)),
                           int(sid), args.get("parent_span_id"),
                           ev.get("pid", 0)))
    return spans


def _union_len(ivals: List[Tuple[float, float]]) -> float:
    if not ivals:
        return 0.0
    ivals.sort()
    total = 0.0
    cur_lo, cur_hi = ivals[0]
    for lo, hi in ivals[1:]:
        if lo > cur_hi:
            total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    total += cur_hi - cur_lo
    return total


def critical_path(events: Iterable[dict],
                  trace_id: Optional[str] = None) -> Optional[CriticalPath]:
    """Attribute one query's wall time over its span DAG.

    ``events`` are Chrome-format event dicts (merged or single-process).
    The root is the ``query`` span of the trace (falling back to the
    longest parentless span); spans whose parent never made it into the
    ring (dropped, or a process that wasn't collected) attach under the
    root so their time is still attributed rather than lost."""
    spans = _extract_spans(events, trace_id)
    if not spans:
        return None
    trace_label = trace_id if trace_id is not None else "(all)"
    ids = {s.span_id for s in spans}
    root_candidates = [s for s in spans if s.parent_id not in ids]
    by_id: Dict[int, _Span] = {s.span_id: s for s in spans}
    children: Dict[int, List[_Span]] = {}
    for s in spans:
        if s.parent_id in by_id and s.parent_id != s.span_id:
            children.setdefault(s.parent_id, []).append(s)
    if not root_candidates:
        # cycles / all-parented (shouldn't happen): longest span wins
        root_candidates = [max(spans, key=lambda s: s.dur)]
    roots_q = [s for s in root_candidates if s.cat == "query"]
    root = max(roots_q or root_candidates, key=lambda s: s.dur)
    # orphans: parentless spans other than the root adopt the root, so
    # their cost is attributed instead of silently dropped
    orphans = [s for s in root_candidates if s.span_id != root.span_id]
    if orphans:
        children.setdefault(root.span_id, []).extend(orphans)

    categories: Dict[str, float] = {}
    visited = set()

    def attribute(span: _Span, lo: float, hi: float) -> None:
        if span.span_id in visited:
            return
        visited.add(span.span_id)
        lo = max(lo, span.ts)
        hi = min(hi, span.end)
        if hi <= lo:
            return
        kids = children.get(span.span_id, [])
        clipped = []
        for k in kids:
            klo, khi = max(lo, k.ts), min(hi, k.end)
            if khi > klo:
                clipped.append((klo, khi))
        self_time = (hi - lo) - _union_len(clipped)
        if self_time > 0:
            cat = span_category(span.cat)
            categories[cat] = categories.get(cat, 0.0) + self_time
        for k in kids:
            attribute(k, lo, hi)

    attribute(root, root.ts, root.end)

    # greedy longest chain root -> leaf (each hop: the child covering the
    # most of its parent's window)
    ranked: List[Dict] = []
    node, lo, hi = root, root.ts, root.end
    chain_seen = set()
    while node is not None and node.span_id not in chain_seen:
        chain_seen.add(node.span_id)
        lo, hi = max(lo, node.ts), min(hi, node.end)
        ranked.append({"name": node.name, "cat": node.cat,
                       "category": span_category(node.cat),
                       "dur_s": round(max(hi - lo, 0.0) / 1e6, 6),
                       "pid": node.pid})
        best, best_len = None, 0.0
        for k in children.get(node.span_id, []):
            klen = min(hi, k.end) - max(lo, k.ts)
            if klen > best_len:
                best, best_len = k, klen
        node = best
    # µs -> seconds
    categories_s = {k: v / 1e6 for k, v in categories.items()}
    return CriticalPath(trace_label, root.dur / 1e6, categories_s,
                        ranked, len(spans))


def critical_path_from_tracer(tracer,
                              trace_id: str) -> Optional[CriticalPath]:
    """Critical path over the LIVE in-process tracer ring (driver side;
    the eventlog's query_end hook) — no export round trip."""
    events = [e.to_chrome(pid=os.getpid()) for e in tracer.events()]
    return critical_path(events, trace_id)


def query_trace_ids(events: Iterable[dict]) -> List[Tuple[str, float]]:
    """(trace_id, query-span duration seconds) for every query span
    present, longest first — the pick list for critical-path reports."""
    out = []
    for ev in events:
        if ev.get("ph") == "X" and ev.get("cat") == "query":
            tid = ev.get("args", {}).get("trace_id")
            if tid:
                out.append((tid, float(ev.get("dur", 0.0)) / 1e6))
    out.sort(key=lambda kv: -kv[1])
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def _cmd_merge(argv: List[str]) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools.trace merge",
        description="Merge per-process Chrome traces into one "
                    "Perfetto-loadable timeline.")
    ap.add_argument("sources", nargs="+",
                    help="trace-*.json files and/or directories of them")
    ap.add_argument("-o", "--output", default="merged-trace.json")
    ap.add_argument("--trace-id", default=None,
                    help="keep only one query's spans")
    ns = ap.parse_args(argv)
    traces = load_process_traces(ns.sources)
    if not traces:
        print("no input traces found")
        return 1
    merged = merge_process_traces(traces, trace_id=ns.trace_id)
    with open(ns.output, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    procs = merged["otherData"]["processes"]
    trunc = merged["otherData"]["truncated_processes"]
    print(f"merged {len(procs)} process traces "
          f"({sum(p['events'] for p in procs)} events) -> {ns.output}")
    for p in procs:
        flag = "  [TRUNCATED: %d spans dropped]" % p["dropped_events"] \
            if p["truncated"] else ""
        print(f"  pid {p['pid']}: {p['process_name']:<16} "
              f"role={p['role']:<10} offset={p['clock_offset_s']:+.6f}s "
              f"events={p['events']}{flag}")
    return 0


def _cmd_critical_path(argv: List[str]) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="spark_rapids_tpu.tools.trace critical-path",
        description="Per-query critical-path attribution over a "
                    "(merged) Chrome trace.")
    ap.add_argument("trace", help="merged trace JSON")
    ap.add_argument("--trace-id", default=None,
                    help="query to attribute (default: every query span)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable JSON")
    ns = ap.parse_args(argv)
    with open(ns.trace, "r", encoding="utf-8") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", [])
    tids = [ns.trace_id] if ns.trace_id else \
        [t for t, _ in query_trace_ids(events)]
    if not tids:
        print("no query spans with a trace_id found")
        return 1
    out = []
    for tid in tids:
        cp = critical_path(events, tid)
        if cp is None:
            continue
        out.append(cp)
    if ns.json:
        print(json.dumps([cp.to_dict() for cp in out], indent=2))
    else:
        for cp in out:
            print(cp.render())
            print()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import sys
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m spark_rapids_tpu.tools.trace "
              "{merge,critical-path} ...")
        return 0 if argv else 1
    cmd, rest = argv[0], argv[1:]
    if cmd == "merge":
        return _cmd_merge(rest)
    if cmd in ("critical-path", "critical_path"):
        return _cmd_critical_path(rest)
    print(f"unknown subcommand: {cmd!r} (expected merge | critical-path)")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
