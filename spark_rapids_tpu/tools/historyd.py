"""History-server UI: browse the persistent run store over HTTP.

The Spark History Server analogue for the store ``tools/history.py``
owns, in the ``tools/statusd.py`` style: stdlib ``http.server`` only, a
``ThreadingHTTPServer`` on 127.0.0.1 whose serve loop runs on a named
daemon thread, port 0 binds an ephemeral port, ``stop()`` shuts it
down. Endpoints:

- ``GET /`` — application list: one row per stored run (queries, wall,
  errors, sentinel verdict) plus a total-wall trend sparkline across
  runs, newest last.
- ``GET /app/<app_id>`` — per-run page: query table with wall time,
  errors, sync/compile counts, worst shuffle imbalance, links to the
  per-query pages and a diff-against-any-other-run form.
- ``GET /app/<app_id>/query/<qid>`` — per-query detail: the analyzed
  plan tree with per-node SELF-time %% (tools/profiler.py
  ``compute_self_times``, the one attribution rule EXPLAIN ANALYZE and
  diagnose share), operator metric tables, critical-path category
  breakdown, memory flight-recorder summary, the v11 data-movement
  table (per-site D2H/H2D bytes, wall, blocking syncs, round trips
  from the movement ledger), kernel/compile table, and the v7
  shuffle-skew records.
- ``GET /diff?a=<app>&b=<app>`` — two-run diff rendered from
  ``tools/compare.py`` (A = baseline, B = candidate).
- ``GET /healthz`` — liveness JSON (store root, runs indexed).
- ``GET /metrics`` — Prometheus text: store size in bytes, runs
  indexed, sentinel verdict counts by outcome — the counters a fleet
  scraper needs to alert on a red sentinel without polling the UI.

CLI: ``python -m spark_rapids_tpu.tools.historyd --dir STORE [--port N]``.
"""
from __future__ import annotations

import html
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from .history import HistoryStore

__all__ = ["HistoryServer"]

_STYLE = """
body { font-family: monospace; margin: 1.5em; color: #222; }
table { border-collapse: collapse; margin: 0.6em 0; }
th, td { border: 1px solid #bbb; padding: 2px 8px; text-align: left; }
th { background: #eee; }
.bar { background: #4C78A8; display: inline-block; height: 0.7em; }
.err { color: #b00; font-weight: bold; }
.ok { color: #070; }
a { color: #246; }
pre { background: #f6f6f6; padding: 0.6em; overflow-x: auto; }
"""


def _page(title: str, body: str) -> str:
    return (f"<!doctype html><html><head><title>{html.escape(title)}"
            f"</title><style>{_STYLE}</style></head>"
            f"<body><h2>{html.escape(title)}</h2>{body}</body></html>")


def _sparkline(values: List[float], width: int = 220,
               height: int = 36) -> str:
    """Inline SVG polyline of a metric trend across runs (oldest →
    newest); empty string with fewer than two points."""
    if len(values) < 2:
        return ""
    vmax = max(values) or 1.0
    n = len(values)
    pts = []
    for i, v in enumerate(values):
        x = 4 + i * (width - 8) / (n - 1)
        y = height - 4 - (v / vmax) * (height - 8)
        pts.append(f"{x:.1f},{y:.1f}")
    return (f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' "
            f"height='{height}'><polyline points='{' '.join(pts)}' "
            f"fill='none' stroke='#4C78A8' stroke-width='1.5'/>"
            f"<circle cx='{pts[-1].split(',')[0]}' "
            f"cy='{pts[-1].split(',')[1]}' r='2.5' fill='#4C78A8'/>"
            "</svg>")


def _fmt_bytes(n: float) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"  # pragma: no cover — loop always returns


def _verdict_cell(headline: Dict) -> str:
    v = headline.get("verdict")
    if not v:
        return "-"
    if v.get("ok"):
        return "<span class='ok'>clean</span>"
    return ("<span class='err'>REGRESSED</span> ("
            + html.escape(",".join(v.get("flags", []))) + ")")


class _HistoryHandler(BaseHTTPRequestHandler):
    server_version = "spark-rapids-tpu-historyd"

    def log_message(self, fmt, *args):  # no stderr chatter per request
        pass

    @property
    def store(self) -> HistoryStore:
        return self.server.store  # type: ignore[attr-defined]

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        parsed = urlparse(self.path)
        path = parsed.path.rstrip("/") or "/"
        try:
            if path == "/":
                self._send(200, self._render_index(), "text/html")
            elif path == "/healthz":
                body = {"status": "ok", "store": self.store.root,
                        "runs_indexed": len(self.store.index())}
                self._send(200, json.dumps(body), "application/json")
            elif path == "/metrics":
                self._send(200, self._render_metrics(),
                           "text/plain; version=0.0.4")
            elif path == "/diff":
                q = parse_qs(parsed.query)
                a = (q.get("a") or [""])[0]
                b = (q.get("b") or [""])[0]
                self._send(200, self._render_diff(a, b), "text/html")
            elif path.startswith("/app/"):
                parts = path.split("/")
                # /app/<id> or /app/<id>/query/<qid>
                if len(parts) == 3:
                    self._send(200, self._render_app(parts[2]),
                               "text/html")
                elif len(parts) == 5 and parts[3] == "query":
                    self._send(200, self._render_query(
                        parts[2], int(parts[4])), "text/html")
                else:
                    self._not_found()
            else:
                self._not_found()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response
        except (KeyError, FileNotFoundError, ValueError) as e:
            self._send(404, _page("not found", f"<pre>{html.escape(str(e))}"
                                               "</pre>"), "text/html")

    def _not_found(self) -> None:
        self._send(404, json.dumps(
            {"error": "not found",
             "endpoints": ["/", "/app/<app_id>",
                           "/app/<app_id>/query/<qid>",
                           "/diff?a=<app>&b=<app>", "/healthz",
                           "/metrics"]}), "application/json")

    # -- pages ----------------------------------------------------------------
    def _render_index(self) -> str:
        apps = self.store.apps()
        walls = [h.get("total_wall_s", 0.0) for h in apps]
        rows = []
        for h in reversed(apps):  # newest first in the table
            aid = html.escape(h["app_id"])
            err = (f"<span class='err'>{h['n_errors']}</span>"
                   if h.get("n_errors") else "0")
            rows.append(
                f"<tr><td><a href='/app/{aid}'>{aid}</a></td>"
                f"<td>{h.get('n_queries', 0)}</td>"
                f"<td>{h.get('total_wall_s', 0.0):.4f}</td>"
                f"<td>{err}</td><td>{_verdict_cell(h)}</td></tr>")
        spark = _sparkline(walls)
        trend = (f"<p>total wall trend (oldest → newest): {spark}</p>"
                 if spark else "")
        body = (trend
                + "<table><tr><th>application</th><th>queries</th>"
                  "<th>total wall s</th><th>errors</th>"
                  "<th>sentinel</th></tr>"
                + "".join(rows) + "</table>"
                + f"<p>{len(apps)} run(s) in {html.escape(self.store.root)}"
                  "</p>")
        return _page("query history", body)

    def _render_app(self, app_id: str) -> str:
        headline = self.store.index().get(app_id)
        if headline is None:
            raise KeyError(f"unknown application {app_id}")
        aid = html.escape(app_id)
        rows = []
        for qid, q in sorted(headline.get("queries", {}).items(),
                             key=lambda kv: int(kv[0])):
            err = (f"<span class='err'>{html.escape(str(q['error']))}"
                   "</span>" if q.get("error") else "")
            skew = q.get("skew_imbalance")
            rows.append(
                f"<tr><td><a href='/app/{aid}/query/{qid}'>q{qid}</a></td>"
                f"<td>{q.get('wall_s', 0.0):.4f}</td>"
                f"<td>{q.get('rows', 0)}</td>"
                f"<td>{_fmt_bytes(q.get('peak_bytes', 0))}</td>"
                f"<td>{q.get('sync_count', 0)}</td>"
                f"<td>{q.get('compile_count', 0)}</td>"
                f"<td>{'' if skew is None else f'{skew:.2f}x'}</td>"
                f"<td>{err}</td></tr>")
        others = [h["app_id"] for h in self.store.apps()
                  if h["app_id"] != app_id]
        diff_links = " ".join(
            f"<a href='/diff?a={html.escape(o)}&b={aid}'>vs {html.escape(o)}"
            "</a>" for o in others[-5:])
        verdict = self.store.verdict(app_id)
        vblock = ""
        if verdict:
            status = ("<span class='ok'>clean</span>" if verdict.get("ok")
                      else "<span class='err'>REGRESSED</span>")
            vblock = (f"<p>sentinel: {status} vs baseline "
                      f"{html.escape(str(verdict.get('baseline')))} — "
                      f"flags: {html.escape(','.join(verdict.get('flags', [])) or 'none')}</p>")
        body = ("<p><a href='/'>← all runs</a></p>" + vblock
                + "<table><tr><th>query</th><th>wall s</th><th>rows</th>"
                  "<th>peak HBM</th><th>syncs</th><th>compiles</th>"
                  "<th>worst skew</th><th>error</th></tr>"
                + "".join(rows) + "</table>"
                + (f"<p>diff this run (as candidate B): {diff_links}</p>"
                   if diff_links else ""))
        return _page(f"run {app_id}", body)

    def _render_query(self, app_id: str, qid: int) -> str:
        from .profiler import compute_self_times
        app = self.store.load(app_id)
        q = app.query(qid)
        aid = html.escape(app_id)
        self_s = compute_self_times(q.nodes)
        total_self = sum(self_s.values()) or 1.0
        # plan tree with self-time %
        rows = []
        for n in q.nodes:
            frac = self_s.get(n["node_id"], 0.0) / total_self
            indent = "&nbsp;" * 2 * n.get("depth", 0)
            bar = f"<span class='bar' style='width:{frac * 120:.0f}px'></span>"
            rows.append(
                f"<tr><td>{indent}{html.escape(n['name'])}</td>"
                f"<td>{html.escape(n.get('desc', '')[:60])}</td>"
                f"<td>{n.get('wall_s', 0.0):.4f}</td>"
                f"<td>{self_s.get(n['node_id'], 0.0):.4f}</td>"
                f"<td>{frac:.1%} {bar}</td>"
                f"<td>{n.get('rows', 0)}</td>"
                f"<td>{_fmt_bytes(n.get('peak_device_bytes', 0))}</td>"
                "</tr>")
        plan_tbl = ("<h3>plan (self-time attribution)</h3>"
                    "<table><tr><th>operator</th><th>desc</th>"
                    "<th>wall s</th><th>self s</th><th>self %</th>"
                    "<th>rows</th><th>peak HBM</th></tr>"
                    + "".join(rows) + "</table>")
        # per-node metric snapshots
        mrows = []
        for n in q.nodes:
            for k, v in sorted((n.get("metrics") or {}).items()):
                mrows.append(f"<tr><td>{html.escape(n['name'])}</td>"
                             f"<td>{html.escape(k)}</td><td>{v}</td></tr>")
        metrics_tbl = ("<h3>operator metrics</h3><table><tr><th>operator"
                       "</th><th>metric</th><th>value</th></tr>"
                       + "".join(mrows) + "</table>") if mrows else ""
        # critical path
        cp_tbl = ""
        if q.critical_path:
            cats = q.critical_path.get("categories_s", {})
            fracs = q.critical_path.get("fractions", {})
            crow = "".join(
                f"<tr><td>{html.escape(k)}</td><td>{v:.4f}</td>"
                f"<td>{fracs.get(k, 0.0):.1%}</td></tr>"
                for k, v in sorted(cats.items(), key=lambda kv: -kv[1]))
            cp_tbl = ("<h3>critical path</h3><table><tr><th>category</th>"
                      "<th>seconds</th><th>share</th></tr>" + crow
                      + "</table>")
        # memory summary
        mem_tbl = ""
        ms = q.memory_summary
        if ms:
            per_op = ms.get("per_operator") or {}
            orow = "".join(
                f"<tr><td>{html.escape(op)}</td>"
                f"<td>{_fmt_bytes(d.get('peak_bytes', 0))}</td>"
                f"<td>{_fmt_bytes(d.get('spilled_bytes', 0))}</td></tr>"
                for op, d in sorted(
                    per_op.items(),
                    key=lambda kv: -(kv[1].get("peak_bytes") or 0)))
            mem_tbl = (f"<h3>memory (peak {_fmt_bytes(ms.get('peak_bytes', 0))}"
                       ")</h3><table><tr><th>operator</th><th>peak</th>"
                       "<th>spilled</th></tr>" + orow + "</table>")
        # kernel / compile table
        k_tbl = ""
        if q.kernels:
            krow = "".join(
                f"<tr><td>{html.escape(str(k.get('node_name') or ''))}</td>"
                f"<td>{html.escape(k.get('signature', '')[:48])}</td>"
                f"<td>{k.get('compiles', 0)}</td><td>{k.get('hits', 0)}</td>"
                f"<td>{k.get('misses', 0)}</td>"
                f"<td>{k.get('compile_s', 0.0):.4f}</td></tr>"
                for k in q.kernels)
            k_tbl = ("<h3>kernels (XLA programs)</h3><table><tr>"
                     "<th>operator</th><th>signature</th><th>compiles</th>"
                     "<th>hits</th><th>misses</th><th>compile s</th></tr>"
                     + krow + "</table>")
        # data movement (v11 movement ledger)
        mv_tbl = ""
        mv = getattr(q, "movement_summary", None)
        if mv:
            tot = mv.get("totals") or {}
            srow = "".join(
                f"<tr><td>{html.escape(s.get('site', ''))}</td>"
                f"<td>{html.escape(s.get('direction', ''))}</td>"
                f"<td>{s.get('count', 0)}</td>"
                f"<td>{_fmt_bytes(s.get('bytes', 0))}</td>"
                f"<td>{s.get('wall_s', 0.0):.4f}</td>"
                f"<td>{s.get('blocking_count', 0)}</td>"
                f"<td>{s.get('round_trips', 0)}</td></tr>"
                for s in mv.get("sites") or [])
            mv_tbl = (
                f"<h3>data movement (v11: D2H "
                f"{_fmt_bytes(tot.get('d2h_bytes', 0))}, H2D "
                f"{_fmt_bytes(tot.get('h2d_bytes', 0))}, "
                f"{tot.get('blocking_count', 0)} blocking sync(s), "
                f"{tot.get('round_trips', 0)} round trip(s))</h3>"
                "<table><tr><th>site</th><th>dir</th><th>count</th>"
                "<th>bytes</th><th>wall s</th><th>blocking</th>"
                "<th>round trips</th></tr>" + srow + "</table>")
        # shuffle observatory (v12: per-tier transfers + straggler)
        sh_tbl = ""
        sh = getattr(q, "shuffle_summary", None)
        if sh:
            tot = sh.get("totals") or {}
            trow = "".join(
                f"<tr><td>{html.escape(t.get('tier', ''))}</td>"
                f"<td>{t.get('count', 0)}</td>"
                f"<td>{_fmt_bytes(t.get('logical_bytes', 0))}</td>"
                f"<td>{_fmt_bytes(t.get('wire_bytes', 0))}</td>"
                f"<td>{t.get('wall_s', 0.0):.4f}</td>"
                f"<td>{t.get('retries', 0)}</td>"
                f"<td>{t.get('max_queue_depth', 0)}</td></tr>"
                for t in sh.get("tiers") or [])
            strag = ""
            st = sh.get("straggler")
            if st:
                worst = st.get("worst") or {}
                strag = (
                    f"<p>straggler: slowest partition "
                    f"{st.get('slowest_wall_s', 0.0):.4f}s vs p50 "
                    f"{st.get('p50_wall_s', 0.0):.4f}s "
                    f"({st.get('skew', 0.0):.1f}x) — shuffle "
                    f"{html.escape(str(worst.get('shuffle_id')))} partition "
                    f"{html.escape(str(worst.get('partition')))} on "
                    f"{html.escape(str(worst.get('tier')))}</p>")
            sh_tbl = (
                f"<h3>shuffle observatory (v12: "
                f"{tot.get('transfers', 0)} transfer(s), "
                f"{_fmt_bytes(tot.get('logical_bytes', 0))} logical, "
                f"{_fmt_bytes(tot.get('wire_bytes', 0))} on the wire, "
                f"{tot.get('retries', 0)} retr(y/ies), "
                f"{tot.get('stitched', 0)} stitched)</h3>"
                "<table><tr><th>tier</th><th>count</th><th>logical</th>"
                "<th>wire</th><th>wall s</th><th>retries</th>"
                "<th>max queue</th></tr>" + trow + "</table>" + strag)
        # shuffle skew (v7)
        skew_tbl = ""
        if q.shuffle_skew:
            srow = "".join(
                f"<tr><td>{html.escape(r.get('name', ''))} "
                f"(node {r.get('node_id')})</td>"
                f"<td>{r.get('partitions')}</td>"
                f"<td>{r['rows'].get('min')}/{r['rows'].get('p50')}/"
                f"{r['rows'].get('max')}</td>"
                f"<td>{r['rows'].get('imbalance', 1.0):.2f}x</td>"
                f"<td>{_fmt_bytes(r['bytes'].get('max', 0))}</td></tr>"
                for r in q.shuffle_skew)
            skew_tbl = ("<h3>shuffle skew (v7)</h3><table><tr>"
                        "<th>exchange</th><th>partitions</th>"
                        "<th>rows min/p50/max</th><th>imbalance</th>"
                        "<th>max partition bytes</th></tr>" + srow
                        + "</table>")
        err = (f"<p class='err'>ERROR: {html.escape(q.error)}</p>"
               if q.error else "")
        body = (f"<p><a href='/app/{aid}'>← run {aid}</a></p>" + err
                + f"<p>wall {q.wall_s:.4f}s</p>"
                + plan_tbl + cp_tbl + mem_tbl + mv_tbl + sh_tbl + skew_tbl
                + k_tbl + metrics_tbl)
        return _page(f"{app_id} — query {qid}", body)

    def _render_diff(self, a: str, b: str) -> str:
        from .compare import compare_apps
        report = compare_apps(self.store.load(a), self.store.load(b))
        back = (f"<p><a href='/app/{html.escape(b)}'>← run "
                f"{html.escape(b)}</a></p>")
        return _page(f"diff {a} → {b}",
                     back + f"<pre>{html.escape(report.summary())}</pre>")

    def _render_metrics(self) -> str:
        index = self.store.index()
        verdicts = {"clean": 0, "regressed": 0, "none": 0}
        for h in index.values():
            v = h.get("verdict")
            if v is None:
                verdicts["none"] += 1
            elif v.get("ok"):
                verdicts["clean"] += 1
            else:
                verdicts["regressed"] += 1
        lines = [
            "# HELP spark_rapids_tpu_history_runs_indexed runs in the "
            "history store index",
            "# TYPE spark_rapids_tpu_history_runs_indexed gauge",
            f"spark_rapids_tpu_history_runs_indexed {len(index)}",
            "# HELP spark_rapids_tpu_history_store_bytes total bytes on "
            "disk under the store root",
            "# TYPE spark_rapids_tpu_history_store_bytes gauge",
            f"spark_rapids_tpu_history_store_bytes "
            f"{self.store.store_size_bytes()}",
            "# HELP spark_rapids_tpu_history_sentinel_verdicts runs by "
            "sentinel outcome",
            "# TYPE spark_rapids_tpu_history_sentinel_verdicts gauge",
        ]
        for outcome, count in sorted(verdicts.items()):
            lines.append(
                "spark_rapids_tpu_history_sentinel_verdicts"
                f'{{outcome="{outcome}"}} {count}')
        return "\n".join(lines) + "\n"

    def _send(self, code: int, body: str, ctype: str) -> None:
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class HistoryServer:
    """Background HTTP server bound to 127.0.0.1 serving one history
    store. Request handling is threaded (daemon threads); the serve loop
    runs on a named daemon thread like statusd's."""

    def __init__(self, store: HistoryStore, port: int = 0,
                 host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, port), _HistoryHandler)
        self._httpd.daemon_threads = True
        self._httpd.store = store  # type: ignore[attr-defined]
        self.store = store
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HistoryServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="tpu-history-httpd")
            self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        t, self._thread = self._thread, None
        if t is None:
            return
        self._httpd.shutdown()
        t.join(timeout=timeout_s)
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.historyd",
        description="Serve the query-history store UI")
    ap.add_argument("--dir", required=True, help="history store root")
    ap.add_argument("--port", type=int, default=18081)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)
    server = HistoryServer(HistoryStore(args.dir), args.port,
                           args.host).start()
    print(f"history server on {server.url} (store {server.store.root})")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
