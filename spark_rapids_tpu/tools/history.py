"""Persistent cross-run history store + regression sentinel.

Reference: the Spark History Server plus the plugin's qualification and
profiling tools turn per-run event logs into cross-run, browsable
evidence (PAPER.md §1 tooling layer). Our per-run signals — event-log
schema v7 with critical paths, memory summaries and shuffle-skew
records, ``tools/compare.py``, ``tools/diagnose.py`` — evaporate when
the process exits; this module makes them durable:

- ``HistoryStore`` (``spark.rapids.tpu.history.dir``): one directory per
  application holding the event log (``eventlog.jsonl``), any bench or
  trace artifacts, an ``app.json`` headline record, and the sentinel's
  ``verdict.json``. A store-level ``index.json`` (per-query headline
  stats for every run) is DERIVED from the per-app records and replaced
  atomically (tmp + ``os.replace``), so concurrent writers — several
  sessions closing at once — can only ever race to publish a complete
  index, never tear one. Every ``TpuSession`` appends its run on close
  when the conf is set; ``tools/historyd.py`` serves the browsable UI
  over the same store.
- The **regression sentinel** (``python -m spark_rapids_tpu.tools.history
  sentinel --dir <store>``; exit 1 on regression) compares the candidate
  run (default: newest) against the pinned baseline (default: the run
  before it) using the existing compare.py gates — per-operator wall
  time, per-operator peak memory > 10 %, critical-path share > 5 pp —
  plus three gates of its own over the per-query counter deltas the
  event log already carries: **sync count** (``host_sync_d2h_count``,
  the deliberate-D2H funnel counter in columnar/device.py), **compile
  count** (``compile_cache_compiles``), — when the movement ledger
  is on — **D2H bytes** (``movement_d2h_bytes``, floor
  ``BYTES_FLAG_MIN``), and — when shuffle telemetry is on — **shuffle
  wall** (``shuffle_telemetry_wall_s``, floor
  ``SHUFFLE_WALL_FLAG_MIN_S``: time measurably spent inside shuffle
  transfer phases, which a fast machine can hide inside flat wall
  time). Any growing past ``COUNT_FLAG_FRAC`` (10 %,
  absolute floor ``COUNT_FLAG_MIN`` for counts) flags a regression
  wall-time comparison alone would miss: the run got slower
  *structurally* (more host round trips, wider downloads,
  compile-cache churn) even if this machine absorbed it. The verdict is
  written into the store next to the candidate's event log.

CLI::

    python -m spark_rapids_tpu.tools.history list --dir DIR
    python -m spark_rapids_tpu.tools.history append --dir DIR LOG [ART...]
    python -m spark_rapids_tpu.tools.history pin --dir DIR APP_ID
    python -m spark_rapids_tpu.tools.history sentinel --dir DIR \
        [--candidate APP] [--baseline APP] [--threshold 0.2]
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Sequence

from ..conf import register_conf

__all__ = ["HistoryStore", "run_sentinel", "HISTORY_DIR",
           "COUNT_FLAG_FRAC", "COUNT_FLAG_MIN", "SYNC_COUNT_KEY",
           "COMPILE_COUNT_KEY", "D2H_BYTES_KEY", "BYTES_FLAG_MIN",
           "SHUFFLE_WALL_KEY", "SHUFFLE_WALL_FLAG_MIN_S"]

HISTORY_DIR = register_conf(
    "spark.rapids.tpu.history.dir",
    "Root directory of the persistent query-history store (one directory "
    "per application: event log, artifacts, headline stats, sentinel "
    "verdict; plus an atomic store-level index.json). Empty disables the "
    "store. Every session appends its run on close; browse with "
    "tools/historyd.py, gate with 'python -m spark_rapids_tpu.tools."
    "history sentinel'. The Spark History Server log-dir analogue.", "")

HISTORY_BASELINE = register_conf(
    "spark.rapids.tpu.history.baseline",
    "Application id of the pinned regression-sentinel baseline in the "
    "history store. Empty uses the store's pinned baseline (the 'pin' "
    "subcommand) or, failing that, the run immediately before the "
    "candidate.", "")

#: relative growth of a sentinel-gated counter (sync count, compile
#: count) that flags a regression: 10%
COUNT_FLAG_FRAC = 0.10
#: absolute growth floor for the counter gates, so one extra sync on a
#: tiny run doesn't flap the sentinel
COUNT_FLAG_MIN = 2

#: per-query stats key for the sync-count gate (columnar/device.py
#: deliberate-D2H funnel counter, via the host_sync stats source)
SYNC_COUNT_KEY = "host_sync_d2h_count"
#: per-query stats key for the compile-count gate (XLA programs compiled
#: by the query, utils/compile_cache.py)
COMPILE_COUNT_KEY = "compile_cache_compiles"

#: per-query stats key for the D2H transfer-BYTES gate (movement-ledger
#: totals via the movement stats source, utils/movement.py). Where the
#: sync-count gate catches new host round trips, this one catches the
#: same number of syncs moving structurally more data — a widened
#: download that wall time on a fast link absorbs. Requires
#: spark.rapids.tpu.movement.enabled on both runs; absent stats gate
#: nothing.
D2H_BYTES_KEY = "movement_d2h_bytes"
#: absolute growth floor for the byte gate (1 MiB), so per-run row-count
#: jitter on small queries doesn't flap the sentinel
BYTES_FLAG_MIN = 1 << 20

#: per-query stats key for the shuffle-wall gate (shuffle-observatory
#: totals via the shuffle_telemetry stats source, shuffle/telemetry.py):
#: wall measurably spent inside transfer phases (serialize/publish/
#: fetch/deserialize/dispatch). Catches a shuffle tier getting slower
#: even when overlap keeps query wall flat. Requires
#: spark.rapids.tpu.shuffle.telemetry.enabled on both runs; absent
#: stats gate nothing.
SHUFFLE_WALL_KEY = "shuffle_telemetry_wall_s"
#: absolute growth floor for the shuffle-wall gate (50 ms), so
#: scheduler jitter on tiny transfers doesn't flap the sentinel
SHUFFLE_WALL_FLAG_MIN_S = 0.05

#: absolute growth floor for the aggregate total-wall gate (2 s): the
#: MULTICHIP trajectory gate sums per-query walls across the run, so a
#: fleet-wide slowdown spread thinly over every query (each one under
#: the per-query threshold) still flags, while compile-cache jitter on
#: a single tiny query doesn't
TOTAL_WALL_FLAG_MIN_S = 2.0

_EVENTLOG_NAME = "eventlog.jsonl"
_APP_JSON = "app.json"
_VERDICT_JSON = "verdict.json"
_INDEX_JSON = "index.json"
_BASELINE_JSON = "baseline.json"
_ARTIFACT_DIR = "artifacts"


def _atomic_write_json(path: str, obj) -> None:
    """tmp + os.replace so readers never observe a torn file; the tmp
    name is writer-unique so concurrent writers can't clobber each
    other's half-written staging file."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[Dict]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class HistoryStore:
    """One directory per application + a derived, atomically-replaced
    store index. Safe for concurrent appenders: per-app records are
    written before the index rebuild, and every rebuild re-scans the
    app directories, so racing writers converge on a complete index
    (last replace wins; both candidates are supersets of what either
    writer alone knew)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    # -- paths ----------------------------------------------------------------
    def app_dir(self, app_id: str) -> str:
        return os.path.join(self.root, app_id)

    def event_log_path(self, app_id: str) -> str:
        return os.path.join(self.app_dir(app_id), _EVENTLOG_NAME)

    def index_path(self) -> str:
        return os.path.join(self.root, _INDEX_JSON)

    # -- append ---------------------------------------------------------------
    def append_run(self, eventlog_path: str,
                   artifacts: Sequence[str] = (),
                   app_id: Optional[str] = None) -> str:
        """Ingest one finished event log (plus optional artifact files)
        as a new application directory and refresh the index. Returns
        the app id the run is stored under."""
        from .eventlog import load_event_log
        app = load_event_log(eventlog_path)
        app_id = app_id or app.app_id \
            or os.path.splitext(os.path.basename(eventlog_path))[0]
        d = self.app_dir(app_id)
        os.makedirs(d, exist_ok=True)
        shutil.copyfile(eventlog_path, os.path.join(d, _EVENTLOG_NAME))
        if artifacts:
            art_dir = os.path.join(d, _ARTIFACT_DIR)
            os.makedirs(art_dir, exist_ok=True)
            for src in artifacts:
                if os.path.isfile(src):
                    shutil.copyfile(
                        src, os.path.join(art_dir, os.path.basename(src)))
        headline = self._headline(app_id, app, eventlog_path)
        _atomic_write_json(os.path.join(d, _APP_JSON), headline)
        self.rebuild_index()
        return app_id

    @staticmethod
    def _headline(app_id: str, app, eventlog_path: str) -> Dict:
        """Per-query headline stats — everything the index/UI list view
        and the sentinel's trend sparkline need without replaying the
        full log."""
        queries: Dict[str, Dict] = {}
        ts = 0.0
        for q in app.queries.values():
            ts = ts or q.ts_start
            ms = q.memory_summary or {}
            skew = max((r.get("rows", {}).get("imbalance", 1.0)
                        for r in q.shuffle_skew), default=None)
            queries[str(q.query_id)] = {
                "wall_s": round(q.wall_s, 6),
                "error": q.error,
                "rows": sum(n.get("rows", 0) for n in q.nodes
                            if (n.get("parent_id") is None
                                or n["parent_id"] < 0)),
                "peak_bytes": int(ms.get("peak_bytes") or 0),
                "sync_count": int(q.stats.get(SYNC_COUNT_KEY, 0) or 0),
                "compile_count": int(
                    q.stats.get(COMPILE_COUNT_KEY, 0) or 0),
                "d2h_bytes": int(q.stats.get(D2H_BYTES_KEY, 0) or 0),
                "skew_imbalance": skew,
            }
        if not ts:
            try:
                ts = os.path.getmtime(eventlog_path)
            except OSError:
                ts = time.time()
        return {
            "app_id": app_id,
            "ts": ts,
            "schema_version": app.schema_version,
            "n_queries": len(app.queries),
            "n_errors": sum(1 for q in app.queries.values() if q.error),
            "total_wall_s": round(
                sum(q.wall_s for q in app.queries.values()), 6),
            "queries": queries,
        }

    # -- index ----------------------------------------------------------------
    def rebuild_index(self) -> Dict:
        """Re-derive index.json from the per-app records and replace it
        atomically. Returns the new index (app_id -> headline, verdict
        folded in when present)."""
        index: Dict[str, Dict] = {}
        try:
            entries = sorted(os.listdir(self.root))
        except OSError:
            entries = []
        for name in entries:
            headline = _read_json(os.path.join(self.root, name, _APP_JSON))
            if not headline:
                continue
            verdict = _read_json(
                os.path.join(self.root, name, _VERDICT_JSON))
            if verdict is not None:
                headline["verdict"] = {
                    "ok": verdict.get("ok"),
                    "baseline": verdict.get("baseline"),
                    "flags": verdict.get("flags", []),
                }
            index[name] = headline
        _atomic_write_json(self.index_path(), index)
        return index

    def index(self) -> Dict:
        idx = _read_json(self.index_path())
        return idx if idx is not None else self.rebuild_index()

    def apps(self) -> List[Dict]:
        """Headlines, oldest first (the trend/sparkline order)."""
        return sorted(self.index().values(),
                      key=lambda h: (h.get("ts", 0.0), h.get("app_id", "")))

    def load(self, app_id: str):
        """Full replay of one stored run (tools/eventlog.py AppReplay)."""
        from .eventlog import load_event_log
        return load_event_log(self.event_log_path(app_id))

    # -- baseline + verdict ---------------------------------------------------
    def pin_baseline(self, app_id: str) -> None:
        if not os.path.isdir(self.app_dir(app_id)):
            raise FileNotFoundError(f"no such run in the store: {app_id}")
        _atomic_write_json(os.path.join(self.root, _BASELINE_JSON),
                           {"app_id": app_id})

    def baseline_app_id(self) -> Optional[str]:
        rec = _read_json(os.path.join(self.root, _BASELINE_JSON))
        return rec.get("app_id") if rec else None

    def write_verdict(self, app_id: str, verdict: Dict) -> None:
        d = self.app_dir(app_id)
        os.makedirs(d, exist_ok=True)
        _atomic_write_json(os.path.join(d, _VERDICT_JSON), verdict)
        self.rebuild_index()

    def verdict(self, app_id: str) -> Optional[Dict]:
        return _read_json(os.path.join(self.app_dir(app_id),
                                       _VERDICT_JSON))

    def store_size_bytes(self) -> int:
        total = 0
        for dirpath, _dirnames, filenames in os.walk(self.root):
            for name in filenames:
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    pass
        return total


# ---------------------------------------------------------------------------
# Regression sentinel
# ---------------------------------------------------------------------------
def _count_gate(report, key: str,
                flag_min: int = COUNT_FLAG_MIN) -> List[Dict]:
    """Queries whose per-query counter ``key`` grew past the sentinel's
    count gate (relative COUNT_FLAG_FRAC with absolute floor
    ``flag_min`` — COUNT_FLAG_MIN for sync/compile counts,
    BYTES_FLAG_MIN for the transfer-byte gate). Works off
    QueryDelta.metric_deltas, which compare.py already computes as
    candidate minus baseline."""
    flagged = []
    for q in report.queries:
        delta = q.metric_deltas.get(key)
        if not delta or delta <= 0:
            continue
        # reconstruct the baseline's absolute count: compare.py keeps
        # only the delta, so look it up through the ops-independent
        # stats the report retained; fall back to treating the delta as
        # 100% growth when the baseline count is unknown/zero
        base = getattr(q, "_stats_base", {}).get(key, 0)
        grew_enough = delta >= flag_min and (
            base <= 0 or delta > base * COUNT_FLAG_FRAC)
        if grew_enough:
            flagged.append({"query_id": q.query_id, "key": key,
                            "delta": delta, "baseline": base})
    return flagged


def run_sentinel(store: HistoryStore,
                 candidate: Optional[str] = None,
                 baseline: Optional[str] = None,
                 threshold: float = 0.2,
                 min_seconds: float = 0.001) -> Dict:
    """Compare the candidate run (default newest) against the baseline
    (explicit > pinned > previous run), write the verdict record into
    the store under the candidate, and return it. ``verdict["ok"]`` is
    False on any regression — wall time, critical-path share, peak
    memory, sync count, or compile count."""
    from .compare import compare_apps
    runs = store.apps()
    if not runs:
        raise FileNotFoundError(f"history store {store.root} has no runs")
    cand_id = candidate or runs[-1]["app_id"]
    base_id = baseline or store.baseline_app_id()
    if base_id is None:
        prior = [h["app_id"] for h in runs if h["app_id"] != cand_id
                 and h.get("ts", 0.0) <= next(
                     h2.get("ts", 0.0) for h2 in runs
                     if h2["app_id"] == cand_id)]
        base_id = prior[-1] if prior else None
    if base_id is None or base_id == cand_id:
        verdict = {"ok": True, "status": "no-baseline",
                   "candidate": cand_id, "baseline": None,
                   "ts": time.time(), "flags": [], "summary":
                   "no baseline run to compare against; recorded only"}
        store.write_verdict(cand_id, verdict)
        return verdict
    app_base = store.load(base_id)
    app_cand = store.load(cand_id)
    report = compare_apps(app_base, app_cand, threshold, min_seconds)
    # stash each query's BASELINE counters on the deltas so the count
    # gates can apply their relative threshold
    for q in report.queries:
        qb = app_base.queries.get(q.query_id)
        q._stats_base = dict(qb.stats) if qb is not None else {}
    # chaos-awareness (event-log v8): a candidate query that recovered
    # from INJECTED faults and still answered correctly pays its
    # recovery overhead on purpose — exempt it from every gate instead
    # of flagging the slowdown as a regression. Uninjected recovery
    # (fault records absent) still gates: that slowdown is real.
    # v9: same exemption for queries the BENCH_OOM phase ran under a
    # shrunken HBM pool — their oom_retry records (spills, retries,
    # splits) are deliberate pressure, not a regression.
    # v10: ditto for queries that recovered via host fallback — the
    # download/host-execute/upload round trips are the degradation
    # working as designed, not a device-path slowdown.
    chaos_ok = {q.query_id for q in app_cand.queries.values()
                if (getattr(q, "faults", None)
                    or getattr(q, "oom_retries", None)
                    or getattr(q, "fallbacks", None))
                and q.error is None}
    sync_flags = [f for f in _count_gate(report, SYNC_COUNT_KEY)
                  if f["query_id"] not in chaos_ok]
    compile_flags = [f for f in _count_gate(report, COMPILE_COUNT_KEY)
                     if f["query_id"] not in chaos_ok]
    # v11: movement-ledger D2H byte growth — same relative threshold as
    # the count gates, but floored at BYTES_FLAG_MIN so only material
    # transfer growth flags
    d2h_flags = [f for f in _count_gate(report, D2H_BYTES_KEY,
                                        BYTES_FLAG_MIN)
                 if f["query_id"] not in chaos_ok]
    # v12: shuffle-observatory transfer-wall growth — time spent inside
    # shuffle phases regressing past 10% and the 50ms floor flags even
    # when pipeline overlap keeps end-to-end wall flat
    shuffle_flags = [f for f in _count_gate(report, SHUFFLE_WALL_KEY,
                                            SHUFFLE_WALL_FLAG_MIN_S)
                     if f["query_id"] not in chaos_ok]
    # v13: aggregate total-wall gate (the MULTICHIP trajectory number) —
    # per-query wall gates can miss a fleet-wide slowdown spread thinly
    # across the run; sum walls over the query ids present in BOTH runs
    # (chaos-exempt ones excluded, like every other gate) and flag
    # material aggregate growth past the relative threshold + 2s floor
    shared_q = [k for k in set(app_base.queries) & set(app_cand.queries)
                if k not in chaos_ok]
    base_total = sum(app_base.queries[k].wall_s for k in shared_q)
    cand_total = sum(app_cand.queries[k].wall_s for k in shared_q)
    total_wall = {"baseline_s": round(base_total, 4),
                  "candidate_s": round(cand_total, 4),
                  "n_queries": len(shared_q)} if shared_q else None
    total_wall_flagged = bool(
        shared_q
        and cand_total - base_total > TOTAL_WALL_FLAG_MIN_S
        and cand_total > base_total * (1.0 + threshold))
    wall_q = [q.query_id for q in report.regressed_queries()
              if q.query_id not in chaos_ok]
    wall_ops = [(op.query_id, op.name) for op in report.regressions()
                if op.query_id not in chaos_ok]
    cp_q = [q.query_id for q in report.critical_path_regressions()
            if q.query_id not in chaos_ok]
    mem_q = [q.query_id for q in report.memory_regressions()
             if q.query_id not in chaos_ok]
    flags: List[str] = []
    if wall_q or wall_ops:
        flags.append("wall_time")
    if cp_q:
        flags.append("critical_path")
    if mem_q:
        flags.append("memory")
    if sync_flags:
        flags.append("sync_count")
    if compile_flags:
        flags.append("compile_count")
    if d2h_flags:
        flags.append("d2h_bytes")
    if shuffle_flags:
        flags.append("shuffle_wall")
    if total_wall_flagged:
        flags.append("total_wall")
    verdict = {
        "ok": not flags,
        "status": "regressed" if flags else "clean",
        "candidate": cand_id,
        "baseline": base_id,
        "ts": time.time(),
        "threshold": threshold,
        "flags": flags,
        "wall_regressed_queries": wall_q,
        "wall_regressed_ops": [
            {"query_id": qid, "name": name} for qid, name in wall_ops],
        "critical_path_regressed_queries": cp_q,
        "memory_regressed_queries": mem_q,
        "sync_count_regressions": sync_flags,
        "compile_count_regressions": compile_flags,
        "d2h_bytes_regressions": d2h_flags,
        "shuffle_wall_regressions": shuffle_flags,
        "total_wall": total_wall,
        "chaos_recovered_queries": sorted(chaos_ok),
        "summary": report.summary(),
    }
    store.write_verdict(cand_id, verdict)
    return verdict


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.history",
        description="Query-history store: list runs, append event logs, "
                    "pin a baseline, run the regression sentinel")
    sub = ap.add_subparsers(dest="cmd")
    p_list = sub.add_parser("list", help="list stored runs")
    p_list.add_argument("--dir", required=True)
    p_append = sub.add_parser("append", help="ingest an event log")
    p_append.add_argument("--dir", required=True)
    p_append.add_argument("eventlog")
    p_append.add_argument("artifacts", nargs="*")
    p_pin = sub.add_parser("pin", help="pin the sentinel baseline run")
    p_pin.add_argument("--dir", required=True)
    p_pin.add_argument("app_id")
    p_sent = sub.add_parser(
        "sentinel",
        help="compare the newest (or --candidate) run against the "
             "baseline; exit 1 on regression")
    p_sent.add_argument("--dir", required=True)
    p_sent.add_argument("--candidate", default=None)
    p_sent.add_argument("--baseline", default=None)
    p_sent.add_argument("--threshold", type=float, default=0.2)
    p_sent.add_argument("--min-seconds", type=float, default=0.001)
    args = ap.parse_args(argv)
    if args.cmd is None:
        ap.print_help()
        return 2
    store = HistoryStore(args.dir)
    if args.cmd == "list":
        for h in store.apps():
            verdict = h.get("verdict") or {}
            mark = {True: "clean", False: "REGRESSED"}.get(
                verdict.get("ok"), "-")
            print(f"{h['app_id']:<40} queries={h['n_queries']:<3} "
                  f"wall={h['total_wall_s']:.4f}s errors={h['n_errors']} "
                  f"sentinel={mark}")
        return 0
    if args.cmd == "append":
        app_id = store.append_run(args.eventlog, args.artifacts)
        print(f"appended {app_id} -> {store.app_dir(app_id)}")
        return 0
    if args.cmd == "pin":
        store.pin_baseline(args.app_id)
        print(f"pinned baseline {args.app_id}")
        return 0
    # sentinel
    verdict = run_sentinel(store, args.candidate, args.baseline,
                           args.threshold, args.min_seconds)
    print(f"sentinel: candidate={verdict['candidate']} "
          f"baseline={verdict['baseline']} status={verdict['status']}"
          + (f" flags={','.join(verdict['flags'])}"
             if verdict["flags"] else ""))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
