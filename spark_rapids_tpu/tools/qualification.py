"""Qualification tool: score a workload's device suitability.

Reference: tools/ QualificationMain / QualificationAppInfo
(tools/.../qualification/Qualification.scala:34) — scores CPU Spark apps for
GPU suitability using PluginTypeChecker against the supported-ops data. The
reference replays event logs; this framework is standalone, so qualification
walks the query plan directly through the SAME meta/tagging layer the device
lowering uses (plan/meta.py) — the score can't drift from what the engine
actually supports.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..conf import RapidsConf
from ..plan.meta import wrap_plan
from ..plan.planner import plan_physical

__all__ = ["qualify", "qualify_event_log", "QualificationReport",
           "EventLogQualificationReport"]

# cost model shared with the cost-based optimizer so the qualification score
# and the CBO demotion decision can't drift apart
from ..plan.cbo import DEFAULT_WEIGHT as _DEFAULT_WEIGHT
from ..plan.cbo import OP_WEIGHTS as _OP_WEIGHTS
from ..plan.cbo import OPTIMIZER_SPEEDUP as _OPTIMIZER_SPEEDUP


@dataclasses.dataclass
class QualificationReport:
    score: float                       # 0..1 weighted device-runnable share
    total_ops: int
    supported_ops: int
    per_op: List[Tuple[str, bool, str]]   # (name, supported, reasons)
    estimated_speedup: float

    def summary(self) -> str:
        lines = [
            f"qualification score : {self.score:.2f}",
            f"device-runnable ops : {self.supported_ops}/{self.total_ops}",
            f"estimated speedup   : {self.estimated_speedup:.2f}x",
            "",
        ]
        for name, ok, reasons in self.per_op:
            mark = "+" if ok else "!"
            lines.append(f"  {mark} {name}" + (f" — {reasons}" if reasons else ""))
        return "\n".join(lines)


def qualify(df, conf: Optional[RapidsConf] = None) -> QualificationReport:
    """Score one DataFrame's plan. ``df`` may also be a logical plan."""
    logical = getattr(df, "logical", df)
    session_conf = getattr(getattr(df, "session", None), "conf", None)
    conf = conf or session_conf or RapidsConf()
    cpu = plan_physical(logical, conf)
    meta = wrap_plan(cpu)
    meta.tag(conf)

    per_op: List[Tuple[str, bool, str]] = []
    w_total = w_ok = 0.0
    n_total = n_ok = 0
    for m in meta.walk():
        name = type(m.plan).__name__
        w = _OP_WEIGHTS.get(name, _DEFAULT_WEIGHT)
        ok = m.can_run
        w_total += w
        n_total += 1
        if ok:
            w_ok += w
            n_ok += 1
        per_op.append((name, ok, "; ".join(m.reasons)))

    score = (w_ok / w_total) if w_total else 0.0
    # crude amdahl: device section accelerated by the configured speedup
    # (default mirrors the reference's "4x typical", docs/FAQ.md:100-106),
    # host remainder at 1x
    speedup = conf.get(_OPTIMIZER_SPEEDUP)
    est = 1.0 / ((1.0 - score) + score / speedup) if w_total else 1.0
    return QualificationReport(score, n_total, n_ok, per_op, est)


# ---------------------------------------------------------------------------
# Offline qualification from a recorded event log (round-4 VERDICT item 10;
# reference: Qualification.scala:34 scores RECORDED CPU apps from their
# event logs without re-running them)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class EventLogQualificationReport:
    app_path: str
    queries: List[Tuple[int, float, float, float]]  # (qid, wall_s, score, est)
    score: float                 # wall-time-weighted device-runnable share
    estimated_speedup: float
    unsupported_ops: Dict[str, float]     # op name -> host wall_s

    def summary(self) -> str:
        lines = [f"event-log qualification: {self.app_path}",
                 f"app score (time-weighted) : {self.score:.2f}",
                 f"estimated app speedup     : "
                 f"{self.estimated_speedup:.2f}x", ""]
        for qid, wall, score, est in self.queries:
            lines.append(f"  query {qid}: wall={wall:.3f}s "
                         f"score={score:.2f} est={est:.2f}x")
        if self.unsupported_ops:
            lines.append("")
            lines.append("top host-bound operators:")
            for name, s in sorted(self.unsupported_ops.items(),
                                  key=lambda kv: -kv[1])[:10]:
                lines.append(f"  ! {name}: {s:.3f}s")
        return "\n".join(lines)


def _supported_exec_names() -> set:
    """Exec class names with a registered device rule (the offline stand-in
    for PluginTypeChecker's supported-execs data file)."""
    from ..plan import overrides  # noqa: F401  (registers rules on import)
    from ..plan.meta import EXEC_RULES
    names = set()
    for cls in EXEC_RULES:
        names.add(cls.__name__)
        names.add(cls.__name__.replace("Cpu", "", 1))
    return names


def qualify_event_log(path: str,
                      conf: Optional[RapidsConf] = None
                      ) -> EventLogQualificationReport:
    """Score a recorded app (tools/eventlog.py JSONL) for device
    suitability WITHOUT re-running it: per-operator measured wall time
    weights each op, so the estimate reflects where this app actually
    spent its time (stronger than plan-shape weighting — the reference
    uses recorded SQL metrics the same way)."""
    from .eventlog import load_event_log
    conf = conf or RapidsConf()
    supported = _supported_exec_names()
    speedup = conf.get(_OPTIMIZER_SPEEDUP)

    app = load_event_log(path)
    queries = []
    unsupported: Dict[str, float] = {}
    t_total = t_dev = 0.0
    for qid in sorted(app.queries):
        q = app.query(qid)
        if q.error:
            continue
        w_total = w_dev = 0.0
        for n in q.nodes:
            name = n["name"]
            w = max(float(n.get("wall_s", 0.0)), 0.0)
            w_total += w
            # Tpu* nodes RAN on device; Cpu* nodes qualify when a device
            # rule exists for them
            if name.startswith("Tpu") or name in supported:
                w_dev += w
            else:
                unsupported[name] = unsupported.get(name, 0.0) + w
        score = (w_dev / w_total) if w_total else 1.0
        est = 1.0 / ((1.0 - score) + score / speedup)
        queries.append((qid, q.wall_s, score, est))
        t_total += w_total
        t_dev += w_dev
    app_score = (t_dev / t_total) if t_total else 1.0
    app_est = 1.0 / ((1.0 - app_score) + app_score / speedup)
    return EventLogQualificationReport(path, queries, app_score, app_est,
                                       unsupported)
