"""Qualification tool: score a workload's device suitability.

Reference: tools/ QualificationMain / QualificationAppInfo
(tools/.../qualification/Qualification.scala:34) — scores CPU Spark apps for
GPU suitability using PluginTypeChecker against the supported-ops data. The
reference replays event logs; this framework is standalone, so qualification
walks the query plan directly through the SAME meta/tagging layer the device
lowering uses (plan/meta.py) — the score can't drift from what the engine
actually supports.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..conf import RapidsConf
from ..plan.meta import wrap_plan
from ..plan.planner import plan_physical

__all__ = ["qualify", "QualificationReport"]

# cost model shared with the cost-based optimizer so the qualification score
# and the CBO demotion decision can't drift apart
from ..plan.cbo import DEFAULT_WEIGHT as _DEFAULT_WEIGHT
from ..plan.cbo import OP_WEIGHTS as _OP_WEIGHTS
from ..plan.cbo import OPTIMIZER_SPEEDUP as _OPTIMIZER_SPEEDUP


@dataclasses.dataclass
class QualificationReport:
    score: float                       # 0..1 weighted device-runnable share
    total_ops: int
    supported_ops: int
    per_op: List[Tuple[str, bool, str]]   # (name, supported, reasons)
    estimated_speedup: float

    def summary(self) -> str:
        lines = [
            f"qualification score : {self.score:.2f}",
            f"device-runnable ops : {self.supported_ops}/{self.total_ops}",
            f"estimated speedup   : {self.estimated_speedup:.2f}x",
            "",
        ]
        for name, ok, reasons in self.per_op:
            mark = "+" if ok else "!"
            lines.append(f"  {mark} {name}" + (f" — {reasons}" if reasons else ""))
        return "\n".join(lines)


def qualify(df, conf: Optional[RapidsConf] = None) -> QualificationReport:
    """Score one DataFrame's plan. ``df`` may also be a logical plan."""
    logical = getattr(df, "logical", df)
    session_conf = getattr(getattr(df, "session", None), "conf", None)
    conf = conf or session_conf or RapidsConf()
    cpu = plan_physical(logical, conf)
    meta = wrap_plan(cpu)
    meta.tag(conf)

    per_op: List[Tuple[str, bool, str]] = []
    w_total = w_ok = 0.0
    n_total = n_ok = 0
    for m in meta.walk():
        name = type(m.plan).__name__
        w = _OP_WEIGHTS.get(name, _DEFAULT_WEIGHT)
        ok = m.can_run
        w_total += w
        n_total += 1
        if ok:
            w_ok += w
            n_ok += 1
        per_op.append((name, ok, "; ".join(m.reasons)))

    score = (w_ok / w_total) if w_total else 0.0
    # crude amdahl: device section accelerated by the configured speedup
    # (default mirrors the reference's "4x typical", docs/FAQ.md:100-106),
    # host remainder at 1x
    speedup = conf.get(_OPTIMIZER_SPEEDUP)
    est = 1.0 / ((1.0 - score) + score / speedup) if w_total else 1.0
    return QualificationReport(score, n_total, n_ok, per_op, est)
