"""API-validation tool.

Reference: api_validation/ (ApiValidation.scala) reflects over every Gpu exec
and compares its constructor signature against the corresponding Spark exec,
printing a drift report — it catches silent API skew between the plugin and
the engine it overrides.

Here the two surfaces that can skew are (a) the CPU physical operator set vs
the exec rule registry (a new Cpu exec with no rule and no documented
host-only reason silently never lowers) and (b) the expression library vs
the expression rule registry. ``validate()`` reflects over the plan/expr
modules, resolves each class through the same MRO lookup the planner uses,
and reports anything unaccounted for; ``report()`` renders the
ApiValidation-style table.
"""
from __future__ import annotations

import inspect
from typing import Dict, List

__all__ = ["validate", "report"]

# Cpu execs that intentionally have no device rule, with the documented
# reason (the reference likewise documents known-unsupported operators).
KNOWN_HOST_ONLY_EXECS: Dict[str, str] = {
    "CpuMapInPandasExec": "opaque Python bridge; runs host-side with the "
                          "device semaphore released",
    "CpuGroupedMapPandasExec": "opaque per-group Python bridge; host-side "
                               "with the device semaphore released",
    "CpuCoGroupedMapPandasExec": "opaque co-grouped Python bridge; "
                                 "host-side with the semaphore released",
    "PhysicalPlan": "abstract base",
}

# Expression base classes that are deliberately host-only or abstract.
KNOWN_HOST_ONLY_EXPRS: Dict[str, str] = {
    "Expression": "abstract base",
    "AggregateFunction": "checked inside the aggregate exec rule",
    "WindowExpression": "lowered by the window exec, not expression rules",
    "SortOrder": "operator argument, not a standalone expression",
}


def _plan_classes():
    from ..plan import generate, physical, physical_joins, physical_window
    from ..exec import cache
    from ..udf import python_exec
    mods = [physical, physical_joins, physical_window, generate, cache,
            python_exec]
    seen = {}
    for mod in mods:
        for name, obj in vars(mod).items():
            if inspect.isclass(obj) and obj.__module__ == mod.__name__ \
                    and name.startswith("Cpu"):
                seen[name] = obj
    return seen


def _rule_for(cls, registry):
    for c in cls.__mro__:
        if c in registry:
            return registry[c]
    return None


def validate() -> List[str]:
    """-> list of violations (empty = registries and operator sets agree)."""
    from ..plan import aqe, overrides  # noqa: F401 — populates the registries
    from ..plan.meta import EXEC_RULES, EXPR_RULES
    violations: List[str] = []

    for name, cls in _plan_classes().items():
        rule = _rule_for(cls, EXEC_RULES)
        if rule is None and name not in KNOWN_HOST_ONLY_EXECS:
            violations.append(
                f"exec {name} has no device rule and no documented "
                "host-only reason")
        if rule is not None and not callable(rule.convert_fn):
            violations.append(f"exec {name}: rule convert_fn not callable")

    # every registered exec rule must point at a real, constructible class
    for cls, rule in EXEC_RULES.items():
        if not inspect.isclass(cls):
            violations.append(f"exec rule key {cls!r} is not a class")
        if not rule.conf_key.startswith("spark.rapids.sql.exec."):
            violations.append(f"exec rule {cls.__name__}: bad conf key "
                              f"{rule.conf_key}")

    from ..expr.base import Expression
    import spark_rapids_tpu.expr as expr_pkg
    import pkgutil
    import importlib
    expr_classes = {}
    for info in pkgutil.iter_modules(expr_pkg.__path__):
        mod = importlib.import_module(f"{expr_pkg.__name__}.{info.name}")
        for name, obj in vars(mod).items():
            if inspect.isclass(obj) and issubclass(obj, Expression) \
                    and obj.__module__ == mod.__name__:
                expr_classes[name] = obj

    unruled = []
    for name, cls in sorted(expr_classes.items()):
        if name.startswith("_") or name in KNOWN_HOST_ONLY_EXPRS:
            continue
        if _rule_for(cls, EXPR_RULES) is None:
            unruled.append(name)
    # expressions with no rule DO fall back gracefully (tagged
    # "no device implementation"), so drift here is informational until it
    # regresses: fail only if coverage drops below the recorded floor
    coverage = 1.0 - len(unruled) / max(1, len(expr_classes))
    if coverage < 0.55:
        violations.append(
            f"expression rule coverage regressed to {coverage:.0%} "
            f"({len(unruled)}/{len(expr_classes)} unruled): "
            + ", ".join(unruled[:10]))

    for cls, rule in EXPR_RULES.items():
        if not issubclass(cls, Expression):
            violations.append(
                f"expr rule key {cls.__name__} is not an Expression")
    return violations


def report() -> str:
    """ApiValidation-style drift report."""
    from ..plan import aqe, overrides  # noqa: F401 — populates the registries
    from ..plan.meta import EXEC_RULES, EXPR_RULES
    lines = ["api validation report", "====================="]
    plan_classes = _plan_classes()
    lines.append(f"cpu execs: {len(plan_classes)}; exec rules: "
                 f"{len(EXEC_RULES)}; expr rules: {len(EXPR_RULES)}")
    for name, cls in sorted(plan_classes.items()):
        rule = _rule_for(cls, EXEC_RULES)
        if rule is not None:
            via = next(c.__name__ for c in cls.__mro__ if c in EXEC_RULES)
            note = f"rule via {via}" if via != name else "rule"
        else:
            note = "host-only: " + KNOWN_HOST_ONLY_EXECS.get(name, "MISSING")
        lines.append(f"  {name:<36} {note}")
    v = validate()
    lines.append(f"violations: {len(v)}")
    lines.extend(f"  ! {x}" for x in v)
    return "\n".join(lines)
