"""host-sync checker: blocking device->host syncs on the hot path.

ROADMAP item 1: sync wait rivals device compute (q4: 4.13s wait vs
3.57s dev) and Theseus (PAPERS.md) treats data movement as THE thing a
distributed accelerator engine must minimize. This checker inventories
the call patterns that force the host to block on device state:

- ``sync-item``              ``x.item()`` — one scalar per round trip
- ``sync-asarray``           ``np.asarray(x)`` (numpy resolved through
                             imports, so ``jnp.asarray`` never matches)
- ``sync-device-get``        ``jax.device_get(x)``
- ``sync-block-until-ready`` ``x.block_until_ready()``
- ``sync-int-scalar``        ``int(x.num_rows)`` / ``int(jnp.sum(...))``
                             — device scalars by convention in this
                             codebase (DeviceTable.num_rows is a traced
                             int32), so ``int()`` blocks on the device;
                             the exchange row-count syncs ROADMAP item 1
                             calls out are exactly this shape
- ``movement-unledgered``    a direct ``jax.device_get``/``.item()`` in
                             a HOT package whose enclosing scope never
                             talks to the movement ledger
                             (utils/movement.py ``note_d2h``/``note_h2d``
                             /``clock``) — the crossing happens but the
                             data-movement observatory can't see it, so
                             its bytes/wall never reach the v11
                             movement_summary or the diagnose ranking.
                             Only fires inside the package (loose
                             fixture files are exempt); deliberate
                             unledgered syncs carry the same
                             ``# srtpu: sync-ok(reason)`` suppression as
                             the other sync rules. The async-first
                             funnels — ``resolve_scalars`` (batched
                             scalar decisions) and ``to_host_batched``
                             (one bulk download per drain), both in
                             columnar/device.py — note to the ledger
                             internally, so a scope that routes its
                             syncs through them counts as ledgered.

Only ``hot`` and ``warm`` packages are scanned (exec/, expr/,
columnar/, shuffle/, memory/ + the per-partition tier); tools and
session setup may sync freely. Statically we cannot prove an
``np.asarray`` argument is device-resident — sites that are host-only
or genuinely cold carry ``# srtpu: sync-ok(reason)`` so the baseline
reflects real hot-path debt (ISSUE 6 audit satellite).

``np.array`` literal construction is deliberately NOT flagged: in this
codebase device->host conversion goes through asarray/device_get/
to_host, while ``np.array([...])`` builds host constants.
"""
from __future__ import annotations

import ast
from typing import List, Set, Tuple

from . import Finding, Project, ScopedVisitor, _HOT_PACKAGES

__all__ = ["check"]

#: severities the sync checker reports on (cold packages sync by design)
REPORTED_SEVERITIES = ("hot", "warm")

#: utils/movement.py hooks whose presence in a scope marks its syncs as
#: ledgered (the funnel reports the crossing to the observatory)
_LEDGER_HOOKS = ("note_d2h", "note_h2d", "clock")

#: columnar/device.py funnels that note to the movement ledger
#: internally — calling one makes the caller's scope ledgered too
#: (the async-first batched-scalar and bulk-download funnels)
_LEDGER_FUNNELS = ("resolve_scalars", "to_host_batched")


def _movement_eligible(ctx) -> bool:
    """movement-unledgered only fires on HOT packages INSIDE the
    package tree: loose files rank hot by policy (fixtures rely on it)
    but carry no ledger obligation."""
    parts = ctx.relpath.split("/")
    return (parts[0] == "spark_rapids_tpu" and len(parts) >= 3
            and parts[1] in _HOT_PACKAGES)


class _SyncVisitor(ScopedVisitor):
    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []
        self.movement_eligible = _movement_eligible(ctx)
        # movement-unledgered bookkeeping: candidate direct-sync calls
        # plus every scope that talks to the movement ledger — resolved
        # after the walk so hook order within a function doesn't matter
        self.unledgered: List[Tuple[ast.Call, str, str]] = []
        self.ledgered_symbols: Set[str] = set()

    def _hit(self, node: ast.Call, rule: str, what: str) -> None:
        self.findings.append(self.ctx.finding(
            "sync", rule, node, self.symbol,
            f"blocking device->host sync: {what}"))

    def visit_Call(self, node: ast.Call) -> None:
        q = self.ctx.qualify(node.func)
        # .item()/.block_until_ready() match on the RAW attribute, not
        # the qualified chain: the receiver may be a computed expression
        # ((a - b).item()) that qualify() cannot name
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        if (self.movement_eligible and attr in _LEDGER_HOOKS
                and self.ctx.qualify(node.func.value)
                    .endswith("movement")):
            self.ledgered_symbols.add(self.symbol)
        if self.movement_eligible and _tail(q, 1) in _LEDGER_FUNNELS:
            self.ledgered_symbols.add(self.symbol)
        if attr == "item" and not node.args and not node.keywords:
            self._hit(node, "sync-item", f"{_tail(q) or '.item'}()")
            if self.movement_eligible:
                self.unledgered.append(
                    (node, self.symbol, f"{_tail(q) or '.item'}()"))
        elif q in ("numpy.asarray", "numpy.ndarray.__array__"):
            self._hit(node, "sync-asarray", "np.asarray(...)")
        elif q == "jax.device_get" or q.endswith(".device_get"):
            self._hit(node, "sync-device-get", "jax.device_get(...)")
            if self.movement_eligible:
                self.unledgered.append(
                    (node, self.symbol, "jax.device_get(...)"))
        elif attr == "block_until_ready":
            self._hit(node, "sync-block-until-ready",
                      f"{_tail(q) or '.block_until_ready'}()")
        elif q == "int" and len(node.args) == 1 and not node.keywords:
            aq = self.ctx.qualify(node.args[0])
            if aq.endswith(".num_rows") or aq.startswith("jax.numpy."):
                self._hit(node, "sync-int-scalar",
                          f"int({_tail(aq)}) on a device scalar")
        self.generic_visit(node)

    def movement_findings(self) -> List[Finding]:
        """Resolve the candidates against the ledgered scopes: a direct
        sync is covered when its own scope — or an enclosing/nested one
        (closures like the exchange drain) — reports to the ledger."""
        def covered(sym: str) -> bool:
            return any(s == sym or sym.startswith(s + ".")
                       or s.startswith(sym + ".")
                       for s in self.ledgered_symbols)
        return [self.ctx.finding(
                    "sync", "movement-unledgered", node, sym,
                    f"direct {what} bypasses the movement ledger — "
                    "route through a utils/movement.py note_d2h/"
                    "note_h2d funnel or suppress with a reason")
                for node, sym, what in self.unledgered
                if not covered(sym)]


def _tail(q: str, n: int = 2) -> str:
    return ".".join(q.split(".")[-n:])


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        if ctx.severity not in REPORTED_SEVERITIES:
            continue
        v = _SyncVisitor(ctx)
        v.visit(ctx.tree)
        out.extend(v.findings)
        out.extend(v.movement_findings())
    return out
