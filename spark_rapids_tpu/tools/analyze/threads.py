"""thread checker: queue bounds, thread hygiene, and engine sleeps.

Subsumes the ad-hoc regex lint that lived in tests/test_pipeline.py
(PR 3): every queue at a pipeline stage boundary must be bounded, or a
slow consumer silently re-materializes whole partitions in memory. The
AST version also enforces the no-leaked-threads contract statically —
a thread the shutdown tests cannot NAME cannot be reaped or attributed
in watchdog forensics (utils/health.py dumps stacks by thread name).

- ``thread-unbounded-queue`` — ``queue.Queue()`` / ``LifoQueue()`` /
  ``PriorityQueue()`` with no bound (positional or ``maxsize=``), and
  any ``queue.SimpleQueue()`` (unbounded by construction).
- ``thread-unnamed``         — ``threading.Thread`` without ``name=``,
  or a ``ThreadPoolExecutor`` without ``thread_name_prefix=``.
- ``thread-non-daemon``      — ``threading.Thread`` without
  ``daemon=True``: a non-daemon engine thread blocks interpreter exit
  if any shutdown path misses it.
- ``thread-sleep``           — ``time.sleep`` in engine code; polling
  belongs on ``Event.wait``/queue timeouts. The health watchdog
  (utils/health.py) and the tools tree are exempt by path.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

_QUEUE_CLASSES = frozenset({"queue.Queue", "queue.LifoQueue",
                            "queue.PriorityQueue"})
#: paths (relpath substrings) where time.sleep is legitimate
_SLEEP_EXEMPT = ("spark_rapids_tpu/tools/", "spark_rapids_tpu/utils/health")


def _kw(node: ast.Call, name: str) -> Optional[ast.keyword]:
    return next((k for k in node.keywords if k.arg == name), None)


class _ThreadVisitor(ScopedVisitor):
    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []

    def _hit(self, node, rule: str, msg: str) -> None:
        self.findings.append(self.ctx.finding(
            "thread", rule, node, self.symbol, msg))

    def visit_Call(self, node: ast.Call) -> None:
        q = self.ctx.qualify(node.func)
        if q in _QUEUE_CLASSES:
            if not node.args and _kw(node, "maxsize") is None:
                self._hit(node, "thread-unbounded-queue",
                          f"{q}() has no maxsize bound — an unbounded "
                          f"queue re-materializes whole partitions in "
                          f"memory")
        elif q == "queue.SimpleQueue":
            self._hit(node, "thread-unbounded-queue",
                      "queue.SimpleQueue is unbounded by construction")
        elif q == "threading.Thread":
            if _kw(node, "name") is None:
                self._hit(node, "thread-unnamed",
                          "threading.Thread without name= — unnamed "
                          "threads cannot be reaped by the shutdown "
                          "tests or attributed in stall forensics")
            daemon = _kw(node, "daemon")
            if daemon is None or (isinstance(daemon.value, ast.Constant)
                                  and daemon.value.value is not True):
                self._hit(node, "thread-non-daemon",
                          "threading.Thread without daemon=True — a "
                          "non-daemon engine thread blocks interpreter "
                          "exit when a shutdown path misses it")
        elif q.endswith("ThreadPoolExecutor"):
            if _kw(node, "thread_name_prefix") is None:
                self._hit(node, "thread-unnamed",
                          "ThreadPoolExecutor without thread_name_prefix= "
                          "— pool workers show up as ThreadPoolExecutor-N "
                          "in watchdog stack dumps")
        elif q == "time.sleep":
            if not any(x in self.ctx.relpath for x in _SLEEP_EXEMPT):
                self._hit(node, "thread-sleep",
                          "time.sleep in engine code — poll with "
                          "Event.wait()/queue timeouts so shutdown can "
                          "interrupt the wait")
        self.generic_visit(node)


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        v = _ThreadVisitor(ctx)
        v.visit(ctx.tree)
        out.extend(v.findings)
    return out
