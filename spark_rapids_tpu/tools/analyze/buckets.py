"""bucket checker: shape-bucket choices bypassing the central policy.

The canonical bucket ladder (``columnar/device.py`` ``BucketPolicy``,
``spark.rapids.tpu.shapeBuckets.*``) exists so every device batch lands on
a small, REPEATABLE set of row capacities — the precondition for both
bounded XLA compile counts and the persistent compile tier (a persisted
executable only re-hits when a rerun reproduces the same shapes). A
hardcoded bucket literal forks the ladder: that call site compiles its own
shape family that no conf can steer and no other site shares.

- ``bucket-literal``       — a numeric literal passed as the bucket floor:
  ``min_bucket=<int>`` at any call site, or the ``min_bucket`` positional
  of ``bucket_rows`` / ``shrink_to_fit`` / ``concat_device_tables`` /
  ``DeviceTable.from_host``. Thread ``conf.min_bucket_rows`` (planner
  nodes) or pass ``None`` to inherit the policy.
- ``bucket-adhoc-default`` — a function parameter named ``min_bucket``
  with a numeric literal default (the pre-policy ``= 1024`` pattern);
  default ``None`` and resolve through ``resolve_min_bucket``.

Hot + warm packages only (tools/doc generators may hardcode freely);
deliberate protocol constants carry ``# srtpu: bucket-ok(reason)``.
"""
from __future__ import annotations

import ast
from typing import List

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

#: callables whose second positional argument is the bucket floor
_BUCKET_CALLS = ("bucket_rows", "shrink_to_fit", "concat_device_tables",
                 "from_host")


def _is_num(node) -> bool:
    return isinstance(node, ast.Constant) \
        and isinstance(node.value, (int, float)) \
        and not isinstance(node.value, bool)


class _BucketVisitor(ScopedVisitor):
    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []

    def _hit(self, node, rule: str, msg: str) -> None:
        self.findings.append(self.ctx.finding(
            "bucket", rule, node, self.symbol, msg))

    def visit_Call(self, node: ast.Call) -> None:
        kw = next((k for k in node.keywords if k.arg == "min_bucket"), None)
        if kw is not None and _is_num(kw.value):
            self._hit(node, "bucket-literal",
                      f"min_bucket={kw.value.value!r} hardcodes a bucket "
                      f"floor outside the central shape-bucket policy — "
                      f"thread conf.min_bucket_rows or pass None")
        else:
            q = self.ctx.qualify(node.func)
            name = q.rsplit(".", 1)[-1]
            if name in _BUCKET_CALLS and len(node.args) >= 2 \
                    and _is_num(node.args[1]):
                self._hit(node, "bucket-literal",
                          f"{name}(..., {node.args[1].value!r}) hardcodes "
                          f"a bucket floor outside the central shape-"
                          f"bucket policy — thread conf.min_bucket_rows "
                          f"or pass None")
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        args = node.args
        pos = args.posonlyargs + args.args
        defaults = args.defaults
        for a, d in zip(pos[len(pos) - len(defaults):], defaults):
            if a.arg == "min_bucket" and _is_num(d):
                self._hit(d, "bucket-adhoc-default",
                          f"parameter min_bucket defaults to {d.value!r} — "
                          f"ad-hoc per-node bucket defaults scatter the "
                          f"ladder; default None and resolve through "
                          f"resolve_min_bucket()")
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and a.arg == "min_bucket" and _is_num(d):
                self._hit(d, "bucket-adhoc-default",
                          f"parameter min_bucket defaults to {d.value!r} — "
                          f"default None and resolve through "
                          f"resolve_min_bucket()")

    def _visit_def(self, node) -> None:
        # enter the function scope BEFORE checking its defaults so the
        # finding keys on the def itself (line drift immunity)
        self._scope.append(node.name)
        try:
            self._check_defaults(node)
            self.generic_visit(node)
        finally:
            self._scope.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        if ctx.severity == "cold":
            continue
        v = _BucketVisitor(ctx)
        v.visit(ctx.tree)
        out.extend(v.findings)
    return out
