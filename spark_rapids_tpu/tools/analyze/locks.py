"""lock checker: TpuSemaphore discipline under materialize locks.

The PR-3 deadlock class: pipelined partition drains race to materialize
a shared node (exchange, AQE stage, broadcast build) behind a
``_mat_lock``; if the lock holder then BLOCKS acquiring the TpuSemaphore
while an admitted task waits on that same lock, the engine wedges at
``concurrentGpuTasks=1`` (parallel/pipeline.py ``exempt_admission``
invariant). PR-3 fixed it by convention only — every materialize body
wraps itself in ``exempt_admission()``. This checker enforces the
convention with a project-wide call-graph walk:

- ``lock-sem-under-materialize`` — inside a ``with <x>._mat_lock:``
  body, a call that (transitively) reaches semaphore acquisition
  (``acquire_if_necessary`` / ``held`` / ``task_scope``) and is not
  wrapped in ``exempt_admission()`` / ``_worker_scope()``.
- ``lock-bare-contextmanager`` — ``sem.task_scope()`` / ``sem.held()``
  / ``exempt_admission()`` as a bare expression statement: the context
  manager is created but never entered, so the call silently does
  nothing (or leaks a hold when entered manually).
- ``lock-release-all-in-scope`` — ``release_all()`` lexically inside a
  ``with sem.held()/task_scope():`` body: it drops the scope's own hold
  mid-scope, so the scope exit releases a permit it no longer owns.

The call graph is name-based (a call or function-reference argument to
``f`` links to every analyzed def named ``f``) — deliberately coarse:
false positives are cheap to suppress with ``# srtpu: lock-ok(reason)``,
while a missed edge would hide a deadlock.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

#: attribute calls that acquire (or may block on) the semaphore
_ACQUIRING_ATTRS = frozenset({"acquire_if_necessary", "held", "task_scope"})
#: context managers inside which semaphore acquires are no-ops
_EXEMPT_NAMES = frozenset({"exempt_admission", "_worker_scope"})
#: with-context attribute names that mark a shared materialize lock
_MAT_LOCK_MARKERS = ("_mat_lock", "materialize_lock")


def _bare_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_exempt_with(item: ast.withitem) -> bool:
    cm = item.context_expr
    return isinstance(cm, ast.Call) \
        and _bare_name(cm.func) in _EXEMPT_NAMES


def _is_mat_lock_with(item: ast.withitem) -> bool:
    name = _bare_name(item.context_expr)
    return name is not None \
        and any(m in name for m in _MAT_LOCK_MARKERS)


def _is_scope_with(item: ast.withitem) -> bool:
    cm = item.context_expr
    return isinstance(cm, ast.Call) \
        and _bare_name(cm.func) in ("held", "task_scope")


class _GraphBuilder(ScopedVisitor):
    """Per-function: does it directly acquire, and which names does it
    call (or pass around as a function reference)?"""

    def __init__(self):
        super().__init__()
        self.direct_acquirers: Set[str] = set()
        self.edges: Dict[str, Set[str]] = {}
        self.known_defs: Set[str] = set()
        self._fn_stack: List[str] = []
        self._exempt_depth = 0

    def _scoped_fn(self, node):
        self.known_defs.add(node.name)
        self._fn_stack.append(node.name)
        self._scope.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._scope.pop()
            self._fn_stack.pop()

    visit_FunctionDef = _scoped_fn
    visit_AsyncFunctionDef = _scoped_fn

    def visit_With(self, node: ast.With) -> None:
        exempt = any(_is_exempt_with(i) for i in node.items)
        acquiring = any(_is_scope_with(i) for i in node.items)
        if acquiring and self._fn_stack and not self._exempt_depth:
            self.direct_acquirers.add(self._fn_stack[-1])
        if exempt:
            self._exempt_depth += 1
        try:
            self.generic_visit(node)
        finally:
            if exempt:
                self._exempt_depth -= 1

    def visit_Call(self, node: ast.Call) -> None:
        name = _bare_name(node.func)
        if self._fn_stack:
            cur = self._fn_stack[-1]
            if name in _ACQUIRING_ATTRS and not self._exempt_depth:
                self.direct_acquirers.add(cur)
            if name:
                self.edges.setdefault(cur, set()).add(name)
            # a function passed BY REFERENCE may be invoked downstream
            # (parallel_map(drain, ...)): link it too
            for arg in list(node.args) + [k.value for k in node.keywords]:
                ref = _bare_name(arg)
                if ref:
                    self.edges.setdefault(cur, set()).add(ref)
        self.generic_visit(node)


def _transitive_acquirers(builders: List[_GraphBuilder]) -> Set[str]:
    acquirers: Set[str] = set()
    edges: Dict[str, Set[str]] = {}
    known: Set[str] = set()
    for b in builders:
        acquirers |= b.direct_acquirers
        known |= b.known_defs
        for k, v in b.edges.items():
            edges.setdefault(k, set()).update(v)
    # only propagate through names that are actual defs somewhere in the
    # project (a call to e.g. list() must not become an edge)
    changed = True
    while changed:
        changed = False
        for fn, callees in edges.items():
            if fn in acquirers:
                continue
            if any(c in acquirers and c in (known | _ACQUIRING_ATTRS)
                   for c in callees):
                acquirers.add(fn)
                changed = True
    return acquirers


class _SiteVisitor(ScopedVisitor):
    """Flag the three rules, given the project-wide acquirer set."""

    def __init__(self, ctx, acquirers: Set[str]):
        super().__init__()
        self.ctx = ctx
        self.acquirers = acquirers
        self.findings: List[Finding] = []
        self._mat_depth = 0
        self._exempt_depth = 0
        self._scope_with_depth = 0

    def visit_With(self, node: ast.With) -> None:
        mat = any(_is_mat_lock_with(i) for i in node.items)
        exempt = any(_is_exempt_with(i) for i in node.items)
        scope = any(_is_scope_with(i) for i in node.items)
        if mat:
            self._mat_depth += 1
        if exempt:
            self._exempt_depth += 1
        if scope:
            self._scope_with_depth += 1
        try:
            self.generic_visit(node)
        finally:
            if mat:
                self._mat_depth -= 1
            if exempt:
                self._exempt_depth -= 1
            if scope:
                self._scope_with_depth -= 1

    def visit_Expr(self, node: ast.Expr) -> None:
        call = node.value
        if isinstance(call, ast.Call):
            name = _bare_name(call.func)
            if name in ("task_scope", "held") or name in _EXEMPT_NAMES:
                self.findings.append(self.ctx.finding(
                    "lock", "lock-bare-contextmanager", node, self.symbol,
                    f"'{name}(...)' creates a context manager that is "
                    f"never entered — use 'with {name}(...):'"))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = _bare_name(node.func)
        if self._mat_depth and not self._exempt_depth:
            reaches = name in _ACQUIRING_ATTRS or name in self.acquirers
            refs = [] if reaches else [
                _bare_name(a) for a in
                list(node.args) + [k.value for k in node.keywords]]
            via = name if reaches else next(
                (r for r in refs if r in self.acquirers), None)
            if reaches or via:
                self.findings.append(self.ctx.finding(
                    "lock", "lock-sem-under-materialize", node, self.symbol,
                    f"'{via or name}' may block on the TpuSemaphore while "
                    f"holding a materialize lock — wrap the locked body in "
                    f"exempt_admission() (PR-3 deadlock class)"))
        if name == "release_all" and self._scope_with_depth:
            self.findings.append(self.ctx.finding(
                "lock", "lock-release-all-in-scope", node, self.symbol,
                "release_all() inside a held()/task_scope() body drops "
                "the scope's own hold; the scope exit then releases a "
                "permit it no longer owns"))
        self.generic_visit(node)


def check(project: Project) -> List[Finding]:
    builders: List[_GraphBuilder] = []
    for ctx in project.modules:
        b = _GraphBuilder()
        b.visit(ctx.tree)
        builders.append(b)
    acquirers = _transitive_acquirers(builders)
    out: List[Finding] = []
    for ctx in project.modules:
        v = _SiteVisitor(ctx, acquirers)
        v.visit(ctx.tree)
        out.extend(v.findings)
    return out
