"""shuffle checker: every shuffle-tier transfer must be observed.

The shuffle observatory (shuffle/telemetry.py) exists so per-tier
transfer cost, retries and stragglers are attributable from one place —
but only for transfers that actually note it. A new chokepoint added to
the shuffle package without a ``telemetry.note_transfer`` nearby is a
blind spot: its bytes vanish from the event log's ``shuffle_summary``,
the sentinel's shuffle-wall gate, and the MULTICHIP tier breakdown,
and the first anyone learns of it is a straggler nobody can attribute.

Rule:

- ``shuffle-unobserved`` — a transfer-shaped call (``.sendall(``,
  ``.publish(``, ``.publish_table(``, ``.put_lazy(``, ``.fetch(``,
  ``.fetch_tables(``, ``.transfer(``) inside ``spark_rapids_tpu/
  shuffle/`` whose enclosing function never references the telemetry
  module: the transfer has no local evidence of observation. Where the
  observatory is fed by the caller for every path (an in-process mock,
  a helper whose callers all note), suppress inline with
  ``# srtpu: shuffle-ok(<reason>)``.

Scoped to the shuffle package only — transfer verbs like ``fetch`` are
too generic to match engine-wide, and the observatory's contract is
precisely that the shuffle tiers are where wire cost concentrates.
telemetry.py itself is exempt (the observatory does not observe
itself).
"""
from __future__ import annotations

import ast
from typing import List

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

#: attribute-call names that move shuffle payloads between processes,
#: tiers or peers — the transfer chokepoints the observatory instruments
_TRANSFER_ATTRS = frozenset({
    "sendall", "publish", "publish_table", "put_lazy",
    "fetch", "fetch_tables", "transfer",
})

_SCOPE_PREFIX = "spark_rapids_tpu/shuffle/"
_EXEMPT = (_SCOPE_PREFIX + "telemetry.py",)


def _telemetry_names(ctx) -> frozenset:
    """Local names that resolve to the telemetry module or a member of
    it (``from . import telemetry``, ``from .telemetry import
    note_transfer``, aliases included)."""
    names = {"telemetry"}
    for alias, full in ctx.imports.items():
        parts = full.split(".")
        if "telemetry" in parts:
            names.add(alias)
    return frozenset(names)


class _ShuffleVisitor(ScopedVisitor):
    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []
        self._tele_names = _telemetry_names(ctx)
        #: per-function stack: does this function reference telemetry?
        self._observed_stack: List[bool] = []

    def _fn_references_telemetry(self, fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id in self._tele_names:
                return True
        return False

    def _scoped_fn(self, node):
        self._observed_stack.append(self._fn_references_telemetry(node))
        try:
            ScopedVisitor._scoped(self, node)
        finally:
            self._observed_stack.pop()

    visit_FunctionDef = _scoped_fn
    visit_AsyncFunctionDef = _scoped_fn

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _TRANSFER_ATTRS \
                and not any(self._observed_stack):
            self.findings.append(self.ctx.finding(
                "shuffle", "shuffle-unobserved", node, self.symbol,
                f".{f.attr}() moves shuffle payload but no enclosing "
                f"function references shuffle/telemetry.py — the "
                f"transfer is invisible to the observatory (per-tier "
                f"bytes, walls, stragglers); note_transfer() around it, "
                f"or suppress with where the observation happens"))
        self.generic_visit(node)


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        if not ctx.relpath.startswith(_SCOPE_PREFIX) \
                or ctx.relpath in _EXEMPT:
            continue
        v = _ShuffleVisitor(ctx)
        v.visit(ctx.tree)
        out.extend(v.findings)
    return out
