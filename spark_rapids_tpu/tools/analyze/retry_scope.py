"""retry checker: device work in hot packages outside any OOM retry scope.

PR-14's escalation ladder (memory/retry.py) only protects the call sites
that opt in: ``with_retry``/``with_retry_split`` scopes spill, retry and
split-and-retry a failing dispatch; everything else surfaces a raw
``RESOURCE_EXHAUSTED`` and fails the query. Two rules inventory the
unprotected surface statically:

- ``retry-unguarded-dispatch`` — a call to a name bound from
  ``cached_jit(...)`` whose enclosing scope chain never references the
  retry API. The jit wrapper itself carries the jit-level spill+retry
  (compile_cache routes through ``wrap_jit``), but a persistent OOM then
  raises a structured ``DeviceOomError`` — without an enclosing
  ``with_retry_split`` scope nothing can halve the batch, so the query
  dies where a split would have recovered it.
- ``retry-unguarded-upload`` — ``DeviceTable.from_host(...)`` in a scope
  chain with no retry reference. Uploads have no built-in guard at all:
  an HBM-exhausted H2D copy raises instead of walking the ladder
  (``with_retry_split`` + ``split_host_rows`` splits the host batch).

A scope counts as covered when it, or any enclosing function scope,
references ``with_retry``/``with_retry_split``/``wrap_jit``/
``wrap_jit_donating`` (or the compile_cache shims ``oom_retry``/
``oom_spill_noretry``): closures dispatched by a sibling
``with_retry_split`` call are defined in the covered enclosing scope, so
the chain test follows the value flow the AST can see. Sites that are
deliberately spill-only (merge kernels whose inputs cannot split,
broadcast builds) or that manage OOM themselves carry
``# srtpu: retry-ok(<reason>)``; pre-existing debt seeds the committed
baseline like every other check.
"""
from __future__ import annotations

import ast
from typing import List, Set, Tuple

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

#: only the per-batch execution path is reported — cold/warm packages
#: (tools, planning, session setup) run device work rarely enough that
#: a raw OOM failing the call is acceptable, and several do so before a
#: catalog even exists to spill from
REPORTED_SEVERITIES = ("hot",)

#: referencing any of these marks the scope chain as retry-covered
_RETRY_API = ("with_retry", "with_retry_split", "wrap_jit",
              "wrap_jit_donating", "oom_retry", "oom_spill_noretry")


class _RetryVisitor(ScopedVisitor):
    """Collects, per enclosing-scope symbol: retry-API references,
    names bound from ``cached_jit(...)``, and the flaggable sites."""

    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.covered: Set[str] = set()
        self.jit_bound: Set[Tuple[str, str]] = set()  # (scope, name)
        self.uploads: List[Tuple[str, ast.Call]] = []
        self.dispatches: List[Tuple[str, str, ast.Call]] = []

    def visit_Name(self, node: ast.Name) -> None:
        q = self.ctx.qualify(node)
        if q.rsplit(".", 1)[-1] in _RETRY_API:
            self.covered.add(self.symbol)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in _RETRY_API:
            self.covered.add(self.symbol)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            q = self.ctx.qualify(node.value.func)
            if q.rsplit(".", 1)[-1] == "cached_jit":
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.jit_bound.add((self.symbol, n.id))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        if attr == "from_host" \
                and "DeviceTable" in self.ctx.qualify(node.func):
            self.uploads.append((self.symbol, node))
        elif isinstance(node.func, ast.Name):
            self.dispatches.append((self.symbol, node.func.id, node))
        self.generic_visit(node)


def _chain(symbol: str):
    parts = symbol.split(".")
    return [".".join(parts[:i]) for i in range(1, len(parts) + 1)]


def _scope_covered(symbol: str, covered: Set[str]) -> bool:
    """True when ``symbol`` or any enclosing scope references the retry
    API — closures a covered scope hands to with_retry* count."""
    return any(s in covered for s in _chain(symbol)) \
        or "<module>" in covered and symbol == "<module>"


def _bound_in_chain(symbol: str, name: str,
                    jit_bound: Set[Tuple[str, str]]) -> bool:
    return any((s, name) in jit_bound
               for s in _chain(symbol) + ["<module>"])


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        if ctx.severity not in REPORTED_SEVERITIES:
            continue
        v = _RetryVisitor(ctx)
        v.visit(ctx.tree)
        for symbol, node in v.uploads:
            if _scope_covered(symbol, v.covered):
                continue
            out.append(ctx.finding(
                "retry", "retry-unguarded-upload", node, symbol,
                "DeviceTable.from_host outside any OOM retry scope — an "
                "HBM-exhausted upload raises instead of walking the "
                "spill/retry/split ladder (wrap with memory/retry.py "
                "with_retry_split + split_host_rows)"))
        for symbol, name, node in v.dispatches:
            if not _bound_in_chain(symbol, name, v.jit_bound):
                continue
            if _scope_covered(symbol, v.covered):
                continue
            out.append(ctx.finding(
                "retry", "retry-unguarded-dispatch", node, symbol,
                f"cached_jit program '{name}' dispatched with no "
                "enclosing retry scope — a persistent device OOM raises "
                "DeviceOomError with nothing able to split the batch "
                "(wrap the dispatch in memory/retry.py with_retry / "
                "with_retry_split)"))
    return out
