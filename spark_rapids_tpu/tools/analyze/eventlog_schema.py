"""eventlog checker: event-record schema discipline.

The event log's replay contract (tools/eventlog.py) is a closed record
set: every record type a writer may emit is declared in ``RECORD_TYPES``
alongside the schema version that introduced it, and ``SCHEMA_VERSION``
is the ceiling the app_start record advertises. Three drift modes break
replay silently — an unregistered record type loads as dead weight (no
QueryReplay branch, no docs, no version history), a record type
registered above SCHEMA_VERSION ships in logs whose advertised version
predates it (downstream version gates mis-classify the log), and a
record dict whose event type cannot be read statically defeats the
registry audit entirely. Rules:

- ``eventlog-unregistered-record`` — a ``write({"event": <const>, ...})``
  call site naming a type absent from ``RECORD_TYPES``. Adding a record
  type means registering it (with a version bump + migration note), not
  just emitting it.
- ``eventlog-version-skew`` — a ``RECORD_TYPES`` entry whose version
  exceeds ``SCHEMA_VERSION``: the registry promises a schema the writer
  does not declare, i.e. the version bump was forgotten.
- ``eventlog-dynamic-record`` — the dict passed to ``write()`` has no
  statically-readable ``"event"`` string: the key is missing, computed,
  or a ``**spread`` placed after it can override the type at runtime.
  Where the spread source provably never carries an ``event`` key (the
  health monitor's flat heartbeat sample), suppress inline with
  ``# srtpu: eventlog-ok(<reason>)``; otherwise put the spread FIRST so
  the literal key wins.

Only ``write``/``self.write`` attribute calls whose first argument is a
dict literal are considered — file-handle ``.write(str)`` sites and
other write methods don't match the shape and stay silent.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

_EVENTLOG_RELPATH = "spark_rapids_tpu/tools/eventlog.py"


def _registry_from_ast(tree: ast.AST) -> Tuple[Dict[str, int], int,
                                               Optional[ast.AST]]:
    """Extract (RECORD_TYPES, SCHEMA_VERSION, registry assignment node)
    from eventlog.py's module AST — the checker must not import the
    runtime module (analysis runs without jax)."""
    registry: Dict[str, int] = {}
    version = 0
    reg_node: Optional[ast.AST] = None
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            targets = [node.target.id]
        else:
            continue
        value = node.value
        if "SCHEMA_VERSION" in targets \
                and isinstance(value, ast.Constant) \
                and isinstance(value.value, int):
            version = value.value
        elif "RECORD_TYPES" in targets and isinstance(value, ast.Dict):
            reg_node = node
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(v, ast.Constant):
                    registry[str(k.value)] = int(v.value)
    return registry, version, reg_node


def _record_event(call: ast.Call) -> Tuple[Optional[str], bool]:
    """(event type, verifiable) for a ``write({...})`` call: the
    ``"event"`` constant from the dict literal, and whether that value
    is trustworthy (no later ``**spread`` can override it)."""
    arg = call.args[0]
    assert isinstance(arg, ast.Dict)
    event: Optional[str] = None
    event_pos = -1
    last_spread = -1
    for i, (k, v) in enumerate(zip(arg.keys, arg.values)):
        if k is None:  # **spread
            last_spread = i
        elif isinstance(k, ast.Constant) and k.value == "event":
            event_pos = i
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                event = v.value
    if event is None:
        return None, False
    return event, last_spread < event_pos


class _EventlogVisitor(ScopedVisitor):
    def __init__(self, ctx, registry: Dict[str, int]):
        super().__init__()
        self.ctx = ctx
        self.registry = registry
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        is_write = (isinstance(func, ast.Attribute)
                    and func.attr == "write") \
            or (isinstance(func, ast.Name) and func.id == "write")
        if is_write and node.args and isinstance(node.args[0], ast.Dict):
            event, verifiable = _record_event(node)
            if event is None:
                self.findings.append(self.ctx.finding(
                    "eventlog", "eventlog-dynamic-record", node,
                    self.symbol,
                    "record dict has no constant \"event\" key — the "
                    "schema registry cannot audit this write site; name "
                    "the type literally"))
            elif not verifiable:
                self.findings.append(self.ctx.finding(
                    "eventlog", "eventlog-dynamic-record", node,
                    self.symbol,
                    f"\"event\": \"{event}\" precedes a **spread that "
                    "can override it at runtime — put the spread first "
                    "so the literal type wins, or suppress with the "
                    "reason the source can never carry an event key"))
            elif event not in self.registry:
                self.findings.append(self.ctx.finding(
                    "eventlog", "eventlog-unregistered-record", node,
                    self.symbol,
                    f"record type \"{event}\" is not in "
                    "RECORD_TYPES — register it with the schema version "
                    "that introduces it (and bump SCHEMA_VERSION + the "
                    "docs/observability.md migration note)"))
        self.generic_visit(node)


def check(project: Project) -> List[Finding]:
    eventlog_mod = project.module_for(_EVENTLOG_RELPATH)
    if eventlog_mod is None:
        # partial-tree invocation (explicit paths without eventlog.py):
        # no registry to audit against, so no claims either way
        return []
    registry, version, reg_node = _registry_from_ast(eventlog_mod.tree)
    out: List[Finding] = []
    if registry and reg_node is not None:
        stale = {k: v for k, v in registry.items() if v > version}
        if stale:
            worst = max(stale.values())
            out.append(eventlog_mod.finding(
                "eventlog", "eventlog-version-skew", reg_node, "<module>",
                f"RECORD_TYPES registers {sorted(stale)} at version "
                f"{worst} but SCHEMA_VERSION is {version} — bump "
                "SCHEMA_VERSION so app_start advertises the schema "
                "these records belong to"))
    for ctx in project.modules:
        v = _EventlogVisitor(ctx, registry)
        v.visit(ctx.tree)
        out.extend(v.findings)
    return out
