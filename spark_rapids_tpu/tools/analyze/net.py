"""net checker: socket deadline discipline + swallowed transport errors.

The fault-injection arc (utils/faults.py, docs/fault_tolerance.md) made
network failure a first-class, recoverable event — but recovery only
triggers if the failure SURFACES. Two patterns defeat it statically:

- a socket operation with no deadline turns a dead peer into an
  infinite hang (the exact 300s-wedge the worker supervisor exists to
  kill, except nothing supervises the shuffle client's own sockets);
- a blanket ``except ...: pass`` around transport code turns a real
  fault into silently-missing data.

Rules (all scoped to ``hot``/``warm`` packages — tools and session
setup may block interactively):

- ``net-connect-no-timeout`` — ``socket.create_connection(...)`` with
  no ``timeout`` (second positional or keyword): connect hangs ride the
  kernel's default, minutes long. Pass the conf-driven connect timeout.
- ``net-socket-no-timeout`` — a ``.recv(``/``.accept(``/``.connect(``
  call inside a function that never calls ``settimeout`` (and, for
  connect, doesn't use a deadline-bearing ``create_connection``): the
  blocking call has no local evidence of a deadline. Where the deadline
  is provably set by every caller (a helper that receives an
  already-configured socket), suppress inline with
  ``# srtpu: net-ok(<reason>)``.
- ``net-bare-except-pass`` — ``except Exception:`` / bare ``except:``
  whose entire body is ``pass``: transport and spill errors vanish
  instead of reaching the retry/recompute machinery. Best-effort
  close() paths are the legitimate case — suppress with the reason.
"""
from __future__ import annotations

import ast
from typing import List

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

#: socket methods that block until the peer acts
_BLOCKING_ATTRS = frozenset({"recv", "accept", "connect"})


def _has_timeout_arg(node: ast.Call) -> bool:
    """create_connection(addr[, timeout]) — deadline as 2nd positional
    or timeout= keyword."""
    if len(node.args) >= 2:
        return True
    return any(k.arg == "timeout" for k in node.keywords)


def _body_is_pass(handler: ast.ExceptHandler) -> bool:
    return len(handler.body) == 1 and isinstance(handler.body[0], ast.Pass)


def _swallows_everything(handler: ast.ExceptHandler, ctx) -> bool:
    """Bare ``except:`` or ``except Exception:`` (incl. BaseException);
    typed handlers (OSError, ...) express intent and stay silent."""
    if handler.type is None:
        return True
    q = ctx.qualify(handler.type)
    return q in ("Exception", "BaseException",
                 "builtins.Exception", "builtins.BaseException")


class _NetVisitor(ScopedVisitor):
    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []
        #: per-function stack: does the enclosing function set a
        #: deadline anywhere (settimeout, or create_connection with one)?
        self._deadline_stack: List[bool] = []

    def _hit(self, node, rule: str, msg: str) -> None:
        self.findings.append(self.ctx.finding(
            "net", rule, node, self.symbol, msg))

    def _fn_sets_deadline(self, fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "settimeout":
                return True
            if self.ctx.qualify(f) == "socket.create_connection" \
                    and _has_timeout_arg(node):
                return True
        return False

    def _scoped_fn(self, node):
        self._deadline_stack.append(self._fn_sets_deadline(node))
        try:
            ScopedVisitor._scoped(self, node)
        finally:
            self._deadline_stack.pop()

    visit_FunctionDef = _scoped_fn
    visit_AsyncFunctionDef = _scoped_fn

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if self.ctx.qualify(f) == "socket.create_connection":
            if not _has_timeout_arg(node):
                self._hit(node, "net-connect-no-timeout",
                          "socket.create_connection without a timeout — "
                          "a dead peer hangs the connect for the kernel "
                          "default (minutes); pass the conf-driven "
                          "connect timeout")
        elif isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS:
            in_fn = bool(self._deadline_stack)
            deadline = self._deadline_stack[-1] if in_fn else False
            if in_fn and not deadline:
                self._hit(node, "net-socket-no-timeout",
                          f".{f.attr}() in a function that never sets a "
                          f"socket deadline — a dead peer blocks here "
                          f"forever; settimeout() the socket (or suppress "
                          f"with why every caller already did)")
        self.generic_visit(node)

    def visit_Try(self, node: ast.Try) -> None:
        for handler in node.handlers:
            if _body_is_pass(handler) \
                    and _swallows_everything(handler, self.ctx):
                # anchor on the ``pass`` statement so a trailing
                # suppression comment on that line applies
                self.findings.append(self.ctx.finding(
                    "net", "net-bare-except-pass", handler.body[0],
                    self.symbol,
                    "except-everything with a pass body — transport and "
                    "spill faults vanish here instead of reaching the "
                    "retry/recompute machinery; catch the specific "
                    "error or suppress with why best-effort is correct"))
        self.generic_visit(node)


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        if ctx.severity == "cold":
            continue  # tools/session setup may block interactively
        v = _NetVisitor(ctx)
        v.visit(ctx.tree)
        out.extend(v.findings)
    return out
