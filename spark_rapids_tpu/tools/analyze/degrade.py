"""degrade checker: device failures that can escape the degradation ladder.

PR-15's graceful-degradation arc (exec/fallback.py, utils/deadline.py)
only helps at the call sites that participate: ``with_host_fallback``
re-executes a terminally-failing batch on the host engine,
``quarantine_on_failure`` at least notes the failure for plan-time
quarantine, and the ladder's structured errors (``DeviceOomError``,
``QueryTimeoutError``) must PROPAGATE to reach the boundary that knows
what to do with them. Two rules inventory the escape hatches statically:

- ``degrade-unguarded-dispatch`` — a call to a name bound from
  ``cached_jit(...)`` whose enclosing scope chain references neither the
  OOM retry API nor the degradation API. Such a site is outside BOTH
  the retry scope and the fallback boundary: a terminal device failure
  there kills the query with no retry, no host re-execution and no
  quarantine note — the planner will happily schedule the same doomed
  operator again next run.
- ``degrade-swallowed-failure`` — an ``except`` handler in engine
  packages that catches ``Exception``/``BaseException``/bare (or the
  ladder's own ``DeviceOomError``/``QueryTimeoutError``) and neither
  re-raises nor classifies the failure. A swallowed ``DeviceOomError``
  voids split-and-retry bookkeeping; a swallowed ``QueryTimeoutError``
  un-cancels a query the deadline already killed, leaking the very
  permits/threads the cooperative-cancellation design exists to free.

A scope chain counts as fallback-covered when it references
``with_host_fallback``/``quarantine_on_failure``/``classify_failure``
(or ``plan_quarantine_pass`` — planner-side routing); retry coverage
uses the same API set as the ``retry`` checker. Handlers that re-raise
(any ``raise``), call ``classify_failure``, or deliberately terminate a
worker loop carry ``# srtpu: degrade-ok(<reason>)``; pre-existing debt
seeds the committed baseline like every other check.
"""
from __future__ import annotations

import ast
from typing import List, Set, Tuple

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

#: dispatch rule: per-batch execution path only (matches the retry
#: checker); the swallow rule also covers warm packages — a swallowed
#: QueryTimeoutError in plan/parallel/io un-cancels the query just the
#: same
DISPATCH_SEVERITIES = ("hot",)
SWALLOW_SEVERITIES = ("hot", "warm")

#: referencing any of these marks the scope chain as retry-covered
#: (mirrors retry_scope._RETRY_API — the two checkers must agree on
#: what "inside the ladder" means)
_RETRY_API = ("with_retry", "with_retry_split", "wrap_jit",
              "wrap_jit_donating", "oom_retry", "oom_spill_noretry")

#: referencing any of these marks the scope chain as fallback-covered
_DEGRADE_API = ("with_host_fallback", "quarantine_on_failure",
                "classify_failure", "plan_quarantine_pass")

#: catching one of these (or a catch-all) without re-raising swallows a
#: structured degradation signal
_STRUCTURED = ("DeviceOomError", "QueryTimeoutError")
_CATCH_ALL = ("Exception", "BaseException")


class _DegradeVisitor(ScopedVisitor):
    """Collects, per enclosing-scope symbol: retry/fallback API
    references, names bound from ``cached_jit(...)``, dispatch sites
    and except handlers."""

    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.retry_covered: Set[str] = set()
        self.degrade_covered: Set[str] = set()
        self.jit_bound: Set[Tuple[str, str]] = set()  # (scope, name)
        self.dispatches: List[Tuple[str, str, ast.Call]] = []
        self.handlers: List[Tuple[str, ast.ExceptHandler]] = []

    def _note_ref(self, name: str) -> None:
        if name in _RETRY_API:
            self.retry_covered.add(self.symbol)
        if name in _DEGRADE_API:
            self.degrade_covered.add(self.symbol)

    def visit_Name(self, node: ast.Name) -> None:
        self._note_ref(self.ctx.qualify(node).rsplit(".", 1)[-1])
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self._note_ref(node.attr)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            q = self.ctx.qualify(node.value.func)
            if q.rsplit(".", 1)[-1] == "cached_jit":
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.jit_bound.add((self.symbol, n.id))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name):
            self.dispatches.append((self.symbol, node.func.id, node))
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        self.handlers.append((self.symbol, node))
        self.generic_visit(node)


def _chain(symbol: str):
    parts = symbol.split(".")
    return [".".join(parts[:i]) for i in range(1, len(parts) + 1)]


def _covered(symbol: str, covered: Set[str]) -> bool:
    return any(s in covered for s in _chain(symbol) + ["<module>"])


def _bound_in_chain(symbol: str, name: str,
                    jit_bound: Set[Tuple[str, str]]) -> bool:
    return any((s, name) in jit_bound
               for s in _chain(symbol) + ["<module>"])


def _caught_names(handler: ast.ExceptHandler) -> List[str]:
    """Leaf names of the caught type expression ('' for a bare except)."""
    if handler.type is None:
        return [""]
    nodes = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    out = []
    for n in nodes:
        if isinstance(n, ast.Attribute):
            out.append(n.attr)
        elif isinstance(n, ast.Name):
            out.append(n.id)
    return out


def _swallows(handler: ast.ExceptHandler) -> bool:
    """True when no path through the handler body re-raises or
    classifies the failure — the conservative static read is that the
    exception dies here."""
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return False
        if isinstance(n, ast.Call):
            f = n.func
            leaf = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else ""
            if leaf == "classify_failure":
                return False
    return True


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        if ctx.severity not in SWALLOW_SEVERITIES:
            continue
        v = _DegradeVisitor(ctx)
        v.visit(ctx.tree)
        if ctx.severity in DISPATCH_SEVERITIES:
            for symbol, name, node in v.dispatches:
                if not _bound_in_chain(symbol, name, v.jit_bound):
                    continue
                if _covered(symbol, v.retry_covered) \
                        or _covered(symbol, v.degrade_covered):
                    continue
                out.append(ctx.finding(
                    "degrade", "degrade-unguarded-dispatch", node, symbol,
                    f"cached_jit program '{name}' dispatched outside both "
                    "the OOM retry scope and the fallback boundary — a "
                    "terminal device failure here kills the query with no "
                    "retry, no host re-execution and no quarantine note "
                    "(wrap with exec/fallback.py with_host_fallback, or at "
                    "least quarantine_on_failure)"))
        for symbol, handler in v.handlers:
            names = _caught_names(handler)
            catches_all = any(n in _CATCH_ALL or n == "" for n in names)
            catches_structured = any(n in _STRUCTURED for n in names)
            if not (catches_all or catches_structured):
                continue
            if not _swallows(handler):
                continue
            what = "/".join(n for n in names if n in _STRUCTURED) \
                if catches_structured else "a catch-all"
            out.append(ctx.finding(
                "degrade", "degrade-swallowed-failure", handler, symbol,
                f"except handler ({what}) neither re-raises nor classifies "
                "— a swallowed DeviceOomError voids the split ladder and a "
                "swallowed QueryTimeoutError un-cancels a query the "
                "deadline already killed (re-raise, or route through "
                "exec/fallback.py classify_failure)"))
    return out
