"""mesh checker: per-shard Python loops that serialize mesh-wide work.

The ICI exchange re-homes rows across the whole mesh in ONE collective
program (shuffle/ici.py), and mesh-stage execution (exec/mesh.py) runs
post-exchange operator chains as ONE ``shard_map`` program over the
``dp`` axis. A Python ``for`` loop over the mesh extent — ``for i in
range(mesh.shape[axis])`` / ``range(...num_partitions)`` — in those same
hot scopes is the serialization anti-pattern this pipeline exists to
remove: each iteration dispatches single-device work while n-1 devices
idle.

- ``mesh-shard-loop`` — a ``for`` statement iterating ``range(X)`` where
  X derives from the mesh extent (``mesh.shape[...]``, a
  ``num_partitions`` attribute, or a local name assigned from either),
  inside an ``exec``/``shuffle`` package function whose scope never
  references ``shard_map``. Route the work through a single shard_map
  program (exec/mesh.py) or justify with ``# srtpu: mesh-ok(reason)``.

Deliberately narrow: comprehensions (allocation patterns like
``[[] for _ in range(n)]``) and non-``range`` iteration never flag, and a
scope that builds or dispatches a shard_map program is exempt wholesale —
its loops are spec/plumbing around the collective, not per-shard compute.
"""
from __future__ import annotations

import ast
from typing import List, Set

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

#: packages whose scopes sit on the post-exchange execution path
_MESH_PACKAGES = ("exec", "shuffle")


def _in_scope(ctx) -> bool:
    parts = ctx.relpath.split("/")
    return (len(parts) > 2 and parts[0] == "spark_rapids_tpu"
            and parts[1] in _MESH_PACKAGES)


def _mesh_extent(node: ast.AST, ctx, tainted: Set[str]) -> bool:
    """Whether an expression derives from the mesh extent: mentions
    ``X.shape[...]`` with a mesh-ish base, a ``num_partitions``
    attribute, or a name assigned from either."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == "num_partitions":
            return True
        if isinstance(n, ast.Subscript) \
                and isinstance(n.value, ast.Attribute) \
                and n.value.attr == "shape" \
                and "mesh" in ctx.qualify(n.value.value).lower():
            return True
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
    return False


class _Frame:
    __slots__ = ("tainted", "shard_map", "candidates")

    def __init__(self):
        self.tainted: Set[str] = set()
        self.shard_map = False
        self.candidates: List[tuple] = []  # (ast node, symbol)


class _MeshVisitor(ScopedVisitor):
    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []
        self._frames: List[_Frame] = []

    def _visit_def(self, node) -> None:
        self._scope.append(node.name)
        self._frames.append(_Frame())
        try:
            self.generic_visit(node)
        finally:
            frame = self._frames.pop()
            self._scope.pop()
            if not frame.shard_map:
                for loop, symbol in frame.candidates:
                    self.findings.append(self.ctx.finding(
                        "mesh", "mesh-shard-loop", loop, symbol,
                        "Python loop over the mesh extent serializes "
                        "per-shard work (one device computes while the "
                        "rest idle) — run the stage as one shard_map "
                        "program over the dp axis (exec/mesh.py) or "
                        "justify with mesh-ok(reason)"))

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def _note_shard_map(self, qualname: str) -> None:
        if qualname.rsplit(".", 1)[-1].rstrip("()") == "shard_map":
            for f in self._frames:
                f.shard_map = True

    def visit_Name(self, node: ast.Name) -> None:
        self._note_shard_map(self.ctx.qualify(node))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr == "shard_map":
            self._note_shard_map("shard_map")
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._frames and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _mesh_extent(node.value, self.ctx,
                                 self._frames[-1].tainted):
            self._frames[-1].tainted.add(node.targets[0].id)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if self._frames and isinstance(node.iter, ast.Call) \
                and self.ctx.qualify(node.iter.func) == "range" \
                and any(_mesh_extent(a, self.ctx,
                                     self._frames[-1].tainted)
                        for a in node.iter.args):
            self._frames[-1].candidates.append((node, self.symbol))
        self.generic_visit(node)


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        if not _in_scope(ctx):
            continue
        v = _MeshVisitor(ctx)
        v.visit(ctx.tree)
        out.extend(v.findings)
    return out
