"""srtpu-analyze — AST static-analysis pass suite for the engine.

The reference plugin ships static tooling that reads *plans* (the
qualification tool and AutoTuner, tools/ in spark-rapids); this package
is the same idea pointed at our own *source*: a pluggable set of AST
checkers that inventory the blocking-sync surface (ROADMAP item 1 — sync
wait rivals device compute and we had no map of where the syncs live),
and statically enforce the concurrency conventions the PR-3/PR-4 arc
established only by comment (semaphore-under-materialize-lock, bounded
queues, named daemon threads, jit purity).

Checkers (see the sibling modules):

- ``sync``   — blocking device->host syncs (``.item()``, ``np.asarray``,
               ``jax.device_get``, ``block_until_ready``) in hot-path
               packages, severity-ranked by package.
- ``lock``   — TpuSemaphore acquisition reachable under a materialize
               lock outside ``exempt_admission``; context-manager misuse.
- ``thread`` — unbounded queues, unnamed/non-daemon threads, pools
               without a thread-name prefix, ``time.sleep`` in engine code.
- ``jit``    — side effects inside functions traced by ``cached_jit`` /
               ``jax.jit`` / ``shard_map``; use-after-donation of
               ``donate_argnums`` arguments.
- ``bucket`` — hardcoded shape-bucket floors (``min_bucket`` literals /
               ad-hoc numeric defaults) bypassing the central
               ``shapeBuckets`` policy in columnar/device.py.
- ``trace``  — tracer spans opened without a closing ``with`` scope;
               ProcessCluster task-queue submissions bypassing the
               ``_submit`` trace-context injection chokepoint.
- ``memtrack`` — ``DeviceTable.from_host`` uploads in hot packages whose
               enclosing function never reaches
               ``BufferCatalog.register`` — HBM invisible to spill,
               watermark attribution, and OOM postmortems
               (utils/memprof.py).
- ``net``    — socket deadline discipline: blocking socket calls with
               no timeout (a dead peer hangs them forever, defeating
               the fault-tolerance arc's retry/recompute machinery) and
               except-everything-pass handlers that swallow transport
               faults in hot/warm packages.
- ``retry``  — device compute (``cached_jit`` dispatch) and
               ``DeviceTable.from_host`` uploads in hot packages whose
               scope chain never references the OOM retry API
               (memory/retry.py) — a device OOM there raises instead of
               walking the spill/retry/split ladder.
- ``degrade`` — dispatch sites outside BOTH the retry scope and the
               fallback boundary (exec/fallback.py) — a terminal device
               failure there gets no host re-execution and no
               quarantine note; plus except handlers that swallow the
               ladder's structured errors (``DeviceOomError``,
               ``QueryTimeoutError``) without re-raising or
               classifying, breaking split-and-retry bookkeeping and
               cooperative cancellation.

Workflow: findings are compared against a COMMITTED baseline
(``tools/analyze/baseline.json``) so pre-existing debt is inventoried
while any *new* violation fails tier-1 (tests/test_analyze.py). Sites
that are genuinely fine carry an inline suppression::

    np.asarray(mask)  # srtpu: sync-ok(result materialization, cold path)

The suppression syntax is ``# srtpu: <check>-ok(<reason>)``; a non-empty
reason is mandatory (an empty one is itself reported, check ``meta``).
A suppression on its own line applies to the next line of code.

CLI::

    python -m spark_rapids_tpu.tools.analyze spark_rapids_tpu/ [--json]
        [--checks sync,lock] [--baseline PATH | --no-baseline]
        [--write-baseline] [--top N]
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Finding", "ModuleContext", "Project", "Report",
           "analyze_paths", "default_baseline_path", "load_baseline",
           "write_baseline", "compare_to_baseline", "baseline_summary",
           "CHECKS", "SEVERITIES"]

#: package -> severity tier. ``hot`` packages sit on the per-batch
#: execution path (a sync there stalls the device pipeline); ``warm``
#: packages run per-partition or per-query; everything else is ``cold``
#: (tools, session setup, doc generators) and the sync checker skips it.
_HOT_PACKAGES = frozenset({"exec", "expr", "columnar", "shuffle", "memory"})
_WARM_PACKAGES = frozenset({"plan", "parallel", "io", "udf", "native"})
SEVERITIES = ("hot", "warm", "cold")

_PKG_NAME = "spark_rapids_tpu"


def canonical_relpath(path: str) -> str:
    """Stable repo-relative posix path: everything from the last
    ``spark_rapids_tpu`` component on; outside the package, the absolute
    posix path (fixture files in tests)."""
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if _PKG_NAME in parts:
        idx = len(parts) - 1 - parts[::-1].index(_PKG_NAME)
        return "/".join(parts[idx:])
    return "/".join(parts)


def severity_for(path: str) -> str:
    """Severity tier of a file, from its package. Files outside the
    package rank ``hot`` — analyzing a loose file should surface
    everything (this is what test fixtures rely on)."""
    rel = canonical_relpath(path)
    parts = rel.split("/")
    if parts[0] != _PKG_NAME:
        return "hot"
    if len(parts) < 3:          # spark_rapids_tpu/session.py etc.
        return "cold"
    pkg = parts[1]
    if pkg in _HOT_PACKAGES:
        return "hot"
    if pkg in _WARM_PACKAGES:
        return "warm"
    return "cold"


@dataclasses.dataclass
class Finding:
    """One checker hit at one source location."""
    check: str      # checker name: sync / lock / thread / jit / meta
    rule: str       # specific rule, e.g. sync-item
    path: str       # canonical relpath (baseline identity component)
    line: int
    col: int
    symbol: str     # enclosing def/class qualname, or "<module>"
    message: str
    severity: str   # hot / warm / cold

    def key(self) -> str:
        """Baseline identity: path + rule + enclosing symbol (NOT the
        line number, so unrelated edits don't churn the baseline)."""
        return f"{self.path}::{self.rule}::{self.symbol}"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}/{self.severity}] {self.message} "
                f"(in {self.symbol})")


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------
_SUPPRESS_RE = re.compile(r"srtpu:\s*([a-z0-9]+)-ok\(([^()]*)\)")


def scan_suppressions(source: str) -> Tuple[Dict[int, Dict[str, str]],
                                            List[Tuple[int, str]]]:
    """Map line -> {check: reason} plus a list of (line, check) whose
    reason is empty (reported as ``meta`` findings; an unexplained
    suppression is debt pretending to be an audit)."""
    supp: Dict[int, Dict[str, str]] = {}
    empty: List[Tuple[int, str]] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            for m in _SUPPRESS_RE.finditer(tok.string):
                check, reason = m.group(1), m.group(2).strip()
                if not reason:
                    empty.append((tok.start[0], check))
                    continue
                lines = [tok.start[0]]
                if tok.line.strip().startswith("#"):
                    # standalone comment: applies to the next code line
                    lines.append(tok.start[0] + 1)
                for ln in lines:
                    supp.setdefault(ln, {})[check] = reason
    except tokenize.TokenizeError:
        pass
    return supp, empty


# ---------------------------------------------------------------------------
# per-module context
# ---------------------------------------------------------------------------
class ModuleContext:
    """One parsed source file plus the lookup tables checkers share:
    import aliases (so ``np.asarray`` qualifies to ``numpy.asarray``)
    and the suppression map."""

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = path
        self.relpath = canonical_relpath(path)
        self.source = source
        self.tree = tree
        self.severity = severity_for(path)
        self.suppressions, self.empty_suppressions = \
            scan_suppressions(source)
        self.imports = self._collect_imports(tree)

    @staticmethod
    def _collect_imports(tree: ast.AST) -> Dict[str, str]:
        table: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    table[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.names:
                mod = (node.module or "").lstrip(".")
                for a in node.names:
                    if a.name == "*":
                        continue
                    full = f"{mod}.{a.name}" if mod else a.name
                    table[a.asname or a.name] = full
        return table

    def qualify(self, node: Optional[ast.AST]) -> str:
        """Dotted name of an expression with import aliases resolved:
        ``np.asarray`` -> ``numpy.asarray``, a bare ``device_get``
        imported from jax -> ``jax.device_get``. Non-name bases
        (calls, subscripts) qualify through their value so
        ``x.sum().item`` still ends with ``.item``."""
        if isinstance(node, ast.Name):
            return self.imports.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.qualify(node.value)
            return f"{base}.{node.attr}" if base else node.attr
        if isinstance(node, ast.Call):
            return self.qualify(node.func) + "()"
        if isinstance(node, ast.Subscript):
            return self.qualify(node.value) + "[]"
        return ""

    def finding(self, check: str, rule: str, node: ast.AST, symbol: str,
                message: str, severity: Optional[str] = None) -> Finding:
        return Finding(check=check, rule=rule, path=self.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       symbol=symbol, message=message,
                       severity=severity or self.severity)

    def is_suppressed(self, f: Finding) -> bool:
        entry = self.suppressions.get(f.line)
        return bool(entry) and (f.check in entry or "all" in entry)


class Project:
    """Every module under analysis — checkers get the whole set so
    cross-file passes (the lock call graph, jit builder resolution)
    see the full picture."""

    def __init__(self, modules: List[ModuleContext],
                 parse_failures: List[Finding]):
        self.modules = modules
        self.parse_failures = parse_failures

    def module_for(self, relpath: str) -> Optional[ModuleContext]:
        return next((m for m in self.modules if m.relpath == relpath), None)


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor tracking the enclosing class/def qualname — findings
    key on the symbol so line drift never churns the baseline."""

    def __init__(self):
        self._scope: List[str] = []

    @property
    def symbol(self) -> str:
        return ".".join(self._scope) or "<module>"

    def _scoped(self, node):
        self._scope.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._scope.pop()

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_ClassDef = _scoped


# ---------------------------------------------------------------------------
# project loading / running
# ---------------------------------------------------------------------------
def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def load_project(paths: Sequence[str]) -> Project:
    modules: List[ModuleContext] = []
    failures: List[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError, ValueError) as e:
            failures.append(Finding(
                check="meta", rule="meta-parse-error",
                path=canonical_relpath(path),
                line=getattr(e, "lineno", 0) or 0, col=0,
                symbol="<module>", message=f"cannot analyze: {e}",
                severity=severity_for(path)))
            continue
        modules.append(ModuleContext(path, source, tree))
    return Project(modules, failures)


def _checkers() -> Dict[str, object]:
    from . import (buckets, degrade, eventlog_schema, host_sync, jit_purity,
                   locks, memtrack, mesh_loops, net, retry_scope,
                   shuffle_observed, threads, trace_ctx)
    return {"sync": host_sync, "lock": locks,
            "thread": threads, "jit": jit_purity, "bucket": buckets,
            "trace": trace_ctx, "memtrack": memtrack,
            "eventlog": eventlog_schema, "net": net, "retry": retry_scope,
            "degrade": degrade, "shuffle": shuffle_observed,
            "mesh": mesh_loops}


CHECKS = ("sync", "lock", "thread", "jit", "bucket", "trace", "memtrack",
          "eventlog", "net", "retry", "degrade", "shuffle", "mesh")


def analyze_paths(paths: Sequence[str],
                  checks: Optional[Sequence[str]] = None) -> "Report":
    """Run the selected checkers (default: all) over ``paths`` and
    return the Report (suppressed findings split out, meta findings for
    parse failures and empty-reason suppressions folded in)."""
    project = load_project(paths)
    registry = _checkers()
    names = list(checks) if checks else list(CHECKS)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown checks {unknown}; have {list(registry)}")
    findings: List[Finding] = list(project.parse_failures)
    for name in names:
        findings.extend(registry[name].check(project))
    for ctx in project.modules:
        for line, check in ctx.empty_suppressions:
            findings.append(ctx.finding(
                "meta", "meta-empty-suppression-reason",
                type("L", (), {"lineno": line, "col_offset": 0})(),
                "<module>",
                f"suppression '{check}-ok()' has no reason — every "
                f"suppression must say why the site is fine"))
    by_path = {m.relpath: m for m in project.modules}
    kept, suppressed = [], []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        ctx = by_path.get(f.path)
        if ctx is not None and f.check != "meta" and ctx.is_suppressed(f):
            suppressed.append(f)
        else:
            kept.append(f)
    return Report(kept, suppressed, files=len(project.modules),
                  checks=names)


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------
_SEV_ORDER = {"hot": 0, "warm": 1, "cold": 2}


class Report:
    def __init__(self, findings: List[Finding], suppressed: List[Finding],
                 files: int, checks: Sequence[str]):
        self.findings = findings
        self.suppressed = suppressed
        self.files = files
        self.checks = list(checks)

    def count(self, check: Optional[str] = None,
              severity: Optional[str] = None) -> int:
        return sum(1 for f in self.findings
                   if (check is None or f.check == check)
                   and (severity is None or f.severity == severity))

    def key_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.key()] = out.get(f.key(), 0) + 1
        return out

    def summary(self) -> Dict:
        """Per-check, per-severity counts + the top files by hot sync
        debt — the shape bench.py copies into the bench JSON and
        tools/diagnose.py cross-references against trace spans."""
        checks: Dict[str, Dict[str, int]] = {}
        for f in self.findings:
            c = checks.setdefault(f.check,
                                  {"hot": 0, "warm": 0, "cold": 0,
                                   "total": 0})
            c[f.severity] += 1
            c["total"] += 1
        per_file: Dict[str, int] = {}
        for f in self.findings:
            if f.check == "sync" and f.severity == "hot":
                per_file[f.path] = per_file.get(f.path, 0) + 1
        top = sorted(per_file.items(), key=lambda kv: (-kv[1], kv[0]))
        return {"files": self.files, "checks": checks,
                "suppressed": len(self.suppressed),
                "top_sync_files": [{"path": p, "hot_syncs": n}
                                   for p, n in top[:10]]}

    def render(self, top: int = 0) -> str:
        lines = [f"== srtpu-analyze: {self.files} files, "
                 f"checks={','.join(self.checks)} =="]
        shown = sorted(self.findings,
                       key=lambda f: (_SEV_ORDER[f.severity], f.path,
                                      f.line))
        cut = shown[:top] if top else shown
        lines.extend(f.render() for f in cut)
        if top and len(shown) > top:
            lines.append(f"... and {len(shown) - top} more")
        s = self.summary()
        for check, c in sorted(s["checks"].items()):
            lines.append(f"{check}: {c['total']} finding(s) "
                         f"(hot={c['hot']} warm={c['warm']} "
                         f"cold={c['cold']})")
        lines.append(f"suppressed: {len(self.suppressed)}")
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps({
            "summary": self.summary(),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
        }, indent=1)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str] = None) -> Dict:
    with open(path or default_baseline_path(), encoding="utf-8") as f:
        return json.load(f)


def write_baseline(report: Report, path: Optional[str] = None) -> Dict:
    """Persist the report as the new baseline. ``initial_inventory`` is
    sticky: recorded the FIRST time a baseline is written and carried
    forward on every regeneration, so the sync-debt trajectory (current
    vs initial) survives baseline refreshes — the tier-1 test pins
    current < initial (real fixes landed, not just churn)."""
    path = path or default_baseline_path()
    initial = None
    if os.path.exists(path):
        try:
            initial = load_baseline(path).get("initial_inventory")
        except (OSError, ValueError):
            initial = None
    if not initial:
        initial = {c: report.count(c) for c in report.checks}
    else:
        # a checker added after the first baseline write records ITS
        # initial inventory the first time it appears; existing entries
        # stay sticky
        for c in report.checks:
            initial.setdefault(c, report.count(c))
    lines: Dict[str, List[int]] = {}
    for f in report.findings:
        lines.setdefault(f.key(), []).append(f.line)
    data = {
        "version": 1,
        "tool": "srtpu-analyze",
        "initial_inventory": initial,
        "summary": report.summary(),
        "counts": {k: {"count": len(v), "lines": sorted(v)}
                   for k, v in sorted(lines.items())},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


def compare_to_baseline(report: Report,
                        baseline: Dict) -> List[Finding]:
    """New violations: findings whose baseline key occurs MORE often than
    the baseline recorded (entirely new keys count from zero). For a
    grown key the latest occurrences (by line) are reported."""
    base_counts = {k: v.get("count", 0)
                   for k, v in (baseline.get("counts") or {}).items()}
    grouped: Dict[str, List[Finding]] = {}
    for f in report.findings:
        grouped.setdefault(f.key(), []).append(f)
    regressions: List[Finding] = []
    for key, fs in grouped.items():
        allowed = base_counts.get(key, 0)
        if len(fs) > allowed:
            fs = sorted(fs, key=lambda f: f.line)
            regressions.extend(fs[allowed:])
    return sorted(regressions, key=lambda f: (f.path, f.line))


def baseline_summary(path: Optional[str] = None) -> Dict:
    """The committed baseline's summary block (plus initial inventory) —
    what bench.py records so sync-site count becomes a tracked
    trajectory metric. Never raises: {} when absent/corrupt."""
    try:
        data = load_baseline(path)
    except (OSError, ValueError):
        return {}
    return {"initial_inventory": data.get("initial_inventory", {}),
            "summary": data.get("summary", {})}
