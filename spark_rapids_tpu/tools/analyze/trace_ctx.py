"""trace checker: span scoping + cross-process context injection.

The distributed-tracing contract (utils/tracing.py, parallel/runtime.py)
has two conventions a reviewer can't reliably hold by eye:

- ``trace-span-no-with`` — ``tracer.span(...)`` / ``get_tracer().span(...)``
  called anywhere except as a ``with`` item. ``span()`` is a
  contextmanager: a bare call records nothing, re-parents nothing, and
  silently punches a hole in the merged span DAG (the event "exists"
  at the call site but never reaches the ring).
- ``trace-ctx-bypass`` — a task envelope enqueued onto a ProcessCluster
  ``_task_qs`` queue outside ``_submit``. ``_submit`` is the single
  chokepoint that stamps the active TraceContext into every envelope;
  a direct ``.put()`` ships a task whose worker spans orphan from the
  driver's query span in the merged timeline. Non-envelope puts (the
  shutdown ``None`` sentinel) carry an inline
  ``# srtpu: trace-ok(<reason>)`` suppression.
"""
from __future__ import annotations

import ast
from typing import List, Set

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]


def _is_span_call(qualified: str) -> bool:
    """True for tracer span openings: get_tracer().span, tracer.span,
    self.tracer.span, self._tracer.span — NOT arbitrary ``.span``
    attributes (a DataFrame column named span must not flag)."""
    if not qualified.endswith(".span"):
        return False
    base = qualified[: -len(".span")]
    return (base.endswith("get_tracer()")
            or base == "tracer"
            or base.endswith(".tracer")
            or base.endswith("._tracer"))


class _TraceVisitor(ScopedVisitor):
    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []
        #: Call nodes that ARE with-items (properly scoped spans)
        self._with_items: Set[int] = set()

    def _hit(self, node, rule: str, msg: str) -> None:
        self.findings.append(self.ctx.finding(
            "trace", rule, node, self.symbol, msg))

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            if isinstance(item.context_expr, ast.Call):
                self._with_items.add(id(item.context_expr))
        self.generic_visit(node)

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        q = self.ctx.qualify(node.func)
        if _is_span_call(q) and id(node) not in self._with_items:
            self._hit(node, "trace-span-no-with",
                      f"{q}(...) called outside a with statement — "
                      "span() is a contextmanager; a bare call records "
                      "nothing and breaks the span DAG it should parent")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "put" \
                and "_task_qs" in self.ctx.qualify(node.func.value) \
                and not self.symbol.endswith("_submit"):
            self._hit(node, "trace-ctx-bypass",
                      "task queue .put() outside ProcessCluster._submit — "
                      "_submit is the chokepoint that injects the "
                      "TraceContext into every envelope; a direct put "
                      "orphans the worker's spans from the query trace")
        self.generic_visit(node)


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        v = _TraceVisitor(ctx)
        v.visit(ctx.tree)
        out.extend(v.findings)
    return out
