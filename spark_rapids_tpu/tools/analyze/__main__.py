"""CLI: python -m spark_rapids_tpu.tools.analyze [paths...]

Default invocation (no args) analyzes the installed ``spark_rapids_tpu``
package against the committed baseline and exits non-zero on any NEW
violation — the same contract the tier-1 test (tests/test_analyze.py)
enforces, exposed for pre-commit use.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import (CHECKS, analyze_paths, compare_to_baseline,
               default_baseline_path, load_baseline, write_baseline)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_tpu.tools.analyze",
        description="srtpu-analyze: AST static-analysis pass suite "
                    "(host syncs, lock discipline, thread hygiene, "
                    "jit purity)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the "
                         "spark_rapids_tpu package)")
    ap.add_argument("--checks", default="",
                    help=f"comma-separated subset of {','.join(CHECKS)}")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default="",
                    help="baseline file (default: the committed "
                         "tools/analyze/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report the full inventory; exit 0 regardless")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the new baseline "
                         "(initial_inventory is preserved)")
    ap.add_argument("--top", type=int, default=0,
                    help="cap listed findings in the text report "
                         "(0 = all)")
    ns = ap.parse_args(argv)

    paths = ns.paths
    if not paths:
        import spark_rapids_tpu
        paths = [os.path.dirname(os.path.abspath(
            spark_rapids_tpu.__file__))]
    checks = [c for c in ns.checks.split(",") if c] or None
    if ns.write_baseline and checks:
        # a subset rewrite would erase every OTHER category's recorded
        # allowances from the shared baseline file
        print("--write-baseline requires the full checker set; drop "
              "--checks", file=sys.stderr)
        return 2
    report = analyze_paths(paths, checks=checks)

    if ns.write_baseline:
        path = ns.baseline or default_baseline_path()
        data = write_baseline(report, path)
        print(f"baseline written: {path} "
              f"({sum(v['count'] for v in data['counts'].values())} "
              f"finding(s) across {len(data['counts'])} key(s))")
        return 0

    if ns.json:
        print(report.to_json())
    else:
        print(report.render(top=ns.top))

    if ns.no_baseline:
        return 0
    baseline_path = ns.baseline or default_baseline_path()
    if not os.path.exists(baseline_path):
        print(f"no baseline at {baseline_path} — run with "
              f"--write-baseline to create one", file=sys.stderr)
        return 2
    regressions = compare_to_baseline(report, load_baseline(baseline_path))
    if regressions:
        print(f"\n{len(regressions)} NEW violation(s) vs baseline "
              f"{baseline_path}:", file=sys.stderr)
        for f in regressions:
            print("  " + f.render(), file=sys.stderr)
        print("fix the site, add '# srtpu: <check>-ok(reason)' with a "
              "real reason, or (for accepted debt) regenerate the "
              "baseline with --write-baseline", file=sys.stderr)
        return 1
    print("clean vs baseline: no new violations")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
