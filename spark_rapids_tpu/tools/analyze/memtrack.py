"""memtrack checker: device uploads that never reach the buffer catalog.

The memory flight recorder (utils/memprof.py) can only attribute HBM it
sees — a ``DeviceTable`` uploaded with ``from_host`` but never handed to
``BufferCatalog.register`` is invisible to the spill framework, the
per-operator watermark attribution, AND the OOM postmortem: it holds
real device bytes that ``synchronous_spill`` cannot evict and
``holders_by_operator`` cannot name. This checker inventories those
sites statically:

- ``memtrack-unregistered-upload`` — ``DeviceTable.from_host(...)`` in a
  hot/warm scope whose enclosing function never reaches the catalog
  (no ``*.register(...)`` call and no ``SpillableDeviceTable``
  construction in the same or an enclosing function scope).

Plain ``DeviceTable(cols, mask, ...)`` construction is deliberately NOT
flagged: those are derived views recombining columns of tables that are
already device-resident (usually inside jit-traced operator bodies) —
they pin no *new* HBM beyond their inputs. ``from_host`` is the call
that moves fresh bytes onto the device, so it is the one that must be
accounted.

A helper that uploads and returns the table for its CALLER to register
is a legitimate shape the AST cannot follow across the call; such sites
carry ``# srtpu: memtrack-ok(<reason>)`` (same suppression grammar as
the sync checker) and pre-existing debt is seeded into the committed
baseline like every other check.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

#: severities the memtrack checker reports on (cold packages — tools,
#: session setup, tests — upload outside the spill framework by design)
REPORTED_SEVERITIES = ("hot", "warm")


class _MemVisitor(ScopedVisitor):
    """Collects, per enclosing-scope symbol, the ``from_host`` upload
    sites and whether that scope reaches the catalog."""

    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.uploads: List[Tuple[str, ast.Call]] = []
        self.registering: Set[str] = set()

    def visit_Call(self, node: ast.Call) -> None:
        q = self.ctx.qualify(node.func)
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        if attr == "from_host" and "DeviceTable" in q:
            self.uploads.append((self.symbol, node))
        # catalog accounting: register() on any receiver (the catalog is
        # the only object in the engine exposing that method on tables),
        # or wrapping in a SpillableDeviceTable handle directly
        elif attr == "register" or q == "SpillableDeviceTable" \
                or q.endswith(".SpillableDeviceTable"):
            self.registering.add(self.symbol)
        self.generic_visit(node)


def _scope_registers(symbol: str, registering: Set[str]) -> bool:
    """True when ``symbol`` or any enclosing function scope registers —
    an upload inside a closure whose outer function registers the result
    is accounted (the value flows out through the closure)."""
    parts = symbol.split(".")
    return any(".".join(parts[:i]) in registering
               for i in range(1, len(parts) + 1))


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for ctx in project.modules:
        if ctx.severity not in REPORTED_SEVERITIES:
            continue
        v = _MemVisitor(ctx)
        v.visit(ctx.tree)
        for symbol, node in v.uploads:
            if _scope_registers(symbol, v.registering):
                continue
            out.append(ctx.finding(
                "memtrack", "memtrack-unregistered-upload", node, symbol,
                "DeviceTable.from_host upload never reaches "
                "BufferCatalog.register — these HBM bytes are invisible "
                "to spill, watermark attribution, and OOM postmortems"))
    return out
