"""jit checker: purity at the XLA trace boundary + donation safety.

Flare (PAPERS.md) is the canary: a compiled query engine only works if
the functions handed to the compiler are pure. In this engine a side
effect inside a traced function fires ONCE at trace time and never
again — a metric incremented inside a ``batch_fn`` closure counts one
batch per compile, not per batch; a tracer span measures tracing, not
execution; a conf read freezes the first session's value into the
cached executable (utils/compile_cache.py caches across sessions).

Traced contexts are discovered project-wide:

- a function passed directly to ``jax.jit`` / ``shard_map`` is traced;
- the 2nd argument of ``cached_jit(key, builder)`` is a BUILDER: the
  builder body runs host-side exactly once, but every function DEFINED
  INSIDE it (the closure it returns) is traced. Builder references are
  resolved by name across the project, so ``cached_jit(sig,
  self.batch_fn)`` marks every ``batch_fn``'s nested defs as traced.

Rules:

- ``jit-side-effect``      — print / tracer spans / metric registry
  writes / ``note_progress`` / ``time.*`` reads / conf reads /
  ``os.environ`` / ``open`` inside a traced context.
- ``jit-use-after-donate`` — an argument variable passed at a donated
  position (``donate_argnums``) is read again after the donating call:
  XLA may already have reused its buffers (exec/wholestage.py nbytes-
  before-call comment is this rule by hand). The analysis is lexical
  within one function body — sibling branches of the same ``if`` do
  not count, and a KNOWN LIMITATION is that loop-carried uses (the
  same variable re-donated on the next iteration) are not seen either;
  only reads in statements lexically after the donating call flag.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import Finding, Project, ScopedVisitor

__all__ = ["check"]

#: call names that enter a traced context with the function as arg 0
_DIRECT_JIT = ("jax.jit", "shard_map")
#: qualified-name suffixes that are side effects inside a trace
_TIME_CALLS = frozenset({"time.time", "time.perf_counter",
                         "time.monotonic", "time.sleep",
                         "time.process_time"})


def _bare(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _collect_jit_entries(project: Project) -> Tuple[Set[str], Set[str]]:
    """(builder names, directly-jitted function names) project-wide."""
    builders: Set[str] = set()
    direct: Set[str] = set()
    for ctx in project.modules:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            q = ctx.qualify(node.func)
            if q.endswith("cached_jit") and len(node.args) >= 2:
                name = _bare(node.args[1])
                if name:
                    builders.add(name)
            elif (q in _DIRECT_JIT or q.endswith(".jit")
                  or q.endswith(".shard_map")
                  or q.endswith(".pjit")) and node.args:
                name = _bare(node.args[0])
                if name:
                    direct.add(name)
    return builders, direct


def _traced_defs(tree: ast.AST, builders: Set[str],
                 direct: Set[str]) -> List[ast.FunctionDef]:
    """FunctionDef nodes whose BODY executes under an XLA trace."""
    traced: List[ast.FunctionDef] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in direct:
            traced.append(node)
        elif node.name in builders:
            traced.extend(
                inner for stmt in ast.walk(node)
                for inner in [stmt]
                if isinstance(inner, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                and inner is not node)
    return traced


class _EffectVisitor(ScopedVisitor):
    """Flags side-effectful calls inside one traced function body."""

    def __init__(self, ctx, owner: str):
        super().__init__()
        self.ctx = ctx
        self.owner = owner
        self.findings: List[Finding] = []

    def _hit(self, node, what: str) -> None:
        self.findings.append(self.ctx.finding(
            "jit", "jit-side-effect", node, self.owner,
            f"{what} inside a traced function — runs once at trace "
            f"time, never per batch (and is baked into the cached "
            f"executable)"))

    def visit_FunctionDef(self, node):
        # nested defs inside a traced fn are traced too; keep walking
        self._scoped(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        q = self.ctx.qualify(node.func)
        bare = _bare(node.func) or ""
        chain = q.lower()
        if q == "print":
            self._hit(node, "print()")
        elif q in _TIME_CALLS:
            self._hit(node, f"{q}()")
        elif bare == "note_progress":
            self._hit(node, "note_progress()")
        elif bare == "get_tracer" or ".span" in chain and "tracer" in chain:
            self._hit(node, "tracer span")
        elif bare in ("add", "observe", "timed") and (
                "metrics" in chain or chain.startswith(("registry.",
                                                        "reg."))):
            self._hit(node, f"metric registry write ({q})")
        elif bare == "get" and ("conf" in chain.split(".")[0]
                                or ".conf." in chain):
            self._hit(node, f"conf read ({q})")
        elif q == "RapidsConf" or q.endswith(".RapidsConf"):
            self._hit(node, "RapidsConf construction")
        elif q == "open":
            self._hit(node, "open()")
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.ctx.qualify(node).startswith("os.environ"):
            self._hit(node, "os.environ read")
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# use-after-donation
# ---------------------------------------------------------------------------
def _donated_positions(call: ast.Call) -> List[int]:
    kw = next((k for k in call.keywords if k.arg == "donate_argnums"),
              None)
    if kw is None:
        return []
    v = kw.value
    if isinstance(v, ast.Constant) and isinstance(v.value, int):
        return [v.value]
    if isinstance(v, (ast.Tuple, ast.List)):
        return [e.value for e in v.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)]
    return []


def _later_statements(fn: ast.AST, target: ast.stmt) -> List[ast.stmt]:
    """Statements lexically AFTER the one containing ``target``, at the
    containing block and every enclosing block — sibling branches of the
    same if/try never count."""

    def walk(body: Sequence[ast.stmt]) -> Optional[List[ast.stmt]]:
        for i, stmt in enumerate(body):
            if stmt is target or any(n is target for n in ast.walk(stmt)):
                later = list(body[i + 1:])
                for field in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(stmt, field, None)
                    if isinstance(sub, list) and stmt is not target:
                        blocks = [h.body for h in sub] \
                            if field == "handlers" else [sub]
                        for blk in blocks:
                            deeper = walk(blk)
                            if deeper is not None:
                                return deeper + later
                return later
        return None

    return walk(getattr(fn, "body", [])) or []


def _walk_own_scope(stmt: ast.stmt):
    """ast.walk that does NOT descend into nested function/lambda
    bodies — a nested def's donation is ITS scope's concern (it gets
    its own _check_function pass), and attributing it to the enclosing
    function would flag the outer function's unrelated same-named
    variables."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # nested scope: don't expand its body
        stack.extend(ast.iter_child_nodes(node))


class _DonationVisitor(ScopedVisitor):
    def __init__(self, ctx):
        super().__init__()
        self.ctx = ctx
        self.findings: List[Finding] = []

    def _scoped_fn(self, node):
        self._check_function(node)
        self._scoped(node)

    visit_FunctionDef = _scoped_fn
    visit_AsyncFunctionDef = _scoped_fn

    def _check_function(self, fn: ast.FunctionDef) -> None:
        donating_vars: Dict[str, List[int]] = {}
        for stmt in fn.body:
            for node in _walk_own_scope(stmt):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    pos = _donated_positions(node.value)
                    if pos:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                donating_vars[t.id] = pos
        if not donating_vars:
            return
        symbol = ".".join(self._scope + [fn.name])
        for stmt in fn.body:
            for call in _walk_own_scope(stmt):
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Name)
                        and call.func.id in donating_vars):
                    continue
                donated = [call.args[i].id
                           for i in donating_vars[call.func.id]
                           if i < len(call.args)
                           and isinstance(call.args[i], ast.Name)]
                if not donated:
                    continue
                for later in _later_statements(fn, stmt):
                    for node in ast.walk(later):
                        if isinstance(node, ast.Name) \
                                and isinstance(node.ctx, ast.Load) \
                                and node.id in donated:
                            self.findings.append(self.ctx.finding(
                                "jit", "jit-use-after-donate", node,
                                symbol,
                                f"'{node.id}' is read after being "
                                f"passed at a donated position to "
                                f"'{call.func.id}' — XLA may have "
                                f"already reused its buffers"))
                            donated = [d for d in donated
                                       if d != node.id]


def check(project: Project) -> List[Finding]:
    builders, direct = _collect_jit_entries(project)
    out: List[Finding] = []
    seen: Set[Tuple[str, str, int, int]] = set()
    for ctx in project.modules:
        # traced defs can nest (a builder's closure defining a helper):
        # visiting the outer body covers the inner, so dedupe by site
        for fn in _traced_defs(ctx.tree, builders, direct):
            v = _EffectVisitor(ctx, fn.name)
            for stmt in fn.body:
                v.visit(stmt)
            for f in v.findings:
                site = (f.rule, f.path, f.line, f.col)
                if site not in seen:
                    seen.add(site)
                    out.append(f)
        dv = _DonationVisitor(ctx)
        dv.visit(ctx.tree)
        out.extend(dv.findings)
    return out
