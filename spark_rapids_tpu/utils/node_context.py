"""Thread-local "which operator is executing" stack.

Process-wide services (the XLA compile cache, the buffer catalog's spill
path) do work *on behalf of* whatever exec node happens to be running, but
have no reference to it. The reference plugin threads GpuMetric objects
into those layers explicitly; here the profiler/event-log instrumentation
(tools/profiler.py ``instrument_plan``) pushes a NodeContext around every
resume of a node's batch generator instead, so a compile or a spill that
fires mid-batch is attributed to the innermost node driving it — per
(query, node_id), which is exactly the key the event log accumulates under.

Uninstrumented executions (plain ``collect()`` with no event log and no
profiler) run with an empty stack; attribution callers must tolerate
``current() is None`` and fall back to process-global counters only.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

__all__ = ["NodeContext", "node_scope", "current", "current_registry",
           "active_contexts"]


class NodeContext:
    __slots__ = ("node_id", "name", "registry", "query_id")

    def __init__(self, node_id: int, name: str, registry=None,
                 query_id: Optional[int] = None):
        self.node_id = node_id
        self.name = name
        self.registry = registry  # the node's MetricRegistry (may be None)
        self.query_id = query_id

    def __repr__(self):
        return f"NodeContext({self.node_id}, {self.name!r})"


_TLS = threading.local()

# cross-thread registry of every thread's context stack, so the health
# watchdog (utils/health.py) can report which (query, operator) each live
# thread is executing — a thread-local alone is invisible from the monitor
_ALL_LOCK = threading.Lock()
_ALL_STACKS: dict = {}  # thread ident -> that thread's stack list


def _stack():
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
        with _ALL_LOCK:
            _ALL_STACKS[threading.get_ident()] = st
    return st


@contextmanager
def node_scope(node_id: int, name: str, registry=None,
               query_id: Optional[int] = None):
    """Mark ``node_id`` as the executing operator for the dynamic extent.

    Nested scopes stack: a child generator resumed inside a parent's scope
    pushes itself on top, so ``current()`` is always the innermost node."""
    st = _stack()
    st.append(NodeContext(node_id, name, registry, query_id))
    try:
        yield
    finally:
        st.pop()


def current() -> Optional[NodeContext]:
    """The innermost executing node's context, or None when uninstrumented."""
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


def current_registry():
    """The innermost executing node's MetricRegistry, or None."""
    ctx = current()
    return ctx.registry if ctx is not None else None


def active_contexts() -> dict:
    """Best-effort {thread name: innermost context} across ALL live
    threads (the watchdog's "what was everyone doing" section). Reads
    other threads' stacks racily — a context may pop mid-read — so stale
    or missing entries are tolerated, never an error."""
    alive = {t.ident: t.name for t in threading.enumerate()}
    with _ALL_LOCK:
        # GC stacks of threads that have exited
        for tid in [tid for tid in _ALL_STACKS if tid not in alive]:
            del _ALL_STACKS[tid]
        items = list(_ALL_STACKS.items())
    out = {}
    for tid, st in items:
        try:
            ctx = st[-1]
        except IndexError:
            continue
        out[alive.get(tid, str(tid))] = (
            f"query={ctx.query_id} node={ctx.node_id} {ctx.name}")
    return out
