"""Live engine health: watchdog monitor, stall forensics, heartbeats.

PRs 1-2 built *post-hoc* observability (spans, event logs, EXPLAIN
ANALYZE, diagnose) and PR 3 made the engine heavily concurrent (task
pools, bounded prefetch queues, semaphore admission, materialize locks) —
but nothing watched a *running* engine: a lock/semaphore interaction bug
looked like a silent hang with zero forensics, and there was no endpoint
an operator or load balancer could poll. This module is the Spark
live-UI / executor-heartbeat analogue (reference: the plugin leans on
Spark's heartbeats + live UI; Theseus, arxiv 2508.05029, treats runtime
introspection of a pipelined engine as first-class):

- ``HealthMonitor``: samples, on every tick, the TpuSemaphore state
  (holders with thread names + held durations, wait queue), pipeline
  queue depths and in-flight task ages (parallel/pipeline.py
  introspection API), buffer-catalog HBM used/peak watermarks, and the
  active (query, operator) context of every live thread.
- **Heartbeats**: each tick appends a ``heartbeat`` record to the
  session event log (schema v4, tools/eventlog.py) so post-hoc tools can
  reconstruct the engine's live trajectory — ``tools/diagnose.py`` ranks
  stall windows and flags queries that heartbeated into OOM territory.
- **Stall detector**: if work is in flight but the engine-wide progress
  marker has not moved for ``spark.rapids.tpu.health.stallTimeout``
  seconds, a full forensics report — all-thread stacks via
  ``sys._current_frames``, the semaphore dump (named holders +
  held-durations), per-queue depths, in-flight task ages, active
  operator contexts, and the catalog dump — goes to the diagnostics
  channel and a ``stall-<ts>.txt`` file.
- The HTTP surface (``/healthz``, ``/metrics``, ``/status``) lives in
  ``tools/statusd.py`` and serves this monitor's snapshots.

The monitor thread is off by default and every sample is driven by
``tick()``, which takes an explicit ``now`` — tests inject stalls and
advance time deterministically without sleeping.
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Dict, List, Optional

from ..conf import register_conf

__all__ = ["HEALTH_ENABLED", "HEALTH_INTERVAL_MS", "HEALTH_STALL_TIMEOUT",
           "HEALTH_PORT", "HEALTH_REPORT_DIR", "HealthMonitor",
           "HealthSubsystem", "configure_health"]

HEALTH_ENABLED = register_conf(
    "spark.rapids.tpu.health.enabled",
    "Run the background health monitor thread: per-tick heartbeat records "
    "into the event log (schema v4), HBM watermark sampling, and the stall "
    "watchdog (no-progress-with-work-in-flight dumps all-thread stacks, "
    "semaphore holders and queue states to the diagnostics channel and a "
    "stall-<ts>.txt file). The Spark executor-heartbeat / live-UI "
    "analogue. Off by default; tests drive HealthMonitor.tick() "
    "deterministically instead.", False)

HEALTH_INTERVAL_MS = register_conf(
    "spark.rapids.tpu.health.intervalMs",
    "Health monitor tick interval in milliseconds (heartbeat cadence and "
    "stall-detection resolution).", 1000,
    checker=lambda v: None if int(v) > 0 else "must be positive")

HEALTH_STALL_TIMEOUT = register_conf(
    "spark.rapids.tpu.health.stallTimeout",
    "Seconds of zero engine progress (no operator batch accounted, no "
    "batch crossed a stage boundary, no task completed, no semaphore "
    "admission) while work is in flight before the watchdog declares a "
    "stall and dumps the forensics report. Progress is observed at "
    "batch/queue/task granularity, so this must exceed the longest "
    "single device dispatch your workload legitimately runs. Detection "
    "resolution is one tick (health.intervalMs).", 120.0,
    conf_type=float,
    checker=lambda v: None if float(v) > 0 else "must be positive")

HEALTH_PORT = register_conf(
    "spark.rapids.tpu.health.port",
    "HTTP status endpoint port serving /healthz (liveness; 503 while "
    "stalled), /metrics (Prometheus text exposition of the process stats "
    "registry) and /status (live JSON snapshot: semaphore, pipeline "
    "queues, HBM watermarks, active operators). -1 disables the server; "
    "0 binds an ephemeral port (tests); >0 binds that port on 127.0.0.1.",
    -1)

HEALTH_REPORT_DIR = register_conf(
    "spark.rapids.tpu.health.reportDir",
    "Directory for watchdog stall forensics files (stall-<ts>.txt). Empty "
    "keeps reports in memory + the catalog diagnostics channel only "
    "(reference: spark.rapids.memory.gpu.oomDumpDir state dumps).", "")


class HealthMonitor:
    """Samples live engine state; detects stalls; emits heartbeats.

    ``tick(now=None)`` performs exactly one sample and is safe to call
    from tests with a fabricated clock; ``start()``/``stop()`` run the
    same tick on a daemon thread at ``health.intervalMs``.
    """

    def __init__(self, conf, eventlog_fn: Optional[Callable] = None):
        self.interval_s = int(conf.get(HEALTH_INTERVAL_MS)) / 1000.0
        self.stall_timeout_s = float(conf.get(HEALTH_STALL_TIMEOUT))
        self.report_dir = str(conf.get(HEALTH_REPORT_DIR) or "")
        # /status links the run's history store (tools/historyd.py UI);
        # string key: tools.history registers the entry lazily and utils
        # must not import the tools layer
        try:
            self.history_dir = str(
                conf.get("spark.rapids.tpu.history.dir") or "")
        except KeyError:  # tools.history never imported, conf unset
            self.history_dir = ""
        # returns the session's EventLogWriter or None (heartbeats must
        # not conjure a writer: no eventLog.dir -> no log)
        self._eventlog_fn = eventlog_fn or (lambda: None)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._tick_lock = threading.Lock()
        self.started_at = time.monotonic()
        self.ticks = 0
        self.tick_errors = 0
        self.heartbeats_emitted = 0
        self._seq = 0
        # stall-detector state: token = engine-wide progress marker;
        # unchanged token + work in flight + timeout elapsed => stall
        self._last_token = None
        self._last_progress = time.monotonic()
        self._stall_active = False
        self._was_in_flight = False
        self.stalled = False
        self.stalls_detected = 0
        self.last_stall_report: Optional[str] = None
        self.last_stall_report_path: Optional[str] = None
        #: per-tick HBM watermark samples (catalog.watermarks())
        self.watermark_history: deque = deque(maxlen=256)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "HealthMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    # the watchdog must never die of its own bug; count
                    # and keep ticking
                    self.tick_errors += 1

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="tpu-health-monitor")
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        t, self._thread = self._thread, None
        if t is None:
            return
        self._stop.set()
        t.join(timeout=timeout_s)

    # -- sampling -------------------------------------------------------------
    def tick(self, now: Optional[float] = None,
             emit_heartbeat: bool = True) -> Optional[str]:
        """One watchdog sample. ``now`` is in the ``time.monotonic()``
        domain (tests pass fabricated values to cross the stall timeout
        without sleeping). ``emit_heartbeat=False`` skips the event-log
        record (the /healthz probe-driven path, tools/statusd.py: liveness
        polls must not flood the log). Returns the forensics report text
        when THIS tick fired the stall detector, else None."""
        with self._tick_lock:
            return self._tick_locked(
                time.monotonic() if now is None else now, emit_heartbeat)

    def _tick_locked(self, now: float, emit_heartbeat: bool) -> Optional[str]:
        from ..memory.catalog import peek_catalog
        from ..memory.semaphore import peek_semaphore
        from ..parallel.pipeline import pipeline_snapshot
        from .metrics import get_stats
        self.ticks += 1
        get_stats().add("health_ticks")
        # sample every subsystem ONCE per tick: the progress token, the
        # in-flight check and the heartbeat all read this one sample, so
        # they agree with each other and each tick takes each subsystem
        # lock exactly once
        sem = peek_semaphore()
        snap = pipeline_snapshot()
        cat = peek_catalog()
        # bounded acquire: if a wedged thread holds the catalog lock (the
        # very hang this monitor exists to report), the tick skips the
        # watermark sample instead of joining the hang
        wm = (cat.watermarks(timeout_s=0.5) if cat is not None else None) \
            or {}
        # token: changes whenever the engine demonstrably moved — a batch
        # was accounted (exec/base.py), crossed a prefetch queue, a pooled
        # task finished, or a task was admitted (signals a wedged engine
        # cannot fake)
        token = (snap["progress_counter"],
                 sem.acquire_count if sem is not None else 0)
        if self._last_token is None or token != self._last_token:
            self._last_token = token
            self._last_progress = now
            self._stall_active = False
        age = max(0.0, now - self._last_progress)
        if wm:
            self.watermark_history.append({"ts": time.time(), **wm})
        in_flight = bool(snap["in_flight"] or snap["active_workers"]
                         or (sem is not None
                             and (sem.holder_count() > 0
                                  or sem.waiter_count() > 0)))
        if in_flight and not self._was_in_flight:
            # idle -> busy transition: the progress clock was legitimately
            # frozen while idle; restart it or the first slow stage of a
            # new query after a long quiet gap reads as an instant stall
            self._last_progress = now
            age = 0.0
            self._stall_active = False
        self._was_in_flight = in_flight
        # stall detection: once per stall episode (re-arms on progress)
        report = None
        self.stalled = False
        if in_flight and age >= self.stall_timeout_s:
            self.stalled = True
            if not self._stall_active:
                self._stall_active = True
                self.stalls_detected += 1
                report = self._emit_stall_report(age)
        # heartbeat AFTER detection so the record carries this tick's
        # stalled verdict
        log = self._eventlog_fn() if emit_heartbeat else None
        if log is not None:
            try:
                log.write_heartbeat(
                    self._heartbeat_from(age, snap, wm, sem))
                self.heartbeats_emitted += 1
                get_stats().add("health_heartbeats")
            except Exception:
                self.tick_errors += 1
        return report

    # -- records / snapshots ---------------------------------------------------
    def _heartbeat_from(self, age: float, snap: Dict, wm: Dict, sem) -> Dict:
        """One schema-v4 heartbeat dict from tick()'s single per-tick
        sample (required keys pinned by tests/test_health.py)."""
        queues: Dict[str, int] = {}
        for q in snap["queues"]:
            # concurrent partition drains open one queue per partition
            # under the SAME stage label — sum them so no depth is lost
            queues[q["stage"]] = queues.get(q["stage"], 0) + q["depth"]
        self._seq += 1
        return {
            "seq": self._seq,
            "uptime_s": round(self.uptime_s(), 3),
            "device_used_bytes": wm.get("device_used_bytes", 0),
            "device_peak_bytes": wm.get("device_peak_bytes", 0),
            "device_limit_bytes": wm.get("device_limit_bytes", 0),
            "semaphore_holders":
                sem.holder_count() if sem is not None else 0,
            "semaphore_waiters":
                sem.waiter_count() if sem is not None else 0,
            "queues": queues,
            "queue_depth": sum(q["depth"] for q in snap["queues"]),
            "in_flight": len(snap["in_flight"]),
            "active_workers": snap["active_workers"],
            "last_progress_age_s": round(age, 3),
            "stalled": self.stalled,
        }

    def uptime_s(self) -> float:
        return max(0.0, time.monotonic() - self.started_at)

    def ticking(self) -> bool:
        """True when the monitor thread is running (health.enabled); False
        means samples only happen on explicit tick() calls — the status
        server then ticks on /healthz probes so stall detection still
        works with only health.port set."""
        return self._thread is not None

    def last_progress_age_s(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        return max(0.0, now - self._last_progress)

    def snapshot(self) -> Dict:
        """The /status payload: full live engine state as one JSON-able
        dict (also captured per phase into the bench JSON)."""
        from ..memory.catalog import peek_catalog
        from ..memory.semaphore import peek_semaphore
        from ..parallel.pipeline import pipeline_snapshot
        from .memprof import active as memprof_active
        from .node_context import active_contexts
        cat = peek_catalog()
        sem = peek_semaphore()
        mp = memprof_active()
        return {
            "ts": time.time(),
            "uptime_s": round(time.monotonic() - self.started_at, 3),
            "ticks": self.ticks,
            "tick_errors": self.tick_errors,
            "heartbeats_emitted": self.heartbeats_emitted,
            "stalled": self.stalled,
            "stalls_detected": self.stalls_detected,
            "last_stall_report_path": self.last_stall_report_path,
            "last_progress_age_s": round(self.last_progress_age_s(), 3),
            "semaphore": sem.dump() if sem is not None else None,
            "pipeline": pipeline_snapshot(),
            "catalog":
                cat.watermarks(timeout_s=0.5) if cat is not None else None,
            # memory flight recorder (utils/memprof.py): per-operator HBM
            # attribution + leak/postmortem counters; {"enabled": False}
            # when profiling is off so pollers see the knob state
            "memory": mp.snapshot() if mp is not None
            else {"enabled": False},
            "active_operators": active_contexts(),
            "watermark_history": list(self.watermark_history)[-32:],
            # link to the persistent cross-run store this session appends
            # to on close; browse it with the command in "serve" (the
            # history UI runs out-of-process, so no port to link here)
            "history": {
                "store_dir": self.history_dir,
                "serve": ("python -m spark_rapids_tpu.tools.historyd "
                          f"--dir {self.history_dir}")
                if self.history_dir else None,
            },
        }

    # -- stall forensics -------------------------------------------------------
    def stall_report(self, age: float) -> str:
        """Full forensics text: every thread's stack, the semaphore dump
        (named holders + wait queue), per-queue depths + in-flight task
        ages, active operator contexts, and the catalog dump."""
        from ..memory.catalog import peek_catalog
        from ..memory.semaphore import peek_semaphore
        from ..parallel.pipeline import pipeline_snapshot
        from .node_context import active_contexts
        lines: List[str] = [
            "== spark-rapids-tpu stall report ==",
            time.strftime("time: %Y-%m-%dT%H:%M:%S%z"),
            f"no engine progress for {age:.1f}s with work in flight "
            f"(stallTimeout={self.stall_timeout_s:.1f}s)",
        ]
        sem = peek_semaphore()
        lines.append("\n-- semaphore --")
        if sem is None:
            lines.append("(no semaphore created yet)")
        else:
            d = sem.dump()
            lines.append(f"permits={d['permits']} available={d['available']}"
                         f" acquires={d['acquires']}"
                         f" total_wait_s={d['total_wait_s']}")
            for h in d["holders"]:
                lines.append(
                    f"holder: thread={h['thread']!r} (id {h['thread_id']}) "
                    f"task={h['task_id']} depth={h['depth']} "
                    f"held for {h['held_s']:.1f}s")
            for w in d["waiters"]:
                lines.append(f"waiter: thread={w['thread']!r} "
                             f"task={w['task_id']} "
                             f"waiting for {w['waiting_s']:.1f}s")
        snap = pipeline_snapshot()
        lines.append("\n-- pipeline --")
        lines.append(f"active_workers={snap['active_workers']} "
                     f"progress_counter={snap['progress_counter']} "
                     f"last_progress_age_s={snap['last_progress_age_s']}")
        for q in snap["queues"]:
            lines.append(f"queue: stage={q['stage']!r} depth={q['depth']}/"
                         f"{q['bound']} age={q['age_s']:.1f}s")
        if not snap["queues"]:
            lines.append("(no live prefetch queues)")
        for tsk in snap["in_flight"]:
            lines.append(f"in-flight task: stage={tsk['stage']!r} "
                         f"thread={tsk['thread']!r} "
                         f"running for {tsk['age_s']:.1f}s")
        lines.append("\n-- active operator contexts --")
        ctxs = active_contexts()
        lines.extend(f"{name}: {desc}" for name, desc in sorted(ctxs.items()))
        if not ctxs:
            lines.append("(no instrumented operators executing)")
        lines.append("\n-- thread stacks --")
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sorted(sys._current_frames().items()):
            lines.append(f"thread {names.get(tid, '?')!r} (id {tid}):")
            lines.append("".join(traceback.format_stack(frame)).rstrip())
        lines.append("\n-- catalog --")
        cat = peek_catalog()
        if cat is None:
            lines.append("(no buffer catalog created yet)")
        else:
            # bounded, no-foreign-locks dump: stats()/oom_dump() can block
            # on the very lock the wedged thread holds
            dump = cat.watchdog_dump(timeout_s=1.0)
            if dump is None:
                lines.append("catalog lock UNAVAILABLE after 1s — a "
                             "holder is likely wedged (see stacks above)")
            else:
                lines.append(f"dump: {dump}")
        return "\n".join(lines) + "\n"

    def _emit_stall_report(self, age: float) -> str:
        from ..memory.catalog import peek_catalog
        from .metrics import get_stats
        from .tracing import get_tracer
        report = self.stall_report(age)
        self.last_stall_report = report
        get_stats().add("health_stalls_detected")
        get_tracer().instant("stall_detected", "health",
                             age_s=round(age, 1))
        path = None
        if self.report_dir:
            try:
                os.makedirs(self.report_dir, exist_ok=True)
                path = os.path.join(self.report_dir,
                                    f"stall-{int(time.time() * 1000)}.txt")
                with open(path, "w", encoding="utf-8") as f:
                    f.write(report)
                self.last_stall_report_path = path
            except OSError:
                path = None
        cat = peek_catalog()
        if cat is not None:
            cat.diagnostics.append(
                f"watchdog stall: no progress for {age:.1f}s"
                + (f" (report: {path})" if path else ""))
        import warnings
        warnings.warn(
            f"spark-rapids-tpu watchdog: engine stalled (no progress for "
            f"{age:.1f}s with work in flight)"
            + (f"; forensics at {path}" if path else ""),
            RuntimeWarning)
        return report


class HealthSubsystem:
    """One session's live-health wiring: the monitor plus the optional
    HTTP status server; ``close()`` tears both down (the no-leaked-threads
    contract extends to tpu-health-* threads)."""

    def __init__(self, monitor: HealthMonitor, server=None):
        self.monitor = monitor
        self.server = server

    def close(self) -> None:
        self.monitor.stop()
        if self.server is not None:
            self.server.stop()
            self.server = None


def configure_health(conf, eventlog_fn: Optional[Callable] = None
                     ) -> Optional[HealthSubsystem]:
    """Session-init chokepoint (TpuSession.__init__): start the monitor
    thread when ``health.enabled`` and the HTTP server when ``health.port``
    >= 0. Returns None when both are off — the common case costs nothing."""
    enabled = bool(conf.get(HEALTH_ENABLED))
    port = int(conf.get(HEALTH_PORT))
    if not enabled and port < 0:
        return None
    monitor = HealthMonitor(conf, eventlog_fn=eventlog_fn)
    server = None
    if port >= 0:
        from ..tools.statusd import StatusServer
        server = StatusServer(monitor, port=port)
        server.start()
    if enabled:
        monitor.start()
    return HealthSubsystem(monitor, server)
