"""Merging t-digest sketch (vectorized numpy).

Reference: GpuApproximatePercentile.scala lowers approx_percentile onto
cuDF's t-digest kernels (bounded-size centroid sketches, merged across
partitions, interpolated at query time). This is the host-side analogue: a
one-pass k-scale binning of sorted values (Dunning's merging digest with the
k1 scale function), fully vectorized, with the same partial/merge/evaluate
split the aggregation framework expects.

State encoding (one flat list of floats per group, shuffles as an
ArrayType(DOUBLE) column): ``[vmin, vmax, mean0, weight0, mean1, weight1,
...]``; the empty digest is ``[]``.

Size bound: the k1 scale function k(q) = delta/(2*pi) * asin(2q - 1) spans
``delta/2`` integer bins over q in [0, 1], so a digest holds at most about
``delta/2 + 2`` centroids regardless of input size — the accuracy argument
of approx_percentile maps to ``delta`` (Spark: 1/accuracy relative rank
error; larger accuracy = more centroids = finer sketch).
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["build_digest", "merge_digests", "digest_quantiles"]


def _k(q: np.ndarray, delta: float) -> np.ndarray:
    q = np.clip(q, 0.0, 1.0)
    return delta / (2.0 * np.pi) * np.arcsin(2.0 * q - 1.0)


def _compress(means: np.ndarray, weights: np.ndarray,
              delta: float) -> tuple:
    """Merge weight-ordered centroids that land in the same k-bin."""
    W = weights.sum()
    if W <= 0 or len(means) == 0:
        return means[:0], weights[:0]
    cum = np.cumsum(weights)
    qmid = (cum - weights / 2.0) / W
    bins = np.floor(_k(qmid, delta)).astype(np.int64)
    change = np.nonzero(np.diff(bins))[0] + 1
    starts = np.concatenate([[0], change])
    ends = np.concatenate([change, [len(means)]])
    cw = np.concatenate([[0.0], np.cumsum(weights)])
    cwm = np.concatenate([[0.0], np.cumsum(weights * means)])
    w_out = cw[ends] - cw[starts]
    m_out = (cwm[ends] - cwm[starts]) / w_out
    return m_out, w_out


def _encode(vmin: float, vmax: float, means: np.ndarray,
            weights: np.ndarray) -> List[float]:
    out = [float(vmin), float(vmax)]
    for m, w in zip(means, weights):
        out.append(float(m))
        out.append(float(w))
    return out


def _decode(digest: Sequence[float]):
    if not len(digest):
        return None
    d = np.asarray(digest, dtype=np.float64)
    return d[0], d[1], d[2::2], d[3::2]


def build_digest(values: np.ndarray, delta: int) -> List[float]:
    """Sketch a batch of raw values (the partial-aggregate update)."""
    v = np.sort(np.asarray(values, dtype=np.float64))
    v = v[~np.isnan(v)]
    n = len(v)
    if n == 0:
        return []
    q = (np.arange(n) + 0.5) / n
    bins = np.floor(_k(q, float(delta))).astype(np.int64)
    change = np.nonzero(np.diff(bins))[0] + 1
    starts = np.concatenate([[0], change])
    ends = np.concatenate([change, [n]])
    cs = np.concatenate([[0.0], np.cumsum(v)])
    counts = (ends - starts).astype(np.float64)
    means = (cs[ends] - cs[starts]) / counts
    return _encode(v[0], v[-1], means, counts)


def merge_digests(digests: Sequence[Sequence[float]],
                  delta: int) -> List[float]:
    """Merge partial digests (the merge-aggregate op)."""
    decoded = [d for d in (_decode(x) for x in digests) if d is not None]
    if not decoded:
        return []
    vmin = min(d[0] for d in decoded)
    vmax = max(d[1] for d in decoded)
    means = np.concatenate([d[2] for d in decoded])
    weights = np.concatenate([d[3] for d in decoded])
    order = np.argsort(means, kind="stable")
    m_out, w_out = _compress(means[order], weights[order], float(delta))
    return _encode(vmin, vmax, m_out, w_out)


def digest_quantiles(digest: Sequence[float],
                     qs: Sequence[float]) -> List[float]:
    """Interpolated quantiles (reference t-digest percentile_approx also
    interpolates between centroids, unlike Spark CPU's exact-value pick —
    the reference documents the same divergence)."""
    d = _decode(digest)
    if d is None:
        return [float("nan")] * len(qs)
    vmin, vmax, means, weights = d
    W = weights.sum()
    cum = np.cumsum(weights)
    mid = cum - weights / 2.0
    xs = np.concatenate([[0.0], mid, [W]])
    ys = np.concatenate([[vmin], means, [vmax]])
    t = np.asarray(qs, dtype=np.float64) * W
    return np.interp(t, xs, ys).tolist()
