"""Query deadlines + cooperative cancellation.

A wedged or thrashing query must not occupy the TpuSemaphore (and the
OOM arbiter, and the pipeline's bounded queues) forever — the reference
engine leans on Spark's task-kill machinery for this; this engine owns
its whole runtime, so it owns the deadline too.

``spark.rapids.tpu.query.timeoutSeconds`` arms a process-wide deadline
around each ``DataFrame.collect``. Cancellation is *cooperative*: the
runtime's natural yield points — the retry ladder's dispatch chokepoint
(memory/retry.py ``_invoke``), the OOM arbitration gate, the pipeline's
prefetch-queue hops and pooled-task starts (parallel/pipeline.py) —
each call :func:`check_deadline`, which is one module-global truthiness
check when no deadline is armed (the tracer/faults/memprof hot-path
pattern). The first checkpoint past the deadline raises a structured
:class:`QueryTimeoutError`; worker threads propagate it across the
prefetch queues as an ordinary poison pill, ``pipelined_collect``'s
finally releases every semaphore hold, and the retry ladder passes it
through untouched (the message deliberately contains no OOM marker).

Forensics: the first expiry writes ONE JSON dump — semaphore holders
and waiters, OOM-arbiter state, live pipeline queues, and the memory
flight recorder's postmortem path when profiling is on — to
``health.reportDir`` (falling back to the system temp dir), and every
QueryTimeoutError raised for that deadline carries its path.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

from ..conf import register_conf

__all__ = [
    "QUERY_TIMEOUT",
    "QueryTimeoutError",
    "deadline_scope",
    "check_deadline",
    "deadline_active",
    "deadline_stats",
    "reset_deadline",
]

QUERY_TIMEOUT = register_conf(
    "spark.rapids.tpu.query.timeoutSeconds",
    "Wall-clock deadline per collect() in seconds; 0 (the default) "
    "disables it. A query past its deadline cancels cooperatively at "
    "the runtime's next yield point (retry-ladder dispatch, OOM "
    "arbitration gate, pipeline queue hop) with a structured "
    "QueryTimeoutError carrying a forensics dump — semaphore, arbiter "
    "and pipeline state — so a wedged query never occupies the "
    "TpuSemaphore forever.",
    0.0, conf_type=float,
    checker=lambda v: None if v >= 0 else f"timeoutSeconds must be >= 0, got {v}")


class QueryTimeoutError(RuntimeError):
    """A query exceeded spark.rapids.tpu.query.timeoutSeconds and was
    cancelled cooperatively. The message intentionally contains no OOM
    marker substring so the retry ladder (memory/retry.py
    ``is_retryable_oom``) passes it straight through."""

    def __init__(self, timeout_s: float, elapsed_s: float,
                 forensics_path: Optional[str] = None):
        msg = (f"query exceeded its deadline: {elapsed_s:.2f}s elapsed > "
               f"spark.rapids.tpu.query.timeoutSeconds={timeout_s:g}"
               + (f"; forensics: {forensics_path}" if forensics_path else ""))
        super().__init__(msg)
        self.timeout_s = timeout_s
        self.elapsed_s = elapsed_s
        self.forensics_path = forensics_path


# module deadline state: _ACTIVE is the zero-overhead flag every
# check_deadline() call loads; the rest only matters while armed. The
# deadline is process-global by design — it guards the process-global
# semaphore/arbiter/pipeline, and one session collects at a time.
_ACTIVE = False
_DEADLINE_MONO = 0.0
_TIMEOUT_S = 0.0
_STARTED_MONO = 0.0
_REPORT_DIR = ""
_FIRED_PATH: Optional[str] = None
_FIRE_LOCK = threading.Lock()
_STATS_LOCK = threading.Lock()
_COUNTS: Dict[str, int] = {"deadlines_armed": 0, "deadline_expiries": 0}


def deadline_active() -> bool:
    return _ACTIVE


def check_deadline() -> None:
    """Cooperative cancellation checkpoint. One global truthiness check
    when no deadline is armed; raises QueryTimeoutError past expiry."""
    if not _ACTIVE:
        return
    if time.monotonic() >= _DEADLINE_MONO:
        raise _timeout_error()


def _timeout_error() -> QueryTimeoutError:
    elapsed = time.monotonic() - _STARTED_MONO
    global _FIRED_PATH
    with _FIRE_LOCK:
        if _FIRED_PATH is None:
            with _STATS_LOCK:
                _COUNTS["deadline_expiries"] += 1
            _FIRED_PATH = _write_forensics(elapsed) or ""
    return QueryTimeoutError(_TIMEOUT_S, elapsed,
                             forensics_path=_FIRED_PATH or None)


def _write_forensics(elapsed_s: float) -> Optional[str]:
    """One dump per armed deadline: everything a postmortem of a wedged
    query needs, gathered best-effort (forensics must never mask the
    timeout itself)."""
    dump: Dict[str, Any] = {
        "ts": time.time(),
        "timeout_s": _TIMEOUT_S,
        "elapsed_s": round(elapsed_s, 3),
    }
    try:
        from ..memory.semaphore import peek_semaphore
        sem = peek_semaphore()
        dump["semaphore"] = sem.dump() if sem is not None else None
    except Exception:
        dump["semaphore"] = None
    try:
        from ..memory.retry import arbiter_snapshot
        dump["oom_arbiter"] = arbiter_snapshot()
    except Exception:
        dump["oom_arbiter"] = None
    try:
        from ..parallel.pipeline import pipeline_snapshot
        dump["pipeline"] = pipeline_snapshot()
    except Exception:
        dump["pipeline"] = None
    try:
        from . import memprof
        mp = memprof.active()
        if mp is not None:
            from ..memory.catalog import get_catalog
            dump["memprof_postmortem"] = mp.oom_postmortem(
                f"query deadline expired after {elapsed_s:.2f}s",
                get_catalog()).get("path")
        else:
            dump["memprof_postmortem"] = None
    except Exception:
        dump["memprof_postmortem"] = None
    directory = _REPORT_DIR or tempfile.gettempdir()
    path = os.path.join(directory,
                        f"deadline-{os.getpid()}-{int(time.time() * 1000)}.json")
    try:
        os.makedirs(directory, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(dump, f, indent=1, sort_keys=True)
    except OSError:
        return None
    return path


@contextmanager
def deadline_scope(timeout_s: float, report_dir: str = ""):
    """Arm the process-wide query deadline for the duration of one
    collect. ``timeout_s <= 0`` is a no-op (the common case)."""
    global _ACTIVE, _DEADLINE_MONO, _TIMEOUT_S, _STARTED_MONO, \
        _REPORT_DIR, _FIRED_PATH
    if not timeout_s or timeout_s <= 0:
        yield
        return
    now = time.monotonic()
    _TIMEOUT_S = float(timeout_s)
    _STARTED_MONO = now
    _DEADLINE_MONO = now + float(timeout_s)
    _REPORT_DIR = report_dir or ""
    _FIRED_PATH = None
    _ACTIVE = True
    with _STATS_LOCK:
        _COUNTS["deadlines_armed"] += 1
    try:
        yield
    finally:
        _ACTIVE = False
        _FIRED_PATH = None


def deadline_stats() -> Dict[str, Any]:
    """Stats-registry source (/metrics gauges under the deadline_ prefix)."""
    with _STATS_LOCK:
        out: Dict[str, Any] = dict(_COUNTS)
    out["deadline_armed"] = int(_ACTIVE)
    return out


def reset_deadline() -> None:
    """Test hook: disarm and zero counters."""
    global _ACTIVE, _FIRED_PATH
    _ACTIVE = False
    _FIRED_PATH = None
    with _STATS_LOCK:
        for k in list(_COUNTS):
            _COUNTS[k] = 0
