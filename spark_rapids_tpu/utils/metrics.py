"""Tiered operator metrics + process-wide stats registry.

Reference: GpuMetric / GpuExec.scala:30-131 for the per-exec metric sets
(ESSENTIAL/MODERATE/DEBUG tiers gated by ``spark.rapids.sql.metrics.level``;
timers around device dispatch, upload/download, semaphore waits), and the
MetricsSystem-style aggregation the plugin tools mine out of Spark metrics.

This module adds two observability layers on top of plain counters:

- ``Histogram``: distribution metrics (latency quantiles, batch-size
  distributions) backed by the merging t-digest in ``utils/tdigest.py`` —
  bounded-size sketches, so per-batch observation is safe on hot paths.
- ``StatsRegistry``: one process-global registry that aggregates counters
  from every subsystem (buffer catalog spills/OOM, semaphore waits, XLA
  compile cache, scan upload cache, shuffle tiers) through lazily-imported
  source hooks, and serializes the lot as a Prometheus text exposition.
"""
from __future__ import annotations

import re
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

__all__ = ["MetricLevel", "Metric", "Histogram", "MetricRegistry",
           "StatsRegistry", "get_stats", "reset_stats", "skew_summary"]


def skew_summary(values: List) -> Dict:
    """Distribution summary of one per-partition series (rows or bytes)
    for the event-log v7 ``shuffle_skew`` records: min/p50/max/mean and
    the imbalance ratio max/mean (1.0 = perfectly balanced; the diagnose
    skew finding flags > 2.0). Partition counts are small, so a sort is
    cheaper than carrying a sketch."""
    if not values:
        return {"min": 0, "p50": 0, "max": 0, "mean": 0.0,
                "imbalance": 1.0}
    ordered = sorted(int(v) for v in values)
    mean = sum(ordered) / len(ordered)
    return {"min": ordered[0],
            "p50": ordered[len(ordered) // 2],
            "max": ordered[-1],
            "mean": mean,
            "imbalance": (ordered[-1] / mean) if mean > 0 else 1.0}


def build_skew_record(per_rows: List, per_bytes: List) -> Dict:
    """The shared payload of a v7 ``shuffle_skew`` record, built from one
    exchange's per-output-partition row and byte series. Lives here (not
    tools/eventlog.py) so all three exchange tiers can call it without an
    exec → tools import edge."""
    return {"partitions": len(per_rows),
            "rows": skew_summary(per_rows),
            "bytes": skew_summary(per_bytes),
            "per_partition_rows": [int(r) for r in per_rows]}


class MetricLevel:
    ESSENTIAL = 0
    MODERATE = 1
    DEBUG = 2

    _NAMES = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE, "DEBUG": DEBUG}

    @staticmethod
    def parse(name: str) -> int:
        return MetricLevel._NAMES[name.upper()]


# canonical metric names (reference GpuExec.scala:44-100)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
OP_TIME = "opTime"
SEMAPHORE_WAIT_TIME = "semaphoreWaitTime"
UPLOAD_TIME = "hostToDeviceTime"
UPLOAD_CACHE_HITS = "hostToDeviceCacheHits"
UPLOAD_BYTES = "hostToDeviceBytes"
DOWNLOAD_TIME = "deviceToHostTime"
DOWNLOAD_BYTES = "deviceToHostBytes"
PEAK_DEVICE_MEMORY = "peakDevMemory"
SPILL_BYTES = "spillBytes"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
JOIN_TIME = "joinTime"
COMPILE_TIME = "xlaCompileTime"
COMPILE_CACHE_HITS = "xlaCacheHits"
COMPILE_CACHE_MISSES = "xlaCacheMisses"
SHUFFLE_BYTES = "shuffleBytes"
SHUFFLE_PARTITION_TIME = "shufflePartitionTime"
BATCH_ROWS_HISTOGRAM = "batchRows"
PIPELINE_WAIT = "pipelineWait"
PREFETCH_QUEUE_DEPTH = "prefetchQueueDepth"
DONATED_BYTES = "donatedBytes"
COALESCED_BYTES = "coalescedBytes"

#: metric set every device operator registers up front (the ESSENTIAL tier
#: of the reference's per-exec metric sets, GpuExec.scala:44-60); the
#: tier-1 lint test (tests/test_observability.py) enforces it on every
#: Tpu*Exec class so new operators can't ship unobservable
CORE_NODE_METRICS = (NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES, OP_TIME)

#: metric names whose values are SECONDS — EXPLAIN ANALYZE and the
#: diagnose tool treat these as attributable time, everything else as
#: counters/bytes
TIME_METRICS = frozenset({
    OP_TIME, SEMAPHORE_WAIT_TIME, UPLOAD_TIME, DOWNLOAD_TIME, SORT_TIME,
    AGG_TIME, JOIN_TIME, COMPILE_TIME, SHUFFLE_PARTITION_TIME,
    PIPELINE_WAIT,
})

#: metric names whose values are BYTES (rendered human-readable)
BYTE_METRICS = frozenset({
    UPLOAD_BYTES, DOWNLOAD_BYTES, SPILL_BYTES, SHUFFLE_BYTES,
    PEAK_DEVICE_MEMORY, DONATED_BYTES, COALESCED_BYTES,
})


class Metric:
    __slots__ = ("name", "level", "value")

    def __init__(self, name: str, level: int):
        self.name = name
        self.level = level
        self.value = 0

    def add(self, v):
        self.value += v


class Histogram:
    """Distribution metric backed by the merging t-digest
    (utils/tdigest.py). Raw observations buffer in a small list and fold
    into the bounded sketch lazily, so ``observe`` on a hot path is an
    append + occasional vectorized compress."""

    __slots__ = ("name", "level", "delta", "count", "total", "vmin", "vmax",
                 "_buf", "_digest", "_lock")

    FLUSH_AT = 1024
    DEFAULT_QUANTILES = (0.5, 0.9, 0.99)

    def __init__(self, name: str, level: int = MetricLevel.MODERATE,
                 delta: int = 100):
        self.name = name
        self.level = level
        self.delta = delta
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self._buf: List[float] = []
        self._digest: List[float] = []
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if v < self.vmin:
                self.vmin = v
            if v > self.vmax:
                self.vmax = v
            self._buf.append(v)
            if len(self._buf) >= self.FLUSH_AT:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        from .tdigest import build_digest, merge_digests
        part = build_digest(self._buf, self.delta)
        self._digest = merge_digests([self._digest, part], self.delta) \
            if self._digest else part
        self._buf = []

    def quantiles(self, qs) -> List[float]:
        from .tdigest import digest_quantiles
        with self._lock:
            self._flush_locked()
            digest = list(self._digest)
        return digest_quantiles(digest, list(qs))

    def snapshot(self) -> Dict[str, float]:
        """Summary dict (serializes into event-log node records)."""
        with self._lock:
            self._flush_locked()
            digest = list(self._digest)
            count, total = self.count, self.total
            vmin, vmax = self.vmin, self.vmax
        if not count:
            return {"count": 0, "sum": 0.0}
        from .tdigest import digest_quantiles
        p50, p90, p99 = digest_quantiles(digest, self.DEFAULT_QUANTILES)
        return {"count": count, "sum": total, "min": vmin, "max": vmax,
                "p50": p50, "p90": p90, "p99": p99}


class MetricRegistry:
    """Per-exec metric set, filtered by the configured level.

    Thread-safe: pipelined execution (parallel/pipeline.py) drives one
    node's registry from concurrent partition drains and map-side pools,
    so counter updates and first-touch creation are locked — an unlocked
    ``value += v`` would silently undercount the very metrics EXPLAIN
    ANALYZE and tools/diagnose.py rank by."""

    def __init__(self, collect_level: int = MetricLevel.MODERATE):
        self.collect_level = collect_level
        self._metrics: Dict[str, Metric] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def metric(self, name: str, level: int = MetricLevel.MODERATE) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Metric(name, level)
                self._metrics[name] = m
            return m

    def histogram(self, name: str,
                  level: int = MetricLevel.MODERATE) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = Histogram(name, level)
                self._histograms[name] = h
            return h

    def add(self, name: str, v, level: int = MetricLevel.MODERATE):
        if level <= self.collect_level:
            m = self.metric(name, level)
            with self._lock:
                m.add(v)

    def observe(self, name: str, v, level: int = MetricLevel.MODERATE):
        if level <= self.collect_level:
            self.histogram(name, level).observe(v)

    @contextmanager
    def timed(self, name: str, level: int = MetricLevel.MODERATE):
        if level > self.collect_level:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0, level)

    def snapshot(self) -> Dict:
        with self._lock:
            out: Dict = {k: m.value for k, m in self._metrics.items()}
            hists = list(self._histograms.items())
        for k, h in hists:
            out[k] = h.snapshot()
        return out


# ---------------------------------------------------------------------------
# process-global stats registry
# ---------------------------------------------------------------------------
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    name = _NAME_RE.sub("_", str(name))
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _flatten(prefix: str, value, out: Dict[str, float]) -> None:
    """Fold nested dicts of numbers into flat snake_case keys."""
    if isinstance(value, dict):
        for k, v in value.items():
            _flatten(f"{prefix}_{_sanitize(k)}", v, out)
    elif isinstance(value, bool):
        out[prefix] = float(value)
    elif isinstance(value, (int, float)):
        out[prefix] = value


class StatsRegistry:
    """Process-wide counters + histograms + pluggable subsystem sources.

    A *source* is a zero-arg callable returning a (possibly nested) dict of
    numbers; ``collect()`` flattens each under its source name. The default
    sources pull from the buffer catalog, the semaphore, the XLA compile
    cache, the scan upload cache and the shuffle manager — the counters the
    reference's profiling tools mine out of Spark metrics, gathered at the
    source instead."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._sources: Dict[str, Callable[[], Dict]] = {}

    # -- own metrics ----------------------------------------------------------
    def add(self, name: str, v=1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + v

    def observe(self, name: str, v) -> None:
        self.histogram(name).observe(v)

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = Histogram(name)
                self._histograms[name] = h
            return h

    # -- sources --------------------------------------------------------------
    def register_source(self, name: str, fn: Callable[[], Dict]) -> None:
        with self._lock:
            self._sources[name] = fn

    # -- aggregation ----------------------------------------------------------
    def collect(self) -> Dict[str, float]:
        """One flat dict of every counter in the process. Source failures
        are skipped (a half-initialized subsystem must not break stats)."""
        with self._lock:
            out: Dict[str, float] = dict(self._counters)
            sources = list(self._sources.items())
        for name, fn in sources:
            try:
                _flatten(_sanitize(name), fn() or {}, out)
            except Exception:
                continue
        return out

    @staticmethod
    def delta(after: Dict[str, float],
              before: Dict[str, float]) -> Dict[str, float]:
        """Per-key difference (for per-query attribution of process-wide
        counters). Keys only in ``after`` count from zero."""
        return {k: v - before.get(k, 0) for k, v in after.items()}

    def histograms(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            hs = list(self._histograms.items())
        return {k: h.snapshot() for k, h in hs}

    def prometheus_text(self, prefix: str = "spark_rapids_tpu") -> str:
        """Prometheus text exposition (0.0.4): collected values as
        ``gauge`` samples (several exported series legitimately decrease —
        used bytes, cache entries — and a falsely-typed counter makes
        rate()/increase() hallucinate resets), histograms as ``summary``
        quantiles."""
        lines: List[str] = []
        for key, val in sorted(self.collect().items()):
            name = f"{prefix}_{_sanitize(key)}"
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {_fmt(val)}")
        for key, snap in sorted(self.histograms().items()):
            name = f"{prefix}_{_sanitize(key)}"
            lines.append(f"# TYPE {name} summary")
            for q, label in (("p50", "0.5"), ("p90", "0.9"),
                             ("p99", "0.99")):
                if q in snap:
                    lines.append(f'{name}{{quantile="{label}"}} '
                                 f"{_fmt(snap[q])}")
            lines.append(f"{name}_sum {_fmt(snap.get('sum', 0.0))}")
            lines.append(f"{name}_count {_fmt(snap.get('count', 0))}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._histograms.clear()


def _fmt(v) -> str:
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


# -- default sources (lazy imports; subsystems may not be loaded yet) --------
def _compile_cache_source() -> Dict:
    from .compile_cache import cache_stats
    return cache_stats()


def _catalog_source() -> Dict:
    from ..memory.catalog import peek_catalog
    cat = peek_catalog()
    return cat.counters() if cat is not None else {}


def _semaphore_source() -> Dict:
    from ..memory.semaphore import peek_semaphore
    sem = peek_semaphore()
    if sem is None:
        return {}
    return {"wait_seconds": sem.total_wait_time,
            "acquires": sem.acquire_count,
            "holders": sem.holder_count(),
            "waiters": sem.waiter_count(),
            "held_seconds": sem.held_histogram.snapshot()}


def _upload_cache_source() -> Dict:
    from ..exec.transitions import upload_cache_stats
    return upload_cache_stats()


def _shuffle_source() -> Dict:
    from ..shuffle.manager import shuffle_stats
    return shuffle_stats()


def _pipeline_source() -> Dict:
    from ..parallel.pipeline import pipeline_stats
    return pipeline_stats()


def _tracer_source() -> Dict:
    from .tracing import tracer_stats
    return tracer_stats()


def _memprof_source() -> Dict:
    from .memprof import memprof_stats
    return memprof_stats()


def _host_sync_source() -> Dict:
    from ..columnar.device import host_sync_stats
    return host_sync_stats()


def _faults_source() -> Dict:
    from .faults import faults_stats
    return faults_stats()


def _retry_source() -> Dict:
    from ..memory.retry import retry_stats
    return retry_stats()


def _fallback_source() -> Dict:
    from ..exec.fallback import fallback_stats
    return fallback_stats()


def _deadline_source() -> Dict:
    from .deadline import deadline_stats
    return deadline_stats()


def _movement_source() -> Dict:
    from .movement import movement_stats
    return movement_stats()


def _shuffle_telemetry_source() -> Dict:
    from ..shuffle.telemetry import shuffle_telemetry_stats
    return shuffle_telemetry_stats()


_DEFAULT_SOURCES = {
    "compile_cache": _compile_cache_source,
    "catalog": _catalog_source,
    "semaphore": _semaphore_source,
    "upload_cache": _upload_cache_source,
    "shuffle": _shuffle_source,
    "pipeline": _pipeline_source,
    "tracer": _tracer_source,
    "memprof": _memprof_source,
    "host_sync": _host_sync_source,
    "faults": _faults_source,
    "retry": _retry_source,
    "fallback": _fallback_source,
    "deadline": _deadline_source,
    "movement": _movement_source,
    "shuffle_telemetry": _shuffle_telemetry_source,
}

_GLOBAL_STATS: Optional[StatsRegistry] = None
_GLOBAL_STATS_LOCK = threading.Lock()


def get_stats() -> StatsRegistry:
    """The process-global registry, with the default subsystem sources
    registered."""
    global _GLOBAL_STATS
    with _GLOBAL_STATS_LOCK:
        if _GLOBAL_STATS is None:
            reg = StatsRegistry()
            for name, fn in _DEFAULT_SOURCES.items():
                reg.register_source(name, fn)
            _GLOBAL_STATS = reg
        return _GLOBAL_STATS


def reset_stats() -> None:
    """Drop the global registry's own counters/histograms (sources keep
    their subsystem state; tests reset those separately)."""
    global _GLOBAL_STATS
    with _GLOBAL_STATS_LOCK:
        if _GLOBAL_STATS is not None:
            _GLOBAL_STATS.reset()
