"""Tiered operator metrics (reference: GpuMetric, GpuExec.scala:30-131).

ESSENTIAL/MODERATE/DEBUG tiers gate collection cost by
``spark.rapids.sql.metrics.level``; timers measure wall time around device
dispatch (opTime), upload/download, and semaphore waits.
"""
from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict

__all__ = ["MetricLevel", "Metric", "MetricRegistry"]


class MetricLevel:
    ESSENTIAL = 0
    MODERATE = 1
    DEBUG = 2

    _NAMES = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE, "DEBUG": DEBUG}

    @staticmethod
    def parse(name: str) -> int:
        return MetricLevel._NAMES[name.upper()]


# canonical metric names (reference GpuExec.scala:44-100)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
OP_TIME = "opTime"
SEMAPHORE_WAIT_TIME = "semaphoreWaitTime"
UPLOAD_TIME = "hostToDeviceTime"
UPLOAD_CACHE_HITS = "hostToDeviceCacheHits"
DOWNLOAD_TIME = "deviceToHostTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
SPILL_BYTES = "spillBytes"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
JOIN_TIME = "joinTime"
COMPILE_TIME = "xlaCompileTime"


class Metric:
    __slots__ = ("name", "level", "value")

    def __init__(self, name: str, level: int):
        self.name = name
        self.level = level
        self.value = 0

    def add(self, v):
        self.value += v


class MetricRegistry:
    """Per-exec metric set, filtered by the configured level."""

    def __init__(self, collect_level: int = MetricLevel.MODERATE):
        self.collect_level = collect_level
        self._metrics: Dict[str, Metric] = {}

    def metric(self, name: str, level: int = MetricLevel.MODERATE) -> Metric:
        m = self._metrics.get(name)
        if m is None:
            m = Metric(name, level)
            self._metrics[name] = m
        return m

    def add(self, name: str, v, level: int = MetricLevel.MODERATE):
        if level <= self.collect_level:
            self.metric(name, level).add(v)

    @contextmanager
    def timed(self, name: str, level: int = MetricLevel.MODERATE):
        if level > self.collect_level:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.metric(name, level).add(time.perf_counter() - t0)

    def snapshot(self) -> Dict[str, float]:
        return {k: m.value for k, m in self._metrics.items()}
