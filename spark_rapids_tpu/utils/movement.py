"""Data-movement observatory: the runtime sync/transfer ledger.

ROADMAP item 1 (async-first execution) is blocked on a measurement gap:
the srtpu-analyze ``sync`` checker knows *where* the hot static sync
sites live and the critical-path walker (tools/trace.py) knows *how
much* ``sync_wait`` costs per query, but nothing joins the two. Theseus
(PAPERS.md) is built around minimizing data movement in distributed
query engines; this module is the instrument that turns its principle
into a ranked worklist: every host<->device crossing at the engine's
existing funnels (``DeviceTable.to_host``, the H2D upload exec, the
exchange/manager count passes) reports into a process-wide
**MovementLedger** recording call-site, operator, query, bytes, wall
and blocking-vs-deferred into a bounded ring plus per-(site, operator)
aggregation.

Cost model mirrors utils/faults.py: a module-level ``_LEDGER`` that is
``None`` when disabled, so every funnel pays exactly one global load +
is-None check when the observatory is off (the zero-overhead pin that
tests/test_movement.py asserts on). Byte counts are passed as callables
so nothing is computed on the disabled path.

On top of the raw ledger:

- **device-residency tracking**: ``to_host`` tags the downloaded
  ``HostTable`` with its (query, site) lineage; host-side derivations
  (``HostTable.slice``/``concat``) propagate the tag; the H2D funnels
  check it, so a batch that is downloaded and re-uploaded within one
  query is flagged as a **round trip** — the prime async-first target.
- **static<->runtime join**: every instrumented site is named
  ``path::symbol`` and maps onto the srtpu-analyze baseline keys
  (``path::rule::symbol``) via ``SITES``, so tools/diagnose.py can rank
  the sticky sync debt by *measured* wall/bytes and attach a
  make-nonblocking suggestion.
- **event-log surfacing**: tools/eventlog.py writes ONE schema-v11
  ``movement_summary`` record per query (null payload when the
  observatory is off, matching the memory_summary/recovery convention)
  from ``query_summary()``; ``movement_stats()`` feeds the stats
  registry so per-query deltas, the history sentinel's D2H-bytes gate
  and the statusd ``/metrics`` movement gauges come for free.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from ..conf import register_conf

__all__ = [
    "MovementLedger",
    "MovementSite",
    "SITES",
    "configure_movement",
    "reset_movement",
    "active",
    "clock",
    "note_d2h",
    "note_h2d",
    "tag_lineage",
    "drain_ring",
    "query_summary",
    "movement_stats",
    "site_info",
]

MOVEMENT_ENABLED = register_conf(
    "spark.rapids.tpu.movement.enabled",
    "Enable the data-movement observatory (utils/movement.py): every "
    "host<->device crossing at the engine's sync/transfer funnels is "
    "recorded with call-site, operator, bytes and wall time, batches "
    "are lineage-tagged so host<->device round trips are flagged, and "
    "each query's event log carries a movement_summary record. When "
    "false (the default) every funnel compiles down to a single "
    "module-constant check and nothing is recorded.",
    False)

MOVEMENT_RING_SIZE = register_conf(
    "spark.rapids.tpu.movement.ringSize",
    "Bounded capacity of the movement ledger's raw-event ring. Oldest "
    "events drop first; the per-(site, operator) aggregation is exact "
    "regardless of ring occupancy.",
    4096,
    checker=lambda v: None if int(v) > 0 else "must be positive")


class MovementSite:
    """Static description of one instrumented funnel: its direction,
    the srtpu-analyze baseline keys (``path::rule::symbol``) its
    measured cost attributes to, and the make-nonblocking suggestion
    tools/diagnose.py renders next to the measured ranking."""

    __slots__ = ("direction", "baseline_keys", "hint")

    def __init__(self, direction: str, baseline_keys: Tuple[str, ...],
                 hint: str):
        self.direction = direction
        self.baseline_keys = baseline_keys
        self.hint = hint


#: every instrumented funnel, keyed ``path::symbol`` — the identity the
#: ledger aggregates under and the join point onto the static baseline.
SITES: Dict[str, MovementSite] = {
    "spark_rapids_tpu/columnar/device.py::DeviceTable.to_host":
        MovementSite("d2h", (
            "spark_rapids_tpu/columnar/device.py::sync-asarray"
            "::DeviceTable.to_host",
            "spark_rapids_tpu/columnar/device.py::sync-asarray"
            "::_download_column",
        ), "the deliberate bulk-download funnel — keep results "
           "device-resident longer or defer materialization so compute "
           "overlaps the download (ROADMAP item 1)"),
    "spark_rapids_tpu/columnar/device.py::shrink_to_fit":
        MovementSite("d2h", (
            "spark_rapids_tpu/columnar/device.py::sync-int-scalar"
            "::shrink_to_fit",
        ), "4-byte row-count sync per compaction — thread num_rows in "
           "from a caller that already synced it"),
    "spark_rapids_tpu/columnar/device.py::resolve_scalars":
        MovementSite("d2h", (
            "spark_rapids_tpu/columnar/device.py::sync-device-get"
            "::resolve_scalars",
        ), "the batched-scalar funnel (DeferredScalar boundary): one "
           "transfer per host decision, 4B per scalar — growth here "
           "tracks decision points, not data; widen the batch (hand "
           "more scalars to one call) before anything else"),
    "spark_rapids_tpu/columnar/device.py::to_host_batched":
        MovementSite("d2h", (
            "spark_rapids_tpu/columnar/device.py::sync-device-get"
            "::to_host_batched",
        ), "the deferred-D2H drain funnel: one bulk device_get per "
           "output drain — already the async-first endpoint; growth "
           "here is real result volume, not sync debt"),
    "spark_rapids_tpu/exec/exchange.py"
    "::TpuShuffleExchangeExec._exchange_chunk":
        MovementSite("d2h", (
            "spark_rapids_tpu/exec/exchange.py::sync-asarray"
            "::TpuShuffleExchangeExec._exchange_chunk",
            "spark_rapids_tpu/exec/exchange.py::sync-device-get"
            "::TpuShuffleExchangeExec._exchange_chunk",
        ), "count pass + bulk shard-rows sync per exchanged chunk — "
           "double-buffer so chunk N's count pass overlaps chunk N-1's "
           "all-to-all"),
    "spark_rapids_tpu/shuffle/ici.py::unshard_table":
        MovementSite("d2h", (
            "spark_rapids_tpu/shuffle/ici.py::sync-device-get"
            "::unshard_table",
        ), "the bulk unshard gather: ONE device_get of the whole column "
           "pytree at the shuffle boundary (was one blocking np.asarray "
           "per column plane) — growth here is unshard volume; keep "
           "consumers mesh-capable (exec/mesh.py) so the boundary never "
           "materializes at all"),
    "spark_rapids_tpu/shuffle/manager.py"
    "::ShuffleManager._write_partition_transport":
        MovementSite("d2h", (
            "spark_rapids_tpu/shuffle/manager.py::sync-asarray"
            "::ShuffleManager._write_partition_transport",
        ), "partition-id count pass (4B/row) before the bulk download "
           "— overlap it with the previous batch's serialize"),
    "spark_rapids_tpu/shuffle/manager.py"
    "::ShuffleManager._write_partition_cached":
        MovementSite("d2h", (
            "spark_rapids_tpu/shuffle/manager.py::sync-asarray"
            "::ShuffleManager._write_partition_cached",
        ), "partition-id count pass (4B/row); slices stay on device — "
           "overlap it with the previous batch's gather"),
    "spark_rapids_tpu/shuffle/manager.py"
    "::ShuffleManager._read_partition_cached":
        MovementSite("d2h", (
            "spark_rapids_tpu/shuffle/manager.py::sync-device-get"
            "::ShuffleManager._read_partition_cached",
        ), "batched block-count sync (4B per block) once per reduce "
           "partition — already bulk; growth tracks partition count"),
    "spark_rapids_tpu/exec/transitions.py"
    "::HostToDeviceExec._upload_retryable":
        MovementSite("h2d", (),
                     "uploads are async-dispatched (deferred); growth "
                     "here means device residency was lost upstream — "
                     "check the round-trip count first"),
    "spark_rapids_tpu/shuffle/manager.py::ShuffleManager.read_partition":
        MovementSite("h2d", (),
                     "reduce-side re-upload of host-staged shuffle "
                     "blocks — the cached device tier "
                     "(spark.rapids.tpu.shuffle.cacheWrites) skips the "
                     "whole round trip"),
}


def site_info(site: str) -> Optional[MovementSite]:
    return SITES.get(site)


#: keys of the per-query / process-wide totals dict — one place so the
#: event-log record, the stats source and the tests agree on the shape
TOTAL_KEYS = ("d2h_bytes", "h2d_bytes", "d2h_count", "h2d_count",
              "blocking_count", "deferred_count", "round_trips")


def _zero_totals() -> Dict[str, Any]:
    t: Dict[str, Any] = {k: 0 for k in TOTAL_KEYS}
    t["wall_s"] = 0.0
    return t


_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _canonical(filename: str) -> str:
    """Repo-relative posix path of a frame's file (mirrors
    srtpu-analyze's canonical_relpath so call sites and baseline keys
    share a vocabulary)."""
    parts = filename.replace(os.sep, "/").split("/")
    if "spark_rapids_tpu" in parts:
        idx = len(parts) - 1 - parts[::-1].index("spark_rapids_tpu")
        return "/".join(parts[idx:])
    return "/".join(parts)


class MovementLedger:
    """Process-wide ledger of host<->device crossings.

    Raw events land in a bounded ring (forensics: the exact sequence of
    crossings with call sites); exact aggregation is kept per
    (site, operator) process-wide and per query for the event-log
    ``movement_summary`` record. All state is lock-guarded — funnels
    fire from pipeline workers, shuffle writers and the query thread
    concurrently."""

    def __init__(self, ring_size: int = 4096):
        self.ring_size = int(ring_size)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.ring_size)
        # (site, operator) -> {direction, count, bytes, wall_s,
        #                      blocking_count, round_trips}
        self._agg: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._totals = _zero_totals()
        # query_id -> {"totals", "sites", "operators"} accumulators
        self._queries: Dict[Any, Dict[str, Any]] = {}

    # -- recording --------------------------------------------------------
    def note(self, direction: str, site: str,
             nbytes: Union[int, Callable[[], int]], t0: float,
             blocking: bool, table: Any = None, origin: Any = None,
             plan_sig: Optional[str] = None) -> None:
        """Record one crossing. ``table`` (D2H) is the downloaded host
        batch to lineage-tag; ``origin`` (H2D) is the uploaded host
        batch whose lineage tag marks a round trip. ``nbytes`` may be a
        callable so funnels never compute sizes on the disabled path."""
        wall = (time.perf_counter() - t0) if t0 else 0.0
        n = int(nbytes() if callable(nbytes) else nbytes)
        from . import node_context
        ctx = node_context.current()
        operator = ctx.name if ctx is not None else None
        query_id = ctx.query_id if ctx is not None else None
        call_site = self._call_site(site)
        round_trip = False
        bounced_from = None
        if direction == "d2h" and table is not None:
            try:
                table._tpu_lineage = (query_id, site)
            except (AttributeError, TypeError):
                pass
        elif direction == "h2d" and origin is not None:
            tag = getattr(origin, "_tpu_lineage", None)
            if tag is not None and tag[0] == query_id:
                round_trip = True
                bounced_from = tag[1]
        entry = {
            "ts": time.time(),
            "direction": direction,
            "site": site,
            "call_site": call_site,
            "operator": operator,
            "query_id": query_id,
            "plan_sig": plan_sig,
            "bytes": n,
            "wall_s": wall,
            "blocking": blocking,
            "round_trip": round_trip,
        }
        if bounced_from is not None:
            entry["bounced_from"] = bounced_from
        with self._lock:
            self._ring.append(entry)
            self._fold(self._agg, self._totals, entry)
            q = self._queries.get(query_id)
            if q is None:
                q = self._queries[query_id] = {
                    "totals": _zero_totals(), "sites": {},
                    "operators": {}}
            self._fold(q["sites"], q["totals"], entry,
                       key=site, extra=q["operators"])

    @staticmethod
    def _fold(agg: Dict, totals: Dict[str, Any], entry: Dict,
              key: Any = None, extra: Optional[Dict] = None) -> None:
        direction, n, wall = (entry["direction"], entry["bytes"],
                              entry["wall_s"])
        totals[f"{direction}_bytes"] += n
        totals[f"{direction}_count"] += 1
        totals["blocking_count" if entry["blocking"]
               else "deferred_count"] += 1
        totals["round_trips"] += 1 if entry["round_trip"] else 0
        totals["wall_s"] += wall
        buckets = [(agg, key if key is not None
                    else (entry["site"], entry["operator"] or "<none>"))]
        if extra is not None:
            buckets.append((extra, entry["operator"] or "<none>"))
        for table, k in buckets:
            a = table.get(k)
            if a is None:
                a = table[k] = {"direction": direction, "count": 0,
                                "bytes": 0, "wall_s": 0.0,
                                "blocking_count": 0, "round_trips": 0}
            a["count"] += 1
            a["bytes"] += n
            a["wall_s"] += wall
            if entry["blocking"]:
                a["blocking_count"] += 1
            if entry["round_trip"]:
                a["round_trips"] += 1

    @staticmethod
    def _call_site(site: str) -> Optional[str]:
        """file:line of the first frame OUTSIDE this module and the
        funnel's own file — who asked for the crossing, not where the
        funnel lives (the site already says that)."""
        site_file = site.split("::", 1)[0].rsplit("/", 1)[-1]
        try:
            f = sys._getframe(3)
        except ValueError:  # pragma: no cover — shallow stack
            return None
        while f is not None:
            fn = f.f_code.co_filename
            base = os.path.basename(fn)
            if base != site_file and not fn.startswith(
                    os.path.join(_PKG_ROOT, "utils")):
                return f"{_canonical(fn)}:{f.f_lineno}"
            f = f.f_back
        return None

    # -- reads ------------------------------------------------------------
    def drain_ring(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
            return out

    def totals(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._totals)

    def site_aggregate(self) -> List[Dict[str, Any]]:
        """Process-wide per-(site, operator) rows, heaviest wall first."""
        with self._lock:
            rows = [{"site": site, "operator": op, **dict(a)}
                    for (site, op), a in self._agg.items()]
        rows.sort(key=lambda r: (-r["wall_s"], -r["bytes"], r["site"]))
        return rows

    def query_summary(self, query_id: Any,
                      drain: bool = True) -> Dict[str, Any]:
        """The per-query ``movement_summary`` payload: totals plus
        per-site and per-operator breakdowns (wall-heavy first). A query
        that moved nothing gets a zero summary — the event-log record
        set stays stable whether or not data moved."""
        with self._lock:
            q = (self._queries.pop(query_id, None) if drain
                 else self._queries.get(query_id))
        if q is None:
            return {"totals": _zero_totals(), "sites": [],
                    "operators": []}
        sites = [{"site": site, **dict(a)}
                 for site, a in q["sites"].items()]
        sites.sort(key=lambda r: (-r["wall_s"], -r["bytes"], r["site"]))
        ops = [{"operator": op, **dict(a)}
               for op, a in q["operators"].items()]
        ops.sort(key=lambda r: (-r["wall_s"], -r["bytes"], r["operator"]))
        return {"totals": dict(q["totals"]), "sites": sites,
                "operators": ops}


# ---------------------------------------------------------------------------
# module-level ledger: None when disabled (the zero-overhead pin)
# ---------------------------------------------------------------------------
_LEDGER: Optional[MovementLedger] = None


def clock() -> float:
    """Funnel-side timestamp: perf_counter when the observatory is on,
    0.0 (= "don't time") when off. One global load + is-None check on
    the disabled path."""
    if _LEDGER is None:
        return 0.0
    return time.perf_counter()


def note_d2h(site: str, nbytes: Union[int, Callable[[], int]],
             t0: float = 0.0, blocking: bool = True,
             table: Any = None, plan_sig: Optional[str] = None) -> None:
    """Hot-path D2H funnel hook. Disabled: one global load + is-None
    check (the zero-overhead pin)."""
    if _LEDGER is None:
        return
    _LEDGER.note("d2h", site, nbytes, t0, blocking, table=table,
                 plan_sig=plan_sig)


def note_h2d(site: str, nbytes: Union[int, Callable[[], int]],
             t0: float = 0.0, blocking: bool = False,
             origin: Any = None, plan_sig: Optional[str] = None) -> None:
    """Hot-path H2D funnel hook. Disabled: one global load + is-None
    check (the zero-overhead pin)."""
    if _LEDGER is None:
        return
    _LEDGER.note("h2d", site, nbytes, t0, blocking, origin=origin,
                 plan_sig=plan_sig)


def tag_lineage(dst: Any, *srcs: Any) -> None:
    """Propagate device-residency lineage onto a host batch derived from
    ``srcs`` (HostTable.slice/concat call this) so a downloaded batch
    that is re-uploaded after host-side reshaping still flags as a
    round trip. Disabled: one global load + is-None check."""
    if _LEDGER is None:
        return
    for s in srcs:
        tag = getattr(s, "_tpu_lineage", None)
        if tag is not None:
            try:
                dst._tpu_lineage = tag
            except (AttributeError, TypeError):
                pass
            return


def configure_movement(conf) -> Optional[MovementLedger]:
    """Install (or clear) the process-wide ledger from a RapidsConf
    (TpuSession.__init__ chokepoint — the most recent session wins)."""
    global _LEDGER
    if not conf.get(MOVEMENT_ENABLED):
        _LEDGER = None
        return None
    _LEDGER = MovementLedger(int(conf.get(MOVEMENT_RING_SIZE)))
    return _LEDGER


def reset_movement() -> None:
    global _LEDGER
    _LEDGER = None


def active() -> Optional[MovementLedger]:
    return _LEDGER


def drain_ring() -> List[Dict[str, Any]]:
    led = _LEDGER
    return led.drain_ring() if led is not None else []


def query_summary(query_id: Any,
                  drain: bool = True) -> Optional[Dict[str, Any]]:
    """Per-query movement summary for the event log; None when the
    observatory is off (the v11 record's null-payload convention)."""
    led = _LEDGER
    if led is None:
        return None
    return led.query_summary(query_id, drain=drain)


def movement_stats() -> Dict[str, Any]:
    """Stats-registry source: process-wide movement totals, flattened
    as ``movement_*`` gauges on /metrics and per-query event-log stats
    deltas (the history sentinel's D2H-bytes gate reads
    ``movement_d2h_bytes``). Empty when the observatory is off."""
    led = _LEDGER
    if led is None:
        return {}
    t = led.totals()
    t["wall_s"] = round(t["wall_s"], 6)
    return t
