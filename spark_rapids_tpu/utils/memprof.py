"""Memory flight recorder: allocation-lifecycle profiling for HBM.

Reference: RapidsBufferCatalog can explain any OOM because it tracks
every buffer's full lifecycle across the device/host/disk tiers
(RapidsBufferCatalog.scala:40,156; spark.rapids.memory.gpu.oomDumpDir
state dumps). The catalog here (memory/catalog.py) exposed only O(1)
watermarks and an unattributed ``oom_dump()`` string; this module is the
missing attribution layer:

- **lifecycle ring**: every register/spill/restore/free (plus external-
  bytes updates) lands in a bounded ring with a monotonic sequence
  number, byte delta, tier and the owning (query_id, operator) from the
  thread-local node context (utils/node_context.py) — the flight
  recorder an OOM postmortem replays.
- **per-(query, operator) aggregation**: live bytes, peak bytes,
  alloc/free counts, spill/restore churn and held-duration per operator,
  so ``tools/diagnose.py``, ``/status`` and EXPLAIN ANALYZE can rank
  *who holds the HBM*.
- **peak attribution**: whenever the device total (catalog-resident +
  external sources) makes a new high-water mark, the per-owner live set
  is snapshotted — the holders sum to the catalog's
  ``peak_device_bytes`` exactly, which the tier-1 test pins within 1%.
- **leak detection**: ``query_end(qid)`` flags buffers still registered
  after the query finished, attributed to the operator that allocated
  them (the RMM debug allocator's outstanding-allocations report, per
  query instead of per process).
- **OOM postmortem**: on allocation failure (strict pool register) or
  exhausted OOM recovery the catalog calls ``oom_postmortem()``, which
  dumps ranked holders-by-operator, the last N lifecycle events,
  spill-tier occupancy and the semaphore holder table to
  ``health.reportDir/oom-<ts>.txt`` before the exception propagates,
  and queues a schema-v6 ``oom_postmortem`` event-log record.

Cost model mirrors the tracer (utils/tracing.py): a module-level
``_ACTIVE`` profiler that is ``None`` when disabled, so the catalog hot
path pays one attribute load + is-None check. Lock order is
catalog._lock -> MemoryProfiler._lock (record() is called from inside
catalog mutations and never calls back into the catalog while holding
its own lock).
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..conf import register_conf
from .node_context import current

__all__ = ["MEMPROF_ENABLED", "MEMPROF_RING_SIZE", "MemoryProfiler",
           "active", "get_memprof", "set_memprof", "configure_memprof",
           "memprof_stats"]

MEMPROF_ENABLED = register_conf(
    "spark.rapids.tpu.memory.profile.enabled",
    "Record buffer-catalog allocation lifecycle events (register/spill/"
    "restore/free with byte deltas and owning query+operator) into the "
    "process-wide memory flight recorder: per-operator live/peak HBM "
    "aggregation, retained-buffer leak detection at query end, and OOM "
    "postmortem reports (health.reportDir/oom-<ts>.txt). Reference: "
    "RapidsBufferCatalog lifecycle tracking + "
    "spark.rapids.memory.gpu.oomDumpDir.", True)

MEMPROF_RING_SIZE = register_conf(
    "spark.rapids.tpu.memory.profile.ringSize",
    "Ring-buffer capacity of the memory flight recorder in lifecycle "
    "events; overflow drops the oldest events. The last events feed OOM "
    "postmortems and diagnose reports.", 4096,
    checker=lambda v: None if v > 0 else f"must be positive, got {v}")

#: attribution key for allocations outside any instrumented operator
#: (plain collect() with no event log runs with an empty context stack)
UNATTRIBUTED = (None, -1, "(unattributed)")

#: holder label for device bytes held outside the spill framework
#: (register_external_bytes sources: upload cache etc.)
EXTERNAL_KEY = "(external)"

#: lifecycle kinds that mutate accounting; unknown kinds only hit the ring
_ACCOUNTED = ("register", "spill", "restore", "free", "external")


def _fmt_key(key: Tuple) -> str:
    qid, nid, name = key
    if nid < 0:
        return name
    return f"q{'-' if qid is None else qid}:{name}#{nid}"


def _new_agg() -> Dict:
    return {"live_bytes": 0, "peak_bytes": 0, "allocs": 0, "frees": 0,
            "spilled_bytes": 0, "restored_bytes": 0, "held_s": 0.0}


class MemoryProfiler:
    """Thread-safe bounded lifecycle recorder + per-operator aggregator."""

    def __init__(self, ring_size: int = 4096, report_dir: str = ""):
        self.ring_size = ring_size
        self.report_dir = report_dir
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._ring: deque = deque(maxlen=ring_size)
        # buffer_id -> [owner key, size_bytes, t_register, on_device]
        self._owners: Dict[int, list] = {}
        # (query_id, node_id, name) -> aggregation dict (_new_agg)
        self._agg: Dict[Tuple, Dict] = {}
        self._ext_bytes = 0  # last-seen external device bytes (sum)
        self.live_attributed_bytes = 0  # catalog-resident device bytes
        self.peak_bytes = 0
        self.peak_holders: Dict[str, int] = {}
        self.events_recorded = 0
        self.leaks_detected = 0
        self.postmortems_written = 0
        self._pending_postmortems: List[Dict] = []

    # -- recording (called from inside catalog mutations) ---------------------
    def record(self, kind: str, buffer_id: int, nbytes: int,
               ext_bytes: Optional[int] = None,
               tier: Optional[str] = None) -> None:
        """One lifecycle event. ``ext_bytes`` is the catalog's current
        external-bytes sum (cached ints — satellite: external sources must
        be visible to peak attribution or holders can't sum to the true
        watermark). Unknown ``kind`` values only land in the ring."""
        ctx = current()
        key = (ctx.query_id, ctx.node_id, ctx.name) if ctx is not None \
            else UNATTRIBUTED
        now = time.time()
        with self._lock:
            self.events_recorded += 1
            if ext_bytes is not None:
                self._ext_bytes = int(ext_bytes)
            if kind == "register":
                self._owners[buffer_id] = [key, nbytes, now, True]
                a = self._agg_locked(key)
                a["allocs"] += 1
                a["live_bytes"] += nbytes
                if a["live_bytes"] > a["peak_bytes"]:
                    a["peak_bytes"] = a["live_bytes"]
                self.live_attributed_bytes += nbytes
            elif kind == "spill":
                owner = self._owners.get(buffer_id)
                if owner is not None and owner[3]:
                    owner[3] = False
                    oa = self._agg_locked(owner[0])
                    oa["live_bytes"] -= owner[1]
                    self.live_attributed_bytes -= owner[1]
                # churn is charged to the operator DRIVING the spill (the
                # allocator), matching the catalog's SPILL_BYTES metric
                self._agg_locked(key)["spilled_bytes"] += nbytes
            elif kind == "restore":
                owner = self._owners.get(buffer_id)
                if owner is not None and not owner[3]:
                    owner[3] = True
                    oa = self._agg_locked(owner[0])
                    oa["live_bytes"] += owner[1]
                    if oa["live_bytes"] > oa["peak_bytes"]:
                        oa["peak_bytes"] = oa["live_bytes"]
                    self.live_attributed_bytes += owner[1]
                self._agg_locked(key)["restored_bytes"] += nbytes
            elif kind == "free":
                owner = self._owners.pop(buffer_id, None)
                if owner is not None:
                    okey, obytes, t_reg, on_device = owner
                    oa = self._agg_locked(okey)
                    oa["frees"] += 1
                    oa["held_s"] += now - t_reg
                    if on_device:
                        oa["live_bytes"] -= obytes
                        self.live_attributed_bytes -= obytes
            self._ring.append({
                "seq": next(self._seq), "ts": now, "kind": kind,
                "buffer": buffer_id, "bytes": nbytes, "tier": tier,
                "query_id": key[0], "node_id": key[1], "operator": key[2]})
            total = self.live_attributed_bytes + self._ext_bytes
            if total > self.peak_bytes:
                self.peak_bytes = total
                self.peak_holders = self._holders_dict_locked()

    def _agg_locked(self, key: Tuple) -> Dict:
        a = self._agg.get(key)
        if a is None:
            a = self._agg[key] = _new_agg()
        return a

    def _holders_dict_locked(self) -> Dict[str, int]:
        """Live device bytes by owner label, from the owner table (not the
        per-query aggregation, which query_end prunes — a leaked buffer
        must stay visible in peak/holder attribution)."""
        holders: Dict[str, int] = {}
        for okey, obytes, _t, on_device in self._owners.values():
            if on_device:
                label = _fmt_key(okey)
                holders[label] = holders.get(label, 0) + obytes
        if self._ext_bytes:
            holders[EXTERNAL_KEY] = self._ext_bytes
        return holders

    # -- queries ---------------------------------------------------------------
    def holders_by_operator(self) -> List[Tuple[str, int]]:
        """Current live device bytes per owner, ranked descending — the
        oom_dump / postmortem / /status ranking."""
        with self._lock:
            holders = self._holders_dict_locked()
        return sorted(holders.items(), key=lambda kv: -kv[1])

    def begin_query(self, query_id) -> None:
        """Drop stale aggregation for ``query_id`` (profile_query reuses
        query_id=None across runs; event-log query ids are unique)."""
        with self._lock:
            for key in [k for k in self._agg if k[0] == query_id]:
                del self._agg[key]

    def node_peaks(self, query_id) -> Dict[int, int]:
        """node_id -> peak device bytes for one query (the EXPLAIN
        ANALYZE peak-HBM column and the event-log node records)."""
        with self._lock:
            return {k[1]: a["peak_bytes"] for k, a in self._agg.items()
                    if k[0] == query_id and k[1] >= 0 and a["peak_bytes"]}

    def query_end(self, query_id) -> Dict:
        """Leak scan + per-operator summary at the query boundary.

        Buffers still registered whose owner belongs to ``query_id`` are
        flagged as leaks (attributed: operator + bytes + held duration).
        The query's aggregation entries are pruned afterwards so the
        table stays bounded across a long session."""
        now = time.time()
        with self._lock:
            leaks = []
            for bid, (okey, obytes, t_reg, on_dev) in self._owners.items():
                if okey[0] == query_id:
                    leaks.append({
                        "buffer": bid, "bytes": obytes,
                        "operator": okey[2], "node_id": okey[1],
                        "on_device": on_dev,
                        "held_s": round(now - t_reg, 3)})
            per_op = {}
            for key in [k for k in self._agg if k[0] == query_id]:
                a = self._agg.pop(key)
                per_op[f"{key[2]}#{key[1]}"] = {
                    "peak_bytes": a["peak_bytes"],
                    "live_bytes": a["live_bytes"],
                    "allocs": a["allocs"], "frees": a["frees"],
                    "spilled_bytes": a["spilled_bytes"],
                    "restored_bytes": a["restored_bytes"],
                    "held_s": round(a["held_s"], 4)}
            self.leaks_detected += len(leaks)
            summary = {
                "query_id": query_id,
                "peak_bytes": self.peak_bytes,
                "peak_holders": dict(self.peak_holders),
                "per_operator": per_op,
                "leaked_buffers": sorted(leaks, key=lambda d: -d["bytes"]),
                "leaked_bytes": sum(d["bytes"] for d in leaks),
            }
        if leaks:
            from .tracing import get_tracer
            get_tracer().instant(
                "memory_leak", "memory", query_id=query_id,
                buffers=len(leaks), bytes=summary["leaked_bytes"])
        return summary

    # -- OOM postmortem --------------------------------------------------------
    def oom_postmortem(self, context: str, catalog=None,
                       last_n: int = 64) -> Dict:
        """Full attribution dump before an OOM propagates: ranked
        holders-by-operator, external sources, spill-tier occupancy, the
        last N lifecycle events and the semaphore holder table — written
        to ``report_dir/oom-<ts>.txt`` (the stall-report convention,
        utils/health.py) and queued as a schema-v6 event-log record.

        Called from inside the catalog lock on the failing thread (RLock:
        re-entrant); catalog state is read via plain attribute loads."""
        now = time.time()
        with self._lock:
            holders = sorted(self._holders_dict_locked().items(),
                             key=lambda kv: -kv[1])
            ring = list(self._ring)[-last_n:]
            live = self.live_attributed_bytes + self._ext_bytes
            peak = self.peak_bytes
        lines = [
            "== spark-rapids-tpu OOM postmortem ==",
            time.strftime("time: %Y-%m-%dT%H:%M:%S%z"),
            f"context: {context}",
            f"live device bytes: {live} (peak {peak})",
            "",
            "-- holders by operator (live device bytes, ranked) --",
        ]
        lines.extend(f"  {label}: {b}" for label, b in holders)
        if not holders:
            lines.append("  (no live attributed buffers)")
        if catalog is not None:
            ext = dict(catalog._external_cache)
            lines.append("\n-- external device bytes by source --")
            lines.extend(f"  {k}: {v}" for k, v in sorted(ext.items()))
            if not ext:
                lines.append("  (none registered)")
            lines.append("\n-- spill-tier occupancy --")
            lines.append(f"  DEVICE used={catalog.device.used_bytes} "
                         f"limit={catalog.device.limit_bytes}")
            lines.append(f"  HOST   used={catalog.host.used_bytes} "
                         f"limit={catalog.host.limit_bytes}")
            lines.append(f"  DISK   used={catalog.disk.used_bytes}")
            lines.append(f"  spill_count={{host: "
                         f"{catalog.spill_count[1]}, disk: "
                         f"{catalog.spill_count[2]}}} "
                         f"oom_events={catalog.oom_events}")
        lines.append(f"\n-- last {len(ring)} lifecycle events --")
        for ev in ring:
            lines.append(
                f"  #{ev['seq']} {ev['kind']:<9} buffer={ev['buffer']} "
                f"bytes={ev['bytes']} tier={ev['tier']} "
                f"query={ev['query_id']} op={ev['operator']}")
        if not ring:
            lines.append("  (ring empty)")
        lines.append("\n-- semaphore --")
        from ..memory.semaphore import peek_semaphore
        sem = peek_semaphore()
        if sem is None:
            lines.append("  (no semaphore created yet)")
        else:
            d = sem.dump()
            lines.append(
                f"  permits={d['permits']} available={d['available']} "
                f"acquires={d['acquires']}")
            for h in d["holders"]:
                lines.append(f"  holder: thread={h['thread']!r} "
                             f"task={h['task_id']} held {h['held_s']:.1f}s")
            for w in d["waiters"]:
                lines.append(f"  waiter: thread={w['thread']!r} "
                             f"waiting {w['waiting_s']:.1f}s")
        report = "\n".join(lines) + "\n"
        path = None
        if self.report_dir:
            try:
                os.makedirs(self.report_dir, exist_ok=True)
                path = os.path.join(self.report_dir,
                                    f"oom-{int(now * 1000)}.txt")
                with open(path, "w", encoding="utf-8") as f:
                    f.write(report)
            except OSError:
                path = None
        record = {
            "ts": now, "context": context[:500], "path": path,
            "live_bytes": live, "peak_bytes": peak,
            "holders": dict(holders[:10]), "report": report,
        }
        with self._lock:
            self.postmortems_written += 1
            self._pending_postmortems.append(record)
        from .metrics import get_stats
        from .tracing import get_tracer
        get_stats().add("memprof_postmortems")
        get_tracer().instant("oom_postmortem", "memory",
                             context=context[:200], path=path or "")
        return record

    def drain_postmortems(self) -> List[Dict]:
        """Pop queued postmortem records (the event-log writer folds them
        into the query that triggered them)."""
        with self._lock:
            out, self._pending_postmortems = self._pending_postmortems, []
        return out

    # -- snapshots -------------------------------------------------------------
    def events(self, last_n: Optional[int] = None) -> List[Dict]:
        with self._lock:
            evs = list(self._ring)
        return evs if last_n is None else evs[-last_n:]

    def snapshot(self) -> Dict:
        """The /status ``memory`` section (tools/statusd.py via
        HealthMonitor.snapshot): live + peak attribution at a glance."""
        with self._lock:
            holders = sorted(self._holders_dict_locked().items(),
                             key=lambda kv: -kv[1])
            return {
                "enabled": True,
                "live_attributed_bytes": self.live_attributed_bytes,
                "external_bytes": self._ext_bytes,
                "peak_bytes": self.peak_bytes,
                "peak_holders": dict(self.peak_holders),
                "top_holders": [{"owner": k, "bytes": v}
                                for k, v in holders[:10]],
                "events_recorded": self.events_recorded,
                "ring_len": len(self._ring),
                "leaks_detected": self.leaks_detected,
                "postmortems": self.postmortems_written,
            }

    def stats(self) -> Dict:
        """Flat-ish counters for the process StatsRegistry — the nested
        ``operator_live_bytes`` dict flattens into per-operator Prometheus
        gauges (utils/metrics.py _flatten sanitizes the names), which
        /metrics and /federation/metrics then expose per process."""
        with self._lock:
            per_op: Dict[str, int] = {}
            for okey, obytes, _t, on_device in self._owners.values():
                if on_device:
                    per_op[okey[2]] = per_op.get(okey[2], 0) + obytes
            return {
                "enabled": True,
                "events": self.events_recorded,
                "live_attributed_bytes": self.live_attributed_bytes,
                "external_bytes": self._ext_bytes,
                "peak_bytes": self.peak_bytes,
                "live_buffers": len(self._owners),
                "leaks_detected": self.leaks_detected,
                "postmortems": self.postmortems_written,
                "operator_live_bytes": per_op,
            }


# ---------------------------------------------------------------------------
# process-global profiler (the catalog hot path reads this once per event)
# ---------------------------------------------------------------------------
_ACTIVE: Optional[MemoryProfiler] = None
_ACTIVE_LOCK = threading.Lock()


def active() -> Optional[MemoryProfiler]:
    """The live profiler or None when disabled — the catalog's fast path
    (one attribute load + is-None check when profiling is off)."""
    return _ACTIVE


def get_memprof() -> Optional[MemoryProfiler]:
    return _ACTIVE


def set_memprof(mp: Optional[MemoryProfiler]) -> None:
    """Explicitly install/clear the profiler (tests; disabling is an
    explicit act, mirroring the tracer's sticky-enable contract)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = mp


def configure_memprof(conf) -> Optional[MemoryProfiler]:
    """Session-init chokepoint (TpuSession.__init__).

    Sticky semantics like configure_tracer: the profiler is process-wide
    and sessions come and go, so a session with profiling disabled must
    not clear a profiler another session filled (disable explicitly via
    ``set_memprof(None)``). The ring resizes only on a non-default size;
    a non-empty health.reportDir always updates the postmortem target."""
    global _ACTIVE
    from .health import HEALTH_REPORT_DIR
    with _ACTIVE_LOCK:
        if not bool(conf.get(MEMPROF_ENABLED)):
            return _ACTIVE
        ring = int(conf.get(MEMPROF_RING_SIZE))
        report_dir = str(conf.get(HEALTH_REPORT_DIR) or "")
        mp = _ACTIVE
        if mp is None:
            mp = _ACTIVE = MemoryProfiler(ring, report_dir)
            return mp
        if report_dir:
            mp.report_dir = report_dir
        if ring != mp.ring_size and ring != MEMPROF_RING_SIZE.default:
            with mp._lock:
                mp.ring_size = ring
                mp._ring = deque(mp._ring, maxlen=ring)
        return mp


def memprof_stats() -> Dict:
    """StatsRegistry source hook (utils/metrics.py _DEFAULT_SOURCES)."""
    mp = _ACTIVE
    return mp.stats() if mp is not None else {"enabled": False}
